"""Closed-loop load generator for the serving runtime.

Hammers a `ServingSession` with concurrent predict requests and reports
achieved QPS / rows/s / latency percentiles per configuration — the
serving analog of `tools/perf_probe.py predict`.

Modes:
* batch-size sweep (default): one line per request size in `--sweep`,
  each run closed-loop (every worker fires its next request as soon as
  the previous returns).
* target QPS (`--qps N`): workers pace their requests to an aggregate
  open-loop arrival rate, reporting achieved QPS and shed counts — the
  overload-behavior probe.
* overload ramp (`--ramp`): measure the closed-loop saturation rate,
  then step offered load from 0.5x to `--ramp-max`x (default 5x) of
  it, one line per step with goodput, shed %, accepted p99, and the
  admission controller's level/window — the adaptive-admission
  acceptance probe (ISSUE 11).  `--chaos` arms a faultline
  `serve_dispatch` raise mid-ramp to prove accepted requests never
  see a device failure.  The summary line carries
  `serve_goodput_rows_per_sec` (best goodput across steps) and
  `serve_shed_pct` (top step) — the two numbers bench.py tracks.

The model comes from `--model model.txt`, or a synthetic binary model is
trained in-process (same shape family as bench.py, much smaller).

Usage:
    python tools/serve_bench.py                      # sweep 1..4096
    python tools/serve_bench.py --qps 500 --rows 64  # paced load
    python tools/serve_bench.py --ramp --chaos       # overload ramp
    python tools/serve_bench.py --model model.txt --threads 16
"""

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_model(n=20000, f=16, rounds=20):
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    X = rng.normal(size=(n, f))
    y = ((X[:, :4] ** 2 - 1.0).sum(axis=1) + rng.logistic(size=n)
         > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 63,
                     "verbosity": -1}, ds, num_boost_round=rounds,
                    verbose_eval=False)
    return bst, X


def run_closed_loop(sess, name, X, rows, threads, duration_s):
    """Every worker fires back-to-back requests for `duration_s`."""
    stop = time.monotonic() + duration_s
    counts = [0] * threads
    errors = [0] * threads

    def worker(i):
        Xi = X[:rows]
        while time.monotonic() < stop:
            try:
                sess.predict(name, Xi, raw_score=True)
                counts[i] += 1
            except Exception:
                errors[i] += 1

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    dt = time.monotonic() - t0
    return sum(counts), sum(errors), dt


def run_paced(sess, name, X, rows, threads, qps, duration_s):
    """Open-loop: aggregate arrivals paced to `qps` across workers.
    Thin wrapper over run_paced_counted (ONE pacing implementation)."""
    n_ok, n_shed, _n_err, dt = run_paced_counted(
        sess, name, X, rows, threads, qps, duration_s)
    return n_ok, n_shed, dt


def run_paced_counted(sess, name, X, rows, threads, qps, duration_s,
                      deadline_ms=None, chaos_at_s=None):
    """Open-loop paced load distinguishing accepted vs shed vs error;
    optionally arms a serve_dispatch fault `chaos_at_s` into the run."""
    period = threads / float(qps)
    stop = time.monotonic() + duration_s
    ok = [0] * threads
    shed = [0] * threads
    errors = [0] * threads

    def worker(i):
        from lightgbm_tpu.serving import (ServingOverloaded,
                                          ServingQueueFull,
                                          ServingTimeout)

        Xi = X[:rows]
        next_t = time.monotonic() + (i / threads) * period
        while True:
            now = time.monotonic()
            if now >= stop:
                return
            if now < next_t:
                time.sleep(min(next_t - now, 0.01))
                continue
            next_t += period
            try:
                sess.predict(name, Xi, raw_score=True,
                             deadline_ms=deadline_ms)
                ok[i] += 1
            except (ServingOverloaded, ServingQueueFull, ServingTimeout):
                shed[i] += 1
            except Exception:
                errors[i] += 1

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    if chaos_at_s is not None:
        from lightgbm_tpu.utils import faultline

        time.sleep(min(chaos_at_s, duration_s / 2))
        faultline.arm("serve_dispatch", action="raise", times=3)
    for t in ts:
        t.join()
    dt = time.monotonic() - t0
    return sum(ok), sum(shed), sum(errors), dt


def run_ramp(new_session, name, X, rows, threads, duration_s,
             ramp_max=5.0, steps=5, chaos=False, print_fn=print):
    """Overload ramp: saturation probe, then paced steps to
    ramp_max x saturation.  Returns the summary dict."""
    from lightgbm_tpu.utils import faultline

    sess = new_session()
    n_ok, _, dt = run_closed_loop(sess, name, X, rows, max(threads, 4),
                                  duration_s)
    sat_qps = max(n_ok / dt, 1.0)
    sess.close()
    print_fn(json.dumps({"mode": "ramp_saturation",
                         "sat_qps": round(sat_qps, 1),
                         "sat_rows_per_sec": round(sat_qps * rows, 0)}))
    # cold start: a fresh replica's wall time from construction to the
    # first SERVED batch.  The saturation session above already ran the
    # load path once, so with serving_aot_cache_dir set this session
    # deserializes its launch executables instead of compiling them —
    # the number bench.py tracks as serve_cold_start_ms (ISSUE 19)
    t0 = time.monotonic()
    sess = new_session()
    sess.predict(name, X[:rows], raw_score=True)
    cold_ms = (time.monotonic() - t0) * 1e3
    entry = sess.registry.resolve(name)
    table_bytes = int(getattr(entry, "hbm_total_bytes", 0)
                      or entry.hbm_bytes)
    n_dev = len(getattr(entry, "replicas", [])) or 1
    sess.close()
    print_fn(json.dumps({"mode": "ramp_cold_start",
                         "cold_start_ms": round(cold_ms, 1),
                         "table_hbm_bytes": table_bytes,
                         "devices": n_dev}))
    best_goodput = 0.0
    top = None
    slo_ms = None
    for k in range(steps):
        mult = 0.5 + (ramp_max - 0.5) * k / max(steps - 1, 1)
        qps = sat_qps * mult
        sess = new_session()
        slo_ms = float(sess.config.serving_slo_ms)
        chaos_at = duration_s * 0.4 if (chaos and k == steps - 1) else None
        n_ok, n_shed, n_err, dt = run_paced_counted(
            sess, name, X, rows, threads, qps, duration_s,
            deadline_ms=slo_ms * 4, chaos_at_s=chaos_at)
        faultline.reset()
        st = sess.stats()
        offered = n_ok + n_shed + n_err
        goodput = n_ok * rows / dt
        best_goodput = max(best_goodput, goodput)
        top = {
            "mode": "ramp_step", "offered_x_saturation": round(mult, 2),
            "offered_qps": round(qps, 1),
            "goodput_rows_per_sec": round(goodput, 0),
            "shed_pct": round(100.0 * n_shed / offered, 1) if offered
            else 0.0,
            "errors": n_err,
            "p99_ms": st["latency_p99_ms"],
            "expired": st["requests_expired"],
            "overload_429": st["requests_overload"],
            "queue_full_503": st["requests_shed"],
            "admission_level_rows": st["admission_level_rows"],
            "batch_window_ms": st["batch_window_ms"],
            "chaos": bool(chaos_at is not None),
            "device_fallbacks": st["device_fallbacks"],
        }
        print_fn(json.dumps(top))
        if sess.batcher.devices > 1:
            # per-device goodput/p99 breakdown (ISSUE 19): one line per
            # dispatch worker — uneven rows across devices at high load
            # means the least-loaded router is compensating for a slow
            # or breaker-opened device, not spreading by round-robin
            for d in sess.batcher.device_snapshot():
                line = dict(d, mode="ramp_device",
                            offered_x_saturation=round(mult, 2))
                line["goodput_rows_per_sec"] = round(d["rows"] / dt, 0)
                print_fn(json.dumps(line))
        sess.close()
    summary = {
        "mode": "ramp_summary",
        "serve_goodput_rows_per_sec": round(best_goodput, 0),
        "serve_fleet_goodput_rows_per_sec": round(best_goodput, 0),
        "serve_cold_start_ms": round(cold_ms, 1),
        "serve_table_hbm_bytes": table_bytes,
        "serve_devices": n_dev,
        "serve_shed_pct": top["shed_pct"] if top else 0.0,
        "serve_slo_ms": slo_ms,
        "top_step_p99_ms": top["p99_ms"] if top else 0.0,
        "top_step_errors": top["errors"] if top else 0,
    }
    print_fn(json.dumps(summary))
    return summary


def run_replay_drift(new_session, name, X, rows, threads, duration_s,
                     shift=1.5, print_fn=print):
    """Replay a recorded request stream with an injected covariate
    shift halfway through, a continual controller running train-behind
    the whole time (ISSUE 17).  The first half replays the recorded
    batches as-is; the second half replays the SAME batches shifted by
    `shift` on every feature — the bench's stand-in for live traffic
    walking off the training distribution.  Reports the drift the
    monitor saw, what the controller did about it (retrains /
    promotions / refusals / deferrals), and that the client hammer saw
    zero errors on accepted requests throughout."""
    from collections import Counter

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.continual import ContinualController

    sess = new_session()
    live = sess.registry.resolve(name)

    def labels_for(Xb):
        """Self-distilled labels: the live model's own answers on the
        batch.  A retrained candidate that tracks the live relationship
        on shifted inputs can tie or beat it — the bench exercises the
        loop's mechanics, not a real label join."""
        p = np.asarray(live.booster.predict(Xb), np.float64)
        if p.ndim > 1:
            return np.argmax(p, axis=1).astype(np.float64)
        obj = str(live.booster._driver.loaded_params.get(
            "objective", ""))
        return (p > 0.5).astype(np.float64) if obj.startswith("binary") \
            else p

    cfg = Config({"tpu_continual_min_rows": min(2048, rows * 4),
                  "tpu_continual_shadow_rows": 512,
                  "tpu_continual_boost_rounds": 5,
                  "tpu_continual_poll_s": 0.05,
                  "verbosity": -1})
    ctl = ContinualController(sess, name, config=cfg)

    # the "recorded" request stream: a fixed batch sequence replayed by
    # every worker (and mirrored, labeled, into the controller)
    n_rec = max(min(len(X) // rows, 64), 1)
    batches = [X[i * rows:(i + 1) * rows] for i in range(n_rec)]
    t0 = time.monotonic()
    t_mid = t0 + duration_s / 2
    t_end = t0 + duration_s
    ok = [0] * threads
    errors = [0] * threads

    def batch_at(i, now):
        b = batches[i % n_rec]
        return b + shift if now >= t_mid else b

    def worker(w):
        i = w
        while True:
            now = time.monotonic()
            if now >= t_end:
                return
            try:
                sess.predict(name, batch_at(i, now), raw_score=True)
                ok[w] += 1
            except Exception:
                errors[w] += 1
            i += 1

    ts = [threading.Thread(target=worker, args=(w,))
          for w in range(threads)]
    for t in ts:
        t.start()
    statuses = Counter()
    psi_max, warned = 0.0, False
    i = 0
    while time.monotonic() < t_end:
        Xb = batch_at(i, time.monotonic())
        ctl.observe(Xb, labels_for(Xb))
        # scrape BEFORE the controller's own scrape absorbs the window
        for d in sess.drift().get("models", {}).values():
            psi_max = max(psi_max, float(d.get("psi_max", 0.0)))
            warned = warned or bool(d.get("warn"))
        statuses[ctl.step()["status"]] += 1
        i += 1
        time.sleep(0.02)
    for t in ts:
        t.join()
    out = {
        "mode": "replay_drift", "shift": shift,
        "requests_ok": sum(ok), "errors": sum(errors),
        "psi_max": round(psi_max, 4), "psi_warn_fired": warned,
        "final_model": sess.registry.resolve(name).key,
        "controller": dict(statuses),
    }
    print_fn(json.dumps(out))
    sess.close()
    return out


def main():
    # bench crashes must never drop a blackbox dump beside the sources
    # the bench is usually run from; an explicit env/param still wins
    import tempfile
    os.environ.setdefault("LIGHTGBM_TPU_BLACKBOX_DIR",
                          tempfile.gettempdir())
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--model", default="", help="model file (default: "
                    "train a small synthetic model in-process)")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--duration", type=float, default=3.0,
                    help="seconds per configuration")
    ap.add_argument("--sweep", default="1,16,256,1024,4096",
                    help="comma-separated request row sizes")
    ap.add_argument("--rows", type=int, default=256,
                    help="rows per request in --qps mode")
    ap.add_argument("--qps", type=float, default=0.0,
                    help="target aggregate QPS (0 = closed-loop sweep)")
    ap.add_argument("--ramp", action="store_true",
                    help="overload ramp mode (saturation probe + paced "
                         "steps to --ramp-max x saturation)")
    ap.add_argument("--ramp-max", type=float, default=5.0,
                    help="top ramp step as a multiple of saturation")
    ap.add_argument("--ramp-steps", type=int, default=5)
    ap.add_argument("--chaos", action="store_true",
                    help="arm a serve_dispatch device fault mid-ramp "
                         "(top step)")
    ap.add_argument("--devices", type=int, default=0,
                    help="serving_devices override: replicate the model "
                         "across N dispatch lanes (0 = config auto)")
    ap.add_argument("--precision", default="",
                    choices=["", "f32", "bf16", "int16"],
                    help="serving_table_precision override for the "
                         "serving tables (default: config)")
    ap.add_argument("--aot-cache", default="",
                    help="serving_aot_cache_dir: persist AOT-compiled "
                         "launch executables so the cold-start probe "
                         "measures deserialize-not-compile")
    ap.add_argument("--replay-drift", action="store_true",
                    help="replay a recorded request stream with an "
                         "injected covariate shift halfway through, a "
                         "continual controller training behind the "
                         "session (ISSUE 17)")
    ap.add_argument("--shift", type=float, default=1.5,
                    help="per-feature covariate shift injected in "
                         "--replay-drift's second half")
    ap.add_argument("--slo-ms", type=float, default=0.0,
                    help="serving_slo_ms override (0 = config default)")
    ap.add_argument("--max-batch-rows", type=int, default=4096)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    args = ap.parse_args()

    from lightgbm_tpu.serving import ServingSession

    def new_session():
        """Fresh session (and stats) per configuration: cumulative
        counters/latency windows would misattribute earlier configs'
        numbers to later sweep lines."""
        params = {
            "serving_max_batch_rows": args.max_batch_rows,
            "serving_max_wait_ms": args.max_wait_ms,
            "verbosity": -1}
        if args.slo_ms > 0:
            params["serving_slo_ms"] = args.slo_ms
        if args.devices > 0:
            params["serving_devices"] = args.devices
        if args.precision:
            params["serving_table_precision"] = args.precision
        if args.aot_cache:
            params["serving_aot_cache_dir"] = args.aot_cache
        s = ServingSession(params=params)
        if args.model:
            s.load("bench", model_file=args.model,
                   params={"tpu_predict_device": "true"})
        else:
            s.load("bench", booster=bst)
        return s

    if args.model:
        probe = ServingSession(params={"serving_warmup": False})
        probe.load("bench", model_file=args.model)
        n_feat = probe.registry.resolve("bench").num_feature
        probe.close()
        rng = np.random.default_rng(3)
        X = rng.normal(size=(max(args.max_batch_rows, 4096), n_feat))
        bst = None
    else:
        bst, X = make_model()
    if args.ramp:
        run_ramp(new_session, "bench", X, args.rows, args.threads,
                 args.duration, ramp_max=args.ramp_max,
                 steps=args.ramp_steps, chaos=args.chaos)
        return
    if args.replay_drift:
        run_replay_drift(new_session, "bench", X, args.rows,
                         args.threads, args.duration, shift=args.shift)
        return
    sess = new_session()

    if args.qps > 0:
        n_ok, n_shed, dt = run_paced(sess, "bench", X, args.rows,
                                     args.threads, args.qps, args.duration)
        st = sess.stats()
        print(json.dumps({
            "mode": "paced", "target_qps": args.qps,
            "achieved_qps": round(n_ok / dt, 1),
            "rows_per_request": args.rows,
            "rows_per_sec": round(n_ok * args.rows / dt, 0),
            "shed": n_shed,
            "p50_ms": st["latency_p50_ms"], "p95_ms": st["latency_p95_ms"],
            "p99_ms": st["latency_p99_ms"],
            "batch_fill_ratio": st["batch_fill_ratio"],
            "compile_cache_misses": st["compile_cache_misses"]}))
    else:
        for i, rows in enumerate(int(s) for s in args.sweep.split(",") if s):
            if i > 0:
                sess.close()
                sess = new_session()  # clean stats per sweep line
            n_ok, n_err, dt = run_closed_loop(sess, "bench", X, rows,
                                              args.threads, args.duration)
            st = sess.stats()
            print(json.dumps({
                "mode": "closed_loop", "rows_per_request": rows,
                "threads": args.threads,
                "qps": round(n_ok / dt, 1),
                "rows_per_sec": round(n_ok * rows / dt, 0),
                "errors": n_err,
                "p50_ms": st["latency_p50_ms"],
                "p99_ms": st["latency_p99_ms"],
                "batch_fill_ratio": st["batch_fill_ratio"],
                "compile_cache_misses": st["compile_cache_misses"]}))
    sess.close()


if __name__ == "__main__":
    main()
