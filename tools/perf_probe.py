"""TPU perf sweep: histogram impl x split batch x block size.

Run on the real chip when tuning the grower:
    python tools/perf_probe.py                  # default sweep
    K=25 BLOCK=16384 IMPL=pallas N=1000000 python tools/perf_probe.py one

Reports ms/tree and train AUC for each configuration at the bench shape
(Higgs-1M: 28 features, 255 leaves, 255 bins), so quality regressions
from batching show up next to the throughput numbers.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_data(n, f=28, seed=42):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    w = rng.normal(size=(f,))
    logits = (X[:, :8] ** 2 - 1.0).sum(axis=1) * 0.3 + X @ w * 0.5
    y = (logits + rng.logistic(size=n) > 0).astype(np.float64)
    return X.astype(np.float64), y


_DS_CACHE = {}


def _exc_inline(exc, limit=400):
    """One-line failure description for keep-going sweeps.

    The old truncation (`str(exc)[:120]`) routinely cut a jax trace-time
    error before the part that names the failing primitive, and NEVER
    showed the `__cause__` chain — a Mosaic lowering rejection surfaces
    as a generic XlaRuntimeError whose cause carries the real story.
    Keep the exception CLASS of every link in the chain plus the first
    line of each message."""
    parts = []
    seen = set()
    e = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        msg = str(e).strip()
        first = msg.splitlines()[0] if msg else ""
        parts.append(f"{type(e).__name__}: {first}" if first
                     else type(e).__name__)
        e = e.__cause__
    return " <- ".join(parts)[:limit]


def run_one(X, y, k, block, impl, iters=8, leaves=255, bins=255,
            partition="select", precision="hilo", ramp=False, alpha=0.0):
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.backend import host_sync
    from sklearn.metrics import roc_auc_score

    # bin once per (data, label, bins): sweep iterations reuse the Dataset
    ds_key = (id(X), id(y), bins)
    if ds_key not in _DS_CACHE:
        _DS_CACHE[ds_key] = lgb.Dataset(X, label=y, params={"max_bin": bins})
    ds = _DS_CACHE[ds_key]
    bst = lgb.Booster(params={
        "objective": "binary", "num_leaves": leaves, "learning_rate": 0.1,
        "min_data_in_leaf": 20, "max_bin": bins, "tpu_split_batch": k,
        "tpu_block_rows": block, "tpu_hist_impl": impl,
        "tpu_partition_impl": partition,
        "tpu_hist_precision": precision,
        "tpu_split_batch_alpha": alpha,
        # exact shapes: sweep numbers must stay byte-comparable with the
        # round-3 3.14 it/s record and bench.py's pinned configuration
        "tpu_shape_buckets": 0,
        "tpu_ramp": ramp}, train_set=ds)
    t0 = time.time()
    bst.update()
    host_sync(bst._driver.train_scores.scores)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(iters):
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    ms = (time.time() - t0) / iters * 1e3
    auc = roc_auc_score(y, bst.predict(X, raw_score=True))
    return ms, compile_s, auc


def sweep(X, y, configs, iters=6, reraise=False):
    """Run a list of config dicts through run_one, printing one line each.

    reraise=True (the single-config "one" mode) propagates failures with
    the full traceback instead of the sweep's keep-going truncation.
    """
    for cfg in configs:
        label = " ".join(f"{k}={v}" for k, v in cfg.items())
        try:
            ms, cs, auc = run_one(X, y, cfg.get("k", 25),
                                  cfg.get("block", 16384),
                                  cfg.get("impl", "xla"), iters=iters,
                                  partition=cfg.get("part", "select"),
                                  precision=cfg.get("prec", "hilo"),
                                  ramp=cfg.get("ramp", False),
                                  alpha=cfg.get("alpha", 0.0))
            print(f"{label}: {ms:6.0f} ms/tree ({1000/ms:5.2f} it/s) "
                  f"compile {cs:5.0f}s auc {auc:.4f}", flush=True)
        except Exception as exc:
            if reraise:
                raise
            print(f"{label}: FAILED {_exc_inline(exc)}", flush=True)


def run_predict_sweep(X, y, rounds=50, leaves=255, bins=255):
    """Prediction-throughput sweep: full-forest raw predict rows/s for
    the device bin-space predictor across row-chunk sizes, next to the
    native walker and the per-iteration valid-eval overhead.

        N=1000000 ROUNDS=50 python tools/perf_probe.py predict
    """
    import lightgbm_tpu as lgb

    ds = lgb.Dataset(X, label=y, params={"max_bin": bins})
    bst = lgb.Booster(params={
        "objective": "binary", "num_leaves": leaves, "learning_rate": 0.1,
        "max_bin": bins, "tpu_shape_buckets": 0,
        "tpu_predict_device": "true"}, train_set=ds)
    t0 = time.time()
    for _ in range(rounds):
        bst.update()
    bst._driver._materialize()
    print(f"trained {rounds} iters in {time.time() - t0:.0f}s "
          f"({bst.num_trees()} trees)", flush=True)
    n = X.shape[0]

    def timed(fn, reps=3):
        fn()  # warm (compile + pack)
        t = time.time()
        for _ in range(reps):
            fn()
        return (time.time() - t) / reps

    # device='cpu' pins the baseline to the native OMP walker — with
    # tpu_predict_device='true' an unqualified predict would route the
    # device path and the comparison would measure it against itself
    s = timed(lambda: bst.predict(X, raw_score=True, device="cpu"))
    print(f"native walker:           {n / s:12.0f} rows/s", flush=True)
    for chunk in (8192, 32768, 65536, 131072, 262144):
        bst.params["tpu_predict_chunk_rows"] = chunk
        # predict_raw_device reads the DRIVER's config (frozen at Booster
        # construction), not the handle's params dict
        bst._driver.config.params["tpu_predict_chunk_rows"] = chunk
        s = timed(lambda: bst.predict(X, raw_score=True, device="tpu"))
        print(f"device chunk={chunk:<7d}     {n / s:12.0f} rows/s",
              flush=True)
    # per-iteration eval overhead: LIVE update+eval iterations (the
    # incremental device tree-score pass + materialize + metric fetch)
    # against plain update iterations — a post-training eval_valid()
    # would only time the score fetch
    from lightgbm_tpu.utils.backend import host_sync

    def train_loop(with_eval, iters=3):
        t = time.time()
        for _ in range(iters):
            bst.update()
            if with_eval:
                bst.eval_valid()
        bst._driver._materialize()
        host_sync(bst._driver.train_scores.scores)
        return (time.time() - t) / iters

    n_eval = min(50_000, n)
    # baseline BEFORE the valid set attaches: once added, every update's
    # materialize pays the per-tree valid scoring, which belongs on the
    # with_eval side of the subtraction
    bst.update()  # warm
    base = train_loop(False)
    vd = ds.create_valid(X[:n_eval].copy(), label=y[:n_eval])
    bst.add_valid(vd, "valid")
    bst.update()
    bst.eval_valid()  # warm the replay + eval compiles
    with_eval = train_loop(True)
    print(f"valid eval ({n_eval} rows): "
          f"{max(with_eval - base, 0.0) * 1e3:8.1f} ms/iter overhead "
          f"(train {base * 1e3:.0f} -> train+eval {with_eval * 1e3:.0f})",
          flush=True)


def run_hist_sweep(X, y, bins=255, reps=4):
    """Histogram-kernel rows/s sweep: precision (hilo/f32/int16/int8) x
    impl (xla/pallas/pallas2) x block size, on the grower's own batched
    contraction (build_histogram_batched_t, K=25 slots), plus the
    auto-selection table `tpu_hist_impl=auto` would pick per precision.

        N=1000000 python tools/perf_probe.py hist
    """
    import jax
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.learner import TPUTreeLearner
    from lightgbm_tpu.ops.histogram import (bench_hist_operands,
                                            build_histogram_batched_t)
    from lightgbm_tpu.utils.backend import host_sync

    on_tpu = jax.devices()[0].platform == "tpu"
    ds = lgb.Dataset(X, label=y, params={"max_bin": bins})
    ds.construct()
    bins_np = np.asarray(ds._inner.bins)
    n_all, F = bins_np.shape
    B = bins + 1
    K = 25
    rng = np.random.default_rng(0)

    def one(precision, impl, block):
        # pallas off-TPU runs the interpreter — cap the rows handed to
        # the helper at ONE block so the sweep finishes; the printed
        # rows/s is still labeled per-config
        n_cap = n_all if (on_tpu or impl == "xla") \
            else min(n_all, max(4096, block))
        if n_cap < block:
            raise ValueError(f"need >= {block} rows, have {n_cap}")
        bins_tb, stats, n_use = bench_hist_operands(
            bins_np[:n_cap], precision, block)
        nb = n_use // block
        leaf_b = jnp.asarray(
            rng.integers(0, K, size=n_use).astype(np.int32)
            .reshape(nb, block))
        slots = jnp.arange(K, dtype=jnp.int32)
        fn = jax.jit(lambda b, s, l: build_histogram_batched_t(
            b, s, l, slots, B, precision, impl=impl))
        host_sync(fn(bins_tb, stats, leaf_b))  # compile
        t0 = time.time()
        for _ in range(reps):
            host_sync(fn(bins_tb, stats, leaf_b))
        return n_use * reps / max(time.time() - t0, 1e-9), n_use

    blocks = {"xla": (8192, 16384), "pallas": (256,),
              "pallas2": (4096, 8192)}
    for precision in ("hilo", "f32", "int16", "int8"):
        for impl in ("xla", "pallas", "pallas2"):
            for block in blocks[impl]:
                label = f"prec={precision:<5s} impl={impl:<7s} block={block}"
                try:
                    rps, n_use = one(precision, impl, block)
                    print(f"{label}: {rps:14.0f} rows/s ({n_use} rows)",
                          flush=True)
                except Exception as exc:
                    print(f"{label}: FAILED {_exc_inline(exc)}", flush=True)

    # ---- frontier step (hist + split scan): the fused megakernel next
    # to the exact unfused composition it replaces (perfeature hist +
    # the vmapped 2K-child per-feature scan).  This is the acceptance
    # microbench for tpu_hist_impl=fused: auto only claims fused on a
    # backend where the fused rows beat the best unfused ones here ----
    def one_frontier(precision, impl, block):
        from lightgbm_tpu.ops import fused as FU
        from lightgbm_tpu.ops import split as SP

        n_cap = n_all if (on_tpu or impl == "xla") \
            else min(n_all, max(4096, block))
        if n_cap < block:
            raise ValueError(f"need >= {block} rows, have {n_cap}")
        bins_tb, stats, n_use = bench_hist_operands(
            bins_np[:n_cap], precision, block)
        nb = n_use // block
        leaf_b = jnp.asarray(
            rng.integers(0, K, size=n_use).astype(np.int32)
            .reshape(nb, block))
        slots = jnp.arange(K, dtype=jnp.int32)
        C = 2 * K
        ctx_np = np.zeros((C + 1, 8), np.float32)
        ctx_np[:C, 0] = 100.0
        ctx_np[:C, 1] = 200.0
        ctx_np[:C, 2] = float(n_use) / C
        ctx_np[:C, 3] = -1e30
        ctx_np[:C, 4] = 1e30
        ctx_np[:C, 5] = (np.arange(C) % 2).astype(np.float32)
        ctx_np[C, :3] = (0.5, 0.25, 1.0)
        ctx = jnp.asarray(ctx_np)
        meta_i = jnp.zeros((F, 8), jnp.int32).at[:, 0].set(B)
        meta_f = jnp.ones((F, 8), jnp.float32)
        parent = jnp.ones((K, F, B, 3), jnp.int32) * (n_use // K)
        kw = dict(l1=0.0, l2=1.0, max_delta_step=0.0, min_data_in_leaf=1.0,
                  min_sum_hessian=1e-3, min_gain_to_split=0.0)
        if impl == "fused":
            fn = jax.jit(lambda b, s, l: FU.fused_hist_scan(
                b, s, l, slots, parent, ctx, meta_i, meta_f, B, precision,
                split_kw=kw))
        else:
            def unfused(b, s, l):
                hist = build_histogram_batched_t(b, s, l, slots, B,
                                                 precision, impl=impl)

                def child(j):
                    k = j % K
                    small = hist[k]
                    hs = jnp.where(ctx[j, 5] > 0, small, parent[k] - small)
                    return SP.per_feature_best_split(
                        hs, ctx[j, 0], ctx[j, 1], ctx[j, 2],
                        meta_i[:, 0], meta_i[:, 1], meta_i[:, 2],
                        meta_i[:, 3], meta_f[:, 0], meta_f[:, 1],
                        min_constraint=ctx[j, 3], max_constraint=ctx[j, 4],
                        acc_scale=ctx[C, :3], **kw)
                return hist, jax.vmap(child)(jnp.arange(C))
            fn = jax.jit(unfused)
        # block_until_ready, not host_sync: both variants return a
        # (hist, records/pf) pytree, not a single array
        jax.block_until_ready(fn(bins_tb, stats, leaf_b))  # compile
        t0 = time.time()
        for _ in range(reps):
            jax.block_until_ready(fn(bins_tb, stats, leaf_b))
        return n_use * reps / max(time.time() - t0, 1e-9), n_use

    print("\nfrontier step (hist + 2K-child split scan), fused vs "
          "unfused:", flush=True)
    for precision in ("int8", "int16"):
        for impl, block in (("xla", 16384), ("pallas2", 8192),
                            ("fused", 8192)):
            label = f"prec={precision:<5s} impl={impl:<7s} block={block}"
            try:
                rps, n_use = one_frontier(precision, impl, block)
                print(f"{label}: {rps:14.0f} rows/s ({n_use} rows)",
                      flush=True)
            except Exception as exc:
                print(f"{label}: FAILED {_exc_inline(exc)}", flush=True)

    print("\nauto-selection (tpu_hist_impl=auto on this backend):",
          flush=True)
    for precision in ("hilo", "f32", "int16", "int8"):
        cfg = Config({"objective": "binary", "num_leaves": 255,
                      "max_bin": bins, "tpu_hist_precision": precision})
        impl, block = TPUTreeLearner._resolve_hist_impl(cfg, B, precision)
        print(f"  {precision:<5s} -> impl={impl} block={block}", flush=True)


def run_tune(bins=255):
    """Autotune round-trip: measure + persist the profile for the bench
    shape bucket, then print what tpu_hist_impl=auto resolves to FROM
    the profile — the durable form of the hist sweep's verdict.

        N=131072 PROFILE=/tmp/at.json python tools/perf_probe.py tune
    """
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.learner import TPUTreeLearner
    from lightgbm_tpu.utils import autotune as AT

    n = int(os.environ.get("N", 131072))
    f = int(os.environ.get("F", 28))
    B = bins + 1
    cfg = None
    for precision in ("int8", "int16", "hilo"):
        params = {"objective": "binary", "num_leaves": 255,
                  "max_bin": bins, "tpu_hist_precision": precision,
                  "tpu_autotune": "tune"}
        if os.environ.get("PROFILE"):
            params["tpu_autotune_profile"] = os.environ["PROFILE"]
        cfg = Config(params)
        try:
            entry = AT.resolve_autotune(cfg, n, f, B, precision)
        except Exception as exc:
            print(f"{precision:<5s}: FAILED {_exc_inline(exc)}", flush=True)
            continue
        print(f"{precision:<5s} bucket={AT.shape_bucket(n, f, B)} -> "
              f"{entry['hist_impl']}:{entry['block_rows']} "
              f"({entry['rows_per_sec']:.0f} rows/s)", flush=True)
        for ck, rps in sorted(entry.get("table", {}).items()):
            print(f"    {ck:<14s} {rps:14.0f} rows/s", flush=True)
        impl, block = TPUTreeLearner._resolve_hist_impl(
            cfg, B, precision, tuned=entry)
        print(f"    resolved auto -> impl={impl} block={block}", flush=True)
    if cfg is not None:
        print(f"profile: {AT.profile_path(cfg)}", flush=True)


def run_ingest_sweep(X, y, bins=255):
    """Ingest-throughput sweep: Dataset construct rows/s for the host
    binning path next to the device kernel across chunk sizes, with the
    sketch (bin finding) phase split out.

        N=1000000 python tools/perf_probe.py ingest
    """
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils import timer as phase_timer

    n = X.shape[0]

    def once(mode, chunk):
        phase_timer.enable(True)
        phase_timer.reset()
        t0 = time.time()
        ds = lgb.Dataset(X, label=y, params={
            "max_bin": bins, "tpu_ingest_device": mode,
            "tpu_ingest_chunk_rows": chunk})
        ds.construct()
        if ds._inner._ingest_bins is not None:
            jax.block_until_ready(ds._inner._ingest_bins)
        wall = time.time() - t0
        ph = dict(phase_timer.summary())
        phase_timer.enable(False)
        return wall, ph.get("sketch", 0.0), ph.get("binning", 0.0)

    s, sk, bn = once("false", 65536)
    print(f"host binning:            {n / s:12.0f} rows/s "
          f"(sketch {sk:5.2f}s bin {bn:5.2f}s)", flush=True)
    for chunk in (16384, 32768, 65536, 131072, 262144):
        s, sk, bn = once("true", chunk)
        print(f"device chunk={chunk:<7d}    {n / s:12.0f} rows/s "
              f"(sketch {sk:5.2f}s bin {bn:5.2f}s)", flush=True)


def run_comm_sweep(shard_counts, reps=10, host_counts=(1,)):
    """Histogram-aggregation sweep: psum (all-reduce) vs psum_scatter
    (reduce-scatter) wall time over (hosts, shards, F, B, K, precision),
    with the predicted per-shard receive bytes split into ICI and DCN
    legs printed next to the measured wall so the scatter win stays
    legible even on the CPU container (where the "collective" is a
    memcpy and the wall mostly tracks bytes touched).  The collectives
    ride the unified (hosts, data, feature) topology — `axis_psum` /
    `axis_psum_scatter` over the ROW_AXES pair, exactly the grower's
    aggregation path — so the sweep measures what training runs.  The
    hierarchical ring model (parallel/mesh.py tiered_* helpers) splits
    the receive bytes: the intra-host ring moves full-payload legs over
    ICI while the cross-host ring moves 1/d-sized legs over DCN; total
    scatter bytes equal the flat ring at every (h, d) factorization, so
    growing the hosts axis re-labels legs without adding traffic.  The
    array is the grower's aggregation payload: the [K, F, B, 3]
    smaller-child histograms in the accumulation dtype (int32 for
    int8/int16, f32 for hilo/f32).

        SHARDS=2,4,8 HOSTS=1,2 python tools/perf_probe.py comm
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.parallel.mesh import (tiered_allreduce_recv_bytes,
                                            tiered_reduce_scatter_recv_bytes)
    from lightgbm_tpu.parallel.strategies import shard_map
    from lightgbm_tpu.parallel.topology import (ROW_AXES, axis_psum,
                                                axis_psum_scatter,
                                                make_topology)

    devices = jax.devices()
    rng = np.random.default_rng(0)
    print(f"{len(devices)} {devices[0].platform} devices; per-shard "
          "receive bytes predicted by the tiered ring cost model "
          "(parallel/mesh.py): ICI = intra-host ring over full payload, "
          "DCN = cross-host ring over the 1/devices-per-host slice",
          flush=True)
    header = (f"{'hosts':>5s} {'shards':>6s} {'F':>5s} {'B':>4s} {'K':>3s} "
              f"{'prec':>5s} {'payload':>9s} "
              f"{'psum ICI':>9s} {'psum DCN':>9s} "
              f"{'scat ICI':>9s} {'scat DCN':>9s} "
              f"{'psum ms':>8s} {'scatter ms':>10s} {'ratio':>6s}")
    print(header, flush=True)
    for hosts in host_counts:
        for p in shard_counts:
            if p > len(devices):
                print(f"{hosts:5d} {p:6d}  SKIP (only {len(devices)} "
                      "devices)", flush=True)
                continue
            if p % hosts != 0:
                print(f"{hosts:5d} {p:6d}  SKIP ({p} shards not divisible "
                      f"by {hosts} hosts)", flush=True)
                continue
            d_local = p // hosts
            topo = make_topology(num_data_shards=p, num_feature_shards=1,
                                 num_hosts=hosts, devices=devices)
            mesh = topo.mesh
            for F, B, K in ((32, 64, 16), (32, 256, 25), (256, 256, 25)):
                # pad F to the shard count like the learner does
                Fp = -(-F // p) * p
                for prec in ("int8", "hilo"):
                    dt = (jnp.int32 if prec in ("int8", "int16")
                          else jnp.float32)
                    h = jnp.asarray(
                        rng.integers(0, 1000, size=(K, Fp, B, 3)), dtype=dt)
                    nbytes = h.size * h.dtype.itemsize

                    def f_psum(x):
                        return axis_psum(x, ROW_AXES)

                    def f_scat(x):
                        return axis_psum_scatter(x, ROW_AXES,
                                                 scatter_dimension=1,
                                                 tiled=True)

                    fns = {}
                    fns["psum"] = jax.jit(shard_map(
                        f_psum, mesh=mesh, in_specs=P(), out_specs=P(),
                        check_vma=False))
                    fns["scatter"] = jax.jit(shard_map(
                        f_scat, mesh=mesh, in_specs=P(),
                        out_specs=P(None, ROW_AXES), check_vma=False))
                    walls = {}
                    for name, fn in fns.items():
                        jax.block_until_ready(fn(h))  # compile
                        t0 = time.time()
                        for _ in range(reps):
                            out = fn(h)
                        jax.block_until_ready(out)
                        walls[name] = (time.time() - t0) / reps * 1e3
                    ar_ici, ar_dcn = tiered_allreduce_recv_bytes(
                        nbytes, hosts, d_local)
                    rs_ici, rs_dcn = tiered_reduce_scatter_recv_bytes(
                        nbytes, hosts, d_local)
                    mb = 1.0 / (1024 * 1024)
                    print(f"{hosts:5d} {p:6d} {Fp:5d} {B:4d} {K:3d} "
                          f"{prec:>5s} {nbytes * mb:8.1f}M "
                          f"{ar_ici * mb:8.1f}M {ar_dcn * mb:8.1f}M "
                          f"{rs_ici * mb:8.1f}M {rs_dcn * mb:8.1f}M "
                          f"{walls['psum']:8.2f} {walls['scatter']:10.2f} "
                          f"{walls['psum'] / max(walls['scatter'], 1e-9):6.2f}",
                          flush=True)


def run_retrace(n=20000, f=10, leaves=31, bins=63, iters=3):
    """Retrace audit: run a canonical train + retrain + predict + serve
    lifecycle with the CompileLedger enabled and print, per phase, how
    many XLA programs were compiled and where (per-site breakdown with
    call signatures) — the tool that attributes compile_s growth to the
    jit site/mode variant that caused it.

        N=20000 python tools/perf_probe.py retrace
    """
    import lightgbm_tpu as lgb
    from lightgbm_tpu.booster import Booster
    from lightgbm_tpu.serving import ServingSession
    from lightgbm_tpu.utils.backend import host_sync
    from lightgbm_tpu.utils.compile_ledger import LEDGER

    X, y = make_data(n, f=f)
    p = {"objective": "binary", "num_leaves": leaves, "max_bin": bins,
         "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1}
    LEDGER.enable()
    LEDGER.reset()
    phases = []

    def phase(label):
        phases.append((label, LEDGER.n_programs()))

    ds = lgb.Dataset(X, label=y, params=p)
    bst = Booster(params=p, train_set=ds)
    for _ in range(iters):
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    phase(f"ingest + train ({iters} iters)")

    # the retrace-elimination contract: an identical second training run
    # reuses every cached executable — any program compiled here is a
    # regression (a jit site keyed on a fresh closure or static value)
    ds2 = lgb.Dataset(X, label=y, params=p)
    bst2 = Booster(params=p, train_set=ds2)
    for _ in range(iters):
        bst2.update()
    host_sync(bst2._driver.train_scores.scores)
    phase("second identical train")

    for sz in (1, 100, 4096, min(n, 20000)):
        # tpu_predict_device pinned: 'auto' on a CPU host vetoes to the
        # native walker and the sweep would audit zero device launches
        bst.predict(X[:sz], raw_score=True, device="tpu",
                    tpu_predict_device="true")
    phase("predict sweep (1..n rows)")

    sess = ServingSession(params={"serving_max_batch_rows": 4096,
                                  "verbosity": -1})
    sess.load("a", booster=bst)
    sess.load("b", booster=bst2)  # same-shaped: must add ZERO programs
    sess.predict("a", X[:100])
    sess.predict("b", X[:100])
    sess.close()
    phase("serve (2 same-shaped models)")

    prev = 0
    print(f"{'phase':<36s} {'new programs':>12s}")
    for label, count in phases:
        print(f"{label:<36s} {count - prev:>12d}", flush=True)
        prev = count
    print()
    print(LEDGER.format_report(), flush=True)
    if os.environ.get("RETRACE_SIGNATURES"):
        for prog in LEDGER.programs():
            print(f"  {prog['site']:<24s} {prog['first_call_s']:7.2f}s "
                  f"{prog['signature'][:120]}", flush=True)
    return dict(phases), LEDGER.n_programs()


def run_trace(n=100_000, iters=3, leaves=255, bins=255):
    """Unified profiling entry point (ISSUE 10; absorbs the old
    tools/profile_step.py): train a few boosting iterations under
    tpu_telemetry=trace, write the Chrome-trace JSON (open in Perfetto
    or chrome://tracing) + the JSONL event stream under TRACE_DIR, and
    print the span summary table (count / total / mean per name).
    XPROF=1 additionally wraps the timed iterations in
    jax.profiler.start_trace and prints the xprof op tables — the
    device-side complement (the telemetry span names appear inside it
    via TraceAnnotation/named_scope mirroring).

        N=1000000 ITERS=3 [XPROF=1] python tools/perf_probe.py trace
    """
    import glob

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.utils.backend import host_sync

    import shutil

    trace_dir = os.environ.get("TRACE_DIR", "/tmp/lgbm_trace")
    shutil.rmtree(trace_dir, ignore_errors=True)
    obs.configure(mode="trace", trace_dir=trace_dir)
    X, y = make_data(n)

    ds = lgb.Dataset(X, label=y, params={"max_bin": bins})
    bst = lgb.Booster(params={
        "objective": "binary", "num_leaves": leaves, "learning_rate": 0.1,
        "min_data_in_leaf": 20, "max_bin": bins,
        # match the BENCH program exactly (bench.py pins buckets off):
        # the point is attributing ITS ms/tree, not the bucketed
        # variant's
        "tpu_shape_buckets": 0,
        **json.loads(os.environ.get("EXTRA", "{}"))}, train_set=ds)
    for _ in range(2):  # compile + warm
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    obs.reset_events()  # profile the WARM loop, not the compile tail

    xprof = os.environ.get("XPROF", "") not in ("", "0")
    if xprof:
        jax.profiler.start_trace(trace_dir)
    t0 = time.time()
    for _ in range(iters):
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    wall = time.time() - t0
    if xprof:
        jax.profiler.stop_trace()
    print(f"{iters} iters in {wall:.2f}s = {iters / wall:.3f} it/s")

    path = obs.write_chrome_trace()
    obs.flush()
    print(f"chrome trace: {path} (load in Perfetto)")

    # span summary: where the host-side wall actually went
    agg = {}
    for ev in obs.events():
        if ev["kind"] != "span":
            continue
        cnt, tot = agg.get(ev["name"], (0, 0.0))
        agg[ev["name"]] = (cnt + 1, tot + ev["dur"])
    print(f"\n{'span':<28s} {'count':>6s} {'total ms':>10s} {'mean ms':>9s}")
    for name, (cnt, tot) in sorted(agg.items(), key=lambda kv: -kv[1][1]):
        print(f"{name:<28s} {cnt:>6d} {tot / 1e3:>10.1f} "
              f"{tot / cnt / 1e3:>9.2f}", flush=True)

    if not xprof:
        return
    # device-side op breakdown via xprof (the old profile_step tail)
    xplanes = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    print("xplane files:", xplanes)
    if not xplanes:
        return
    try:
        from xprof.convert import raw_to_tool_data as r
    except ImportError as exc:
        # the raw trace is still on disk for manual tensorboard use
        print(f"xprof unavailable ({exc}); raw trace kept at {trace_dir}")
        return
    for tool in ("framework_op_stats", "hlo_op_profile", "op_profile"):
        try:
            data, _ = r.xspace_to_tool_data(xplanes, tool, {})
            out = f"{trace_dir}/{tool}.out"
            mode = "wb" if isinstance(data, bytes) else "w"
            with open(out, mode) as f:
                f.write(data)
            print(f"wrote {out} ({len(data)} bytes)")
        except Exception as exc:
            print(f"{tool}: {type(exc).__name__}: {str(exc)[:120]}")


def run_mem(n=20000, f=10, leaves=31, bins=63, iters=3):
    """Device memory/cost accounting (ISSUE 12): run a canonical
    train + predict + serve lifecycle with the CompileLedger's cost
    capture armed and print, per compiled program, its static
    memory_analysis (argument/output/temp/generated-code bytes) and
    cost_analysis (FLOPs, bytes accessed) — plus live device
    memory_stats, the phase-tagged peak watermarks, and the big named
    buffers (histogram pool, packed forest) called out by name.

    Works on ANY backend: on CPU the device gauges read "n/a" but the
    per-program table still carries real FLOPs/bytes (and the memory
    fields via a forced AOT recompile of each small probe program).

        N=20000 python tools/perf_probe.py mem
    """
    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.booster import Booster
    from lightgbm_tpu.obs import resources
    from lightgbm_tpu.serving import ServingSession
    from lightgbm_tpu.utils.backend import host_sync
    from lightgbm_tpu.utils.compile_ledger import LEDGER

    obs.configure(mode="metrics")        # arm the phase watermarks
    LEDGER.enable()
    LEDGER.enable_capture()
    LEDGER.reset()
    resources.reset_phase_peaks()

    X, y = make_data(n, f=f)
    p = {"objective": "binary", "num_leaves": leaves, "max_bin": bins,
         "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1}
    ds = lgb.Dataset(X, label=y, params=p)
    bst = Booster(params=p, train_set=ds)
    for _ in range(iters):
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    bst.predict(X[:4096], raw_score=True, device="tpu",
                tpu_predict_device="true")
    sess = ServingSession(params={"serving_max_batch_rows": 1024,
                                  "verbosity": -1})
    sess.load("m", booster=bst)
    sess.predict("m", X[:64])
    serve_hbm = sess.registry.resolve("m").hbm_bytes
    sess.close()

    mb = 1.0 / (1024 * 1024)
    # ---- live device gauges ----
    print("device memory (memory_stats):")
    devs = jax.devices()
    any_stats = False
    for d, st in zip(devs, resources.all_device_memory_stats()):
        if st is None:
            print(f"  {d}: n/a ({d.platform} backend reports no "
                  "memory_stats)")
        else:
            any_stats = True
            print(f"  {d}: in_use {st.get('bytes_in_use', 0) * mb:.1f}M"
                  f"  peak {st.get('peak_bytes_in_use', 0) * mb:.1f}M")
    # ---- phase watermarks ----
    peaks = resources.phase_peaks()
    if peaks:
        print("phase peak watermarks:")
        for phase, b in sorted(peaks.items(), key=lambda kv: -kv[1]):
            print(f"  {phase:<14s} {b * mb:10.1f}M")
    elif not any_stats:
        print("phase peak watermarks: n/a (no device memory_stats)")

    # ---- named buffers ----
    learner = bst._driver.learner
    pool = getattr(learner, "_pool", None)
    donated = bool(getattr(learner, "_donate", False))
    if pool is not None:
        print(f"histogram pool [L, G/P, B, 3]: shape {tuple(pool.shape)} "
              f"{pool.dtype} = {pool.nbytes * mb:.1f}M"
              f"{' (donated, rewritten in place)' if donated else ''}")
    total, _ = bst._driver._model_subset(-1)
    tables = bst._driver._packed_forest().device(total)
    pf_bytes = sum(int(v.nbytes) for v in tables.values())
    print(f"packed forest ({total} trees): {pf_bytes * mb:.2f}M across "
          f"{len(tables)} tables; serving entry gauge "
          f"{serve_hbm * mb:.2f}M")
    scores = bst._driver.train_scores.scores
    print(f"score buffer: shape {tuple(scores.shape)} {scores.dtype} = "
          f"{scores.nbytes * mb:.2f}M"
          f"{' (donated at the step boundary)' if donated else ''}")

    # ---- per-program static cost table ----
    rows = LEDGER.cost_table(memory=True)  # force AOT analysis on CPU too
    print(f"\nper-program cost table ({len(rows)} programs):")
    print(f"{'site':<26s} {'MFLOPs':>9s} {'acc MB':>8s} {'arg MB':>8s} "
          f"{'out MB':>8s} {'tmp MB':>8s} {'code KB':>8s}")

    def fmt(v, scale, width=8, prec=2):
        return (f"{'n/a':>{width}s}" if v is None
                else f"{v * scale:>{width}.{prec}f}")

    for r in sorted(rows, key=lambda r: -(r["temp_bytes"] or 0)):
        print(f"{r['site']:<26s} "
              f"{fmt(r['flops'], 1e-6, 9)} {fmt(r['bytes_accessed'], mb)} "
              f"{fmt(r['argument_bytes'], mb)} {fmt(r['output_bytes'], mb)} "
              f"{fmt(r['temp_bytes'], mb)} "
              f"{fmt(r['generated_code_bytes'], 1 / 1024)}", flush=True)
    return rows


def run_faults(n=4000, f=6, iters=5):
    """Chaos sweep (ISSUE 7): arm every fault-injection point against
    every relevant handling mode and print one outcome line each — the
    operational proof that an injected device error, torn checkpoint
    write, NaN gradient, or serving-dispatch failure ends in a usable
    booster / recovered checkpoint / breaker-guarded fallback rather
    than a dead run.

        N=4000 python tools/perf_probe.py faults
    """
    import shutil
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.booster import Booster
    from lightgbm_tpu.serving import ServingSession
    from lightgbm_tpu.utils import faultline
    from lightgbm_tpu.utils.checkpoint import CheckpointManager
    from lightgbm_tpu.utils.log import LightGBMError

    X, y = make_data(n, f=f)
    base_params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
                   "learning_rate": 0.1, "min_data_in_leaf": 20,
                   "verbosity": -1}

    def outcome(point, mode, text):
        print(f"{point:<18s} {mode:<6s} {text}", flush=True)

    print(f"{'point':<18s} {'mode':<6s} outcome", flush=True)

    # grow_step x guard modes: a NaN-poisoned iteration under each policy
    for mode in ("off", "warn", "raise", "skip"):
        faultline.reset()
        p = dict(base_params, tpu_guard_numerics=mode)
        bst = Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
        faultline.arm("grow_step", action="poison", at=2)
        try:
            for _ in range(iters):
                bst.update()
            finite = bool(np.isfinite(
                bst.predict(X[:64], raw_score=True)).all())
            skips = bst._driver._guard_skips_total
            outcome("grow_step/poison", mode,
                    f"trained {bst.current_iteration()} iters, "
                    f"predict finite={finite}, skipped={skips}")
        except LightGBMError as exc:
            usable = bool(np.isfinite(
                bst.predict(X[:64], raw_score=True)).all())
            outcome("grow_step/poison", mode,
                    f"raised LightGBMError ({str(exc)[:40]}...), "
                    f"booster usable={usable}")

    # grow_step raise: injected device error -> rollback -> retrain
    faultline.reset()
    bst = Booster(params=dict(base_params),
                  train_set=lgb.Dataset(X, label=y, params=base_params))
    faultline.arm("grow_step", action="raise", at=3)
    errors = 0
    while bst.current_iteration() < iters:
        try:
            bst.update()
        except faultline.FaultInjected:
            errors += 1
    outcome("grow_step/raise", "-",
            f"{errors} injected error(s) rolled back, retrained to "
            f"{bst.current_iteration()} iters")

    # h2d_copy raise: device predict falls to an exception the caller
    # sees; the booster itself stays intact
    faultline.reset()
    faultline.arm("h2d_copy", action="raise")
    try:
        bst.predict(X[:256], raw_score=True, device="tpu",
                    tpu_predict_device="true")
        outcome("h2d_copy/raise", "-", "NOT reached (no device launch)")
    except faultline.FaultInjected:
        faultline.reset()
        ok = bool(np.isfinite(bst.predict(X[:64], raw_score=True)).all())
        outcome("h2d_copy/raise", "-",
                f"predict raised, booster usable={ok}")

    # checkpoint_write truncate: torn bundle is skipped, prior one loads
    faultline.reset()
    d = tempfile.mkdtemp(prefix="faults-ckpt-")
    try:
        bst.save_checkpoint(d)
        good = CheckpointManager(d).latest_iteration()
        bst.update()
        faultline.arm("checkpoint_write", action="truncate")
        bst.save_checkpoint(d)
        loaded = CheckpointManager(d).load_latest()
        outcome("checkpoint_write", "trunc",
                f"torn bundle skipped, recovered iteration="
                f"{loaded[0] if loaded else None} (good={good})")
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # serve_dispatch raise: breaker opens, walker serves, probe closes
    faultline.reset()
    sess = ServingSession(params={"serving_max_batch_rows": 512,
                                  "verbosity": -1,
                                  "serving_breaker_failures": 2,
                                  "serving_breaker_cooldown_ms": 50.0})
    sess.load("m", booster=bst)
    faultline.arm("serve_dispatch", action="raise", times=10)
    for _ in range(3):
        sess.predict("m", X[:64], raw_score=True)
    st = sess.stats()
    time.sleep(0.08)
    faultline.reset()
    sess.predict("m", X[:64], raw_score=True)
    st2 = sess.stats()
    outcome("serve_dispatch", "raise",
            f"fallbacks={st['device_fallbacks']} "
            f"opened={st['breaker_open']} "
            f"probes={st2['breaker_halfopen_probes']} "
            f"final={[m['breaker'] for m in sess.models()]}")
    sess.close()

    # ---- device_alloc oom x guarded site (ISSUE 15): a classified
    # RESOURCE_EXHAUSTED at each guarded allocation site must recover
    # (ladder / chunk shrink / walker failover) or surface structured
    from lightgbm_tpu.obs import REGISTRY
    from lightgbm_tpu.utils import membudget

    def oom_count(metric, **labels):
        return int(REGISTRY.value(metric, **labels))

    # train_step: rollback -> ladder step -> bitwise retry
    faultline.reset()
    p = dict(base_params)
    bst_o = Booster(params=p, train_set=lgb.Dataset(X, label=y, params=p))
    bst_o.update()
    faultline.arm("device_alloc", action="oom", at=1)
    bst_o.update()
    outcome("device_alloc/oom", "train",
            f"recovered to {bst_o.current_iteration()} iters, "
            f"recoveries={oom_count('lgbm_oom_recoveries_total', site='train_step')} "
            f"ladder={bst_o._driver._mem_ladder.describe()}")

    # predict_chunk: chunk shrink -> identical output
    faultline.reset()
    native = bst_o.predict(X[:512], raw_score=True)
    faultline.arm("device_alloc", action="oom", at=1)
    dev = bst_o.predict(X[:512], raw_score=True, device="tpu",
                        tpu_predict_device="true")
    outcome("device_alloc/oom", "pred",
            f"recovered, outputs equal={bool(np.allclose(native, dev))}")

    # ingest_chunk: binning chunk shrink -> bit-identical bins
    faultline.reset()
    pi = dict(base_params, tpu_ingest_device="true", tpu_ingest_min_rows=1,
              tpu_ingest_chunk_rows=2048)
    faultline.arm("device_alloc", action="oom", at=1)
    ds_i = lgb.Dataset(X, label=y, params=pi)
    ds_i.construct()
    faultline.reset()
    ds_h = lgb.Dataset(X, label=y, params=base_params)
    ds_h.construct()
    same = bool(np.array_equal(np.asarray(ds_i._inner.bins),
                               np.asarray(ds_h._inner.bins)))
    outcome("device_alloc/oom", "ingest",
            f"recovered via chunk shrink, bins bit-identical={same}")

    # serve_dispatch: walker failover, zero errors to the caller
    faultline.reset()
    sess_o = ServingSession(params={"verbosity": -1})
    sess_o.load("m", booster=bst_o)
    faultline.arm("device_alloc", action="oom", times=2)
    ok = bool(np.isfinite(np.asarray(
        sess_o.predict("m", X[:64], raw_score=True))).all())
    st_o = sess_o.stats()
    outcome("device_alloc/oom", "serve",
            f"served={ok} dispatch_oom={st_o['dispatch_oom']} "
            f"fallbacks={st_o['device_fallbacks']}")
    faultline.reset()
    sess_o.close()

    # ladder exhaustion: structured error, usable booster
    faultline.arm("device_alloc", action="oom", times=1000)
    try:
        bst_o.update()
        outcome("device_alloc/oom", "exh", "NOT reached (no exhaustion)")
    except membudget.MemoryLadderExhausted as exc:
        faultline.reset()
        usable = bool(np.isfinite(
            bst_o.predict(X[:64], raw_score=True)).all())
        outcome("device_alloc/oom", "exh",
                f"MemoryLadderExhausted at {exc.site!r}, booster "
                f"usable={usable}")
    faultline.reset()


def run_faults_multihost(hosts=2, iters=4, n=1200):
    """Distributed chaos sweep (ISSUE 8): a (point x armed-host x
    live-host) grid over a SIMULATED host group, one outcome line per
    cell — the operational proof that (a) a fault armed for host k at
    absolute call-index i fires on host k and ONLY host k (the
    reproducibility contract multihost chaos runs need), and (b) every
    addressed fault degrades to a flushed checkpoint + bitwise resume
    instead of a hung group.

        HOSTS=2 python tools/perf_probe.py faults --multihost
    """
    import shutil
    import tempfile

    import lightgbm_tpu as lgb
    from lightgbm_tpu.parallel.collective import (CollectiveTimeout,
                                                  HostDropped,
                                                  guarded_collective)
    from lightgbm_tpu.utils import faultline
    from lightgbm_tpu.utils.checkpoint import CheckpointManager

    X, y = make_data(n, f=6)
    base = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
            "learning_rate": 0.1, "min_data_in_leaf": 20,
            "verbosity": -1, "tpu_collective_timeout_s": 5.0}

    def outcome(point, h_armed, h_live, text):
        print(f"{point:<18s} armed=h{h_armed} live=h{h_live} {text}",
              flush=True)

    print(f"{'point':<18s} {'armed':<8s} {'live':<7s} outcome", flush=True)

    for point, action, exc_type in (
            ("collective_sync", "hang", CollectiveTimeout),
            ("host_drop", "raise", HostDropped)):
        for h_armed in range(hosts):
            for h_live in range(hosts):
                faultline.reset()
                faultline.set_host_index(h_live)
                d = tempfile.mkdtemp(prefix="mh-faults-")
                try:
                    p = dict(base, tpu_checkpoint_dir=d)
                    ds = lgb.Dataset(X, label=y, params=p)
                    dv = lgb.Dataset(X[:256], label=y[:256],
                                     reference=ds, params=p)
                    # the metric sync is one collective per iteration:
                    # absolute call-index 3 = iteration 3's eval
                    faultline.arm(point, action=action, at=3,
                                  absolute=True, host=h_armed)
                    try:
                        bst = lgb.train(p, ds, num_boost_round=iters,
                                        valid_sets=[dv],
                                        verbose_eval=False,
                                        keep_training_booster=True)
                        it = bst.current_iteration()
                        tag = ("UNEXPECTED clean run"
                               if h_armed == h_live else "not addressed")
                        outcome(point, h_armed, h_live,
                                f"{tag} -> trained {it} iters clean")
                    except exc_type as exc:
                        faultline.set_host_index(h_live)
                        faultline.disarm()
                        got = CheckpointManager(d).load_latest()
                        ck_it = got[0] if got else None
                        ds2 = lgb.Dataset(X, label=y, params=p)
                        bst2 = lgb.train(p, ds2, num_boost_round=iters,
                                         resume=True, verbose_eval=False,
                                         keep_training_booster=True)
                        outcome(point, h_armed, h_live,
                                f"{type(exc).__name__} at call 3 -> "
                                f"checkpoint@{ck_it} flushed, resumed "
                                f"to {bst2.current_iteration()} iters")
                finally:
                    faultline.reset()
                    shutil.rmtree(d, ignore_errors=True)

    # binning_allgather: single-process ingest never reaches the
    # multihost allgather, so the point is demonstrated at the transport
    # wrapper — same watchdog, same addressing
    for h_armed in range(hosts):
        for h_live in range(hosts):
            faultline.reset()
            faultline.set_host_index(h_live)
            faultline.arm("binning_allgather", action="hang",
                          host=h_armed)
            try:
                guarded_collective(lambda: "mappers",
                                   name="mapper_exchange",
                                   point="binning_allgather", local=True)
                outcome("binning_allgather", h_armed, h_live,
                        "not addressed -> mapper exchange completed")
            except CollectiveTimeout:
                outcome("binning_allgather", h_armed, h_live,
                        "CollectiveTimeout -> bin finding aborted "
                        "cleanly")
            finally:
                faultline.reset()


def run_drift_probe(n=20000, reps=30):
    """Serving drift-monitor overhead (ISSUE 14): sweep
    `serving_drift_sample_rows` x batch size and print the per-predict
    wall beside the monitor-off baseline.  The <1% gate the telemetry
    suite enforces applies to the OFF row (sample_rows=0: no monitor is
    constructed at all); the enabled rows show what sampling actually
    costs — the tap is a bounded row copy, the absorb (binning + PSI)
    runs once per scrape and is amortized over `reps` predicts here,
    exactly like a Prometheus scrape interval would."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ServingSession

    X, y = make_data(n, f=10)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "max_bin": 63, "verbosity": -1}, ds,
                    num_boost_round=20)
    batches = [64, 512, 4096]
    base = {}
    print(f"{'sample_rows':>12} {'batch':>6} {'ms/predict':>11} "
          f"{'overhead_pct':>13}  (absorb amortized over {reps} predicts)")
    for sample_rows in (0, 64, 256, 1024):
        sess = ServingSession(params={
            "serving_max_batch_rows": 4096,
            "serving_drift_sample_rows": sample_rows,
            # the probe replays one fixed row block, which IS a
            # drifted stream statistically — silence the PSI warning,
            # this sweep measures overhead, not drift
            "serving_drift_psi_warn": 1e9, "verbosity": -1})
        sess.load("probe", booster=bst)
        entry = sess.registry.resolve("probe")
        for batch in batches:
            Xb = X[:batch]
            entry.predict(Xb)                       # warm path + jit
            t0 = time.time()
            for _ in range(reps):
                entry.predict(Xb)
            if entry.drift is not None:
                entry.drift.snapshot()              # one scrape's absorb
            ms = (time.time() - t0) / reps * 1e3
            if sample_rows == 0:
                base[batch] = ms
            over = (100.0 * (ms - base[batch]) / base[batch]
                    if base.get(batch) else 0.0)
            flag = "  <1% gate" if sample_rows == 0 else ""
            print(f"{sample_rows:>12} {batch:>6} {ms:>11.3f} "
                  f"{over:>12.1f}%{flag}")
        sess.close()


def run_stream_sweep(n=200_000, f=28, iters=5, leaves=63, bins=255):
    """Out-of-core streaming sweep (ISSUE 16): stream block rows x
    double-buffering x GOSS fractions.  Prints the H2D copy wall beside
    the histogram wall and the achieved overlap ratio — the number the
    double-buffer exists to maximize.  GOSS rows show how much copy
    traffic gradient-based block sampling removes (its models are NOT
    bitwise vs the full stream; the bitwise rows are goss=off)."""
    import lightgbm_tpu as lgb

    X, y = make_data(n, f=f)
    block_rows = [int(s) for s in
                  os.environ.get("STREAM_ROWS", "16384,65536,262144")
                  .split(",")]
    goss = [(0.0, 0.0), (0.2, 0.1)]
    print(f"streamed training: n={n} f={f} iters={iters} "
          f"leaves={leaves} bins={bins}")
    print(f"{'rows/block':>10} {'dbuf':>5} {'goss':>9} {'ms/tree':>9} "
          f"{'h2d_ms':>8} {'hist_ms':>8} {'overlap':>8} "
          f"{'skip':>5} {'Mrows/s':>8}")
    for rows in block_rows:
        for dbuf in (True, False):
            for top, other in goss:
                p = {"objective": "binary", "num_leaves": leaves,
                     "max_bin": bins, "verbosity": -1,
                     "tpu_stream_mode": "streamed",
                     "tpu_stream_block_rows": rows,
                     "tpu_stream_double_buffer": dbuf,
                     "tpu_stream_goss_top": top,
                     "tpu_stream_goss_other": other}
                ds = lgb.Dataset(X, label=y, params=p)
                bst = lgb.Booster(params=p, train_set=ds)
                bst.update()                    # warm compiles
                tot = dict(tree=0.0, h2d=0.0, hist=0.0, est=0.0,
                           hidden=0.0, skip=0.0)
                for _ in range(iters):
                    bst.update()
                    s = bst._driver.learner.stream_stats
                    tot["tree"] += s["tree_wall_s"]
                    tot["h2d"] += s["h2d_wall_s"]
                    tot["hist"] += s["hist_wall_s"]
                    tot["est"] += s["copy_est_s"]
                    tot["hidden"] += (s["overlap_pct"] / 100.0
                                      * s["copy_est_s"])
                    tot["skip"] += s["blocks_skipped"]
                overlap = (100.0 * tot["hidden"] / tot["est"]
                           if tot["est"] else 0.0)
                gs = f"{top}/{other}" if top else "off"
                mrows = n * iters / tot["tree"] / 1e6
                print(f"{rows:>10} {str(dbuf):>5} {gs:>9} "
                      f"{tot['tree'] / iters * 1e3:>9.1f} "
                      f"{tot['h2d'] / iters * 1e3:>8.1f} "
                      f"{tot['hist'] / iters * 1e3:>8.1f} "
                      f"{overlap:>7.1f}% "
                      f"{tot['skip'] / iters:>5.1f} {mrows:>8.2f}")


def main():
    # probe crashes must never drop a blackbox dump beside the sources
    # the probe is usually run from; an explicit env/param still wins
    import tempfile
    os.environ.setdefault("LIGHTGBM_TPU_BLACKBOX_DIR",
                          tempfile.gettempdir())
    arg = sys.argv[1] if len(sys.argv) > 1 else ""
    if arg == "drift":
        run_drift_probe(n=int(os.environ.get("N", 20000)),
                        reps=int(os.environ.get("REPS", 30)))
        return
    if arg == "faults":
        if "--multihost" in sys.argv[2:]:
            run_faults_multihost(hosts=int(os.environ.get("HOSTS", 2)),
                                 iters=int(os.environ.get("ITERS", 4)))
            return
        run_faults(n=int(os.environ.get("N", 4000)),
                   iters=int(os.environ.get("ITERS", 5)))
        return
    if arg == "stream":
        run_stream_sweep(n=int(os.environ.get("N", 200_000)),
                         f=int(os.environ.get("F", 28)),
                         iters=int(os.environ.get("ITERS", 5)),
                         leaves=int(os.environ.get("LEAVES", 63)),
                         bins=int(os.environ.get("BINS", 255)))
        return
    if arg == "mem":
        run_mem(n=int(os.environ.get("N", 20000)),
                leaves=int(os.environ.get("LEAVES", 31)),
                bins=int(os.environ.get("BINS", 63)),
                iters=int(os.environ.get("ITERS", 3)))
        return
    if arg == "retrace":
        run_retrace(n=int(os.environ.get("N", 20000)),
                    leaves=int(os.environ.get("LEAVES", 31)),
                    bins=int(os.environ.get("BINS", 63)),
                    iters=int(os.environ.get("ITERS", 3)))
        return
    if arg == "trace":
        run_trace(n=int(os.environ.get("N", 100_000)),
                  iters=int(os.environ.get("ITERS", 3)),
                  leaves=int(os.environ.get("LEAVES", 255)),
                  bins=int(os.environ.get("BINS", 255)))
        return
    if arg == "comm":
        # no dataset needed.  Default: a virtual CPU mesh sized to the
        # sweep (must pin BEFORE the first jax import); COMM_BACKEND=tpu
        # keeps the attached accelerator mesh for real ICI numbers
        shard_counts = [int(s) for s in
                        os.environ.get("SHARDS", "2,4,8").split(",")]
        host_counts = [int(s) for s in
                       os.environ.get("HOSTS", "1").split(",")]
        if os.environ.get("COMM_BACKEND", "cpu") != "tpu":
            import importlib.util as _ilu

            spec = _ilu.spec_from_file_location(
                "_lgbm_backend_boot",
                os.path.join(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                    "lightgbm_tpu", "utils", "backend.py"))
            mod = _ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
            mod.pin_cpu_backend(force_device_count=max(shard_counts))
        run_comm_sweep(shard_counts, host_counts=host_counts)
        return
    if arg == "tune":
        run_tune(bins=int(os.environ.get("BINS", 255)))
        return
    n = int(os.environ.get("N", 1_000_000))
    X, y = make_data(n)
    if arg == "hist":
        run_hist_sweep(X, y, bins=int(os.environ.get("BINS", 255)))
        return
    if arg == "ingest":
        run_ingest_sweep(X, y, bins=int(os.environ.get("BINS", 255)))
        return
    if arg == "predict":
        run_predict_sweep(X, y, rounds=int(os.environ.get("ROUNDS", 50)),
                          leaves=int(os.environ.get("LEAVES", 255)),
                          bins=int(os.environ.get("BINS", 255)))
        return
    if arg == "one":
        sweep(X, y, [dict(k=int(os.environ.get("K", 25)),
                          block=int(os.environ.get("BLOCK", 16384)),
                          impl=os.environ.get("IMPL", "xla"),
                          part=os.environ.get("PARTITION", "select"),
                          prec=os.environ.get("PRECISION", "hilo"),
                          ramp=os.environ.get("RAMP", "") == "1",
                          alpha=float(os.environ.get("ALPHA", 0.0)))],
              iters=8, reraise=True)
        return
    if arg == "round2":
        # post-pallas leverage sweep (docs/PERF_NOTES.md "next
        # experiments"): S=3 bf16 stats widen K at the same tile width;
        # bigger K cuts rounds per tree
        sweep(X, y, [
            dict(k=25, block=256, impl="pallas", prec="hilo"),  # re-baseline
            # pallas2: per-feature one-hot, 16x fewer grid steps
            dict(k=25, block=4096, impl="pallas2", prec="hilo"),
            dict(k=25, block=8192, impl="pallas2", prec="hilo"),
            # S=3 bf16 stats widen K at the same tile width
            dict(k=42, block=4096, impl="pallas2", prec="bf16"),
            dict(k=84, block=4096, impl="pallas2", prec="bf16"),  # ~6 rounds
            dict(k=84, block=4096, impl="pallas2", prec="bf16", ramp=True),
            dict(k=25, block=4096, impl="pallas2", prec="hilo", ramp=True),
            dict(k=42, block=256, impl="pallas", prec="bf16"),
            dict(k=50, block=256, impl="pallas", prec="hilo"),  # 2 tiles
        ])
        return
    if arg == "round3":
        # post-default-flip sweep: can the near-tie guard (alpha) buy the
        # K=50 round count without K=50's split-order AUC loss?  Guard
        # rounds split only leaves with gain >= alpha * round-max, so
        # high alpha approaches strict best-first at more rounds/tree
        sweep(X, y, [
            dict(k=25, block=8192, impl="pallas2", prec="hilo",
                 ramp=True),  # current default, re-baseline
            dict(k=50, block=8192, impl="pallas2", prec="hilo", ramp=True,
                 alpha=0.2),
            dict(k=50, block=8192, impl="pallas2", prec="hilo", ramp=True,
                 alpha=0.5),
            dict(k=84, block=8192, impl="pallas2", prec="hilo", ramp=True,
                 alpha=0.5),
        ])
        return
    if arg == "round4":
        # partition-lowering A/B at the committed defaults: "vselect"
        # replaces the K unrolled select passes with ONE [K, n] fused
        # block (fewer program points; candidate for the ~170 ms/tree
        # non-contraction time, PERF_NOTES round-4).  Bit-parity with
        # "select" is CPU-proven (tests/test_grower.py TestVselectPartition)
        sweep(X, y, [
            dict(k=25, block=8192, impl="pallas2", prec="hilo",
                 ramp=True, part="select"),   # default, re-baseline
            dict(k=25, block=8192, impl="pallas2", prec="hilo",
                 ramp=True, part="vselect"),
            dict(k=50, block=8192, impl="pallas2", prec="hilo",
                 ramp=True, part="vselect", alpha=0.5),
        ])
        return
    if arg == "decide":
        # the post-outage decision sweep: partition A/B at default K, then
        # K scaling, then the pallas backend at a VMEM-sized block
        sweep(X, y, [
            dict(part="gather", k=15, block=16384, impl="xla"),
            dict(part="select", k=15, block=16384, impl="xla"),
            dict(part="select", k=25, block=16384, impl="xla"),
            dict(part="select", k=50, block=16384, impl="xla"),
            dict(part="select", k=25, block=65536, impl="xla"),
            # pallas: [F*B, block] bf16 one-hot + [F*B, K*S] f32
            # accumulator must fit ~16MB VMEM -> block <= 512 at K=25
            dict(part="select", k=25, block=256, impl="pallas"),
            dict(part="select", k=25, block=512, impl="pallas"),
            dict(part="select", k=12, block=512, impl="pallas"),
        ])
        return
    sweep(X, y, [dict(impl=i, k=k, block=b)
                 for i in ("xla", "pallas") for k in (16, 25)
                 for b in (16384, 65536)], iters=5)


if __name__ == "__main__":
    main()
