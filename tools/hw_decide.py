"""Parse docs/HW_RESULTS_r5.log after the hardware queue ran and print
each staged candidate's decision-rule outcome (docs/PERF_NOTES.md).

The queue (tools/hw_queue.sh) appends raw job output under `---` section
headers; this script extracts the facts the decision rules need so the
post-run triage is mechanical:

  * official bench: platform must be "tpu", value vs the 3.1 it/s bar;
  * packed/vselect validation: the bit-match line or its absence;
  * bucketed-default bench: gap vs the pinned-shape number against the
    predicted ~1/buckets overhead;
  * sweeps/profile: best configs by it/s at matching AUC.

Read-only; prints a summary, exits 1 if the non-negotiable (a TPU bench
record) is missing.
"""
import json
import os
import re
import sys

LOG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "HW_RESULTS_r5.log")


def main():
    if not os.path.exists(LOG):
        print(f"{LOG} does not exist — the queue has not fired")
        return 1
    text = open(LOG).read()
    benches = [json.loads(m.group(0)) for m in re.finditer(
        r'\{"metric": "higgs1m[^\n]*\}', text)]
    tpu = [b for b in benches if b.get("platform") == "tpu"]
    print(f"bench records: {len(benches)} total, {len(tpu)} on TPU")
    ok = bool(tpu)
    pinned = None
    for b in tpu:
        tag = ("bucketed" if b.get("tpu_shape_buckets") else "pinned")
        print(f"  [{tag}] {b['value']} it/s  vs_baseline={b['vs_baseline']}"
              f"  auc={b.get('train_auc')}  compile={b.get('compile_s')}s")
        if not b.get("tpu_shape_buckets"):
            pinned = max(pinned or 0.0, float(b["value"]))
    if pinned is not None:
        bar = 3.1
        print(f"  decision: pinned best {pinned} it/s — "
              + ("CONFIRMS the round-3 3.14 record"
                 if pinned >= bar else
                 f"BELOW the {bar} bar; investigate before adopting "
                 "staged candidates"))
        bucketed = [float(b["value"]) for b in tpu
                    if b.get("tpu_shape_buckets")]
        if bucketed:
            gap = 1.0 - max(bucketed) / pinned
            print(f"  bucketed-default gap: {gap:.1%} "
                  + ("(within the ~1/buckets=3% prediction — keep "
                     "default 32)"
                     if gap <= 0.03 else
                     "(EXCEEDS the ~3% prediction — profile the split "
                     "pipeline's extra dispatches or flip "
                     "tpu_shape_buckets default to 0; PERF_NOTES rule)"))
    if "TPU VALIDATION OK" in text:
        print("packed/vselect: bit-match on hardware — keep defaults")
    elif "MISMATCH ON TPU" in text:
        print("packed/vselect: MISMATCH — flip tpu_pack_bins/"
              "tpu_partition_impl defaults OFF (PERF_NOTES rule)")
    else:
        print("packed/vselect: no verdict in the log yet")
    for section in ("round3 alpha sweep", "round4 partition sweep",
                    "profile", "auc_parity full"):
        present = f"--- {section}" in text
        print(f"{section}: {'ran' if present else 'not reached'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
