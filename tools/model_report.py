"""Promotion-grade model health report + shadow compare (ISSUE 14).

Renders a JSON/markdown health report for a trained model — learning
curves (read back from the PR-10 metrics registry when available),
split/gain importances cross-checked between the model and the
training-time counters, model shape (leaf/depth distributions), the
``tpu_feature_profile:`` training-reference summary, and a drift table
against either a live serving monitor (``--drift-url .../drift``) or a
second dataset (``--compare-data``).

``--shadow`` is the promotion gate ROADMAP item 4 (continuous
learning) needs: score a candidate model and the live model on the
SAME sample, report the prediction-delta distribution, and — when the
sample carries labels — refuse the candidate if its loss is worse than
the live model's (exit code 3).  A refused candidate never reaches the
registry hot-swap.

Usage::

    python tools/model_report.py --model model.txt [--json out.json]
        [--markdown out.md] [--compare-data data.npz] [--drift-url URL]
    python tools/model_report.py --shadow --live live.txt
        --candidate cand.txt --data sample.npz [--tolerance 0.0]
    python tools/model_report.py --smoke    # CI: train -> report ->
                                            # shadow -> verify refusal

Exit codes: 0 ok/promote, 3 shadow refused, 2 usage or input error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXIT_OK = 0
EXIT_ERROR = 2
EXIT_REFUSED = 3


# ---------------------------------------------------------------------------
# data loading
# ---------------------------------------------------------------------------
def load_data(path: str) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """(X, y-or-None) from .npz (keys X / y), .npy (matrix), or a
    numeric CSV (no labels)."""
    if path.endswith(".npz"):
        z = np.load(path)
        X = np.atleast_2d(np.asarray(z["X"], np.float64))
        y = np.asarray(z["y"], np.float64) if "y" in z else None
        return X, y
    if path.endswith(".npy"):
        return np.atleast_2d(np.asarray(np.load(path), np.float64)), None
    return np.atleast_2d(np.asarray(np.loadtxt(path, delimiter=","),
                                    np.float64)), None


# ---------------------------------------------------------------------------
# report sections
# ---------------------------------------------------------------------------
def _shape_section(booster) -> Dict:
    drv = booster._driver
    leaves = [int(t.num_leaves) for t in drv.models]
    depths = [int(t.max_depth()) for t in drv.models]

    def dist(v: List[int]) -> Dict:
        if not v:
            return {"n": 0}
        a = np.asarray(v, np.float64)
        return {"n": len(v), "mean": round(float(a.mean()), 3),
                "min": int(a.min()), "max": int(a.max()),
                "p50": float(np.percentile(a, 50)),
                "p95": float(np.percentile(a, 95))}

    return {"num_trees": booster.num_trees(),
            "num_class": int(drv.num_class),
            "trees_per_iteration": int(drv.num_tree_per_iteration),
            "leaves": dist(leaves), "depth": dist(depths)}


def _importance_section(booster, top: int = 20) -> Dict:
    names = booster.feature_name()
    split = booster.feature_importance("split")
    gain = booster.feature_importance("gain")
    order = np.argsort(-gain)
    rows = []
    for i in order[:top]:
        if split[i] <= 0:
            continue
        rows.append({"feature": (names[i] if i < len(names)
                                 else f"Column_{i}"),
                     "splits": int(split[i]),
                     "gain": round(float(gain[i]), 6)})
    return {"top": rows, "features_used": int((split > 0).sum())}


def _curves_section() -> Dict:
    """Learning curves read back from the registry's lgbm_train_metric
    sample rings (present when training ran with tpu_telemetry=metrics
    in this process; marked unavailable otherwise)."""
    from lightgbm_tpu import obs

    curves: Dict[str, List[float]] = {}
    for ds in obs.REGISTRY.label_values("lgbm_train_metric", "dataset"):
        for mt in obs.REGISTRY.label_values("lgbm_train_metric",
                                            "metric"):
            samples, truncated = obs.REGISTRY.histogram_samples(
                "lgbm_train_metric", with_truncated=True,
                dataset=ds, metric=mt)
            if samples:
                curves[f"{ds}/{mt}"] = {
                    "values": [round(float(v), 6) for v in samples],
                    "truncated": bool(truncated)}
    return curves if curves else {"unavailable": (
        "no lgbm_train_metric series in this process's registry; train "
        "with tpu_telemetry=metrics and valid_sets to record curves")}


def _profile_section(booster) -> Dict:
    prof = booster._driver.health_profile()
    if prof is None:
        return {"unavailable": "model carries no tpu_feature_profile: "
                               "trailer (tpu_profile_capture=false?)"}
    out = prof.summary()
    out["per_feature"] = {
        f["name"]: {"num_bin": f["num_bin"],
                    "nan_frac": round(f["nan_frac"], 6),
                    "zero_frac": round(f["zero_frac"], 6)}
        for f in prof.features.values()}
    return out


def _drift_section(booster, compare_data: Optional[str],
                   drift_url: Optional[str]) -> Dict:
    if drift_url:
        import urllib.request

        try:
            # bounded: a wedged serving endpoint (the scenario the
            # dispatch watchdog exists for) must not hang the report
            with urllib.request.urlopen(drift_url, timeout=30) as resp:
                return {"source": drift_url,
                        **json.loads(resp.read().decode())}
        except Exception as exc:
            return {"unavailable":
                    f"drift fetch from {drift_url} failed: {exc}"}
    if compare_data:
        from lightgbm_tpu.obs import modelhealth

        prof = booster._driver.health_profile()
        ctx = booster._driver._pred_context()
        if prof is None or ctx is None:
            return {"unavailable": "drift needs a profile trailer and "
                                   "bin mappers on the model"}
        X, _ = load_data(compare_data)
        snap = modelhealth.compare_dataset(
            prof, ctx.mappers, X,
            score_fn=lambda Xs: booster._driver.predict_raw(Xs, -1))
        return {"source": compare_data, **snap}
    return {"unavailable": "pass --compare-data or --drift-url"}


def build_report(booster, compare_data: Optional[str] = None,
                 drift_url: Optional[str] = None) -> Dict:
    return {
        "model": _shape_section(booster),
        "importance": _importance_section(booster),
        "learning_curves": _curves_section(),
        "profile": _profile_section(booster),
        "drift": _drift_section(booster, compare_data, drift_url),
    }


def render_markdown(report: Dict, title: str = "Model health report"
                    ) -> str:
    lines = [f"# {title}", ""]
    m = report["model"]
    lines += ["## Model", "",
              f"- trees: {m['num_trees']} "
              f"({m['trees_per_iteration']}/iteration, "
              f"{m['num_class']} class(es))",
              f"- leaves: {m['leaves']}", f"- depth: {m['depth']}", ""]
    imp = report["importance"]
    lines += ["## Importance (top by gain)", "",
              "| feature | splits | gain |", "|---|---|---|"]
    for r in imp["top"]:
        lines.append(f"| {r['feature']} | {r['splits']} | {r['gain']} |")
    lines += ["", f"features used: {imp['features_used']}", ""]
    lines += ["## Learning curves", ""]
    curves = report["learning_curves"]
    if "unavailable" in curves:
        lines.append(f"_{curves['unavailable']}_")
    else:
        for key, c in curves.items():
            v = c["values"]
            tail = " (ring truncated)" if c["truncated"] else ""
            lines.append(f"- `{key}`: {v[0]:.6f} -> {v[-1]:.6f} over "
                         f"{len(v)} recorded iterations{tail}")
    lines += ["", "## Training profile", ""]
    prof = report["profile"]
    if "unavailable" in prof:
        lines.append(f"_{prof['unavailable']}_")
    else:
        lines.append(f"- features profiled: {prof['features']}; label "
                     f"n={prof['label']['n']} "
                     f"mean={prof['label']['mean']:.6g}")
        lines.append(f"- score histogram: {prof['score_bins']} bins x "
                     f"{prof['score_classes']} class(es)")
    lines += ["", "## Drift", ""]
    drift = report["drift"]
    if "unavailable" in drift:
        lines.append(f"_{drift['unavailable']}_")
    elif "features" in drift:
        lines += [f"source: `{drift.get('source', 'live')}` — "
                  f"{drift['rows_sampled']} rows, "
                  f"psi_max={drift['psi_max']:.4f} "
                  f"({'WARN' if drift['warn'] else 'ok'})", "",
                  "| feature | PSI | JS | nan_rate | unseen |",
                  "|---|---|---|---|---|"]
        for name, f in sorted(drift["features"].items(),
                              key=lambda kv: -kv[1]["psi"]):
            lines.append(f"| {name} | {f['psi']:.4f} | {f['js']:.4f} | "
                         f"{f['nan_rate']:.4f} | "
                         f"{f['unseen_rate']:.4f} |")
    else:  # a raw GET /drift payload (possibly several models)
        lines.append("```json")
        lines.append(json.dumps(drift, indent=2)[:4000])
        lines.append("```")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# shadow compare (the promotion gate)
# ---------------------------------------------------------------------------
def shadow_compare(live, candidate, X: np.ndarray,
                   y: Optional[np.ndarray] = None,
                   tolerance: float = 0.0) -> Dict:
    """Score candidate vs live on the same sample.  Returns the
    prediction-delta distribution and — with labels — the promote/
    refuse verdict: promote iff candidate_loss <= live_loss *
    (1 + tolerance).

    Thin wrapper over `lightgbm_tpu.continual.promote.shadow_verdict` —
    the SAME gate the continual controller applies before flipping the
    serving alias, so the offline `--shadow` verdict and the automated
    one can never disagree."""
    from lightgbm_tpu.continual.promote import shadow_verdict

    return shadow_verdict(live, candidate, X, y, tolerance=tolerance)


# ---------------------------------------------------------------------------
# smoke: train -> report -> shadow -> verify the gate refuses
# ---------------------------------------------------------------------------
def run_smoke() -> int:
    """Self-contained CI smoke (multichip dryrun tail): train a tiny
    live model WITH telemetry, render both report formats, then
    shadow-compare a deliberately worse candidate and verify the gate
    REFUSES it (and promotes the live model against itself)."""
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(7)
    X = rng.normal(size=(600, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    P = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
         "min_data_in_leaf": 5, "verbosity": -1,
         "tpu_telemetry": "metrics", "metric": ["binary_logloss"]}
    ds = lgb.Dataset(X, label=y, params=P)
    vd = lgb.Dataset(X[:150], label=y[:150], reference=ds, params=P)
    live = lgb.train(P, ds, num_boost_round=8, valid_sets=[vd],
                     verbose_eval=False)
    # worse candidate: trained on permuted labels (pure noise)
    yb = y.copy()
    rng.shuffle(yb)
    dsb = lgb.Dataset(X, label=yb, params=P)
    cand = lgb.train(P, dsb, num_boost_round=8, verbose_eval=False)

    report = build_report(live)
    md = render_markdown(report)
    json.dumps(report)  # must be serializable
    for want in ("## Model", "## Importance", "## Learning curves",
                 "## Training profile"):
        if want not in md:
            print(f"model_report --smoke: section {want!r} missing")
            return EXIT_ERROR
    if "unavailable" in report["learning_curves"]:
        print("model_report --smoke: learning curves missing despite "
              "tpu_telemetry=metrics")
        return EXIT_ERROR
    if "unavailable" in report["profile"]:
        print("model_report --smoke: profile trailer missing")
        return EXIT_ERROR

    sc = shadow_compare(live, cand, X, y)
    if sc["verdict"] != "refuse":
        print(f"model_report --smoke: worse candidate NOT refused: {sc}")
        return EXIT_ERROR
    sc_self = shadow_compare(live, live, X, y)
    if sc_self["verdict"] != "promote" or sc_self["delta"]["max"] != 0.0:
        print(f"model_report --smoke: self-compare broken: {sc_self}")
        return EXIT_ERROR
    print("model_report --smoke OK: report sections rendered, worse "
          f"candidate refused ({sc['reason']}), self-compare promoted")
    return EXIT_OK


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="model_report.py",
        description="model health report + shadow promotion gate")
    ap.add_argument("--model", help="model file for the health report")
    ap.add_argument("--json", help="write the JSON report here")
    ap.add_argument("--markdown", help="write the markdown report here")
    ap.add_argument("--compare-data",
                    help="dataset (.npz/.npy/.csv) to drift-compare "
                         "against the model's training profile")
    ap.add_argument("--drift-url",
                    help="live serving GET /drift URL to embed")
    ap.add_argument("--shadow", action="store_true",
                    help="shadow-compare --candidate vs --live on "
                         "--data; exit 3 = refused")
    ap.add_argument("--live", help="live model file (shadow mode)")
    ap.add_argument("--candidate", help="candidate model file")
    ap.add_argument("--data", help="sample (.npz with X and optional y)")
    ap.add_argument("--tolerance", type=float, default=0.0,
                    help="allowed relative loss regression before "
                         "refusing (default 0)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI self-test: train tiny model -> report -> "
                         "shadow-compare -> exit code")
    args = ap.parse_args(argv)

    if args.smoke:
        return run_smoke()

    import lightgbm_tpu as lgb

    if args.shadow:
        if not (args.live and args.candidate and args.data):
            ap.error("--shadow needs --live, --candidate and --data")
        try:
            live = lgb.Booster(model_file=args.live)
            cand = lgb.Booster(model_file=args.candidate)
            X, y = load_data(args.data)
        except Exception as exc:
            print(f"model_report: cannot load shadow inputs: {exc}")
            return EXIT_ERROR
        sc = shadow_compare(live, cand, X, y,
                            tolerance=float(args.tolerance))
        print(json.dumps(sc, indent=2))
        return EXIT_REFUSED if sc["verdict"] == "refuse" else EXIT_OK

    if not args.model:
        ap.error("need --model (or --shadow / --smoke)")
    try:
        booster = lgb.Booster(model_file=args.model)
    except Exception as exc:
        print(f"model_report: cannot load {args.model!r}: {exc}")
        return EXIT_ERROR
    try:
        report = build_report(booster, compare_data=args.compare_data,
                              drift_url=args.drift_url)
    except Exception as exc:
        # input errors (missing --compare-data file, malformed npz)
        # stay inside the documented 0/2/3 exit contract
        print(f"model_report: cannot build report: {exc}")
        return EXIT_ERROR
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
    md = render_markdown(report,
                         title=f"Model health: {os.path.basename(args.model)}")
    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(md)
    if not args.json and not args.markdown:
        print(md)
    return EXIT_OK


if __name__ == "__main__":
    sys.exit(main())
