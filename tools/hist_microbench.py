"""Micro-benchmark the batched histogram contraction in isolation.

Separates kernel time from the rest of the grower round so tuning targets
the right thing: K x block x impl at the Higgs-1M bench shape.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.histogram import (build_histogram_batched_t,
                                        pack_stats)


def bench_one(n, F, B, K, block, impl, precision="hilo", iters=20):
    rng = np.random.default_rng(0)
    nb = n // block
    bins_t = jnp.asarray(rng.integers(0, B, size=(nb, F, block)),
                         dtype=jnp.int32)
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.abs(g) + 0.1
    mask = jnp.ones(n, jnp.float32)
    stats = pack_stats(g, h, mask, precision)
    S = stats.shape[0]
    stats_blocks = stats.reshape(S, nb, block)
    leaf_blocks = jnp.asarray(
        rng.integers(0, 2 * K, size=(nb, block)), dtype=jnp.int32)
    slots = jnp.arange(K, dtype=jnp.int32)

    fn = jax.jit(lambda bt, sb, lb, sl: build_histogram_batched_t(
        bt, sb, lb, sl, B, precision, impl=impl))
    t0 = time.time()
    out = fn(bins_t, stats_blocks, leaf_blocks, slots)
    np.asarray(out)  # full host fetch: the tunneled backend's
    #                  block_until_ready returns before compute finishes
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(iters):
        out = fn(bins_t, stats_blocks, leaf_blocks, slots)
    np.asarray(out)
    ms = (time.time() - t0) / iters * 1e3
    flops = 2.0 * n * F * B * K * S
    tflops = flops / (ms / 1e3) / 1e12
    print(f"impl={impl:6s} K={K:2d} S={S} block={block:6d}: {ms:8.2f} ms "
          f"({tflops:6.1f} TFLOP/s eff)  compile {compile_s:5.1f}s",
          flush=True)
    return ms


def main():
    n = 1 << 20
    F, B = 28, 256
    configs = []
    for block in (8192, 16384, 32768, 65536, 131072):
        configs.append((15, block, "xla"))
        configs.append((25, block, "xla"))
    for block in (512, 1024, 2048, 4096):
        configs.append((25, block, "pallas"))
    sel = os.environ.get("ONLY", "")
    for K, block, impl in configs:
        if sel and sel not in impl:
            continue
        try:
            bench_one(n, F, B, K, block, impl)
        except Exception as exc:
            print(f"impl={impl} K={K} block={block}: FAILED "
                  f"{type(exc).__name__}: {str(exc)[:200]}", flush=True)


if __name__ == "__main__":
    main()
