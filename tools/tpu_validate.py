"""Tiny TPU validation of hardware-unvalidated paths.

Validates (by bit-matching full model text against the proven default
lowering, on the real chip):
  - 4-bit packed bins (``tpu_pack_bins``: Mosaic nibble ops + lane concat,
    previously interpret-mode-verified only) against unpacked uint8 bins;
  - the ``vselect`` partition lowering against the default ``select``.

Decision rule (docs/PERF_NOTES.md): models must bit-match on hardware or
the corresponding default flips OFF.  Mirrors the reference's n-bit dense
bin validation posture (/root/reference/src/io/dense_nbits_bin.hpp).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import lightgbm_tpu as lgb


def main():
    import jax
    platform = jax.devices()[0].platform
    print("platform:", platform, flush=True)
    # a silent CPU fallback would "pass" trivially (already proven there)
    # and forge a hardware record — refuse to validate off-chip
    assert platform == "tpu", f"not on TPU (platform={platform}); aborting"
    rng = np.random.default_rng(4)
    X = rng.normal(size=(20000, 10))
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float64)
    out = {}
    for tag, extra in (("packed", {"max_bin": 15, "tpu_pack_bins": True}),
                       ("unpacked", {"max_bin": 15, "tpu_pack_bins": False}),
                       ("vselect", {"max_bin": 63,
                                    "tpu_partition_impl": "vselect"}),
                       ("select", {"max_bin": 63})):
        p = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
             "tpu_hist_impl": "pallas2", "tpu_block_rows": 4096, **extra}
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=3)
        # the learner silently disables packing when its alignment gates
        # fail (learner.py packed_bins computation) — a vacuous bit-match
        # of two unpacked runs must not forge the hardware record
        learner = bst._driver.learner
        if tag == "packed":
            assert learner.packed_bins, \
                "packed path did not engage (alignment gate failed)"
        if tag == "vselect":
            assert learner.params.partition_impl == "vselect", \
                f"vselect not engaged: {learner.params.partition_impl}"
        out[tag] = bst.model_to_string().split("\nparameters:")[0]
    assert out["packed"] == out["unpacked"], "PACKED-BIN MISMATCH ON TPU"
    assert out["vselect"] == out["select"], "VSELECT MISMATCH ON TPU"
    print("TPU VALIDATION OK: packed bins + vselect bit-match on hardware")


if __name__ == "__main__":
    main()
