"""Perf-regression sentinel: compare two bench records metric-by-metric.

The bench trajectory was untrustworthy for three rounds (every round
since r02 ran on degraded CPU fallback) and nothing refused the
apples-to-oranges comparisons — the r04→r05 "regression" cost a
postmortem to diagnose as container variance.  This tool is the gate
that replaces the ad-hoc ``compile_vs_prior`` note:

    python tools/bench_diff.py                      # newest two committed
    python tools/bench_diff.py A.json B.json        # explicit old vs new
    python tools/bench_diff.py --head NEW.json      # newest committed vs NEW
    python tools/bench_diff.py --gate [...]         # exit nonzero on fail

Semantics:

* every known metric carries a DIRECTION (higher-better throughput vs
  lower-better walls/overheads) and a relative TOLERANCE — a metric
  outside tolerance in the bad direction is a regression;
* comparisons are REFUSED (exit 2, loud message) when the two records
  ran on different backends, when either side is a degraded run, or
  when either side is a crash record — a TPU-vs-degraded-CPU ratio is
  fiction and the tool says so instead of printing it;
* ``--allow-degraded`` permits same-backend degraded-vs-degraded
  comparisons (informational runs on the CPU container);
* exit codes: 0 = comparable + no regression, 1 = regression,
  2 = refused, 3 = usage/IO error.  ``--gate`` is an alias that makes
  the intent explicit where the dryrun tail wires it in.
"""

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXIT_OK = 0
EXIT_REGRESSION = 1
EXIT_REFUSED = 2
EXIT_ERROR = 3

# direction: +1 = higher is better, -1 = lower is better.
# tolerance: relative slack before a bad-direction move counts as a
# regression (generous where cross-round container variance is known).
METRICS = {
    "value": (+1, 0.15),                      # headline iters/s
    "predict_rows_per_sec": (+1, 0.15),
    "serve_rows_per_sec": (+1, 0.20),
    "serve_goodput_rows_per_sec": (+1, 0.20),
    "ingest_rows_per_sec": (+1, 0.20),
    "hist_int8_rows_per_sec": (+1, 0.20),
    "hist_hilo_rows_per_sec": (+1, 0.20),
    "train_auc": (+1, 0.01),
    "serve_p99_ms": (-1, 0.30),
    "serve_shed_pct": (-1, 0.50),
    "eval_ms_per_iter": (-1, 0.30),
    "checkpoint_overhead_pct": (-1, 0.50),
    "resume_s": (-1, 0.30),
    "resume_elastic_s": (-1, 0.30),
    "collective_timeout_recovery_s": (-1, 0.30),
    # OOM recovery (ISSUE 15): rollback + ladder step + retried
    # iteration — wide slack, it embeds one training iteration's wall
    "oom_recovery_s": (-1, 0.50),
    # budget minus observed train peak: MORE headroom is better; null
    # on CPU rounds (no capacity report -> no budget resolves).  The
    # slack is WIDE on purpose: headroom is a small difference of two
    # large numbers, so ordinary peak jitter swings it by large
    # fractions — only losing more than the whole baseline headroom
    # (crossing toward over-budget) scores as a regression
    "hbm_budget_headroom_bytes": (+1, 1.00),
    "compile_s": (-1, 0.20),
    "n_programs": (-1, 0.0),                  # program zoo: exact gate
    "n_programs_train": (-1, 0.0),
    "train_peak_hbm_bytes": (-1, 0.10),       # HBM budget (ISSUE 12)
    "serve_model_hbm_bytes": (-1, 0.10),
    # drift-monitor cost (ISSUE 14): absolute percentages at CPU-noise
    # scale, so the slack is wide — the hard bound lives in the
    # telemetry off-overhead test, this just tracks the trend
    "drift_overhead_pct": (-1, 1.00),
    # out-of-core streaming (ISSUE 16): throughput at 4x the resident
    # cap, and the fraction of H2D copy wall hidden behind histogram
    # work.  Both noisy on CPU rounds (copy/compute ratio is nothing
    # like the PCIe/ICI one), hence wide slack; the hard guarantees
    # (bitwise models, bounded programs) live in tests/test_stream.py
    "stream_rows_per_sec": (+1, 0.35),
    "stream_overlap_pct": (+1, 0.50),
    # fused frontier growth (ISSUE 18): per-iteration grow wall, the
    # grow-megakernel probe throughput, and the steady-state autotune
    # profile load+resolve cost.  The bitwise and program-count
    # guarantees live in tests/test_fused_grow.py; these rows track the
    # speed the fusion exists for
    "grow_iter_ms": (-1, 0.30),
    "fused_frontier_rows_per_sec": (+1, 0.30),
    "autotune_resolve_ms": (-1, 0.50),
    # fleet serving (ISSUE 19): replicated-dispatch goodput across the
    # device set, cold-replica time-to-first-batch (AOT deserialization
    # path — wide slack, it embeds process/session startup wall), and
    # the per-model serving-table footprint (quantization exists to
    # shrink it; a tightened 10% band would fight f32 rounds, so the
    # band only flags a real format regrowth)
    "serve_fleet_goodput_rows_per_sec": (+1, 0.25),
    "serve_cold_start_ms": (-1, 0.50),
    "serve_table_hbm_bytes": (-1, 0.10),
}


class RecordError(ValueError):
    """Unreadable/malformed bench record — maps to EXIT_ERROR, never to
    the regression code (CI must distinguish 'bench got slower' from
    'your path is wrong')."""


def load_record(path):
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError) as exc:
        raise RecordError(f"bench_diff: cannot read {path!r}: {exc}")
    parsed = rec.get("parsed", rec)
    if not isinstance(parsed, dict):
        if "parsed" in rec:
            # a committed crash wrapper ({'rc': 1, 'parsed': null},
            # e.g. BENCH_r01): keep it as a record so refusal() fires
            # LOUDLY on it — silently dropping the newest round and
            # diffing two older ones would report 'no regressions'
            # right after a round crashed
            return {"error": f"crashed round (rc={rec.get('rc')}, "
                             "parsed=null)"}
        raise RecordError(f"bench_diff: {path!r} holds no record dict")
    return parsed


def committed_records():
    """Newest-first [(name, parsed record)] of the committed BENCH_r*."""
    files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")),
                   key=lambda p: [int(s) for s in re.findall(r"\d+", p)])
    out = []
    for path in reversed(files):
        try:
            out.append((os.path.basename(path), load_record(path)))
        except RecordError:
            continue
    return out


def _backend(rec):
    return str(rec.get("backend", rec.get("platform", "unknown")))


def refusal(old, new, allow_degraded=False):
    """Reason this comparison must not be scored, or None."""
    for tag, rec in (("old", old), ("new", new)):
        if rec.get("error"):
            return (f"{tag} record is a CRASH record "
                    f"({rec['error']!r}) — nothing to compare")
    b_old, b_new = _backend(old), _backend(new)
    if b_old != b_new:
        return (f"cross-backend comparison refused: old ran on "
                f"{b_old!r}, new on {b_new!r} — a "
                "TPU-vs-degraded-CPU ratio is fiction, not a regression "
                "signal")
    degraded = bool(old.get("degraded")) or bool(new.get("degraded"))
    if degraded and not allow_degraded:
        which = " and ".join(tag for tag, r in (("old", old), ("new", new))
                             if r.get("degraded"))
        return (f"degraded comparison refused: {which} ran on the "
                "degraded fallback path (reduced problem, throwaway "
                "container) — pass --allow-degraded for an "
                "informational same-backend diff")
    return None


def diff(old, new, tolerance_scale=1.0):
    """[(metric, old, new, ratio, verdict)] for every shared metric."""
    rows = []
    for metric, (direction, tol) in METRICS.items():
        a, b = old.get(metric), new.get(metric)
        if a is None or b is None or not isinstance(a, (int, float)) \
                or not isinstance(b, (int, float)):
            continue
        if a == 0:
            # zero baseline: the relative tolerance has no scale, so
            # never score it as a regression — a 0.0 -> 0.01 shed_pct
            # move is noise, not a gate failure; surface it as
            # new-nonzero for the human reader instead
            rows.append((metric, a, b, float("inf") if b else 1.0,
                         "ok" if b == 0 else "new-nonzero"))
            continue
        ratio = b / a
        tol = tol * tolerance_scale
        # tolerance band scaled by |a|, compared as a signed DELTA: a
        # multiplicative band inverts for negative baselines (headroom
        # can legitimately go negative — an over-budget round improving
        # from -1.0e9 to -0.9e9 must not score as a regression)
        band = tol * abs(a)
        delta = b - a
        if direction > 0:            # higher better: a big drop is bad
            bad = delta < -band
            improved = delta > band
        else:                        # lower better: a big rise is bad
            bad = delta > band
            improved = delta < -band
        verdict = "REGRESSION" if bad else ("improved" if improved else "ok")
        rows.append((metric, a, b, ratio, verdict))
    return rows


def format_table(rows, old_name, new_name):
    lines = [f"{'metric':<32s} {'old':>14s} {'new':>14s} {'ratio':>7s}  "
             f"verdict   ({old_name} -> {new_name})"]
    for metric, a, b, ratio, verdict in rows:
        lines.append(f"{metric:<32s} {a:>14.4g} {b:>14.4g} "
                     f"{ratio:>7.3f}  {verdict}")
    return "\n".join(lines)


def run(old_path=None, new_path=None, head=None, allow_degraded=False,
        tolerance_scale=1.0):
    """-> (exit_code, text).  The CLI and the dryrun tail both call
    this; the dryrun treats EXIT_REFUSED as a loud skip, never a
    pass."""
    try:
        if head is not None:
            committed = committed_records()
            if not committed:
                return EXIT_ERROR, "bench_diff: no committed BENCH_r*.json"
            old_name, old = committed[0]
            new_name, new = os.path.basename(head), load_record(head)
        elif old_path is not None and new_path is not None:
            old_name, old = os.path.basename(old_path), \
                load_record(old_path)
            new_name, new = os.path.basename(new_path), \
                load_record(new_path)
        else:
            committed = committed_records()
            if len(committed) < 2:
                return EXIT_ERROR, ("bench_diff: need two committed "
                                    "BENCH_r*.json (or explicit paths)")
            new_name, new = committed[0]
            old_name, old = committed[1]
    except RecordError as exc:
        return EXIT_ERROR, str(exc)
    reason = refusal(old, new, allow_degraded=allow_degraded)
    if reason is not None:
        return EXIT_REFUSED, (f"bench_diff REFUSED ({old_name} -> "
                              f"{new_name}): {reason}")
    rows = diff(old, new, tolerance_scale=tolerance_scale)
    if not rows:
        return EXIT_ERROR, ("bench_diff: the records share no known "
                            "numeric metrics")
    text = format_table(rows, old_name, new_name)
    regressions = [r for r in rows if r[4] == "REGRESSION"]
    if regressions:
        names = ", ".join(r[0] for r in regressions)
        return EXIT_REGRESSION, (
            text + f"\nbench_diff: {len(regressions)} REGRESSION(s): "
            f"{names}")
    return EXIT_OK, text + "\nbench_diff: no regressions"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="OLD.json NEW.json (default: the two newest "
                         "committed BENCH_r*.json)")
    ap.add_argument("--head", default=None, metavar="NEW.json",
                    help="compare the newest committed record against "
                         "this fresh (HEAD) record")
    ap.add_argument("--gate", action="store_true",
                    help="CI intent marker: identical behavior, spelled "
                         "out where a nonzero exit must fail the run")
    ap.add_argument("--allow-degraded", action="store_true",
                    help="permit same-backend degraded-vs-degraded "
                         "comparisons (informational)")
    ap.add_argument("--tolerance-scale", type=float, default=1.0,
                    help="scale every per-metric tolerance (2.0 = twice "
                         "as lenient)")
    args = ap.parse_args(argv)
    if args.paths and len(args.paths) != 2:
        ap.error("pass exactly two record paths (OLD NEW), or none")
    old_path, new_path = (args.paths if args.paths else (None, None))
    code, text = run(old_path=old_path, new_path=new_path, head=args.head,
                     allow_degraded=args.allow_degraded,
                     tolerance_scale=args.tolerance_scale)
    print(text, file=sys.stderr if code in (EXIT_REFUSED, EXIT_ERROR)
          else sys.stdout)
    return code


if __name__ == "__main__":
    sys.exit(main())
