"""Topology rules (T5xx): every collective is written once.

ISSUE 20 folded the two parallel stacks (single-host shard_map
strategies, multihost ``pre_partition`` hand-rolled allgathers) into one
declarative (hosts, data, feature) topology whose collective vocabulary
lives in ``parallel/topology.py`` — `axis_psum`/`axis_psum_scatter`/
`axis_all_gather`/`axis_index`/`axis_best_split_sync` on the device
side, `host_allgather`/`host_sum`/`ragged_all_gather` (each under ONE
guarded_collective watchdog) on the host side.  The PR-13 pattern:
yesterday's root cause — a collective expressed per-site drifts from
its siblings (wrong axis name, missing watchdog, 64-bit payloads
silently demoted in transport) — becomes today's lint.  A raw
`lax.psum`-family call or `multihost_utils.process_allgather` anywhere
else is a finding; the committed baseline stays empty.
"""

from __future__ import annotations

import ast

from .core import FileContext, Rule, dotted_name, register

# the one module allowed to spell the raw primitives
_TOPOLOGY = "lightgbm_tpu/parallel/topology.py"

# device-collective leaves (jax.lax.*) the topology vocabulary wraps
_LAX_COLLECTIVES = frozenset({
    "psum", "psum_scatter", "all_gather", "pmax", "pmin", "pmean",
    "axis_index", "all_to_all", "ppermute",
})


def outside_topology(rel: str) -> bool:
    return not rel.replace("\\", "/").endswith(_TOPOLOGY)


def _check_raw_lax(fc: FileContext):
    for node in ast.walk(fc.tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax.lax" or mod.endswith(".lax"):
                hit = [a.name for a in node.names
                       if a.name in _LAX_COLLECTIVES]
                if hit:
                    yield fc.finding(
                        "T501", node,
                        f"raw jax.lax collective import ({', '.join(hit)}) "
                        "outside parallel/topology.py — use the axis-"
                        "addressed vocabulary (axis_psum, "
                        "axis_psum_scatter, axis_all_gather, axis_index, "
                        "axis_best_split_sync) so every collective is "
                        "written once against the named (hosts, data, "
                        "feature) axes.")
            continue
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        parts = name.split(".")
        if parts[-1] in _LAX_COLLECTIVES and "lax" in parts[:-1]:
            yield fc.finding(
                "T501", node,
                f"raw device collective {name}(...) outside "
                "parallel/topology.py — use the axis-addressed "
                "vocabulary (axis_psum, axis_psum_scatter, "
                "axis_all_gather, axis_index, axis_best_split_sync) so "
                "every collective is written once against the named "
                "(hosts, data, feature) axes.")


def _check_raw_process_allgather(fc: FileContext):
    for node in ast.walk(fc.tree):
        if isinstance(node, ast.ImportFrom):
            if any(a.name == "process_allgather" for a in node.names):
                yield fc.finding(
                    "T502", node,
                    "raw process_allgather import outside "
                    "parallel/topology.py — host exchanges ride "
                    "topology.host_allgather / host_sum / "
                    "ragged_all_gather (one watchdog per logical "
                    "collective, bitsafe 64-bit transport).")
            continue
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name.rsplit(".", 1)[-1] == "process_allgather":
            yield fc.finding(
                "T502", node,
                f"raw {name}(...) outside parallel/topology.py — host "
                "exchanges ride topology.host_allgather / host_sum / "
                "ragged_all_gather (one watchdog per logical collective, "
                "bitsafe 64-bit transport).")


register(Rule(
    id="T501", name="raw-device-collective", family="topology",
    summary=("jax.lax psum/psum_scatter/all_gather/pmax/axis_index "
             "(and friends) may be spelled only in parallel/topology.py; "
             "everything else uses the axis_* vocabulary."),
    rationale=(
        "ISSUE 20: the grower, strategies, and metric layers each "
        "hand-spelled their collectives against a bare 'data' axis "
        "while the multihost path rode outside the mesh entirely — so "
        "the same logical reduction existed in several spellings and "
        "the multihost learner had to refuse whatever the single-host "
        "path expressed differently (EFB, feature sharding).  With one "
        "vocabulary in parallel/topology.py, a collective names its "
        "axes ONCE and lowers identically from a single host to a pod; "
        "a raw lax call is a new spelling waiting to drift."),
    scope=outside_topology,
    check=lambda fc: _check_raw_lax(fc)))

register(Rule(
    id="T502", name="raw-process-allgather", family="topology",
    summary=("multihost_utils.process_allgather may be spelled only in "
             "parallel/topology.py; host exchanges use host_allgather/"
             "host_sum/ragged_all_gather."),
    rationale=(
        "ISSUE 20: hand-rolled process_allgather sites each re-solved "
        "the same three problems — watchdog wrapping (or forgetting "
        "it), ragged lens+pad+slice transport, and 64-bit payloads "
        "that jnp transport silently demotes to 32 bits when x64 is "
        "off.  parallel/topology.py solves each once (guarded "
        "collectives, ragged_all_gather, uint32-view bitsafe "
        "transport); a raw call site re-opens all three."),
    scope=outside_topology,
    check=lambda fc: _check_raw_process_allgather(fc)))
