"""graftlint core: AST walker, rule registry, suppressions, baseline.

The engine is deliberately small: parse every file once into a
`FileContext` (source lines + AST with parent links + suppression
directives), run each registered rule's per-file `check` over the
contexts in its scope, then run project-wide rules (`project_check`)
that need the whole file set (config/docs drift, the canonical_params
folded-field set).  Findings are plain records keyed for baselining by
(rule, path, stripped source line) — line NUMBERS drift with every
edit, line TEXT only changes when the flagged code does, so a committed
baseline survives unrelated churn.

Suppression directives (scanned per raw source line):

    x = jax.jit(f)          # graftlint: disable=J201 <why>
    # graftlint: disable-next-line=D103 <why>
    # graftlint: disable-file=J203 <why>

Multiple ids separate with commas.  Every suppression should carry a
justification in the trailing text — `--format json` surfaces the
directive line so reviews can audit them.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    rule: str
    path: str              # repo-relative, forward slashes
    line: int
    message: str
    snippet: str = ""      # stripped source line (the baseline key)
    baselined: bool = False

    def key(self):
        return (self.rule, self.path, self.snippet)

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "snippet": self.snippet,
                "baselined": self.baselined}


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


@dataclass
class Rule:
    id: str
    name: str
    family: str            # determinism | jit | concurrency | drift
    summary: str
    rationale: str         # --explain body
    scope: Optional[Callable[[str], bool]] = None   # relpath predicate
    check: Optional[Callable[["FileContext"], Iterable[Finding]]] = None
    project_check: Optional[Callable[["Project"], Iterable[Finding]]] = None


RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return rule


# ---------------------------------------------------------------------------
# suppression directives
# ---------------------------------------------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-next-line|-file)?)\s*=\s*"
    r"([A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


def _comment_lines(source: str, lines: Sequence[str]):
    """(lineno, comment_text) for every REAL comment token.  Tokenizing
    (rather than regex over raw lines) keeps directive-shaped text
    inside strings/docstrings — e.g. documentation QUOTING the
    suppression syntax — from silently creating real (even file-wide)
    suppressions.  Token errors fall back to raw-line scanning: a file
    the tokenizer rejects usually fails ast.parse too (reported as
    E000), and over-suppressing an unparseable file is moot."""
    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        for i, raw in enumerate(lines, start=1):
            yield i, raw


def _parse_suppressions(source: str, lines: Sequence[str]):
    """-> (per-line {lineno: set(ids)}, file-wide set(ids))."""
    per_line: Dict[int, set] = {}
    file_wide: set = set()
    for i, comment in _comment_lines(source, lines):
        m = _DIRECTIVE.search(comment)
        if not m:
            continue
        kind = m.group(1)
        ids = {s.strip() for s in m.group(2).split(",") if s.strip()}
        if kind == "disable-file":
            file_wide |= ids
        elif kind == "disable-next-line":
            per_line.setdefault(i + 1, set()).update(ids)
        else:
            per_line.setdefault(i, set()).update(ids)
    return per_line, file_wide


# ---------------------------------------------------------------------------
# file context
# ---------------------------------------------------------------------------


class FileContext:
    """One parsed file: source, AST with parent links, suppressions."""

    def __init__(self, abspath: str, rel: str, source: str):
        self.abspath = abspath
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=abspath)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._gl_parent = node  # type: ignore[attr-defined]
        self._suppress_line, self._suppress_file = _parse_suppressions(
            source, self.lines)

    # -- helpers rules use ---------------------------------------------
    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, rule_id: str, lineno: int) -> bool:
        if rule_id in self._suppress_file:
            return True
        ids = self._suppress_line.get(lineno)
        return bool(ids) and rule_id in ids

    def finding(self, rule_id: str, node_or_line, message: str
                ) -> Optional[Finding]:
        """Build a Finding unless suppressed; rules yield the result if
        not None."""
        lineno = (node_or_line if isinstance(node_or_line, int)
                  else getattr(node_or_line, "lineno", 0))
        if self.suppressed(rule_id, lineno):
            return None
        return Finding(rule=rule_id, path=self.rel, line=lineno,
                       message=message, snippet=self.line_text(lineno))


# -- AST utilities shared by the rule modules --------------------------------


def parents(node: ast.AST):
    p = getattr(node, "_gl_parent", None)
    while p is not None:
        yield p
        p = getattr(p, "_gl_parent", None)


def dotted_name(node: ast.AST) -> str:
    """'jax.random.fold_in' for the func of a Call (best effort)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        # partial(jax.jit, ...)(f) and friends: descend into the callee
        parts.append(dotted_name(node.func))
    return ".".join(reversed(parts))


def subtree_names(node: ast.AST) -> List[str]:
    """Every Name id and Attribute attr below `node` (inclusive)."""
    out: List[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
    return out


def subtree_strings(node: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def enclosing_withs(node: ast.AST) -> List[ast.With]:
    return [p for p in parents(node) if isinstance(p, ast.With)]


# ---------------------------------------------------------------------------
# project: the linted file set + cross-file facts
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", "build", "dist", ".pytest_cache",
              ".hypothesis", ".refbuild", ".jax_cache", "node_modules"}


def iter_py_files(paths: Sequence[str], root: str) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


class Project:
    def __init__(self, root: str, files: List[FileContext]):
        self.root = root
        self.files = files
        self.errors: List[Finding] = []   # parse failures, reported

    @classmethod
    def load(cls, paths: Sequence[str], root: str) -> "Project":
        files: List[FileContext] = []
        errors: List[Finding] = []
        for abspath in iter_py_files(paths, root):
            rel = os.path.relpath(abspath, root).replace(os.sep, "/")
            try:
                with open(abspath, encoding="utf-8") as f:
                    src = f.read()
                files.append(FileContext(abspath, rel, src))
            except (SyntaxError, UnicodeDecodeError) as exc:
                errors.append(Finding(
                    rule="E000", path=rel,
                    line=getattr(exc, "lineno", 0) or 0,
                    message=f"could not parse: {exc}"))
        proj = cls(root, files)
        proj.errors = errors
        return proj

    def file(self, rel_suffix: str) -> Optional[FileContext]:
        for fc in self.files:
            if fc.rel.endswith(rel_suffix):
                return fc
        return None

    def read_text(self, *relparts: str) -> Optional[str]:
        """A non-linted project file (docs/Parameters.md); None when
        absent — project rules skip rather than crash on partial
        checkouts / fixture trees."""
        p = os.path.join(self.root, *relparts)
        try:
            with open(p, encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


def run(paths: Sequence[str], root: str,
        rules: Optional[Sequence[str]] = None) -> List[Finding]:
    # rule modules self-register on import
    from . import collectives, concurrency, determinism, drift, jitrules  # noqa: F401

    project = Project.load(paths, root)
    if not project.files and not project.errors:
        # a typo'd path must not silently disable the gate (the same
        # contract the dryrun tail holds bench_diff to): zero matched
        # files is a usage error, never a clean pass
        raise OSError(
            f"no .py files matched {list(paths)!r} under {root!r}")
    selected = [RULES[r] for r in rules] if rules else list(RULES.values())
    findings: List[Finding] = list(project.errors)
    for rule in selected:
        if rule.check is not None:
            for fc in project.files:
                if rule.scope is not None and not rule.scope(fc.rel):
                    continue
                findings.extend(f for f in rule.check(fc) if f is not None)
        if rule.project_check is not None:
            findings.extend(f for f in rule.project_check(project)
                            if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> List[Dict]:
    """Entries of a baseline file; [] when the file is absent (no
    baseline is a valid state).  A PRESENT-but-unparseable baseline
    raises ValueError: silently ignoring it would resurface every
    baselined finding (confusing) or — worse, had we returned the
    parseable prefix — hide some (gate-defeating)."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return []
    except json.JSONDecodeError as exc:
        raise ValueError(
            f"baseline {path!r} is not valid JSON ({exc}); fix it or "
            "regenerate with --write-baseline") from exc
    if not isinstance(data, dict) or not isinstance(
            data.get("entries", []), list):
        raise ValueError(
            f"baseline {path!r} malformed: expected an object with an "
            "'entries' list")
    return list(data.get("entries", []))


def apply_baseline(findings: List[Finding],
                   entries: List[Dict]) -> List[Finding]:
    """Mark findings matching a baseline entry (rule+path+snippet).
    Returns the NEW (un-baselined) findings; the input list keeps the
    `baselined` flags for full reports."""
    pool: Dict[tuple, int] = {}
    for e in entries:
        k = (e.get("rule", ""), e.get("path", ""), e.get("snippet", ""))
        pool[k] = pool.get(k, 0) + 1
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        if pool.get(k, 0) > 0:
            pool[k] -= 1
            f.baselined = True
        else:
            new.append(f)
    return new


def baseline_payload(findings: List[Finding]) -> Dict:
    return {"_comment": (
        "graftlint baseline: findings accepted as-is.  Every entry "
        "MUST carry a justification; prefer fixing or an inline "
        "suppression comment next to the code.  Regenerate with "
        "python -m tools.graftlint --write-baseline."),
        "entries": [
            {"rule": f.rule, "path": f.path, "snippet": f.snippet,
             "justification": "TODO: justify or fix"}
            for f in findings]}


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------


def to_text(findings: List[Finding], baselined_count: int = 0) -> str:
    lines = []
    for f in findings:
        lines.append(f"{f.path}:{f.line}: {f.rule} {f.message}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    lines.append(f"graftlint: {len(findings)} finding(s)"
                 + (f" ({baselined_count} baselined, not shown)"
                    if baselined_count else ""))
    return "\n".join(lines)


def to_json(findings: List[Finding], all_findings: List[Finding]) -> str:
    per_rule: Dict[str, int] = {}
    for f in findings:
        per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return json.dumps({
        "tool": "graftlint",
        "new_findings": len(findings),
        "baselined": sum(1 for f in all_findings if f.baselined),
        "per_rule": per_rule,
        "findings": [f.to_dict() for f in findings],
    }, indent=2)


def explain(rule_id: str) -> Optional[str]:
    from . import collectives, concurrency, determinism, drift, jitrules  # noqa: F401

    rule = RULES.get(rule_id)
    if rule is None:
        return None
    return (f"{rule.id} ({rule.family}): {rule.name}\n\n"
            f"{rule.summary}\n\n{rule.rationale.strip()}\n")
