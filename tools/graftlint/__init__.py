"""graftlint: determinism / jit-discipline / concurrency / drift /
topology static analysis for the lightgbm_tpu codebase.

Five rule families, each born from a postmortem this repo already
paid for (see `--explain <rule-id>` and ROADMAP item 7):

* **D1xx determinism** — the PR-11 bitwise root causes as lint:
  shape-keyed RNG (D101), f32 reductions over dequantized values
  (D102), fused mul+add on score paths (D103).
* **J2xx jit discipline** — every program on the CompileLedger (J201
  jax.jit, J202 shard_map), no host calls in traced bodies (J203),
  static_argnames in sync with canonical_params (J204).
* **C3xx concurrency** — the serving/obs lock-ownership map (C301),
  no dispatch under a lock (C302); runtime twin in
  lightgbm_tpu/utils/lockcheck.py.
* **P4xx config/docs drift** — every tpu_*/serving_* param read
  somewhere (P401), documented (P402), and nothing documented that
  does not exist (P403).
* **T5xx topology** — every collective is written once, in
  parallel/topology.py: raw jax.lax psum-family calls (T501) and raw
  multihost_utils.process_allgather (T502) anywhere else are findings.

Run: ``python -m tools.graftlint lightgbm_tpu/`` (text) or
``--format json`` (machine-readable, the multichip-dryrun gate).
Suppress inline: ``# graftlint: disable=J201 <why>``.  Accepted legacy
findings live in tools/graftlint/baseline.json (committed, justified).
"""

from .core import (Finding, Project, RULES, apply_baseline, explain,  # noqa: F401
                   load_baseline, run, to_json, to_text)

DEFAULT_BASELINE = "tools/graftlint/baseline.json"


def run_gate(root: str, paths=("lightgbm_tpu",)):
    """The programmatic gate (multichip dryrun tail, tests): lint
    `paths` under `root` against the committed baseline.  Returns
    (new_findings, all_findings) — nonzero new findings fail the
    caller."""
    import os

    findings = run(list(paths), root)
    entries = load_baseline(os.path.join(root, DEFAULT_BASELINE))
    new = apply_baseline(findings, entries)
    return new, findings
