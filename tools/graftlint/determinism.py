"""Determinism rules (D1xx): the PR-11 postmortem bug classes as lint.

Scope: the bitwise-critical modules — `lightgbm_tpu/ops/`,
`lightgbm_tpu/parallel/`, and `lightgbm_tpu/models/learner.py` — where
the cross-shard/cross-topology bitwise contract lives (ROADMAP item 7).
All three PR-11 root causes were syntactically recognizable; these
rules make them machine-checked so the next jit site cannot re-ship
them.  `--explain D101` (etc.) prints the full story.
"""

from __future__ import annotations

import ast
import re

from .core import (FileContext, Rule, dotted_name, register,
                   subtree_names, subtree_strings)

_SCOPE = re.compile(
    r"(^|/)lightgbm_tpu/(ops|parallel)/|(^|/)lightgbm_tpu/models/learner\.py$")


def bitwise_critical(rel: str) -> bool:
    return bool(_SCOPE.search(rel))


_POSTMORTEM = (
    "Background: ROADMAP.md open item 7 — the PR-11 postmortem of the "
    "cross-shard int16 bitwise violation (three stacked root causes, "
    "each one a syntactic pattern this family now rejects).")

# padded-axis spellings: the length of a PADDED axis is topology-
# dependent, so anything derived from it diverges across shard counts
_PAD_NAME = re.compile(r"(^|_)(n_pad|f_pad|g_pad|k_pad|pad|padded|"
                       r"pad_rows|pad_cols|padding)($|_)|_pad$|^pad_")

_RNG_KEYING = ("PRNGKey", "fold_in", "key", "key_data")


def _check_shape_keyed_rng(fc: FileContext):
    for node in ast.walk(fc.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        leaf = name.rsplit(".", 1)[-1]
        if leaf not in _RNG_KEYING or "random" not in name:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            names = subtree_names(arg)
            shapes = [n for n in names if n == "shape"]
            pads = [n for n in names if _PAD_NAME.search(n)]
            if shapes or pads:
                what = "array shape" if shapes else f"padded axis {pads[0]!r}"
                yield fc.finding(
                    "D101", node,
                    f"PRNG keying via {name} derived from {what}: padded/"
                    "sharded axis lengths are topology-dependent, so the "
                    "stream diverges across shard counts.  Key on GLOBAL "
                    "row indices instead (the PCG hash over "
                    "jax.lax.iota of global ids, as bagging does "
                    "post-PR-11).")


_REDUCERS = ("cumsum", "sum", "cumulative_sum", "nancumsum")
_F32_TOKENS = ("float32", "float", "f32", "float64", "f64")


def _casts_int_to_float(node: ast.AST) -> bool:
    """True when the subtree dequantizes: .astype(float...) /
    jnp.float32(...) over something, or names containing 'dequant'."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            dn = dotted_name(n.func)
            leaf = dn.rsplit(".", 1)[-1]
            if leaf == "astype":
                toks = subtree_names(n) + subtree_strings(n)
                if any(t in _F32_TOKENS for t in toks):
                    return True
            if leaf in ("float32", "float64", "bfloat16"):
                return True
    return any("dequant" in n.lower() for n in subtree_names(node))


def _float_dtype_kwarg(node: ast.Call) -> bool:
    """cumsum(x, dtype=jnp.float32) — the kwarg spelling of the same
    dequantizing reduction (`dtype` is an Attribute, not a cast call,
    so _casts_int_to_float alone misses it)."""
    for kw in node.keywords:
        if kw.arg == "dtype":
            toks = subtree_names(kw.value) + subtree_strings(kw.value)
            if any(t in _F32_TOKENS for t in toks):
                return True
    return False


def _check_f32_reduction(fc: FileContext):
    for node in ast.walk(fc.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        leaf = name.rsplit(".", 1)[-1]
        if leaf not in _REDUCERS:
            continue
        # jnp.cumsum(x) / x.cumsum(): scan args AND the method receiver
        operands = list(node.args) + [kw.value for kw in node.keywords]
        if isinstance(node.func, ast.Attribute):
            operands.append(node.func.value)
        if any(_casts_int_to_float(a) for a in operands) or \
                _float_dtype_kwarg(node):
            yield fc.finding(
                "D102", node,
                f"f32 {leaf} over dequantized values: float reductions "
                "reassociate under sharding/fusion (one-ulp split-gain "
                "drift at near-ties).  Reduce on the int32 grid and "
                "dequantize at the BOUNDARY — exact integer scans are "
                "associative at any shard count.")


_SCORE_NAME = re.compile(r"(^|_)scores?($|_)")
_LEAF_NAME = re.compile(r"leaf|output|values")


def _is_mult(node: ast.AST) -> bool:
    return isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult)


def _mult_has_leaf_gather(mult: ast.AST) -> bool:
    for n in ast.walk(mult):
        if isinstance(n, ast.Subscript):
            base = subtree_names(n.value)
            if any(_LEAF_NAME.search(b) for b in base):
                return True
    return False


def _check_fused_mul_add(fc: FileContext):
    seen = set()
    for node in ast.walk(fc.tree):
        mult = other = None
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            if _is_mult(node.left):
                mult, other = node.left, node.right
            elif _is_mult(node.right):
                mult, other = node.right, node.left
            anchor = node
        elif isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add) \
                and _is_mult(node.value):
            mult, other = node.value, node.target
            anchor = node
        if mult is None:
            continue
        if not any(_SCORE_NAME.search(n) for n in subtree_names(other)):
            continue
        if not _mult_has_leaf_gather(mult):
            continue
        if anchor.lineno in seen:
            continue
        seen.add(anchor.lineno)
        yield fc.finding(
            "D103", anchor,
            "fused a*b+c chain on a score/leaf path: XLA/LLVM may (or "
            "may not) contract the mul+add into an FMA depending on the "
            "surrounding program, so serial and shard_map builds drift "
            "one ulp apart at the SAME trees.  Pre-scale the [L] leaf "
            "vector first, then gather + ONE rounded add "
            "(scores.at[...].add(scaled[ids]) — the PR-11 idiom).")


register(Rule(
    id="D101", name="shape-keyed-rng", family="determinism",
    summary=("PRNG keys/streams must never derive from array shapes or "
             "padded-axis lengths in bitwise-critical modules; key on "
             "global row indices."),
    rationale=(
        "PR-11 root cause #1: bagging/GOSS masks were drawn with "
        "shape-keyed threefry over the PADDED row axis, whose length is "
        "topology-dependent — identical seeds produced different masks "
        "at different shard counts, silently breaking the cross-shard "
        "bitwise contract.  The fix keys the PCG hash on GLOBAL row "
        "indices (invariant to padding and sharding).  " + _POSTMORTEM),
    scope=bitwise_critical,
    check=lambda fc: _check_shape_keyed_rng(fc)))

register(Rule(
    id="D102", name="f32-reduction-on-dequantized", family="determinism",
    summary=("No f32 cumsum/sum over dequantized (int-origin) values "
             "where the exact int32 route exists; reduce integer, "
             "dequantize at the boundary."),
    rationale=(
        "PR-11 root cause #3: split-search bin cumsums ran on "
        "pre-dequantized f32 stats — float addition is not associative, "
        "so psum/scatter aggregation orders produced one-ulp gain drift "
        "and flipped near-tied splits.  Quantized precisions carry "
        "exact int32 sums; scanning THOSE and dequantizing the final "
        "values is bit-identical at every shard count.  " + _POSTMORTEM),
    scope=bitwise_critical,
    check=lambda fc: _check_f32_reduction(fc)))

register(Rule(
    id="D103", name="fused-mul-add-on-score-path", family="determinism",
    summary=("No a*b+c mul+add chains touching score/leaf-output "
             "buffers; pre-scale the leaf vector, then gather + one "
             "rounded add."),
    rationale=(
        "PR-11 root cause #2: the fused score update's "
        "`gather * lr + scores` chain contracted into an FMA "
        "differently between the serial and shard_map programs — "
        "scores drifted one ulp apart under IDENTICAL trees.  Scaling "
        "the [L] leaf vector first leaves the per-row path as gather + "
        "one correctly-rounded add, which every backend lowers "
        "identically.  " + _POSTMORTEM),
    scope=bitwise_critical,
    check=lambda fc: _check_fused_mul_add(fc)))
