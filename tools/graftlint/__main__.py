"""CLI: python -m tools.graftlint [paths...] [options].

Exit codes: 0 = clean beyond the baseline, 1 = new findings,
2 = usage/configuration error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import DEFAULT_BASELINE
from .core import (RULES, apply_baseline, baseline_payload, explain,
                   load_baseline, run, to_json, to_text)


def _find_root(start: str) -> str:
    """Walk up until the directory containing the lightgbm_tpu package
    (the repo root) — so the CLI works from subdirectories too."""
    d = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(d, "lightgbm_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start)
        d = parent


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="determinism / jit / concurrency / drift lint")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories (default: lightgbm_tpu/)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         "under the root, when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report everything, ignoring any baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file "
                         "(then hand-edit the justifications)")
    ap.add_argument("--explain", metavar="RULE_ID",
                    help="print one rule's rationale and exit")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default all)")
    args = ap.parse_args(argv)

    if args.explain:
        # load the registry
        from . import collectives, concurrency, determinism, drift, jitrules  # noqa: F401

        text = explain(args.explain)
        if text is None:
            print(f"unknown rule {args.explain!r}; known: "
                  f"{', '.join(sorted(RULES))}", file=sys.stderr)
            return 2
        print(text)
        return 0

    if args.list_rules:
        from . import collectives, concurrency, determinism, drift, jitrules  # noqa: F401

        for rid in sorted(RULES):
            r = RULES[rid]
            print(f"{rid}  [{r.family}]  {r.name}: {r.summary}")
        return 0

    root = args.root or _find_root(os.getcwd())
    paths = args.paths or ["lightgbm_tpu"]
    rules = ([s.strip() for s in args.rules.split(",") if s.strip()]
             if args.rules else None)
    if rules:
        from . import collectives, concurrency, determinism, drift, jitrules  # noqa: F401

        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
    try:
        findings = run(paths, root, rules=rules)
    except (OSError, ValueError) as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    if args.write_baseline:
        # the baseline is shared across ALL rules and paths: writing it
        # from a subset run would silently drop every entry the subset
        # didn't produce, and the next full gate run fails on them
        if rules:
            print("--write-baseline needs a full-rule run (drop "
                  "--rules): a subset write would discard the other "
                  "rules' baseline entries", file=sys.stderr)
            return 2
        if args.paths:
            print("--write-baseline needs the default full path set "
                  "(drop the path arguments): a subset write would "
                  "discard other files' baseline entries",
                  file=sys.stderr)
            return 2
        # parse failures are findings to FIX, never to baseline
        writable = [f for f in findings if f.rule != "E000"]
        payload = baseline_payload(writable)
        bdir = os.path.dirname(baseline_path)
        if bdir:
            os.makedirs(bdir, exist_ok=True)
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        skipped = len(findings) - len(writable)
        print(f"wrote {len(writable)} entr"
              f"{'y' if len(writable) == 1 else 'ies'} to "
              f"{baseline_path}; fill in the justifications."
              + (f"  ({skipped} parse-failure finding(s) NOT baselined "
                 "— fix the files)" if skipped else ""))
        return 0

    try:
        entries = [] if args.no_baseline else load_baseline(baseline_path)
    except ValueError as exc:
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2
    new = apply_baseline(findings, entries)
    if args.format == "json":
        print(to_json(new, findings))
    else:
        print(to_text(new, baselined_count=len(findings) - len(new)))
    return 1 if new else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:     # | head closed the pipe: not an error
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
