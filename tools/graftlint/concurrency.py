"""Concurrency rules (C3xx): the declared lock-ownership map, enforced.

Scope: `lightgbm_tpu/serving/` and `lightgbm_tpu/obs/` — the
multithreaded layers (batcher worker, dispatch helper, HTTP handlers,
admission gate, metrics writers).  The OWNERSHIP table below IS the
contract: each guarded attribute may only be mutated inside a `with`
block on its owning lock.  State deliberately left lock-free (the
flight recorder's GIL-atomic deque ring, `obs.metrics._sample_ring`)
is simply not in the table — adding new shared state means adding a
row here (or documenting why it is lock-free).

The runtime half lives in `lightgbm_tpu/utils/lockcheck.py`: the same
locks, created through `lockcheck.make_lock`, detect lock-ORDER
inversions and hold-while-dispatching dynamically under tests — things
no static map can see.

Conventions the checker honors:
* `__init__`/`__new__` are exempt (the object is not yet shared);
* methods named `*_locked` are exempt (the documented caller-holds-it
  convention, e.g. ModelRegistry._evict_locked);
* a mutation counts as guarded when ANY enclosing `with` manages an
  expression whose terminal attribute/name equals the owning lock's.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional, Tuple

from .core import FileContext, Rule, dotted_name, enclosing_withs, \
    parents, register

_SCOPE = re.compile(r"(^|/)lightgbm_tpu/(serving|obs|continual)/")


def concurrent_scope(rel: str) -> bool:
    return bool(_SCOPE.search(rel))


# (file suffix, class name or None for module level) ->
#     {guarded attribute: owning lock attribute}
OWNERSHIP: Dict[Tuple[str, Optional[str]], Dict[str, str]] = {
    ("serving/registry.py", "ModelRegistry"): {
        "_entries": "_lock", "_latest": "_lock", "_counts": "_lock",
        "_warmed": "_lock"},
    ("serving/batcher.py", "MicroBatcher"): {
        "_queues": "_cv", "_runners": "_cv", "_pending_rows": "_cv",
        "_stop": "_cv", "_draining": "_cv"},
    ("serving/batcher.py", "_SerialDispatcher"): {
        "_work": "_lock", "_busy": "_lock"},
    # fleet dispatch (ISSUE 19): each device worker's lane + goodput
    # accounting under its own condition variable; the placement table
    # is the model->device routing truth the registry writes
    ("serving/batcher.py", "_DeviceWorker"): {
        "_work": "_cv", "_queued_rows": "_cv", "_inflight_rows": "_cv",
        "_stop": "_cv", "_dispatches": "_cv", "_rows_done": "_cv",
        "_wall_s": "_cv", "_lat": "_cv"},
    ("serving/placement.py", "PlacementTable"): {
        "_sets": "_lock"},
    ("serving/stats.py", "ServingStats"): {
        "_fill_rows": "_lock", "_fill_bucket": "_lock",
        "_queue_depth": "_lock", "_shapes": "_lock",
        "_drift_series": "_lock", "_drift_closed": "_lock"},
    # DriftMonitor._pending is deliberately NOT here: it is a bounded
    # deque with GIL-atomic append/popleft (the flight-recorder-ring
    # pattern) written from the dispatch path, which must never lock
    ("obs/modelhealth.py", "DriftMonitor"): {
        "_counts": "_lock", "_nan": "_lock", "_unseen": "_lock",
        "_rows": "_lock", "_score_counts": "_lock",
        "_warned": "_lock", "_warnings": "_lock"},
    ("serving/stats.py", "CircuitBreaker"): {
        "state": "_lock", "_failures": "_lock", "_entered_at": "_lock",
        "_gen": "_lock"},
    ("serving/admission.py", "AdmissionController"): {
        "_level": "_lock", "_window_s": "_lock", "_projection_s": "_lock",
        "_next_update": "_lock", "_draining": "_lock"},
    # the continual loop's shared state (ISSUE 17): the ingest buffer is
    # written by the traffic-mirror thread while the retrain side reads;
    # the controller's watch dict flips between step() and rollback.
    # The dispatch-path drift tap feeding the trigger is DriftMonitor's
    # lock-free deque — the retrain side never adds a lock to it, so
    # C302 stays clean on the serve path by construction.
    ("continual/buffer.py", "RowBuffer"): {
        "_blocks": "_lock", "_rows": "_lock", "_seq": "_lock",
        "_ingested_total": "_lock", "_evicted_total": "_lock"},
    ("continual/controller.py", "ContinualController"): {
        "_watch": "_lock"},
    ("obs/metrics.py", "MetricsRegistry"): {
        "_families": "_lock"},
    ("obs/metrics.py", "_Family"): {
        "children": "lock"},
    ("obs/flightrecorder.py", None): {
        "_last_dump": "_dump_lock", "_dumps": "_dump_lock"},
}

_MUTATORS = {"append", "extend", "add", "remove", "discard", "pop",
             "popleft", "popitem", "clear", "update", "setdefault",
             "insert", "move_to_end", "appendleft"}


def _enclosing_class(node: ast.AST) -> Optional[str]:
    for p in parents(node):
        if isinstance(p, ast.ClassDef):
            return p.name
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # keep walking: methods live inside the class
            continue
    return None


def _exempt_function(node: ast.AST) -> bool:
    for p in parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if p.name in ("__init__", "__new__") or \
                    p.name.endswith("_locked"):
                return True
            # only the INNERMOST def decides; a nested closure inside
            # __init__ is still exempt via the outer hit above
    return False


def _with_locks(node: ast.AST) -> Iterable[str]:
    """Terminal names of every context-manager expression in enclosing
    with blocks: `with self._lock:` -> '_lock', `with fam.lock:` ->
    'lock', `with _dump_lock:` -> '_dump_lock'."""
    for w in enclosing_withs(node):
        for item in w.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):   # e.g. MonkeyPatch.context()
                expr = expr.func
            if isinstance(expr, ast.Attribute):
                yield expr.attr
            elif isinstance(expr, ast.Name):
                yield expr.id


def _attr_of_interest(node: ast.AST, guarded: Dict[str, str]
                      ) -> Optional[str]:
    """If `node` is (or drills into) self.X / obj.X / module-global X
    with X guarded, return X."""
    # unwrap subscripts: self._entries[k] -> self._entries
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in guarded:
        return node.attr
    if isinstance(node, ast.Name) and node.id in guarded:
        return node.id
    return None


def _module_has_global(fn: ast.AST, name: str) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Global) and name in n.names:
            return True
    return False


def _check_unlocked_mutation(fc: FileContext):
    maps = {cls: m for (suffix, cls), m in OWNERSHIP.items()
            if fc.rel.endswith(suffix)}
    if not maps:
        return
    for node in ast.walk(fc.tree):
        guarded_attr = owner = None
        anchor = node
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                cls = _enclosing_class(t)
                # maps.get(None) IS the module-level ownership map
                m = maps.get(cls)
                if m is None:
                    continue
                attr = _attr_of_interest(t, m)
                if attr is not None:
                    # module-level map only applies to real globals
                    if cls is None and isinstance(t, ast.Name):
                        fn = next((p for p in parents(t) if isinstance(
                            p, (ast.FunctionDef, ast.AsyncFunctionDef))),
                            None)
                        if fn is None or not _module_has_global(fn, attr):
                            continue
                    guarded_attr, owner = attr, m[attr]
                    break
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                cls = _enclosing_class(t)
                m = maps.get(cls)
                if m is None:
                    continue
                attr = _attr_of_interest(t, m)
                if attr is not None:
                    guarded_attr, owner = attr, m[attr]
                    break
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            cls = _enclosing_class(node)
            m = maps.get(cls)
            if m is not None:
                attr = _attr_of_interest(node.func.value, m)
                if attr is not None:
                    guarded_attr, owner = attr, m[attr]
        if guarded_attr is None:
            continue
        if _exempt_function(anchor):
            continue
        if owner in set(_with_locks(anchor)):
            continue
        yield fc.finding(
            "C301", anchor,
            f"{guarded_attr!r} mutated outside `with {owner}`: the "
            "lock-ownership map (tools/graftlint/concurrency.py "
            "OWNERSHIP) declares it guarded.  Take the owning lock, "
            "move the mutation into a *_locked helper, or amend the "
            "map with a comment if the state became lock-free by "
            "design.")


_DISPATCH_CALLEES = {"predict", "warmup", "runner", "fallback",
                     "_native_predict", "block_until_ready",
                     "device_get", "device_put"}
_LOCK_NAME = re.compile(r"(^|_)(lock|cv)$|_lock$|_cv$")


def _check_dispatch_under_lock(fc: FileContext):
    for node in ast.walk(fc.tree):
        if not isinstance(node, ast.Call):
            continue
        leaf = dotted_name(node.func).rsplit(".", 1)[-1]
        if leaf not in _DISPATCH_CALLEES:
            continue
        held = [w for w in _with_locks(node) if _LOCK_NAME.search(w)]
        if held:
            yield fc.finding(
                "C302", node,
                f"device-dispatch call {leaf!r} inside `with "
                f"{held[0]}`: a device wall is unbounded from the "
                "host's view, so every thread queued on that lock "
                "stalls behind the launch (the registry runs warmup "
                "OUTSIDE its lock for exactly this reason).  Snapshot "
                "state under the lock, release it, then dispatch.  "
                "The runtime twin is lockcheck.check_dispatch.")


register(Rule(
    id="C301", name="mutation-outside-owning-lock", family="concurrency",
    summary=("Shared mutable state declared in the lock-ownership map "
             "may only be mutated under its owning lock."),
    rationale=(
        "The serving/obs layers are mutated from HTTP handler threads, "
        "the batcher worker, the dispatch helper, and the admission "
        "gate concurrently.  Each shared structure has exactly one "
        "owning lock, declared in the OWNERSHIP table; an undeclared "
        "mutation path is a data race waiting for a scheduler to find "
        "it.  Deliberately lock-free state (the flight recorder's "
        "GIL-atomic ring) is excluded from the table, with the "
        "reasoning documented at the definition.  The runtime half — "
        "lock-order inversions, mutation-without-lock under a thread "
        "hammer — is utils/lockcheck.py, enabled under tests."),
    scope=concurrent_scope,
    check=lambda fc: _check_unlocked_mutation(fc)))

register(Rule(
    id="C302", name="dispatch-while-holding-lock", family="concurrency",
    summary=("No device dispatch (predict/warmup/runner/fallback/"
             "block_until_ready) inside a with-lock block."),
    rationale=(
        "A jit launch or device sync can take seconds (cold compile) "
        "or forever (wedged device — the PR-11 watchdog exists because "
        "it happened).  Holding a serving lock across one turns a "
        "single slow launch into a full-service stall: every HTTP "
        "thread piles up on the lock behind it.  The registry "
        "deliberately runs load/warmup outside its lock and the "
        "batcher dispatches outside its condition variable; this rule "
        "keeps it that way.  lockcheck.check_dispatch() is the runtime "
        "twin at the dispatch sites themselves."),
    scope=concurrent_scope,
    check=lambda fc: _check_dispatch_under_lock(fc)))
