"""Jit-discipline rules (J2xx): every program on the ledger, no host
work inside traced bodies, statics in sync with canonical_params.

The compile ledger (PR 6) is how this repo keeps the program zoo
countable: `n_programs` is a gated bench metric, serving warmup
enumerates exactly the ledgered launch shapes, and perf_probe retrace
attributes compile wall per site.  A bare `jax.jit` is a program the
ledger cannot see; host calls inside a traced body either burn at
trace time (silently keyed to whatever triggered the trace) or force
a sync; and a static_argnames entry naming a canonical_params-folded
mode field re-keys programs the cache claims are shared.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .core import (FileContext, Project, Rule, dotted_name,
                   enclosing_function, parents, register, subtree_names)

_LEDGER_WRAPPERS = {"ledger_jit", "LedgeredJit"}

# the one module allowed to say jax.jit: the wrapper itself
_EXEMPT = re.compile(r"(^|/)lightgbm_tpu/utils/compile_ledger\.py$")


def in_package(rel: str) -> bool:
    return "lightgbm_tpu/" in rel or rel.startswith("lightgbm_tpu")


def _jit_aliases(tree: ast.AST) -> Set[str]:
    """Local names that ARE jax.jit: `from jax import jit [as j]`,
    `j = jax.jit` assignment aliases, and `<m>.jit` for every module
    alias `import jax as m` — the spellings that would otherwise evade
    the literal `jax.jit` match."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name == "jit":
                    out.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    out.add(f"{a.asname or a.name}.jit")
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if dotted_name(node.value) == "jax.jit":
                out.add(node.targets[0].id)
    return out


def _is_jax_jit(node: ast.AST, aliases: Set[str]) -> bool:
    name = dotted_name(node)
    return (name == "jax.jit" or name.endswith(".jax.jit")
            or name in aliases)


def _jit_calls(tree: ast.AST, aliases: Set[str]):
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_jax_jit(node.func, aliases):
            yield node, dotted_name(node.func)
        elif dotted_name(node.func).rsplit(".", 1)[-1] == "partial" and \
                node.args and _is_jax_jit(node.args[0], aliases):
            # partial(jax.jit, static_argnames=...)(f)
            yield node, "partial(jax.jit, ...)"


def _check_unledgered_jit(fc: FileContext):
    if _EXEMPT.search(fc.rel):
        return
    aliases = _jit_aliases(fc.tree)
    decorator_jits = set()
    # decorator spelling: @jax.jit / @jit / @partial(jax.jit, ...)
    for node in ast.walk(fc.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            names = set(subtree_names(dec))
            if names & _LEDGER_WRAPPERS:
                continue
            bare = isinstance(dec, ast.Name) and dec.id in aliases
            if bare or ("jax" in names and "jit" in names) or \
                    (names & aliases):
                decorator_jits.add(id(dec))
                yield fc.finding(
                    "J201", dec,
                    f"@jax.jit decorator on {node.name!r}: use "
                    "@ledger_jit(site=...) so the program lands on the "
                    "compile ledger.")
    for node, name in _jit_calls(fc.tree, aliases):
        if id(node) in decorator_jits:
            continue  # already reported as a decorator
        yield fc.finding(
            "J201", node,
            f"bare {name} call site: programs compiled here are "
            "invisible to the CompileLedger (n_programs gates, retrace "
            "attribution, serving warmup accounting).  Route through "
            "utils.compile_ledger.ledger_jit(site=...), or suppress "
            "with a justification if the site is deliberately "
            "off-ledger.")


def _local_wrapper_names(tree: ast.AST) -> Set[str]:
    """Module functions whose body returns ledger_jit(...)/LedgeredJit
    — 'registered wrappers' a shard_map result may legitimately flow
    into (parallel/strategies.py's _strategy_jit)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for ret in ast.walk(node):
            if isinstance(ret, ast.Return) and ret.value is not None \
                    and isinstance(ret.value, ast.Call):
                leaf = dotted_name(ret.value.func).rsplit(".", 1)[-1]
                if leaf in _LEDGER_WRAPPERS:
                    out.add(node.name)
    return out


def _check_unledgered_shard_map(fc: FileContext):
    wrappers = _LEDGER_WRAPPERS | _local_wrapper_names(fc.tree)
    for node in ast.walk(fc.tree):
        if not isinstance(node, ast.Call):
            continue
        if dotted_name(node.func).rsplit(".", 1)[-1] != "shard_map":
            continue
        # the version-compat def shard_map(f, **kw) shim itself
        fn = enclosing_function(node)
        if fn is not None and fn.name == "shard_map":
            continue
        ok = False
        # (a) already an argument of a wrapper call
        for p in parents(node):
            if isinstance(p, ast.Call) and \
                    dotted_name(p.func).rsplit(".", 1)[-1] in wrappers:
                ok = True
                break
        # (b) assigned to a name that later feeds a wrapper call in the
        # same function
        if not ok:
            assign = next((p for p in parents(node)
                           if isinstance(p, ast.Assign)), None)
            if assign is not None and fn is not None and \
                    len(assign.targets) == 1 and \
                    isinstance(assign.targets[0], ast.Name):
                var = assign.targets[0].id
                for call in ast.walk(fn):
                    if isinstance(call, ast.Call) and \
                            dotted_name(call.func).rsplit(".", 1)[-1] \
                            in wrappers and \
                            any(isinstance(a, ast.Name) and a.id == var
                                for a in call.args):
                        ok = True
                        break
        if not ok:
            yield fc.finding(
                "J202", node,
                "shard_map program never reaches ledger_jit (or a "
                "wrapper returning it): sharded programs are the most "
                "expensive compiles in the zoo and MUST be on the "
                "ledger.  Wrap the result in ledger_jit(site=...).")


_BANNED_IN_JIT = {
    "time.time": "wall-clock read burns at TRACE time (a constant "
                 "keyed to whatever call triggered the compile)",
    "time.monotonic": "wall-clock read burns at trace time",
    "time.perf_counter": "wall-clock read burns at trace time",
    "jax.device_get": "host sync inside a traced body",
    "device_get": "host sync inside a traced body",
}


def _jitted_defs(tree: ast.AST) -> List[ast.FunctionDef]:
    """Function defs traced by jax: decorated with jit/ledger_jit, or
    whose NAME appears anywhere inside the argument subtree of a
    jit/ledger_jit call (covers `ledger_jit(make_step(_pre, _post))`:
    _pre/_post are traced through the returned closure)."""
    aliases = _jit_aliases(tree)
    jit_arg_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            leaf = dotted_name(node.func).rsplit(".", 1)[-1]
            if leaf in ("jit",) or leaf in _LEDGER_WRAPPERS or \
                    leaf in aliases:
                for a in node.args:
                    jit_arg_names.update(
                        n.id for n in ast.walk(a)
                        if isinstance(n, ast.Name))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        decorated = any(
            ("jit" in subtree_names(d)) or
            (set(subtree_names(d)) & (_LEDGER_WRAPPERS | aliases))
            for d in node.decorator_list)
        if decorated or node.name in jit_arg_names:
            out.append(node)
    return out


def _check_host_call_in_jit(fc: FileContext):
    for fn in _jitted_defs(fc.tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            leaf = name.rsplit(".", 1)[-1]
            why = _BANNED_IN_JIT.get(name) or _BANNED_IN_JIT.get(leaf)
            if why is None and leaf == "item" and \
                    isinstance(node.func, ast.Attribute):
                why = (".item() forces a device->host sync and a "
                       "concrete value inside a traced body")
            if why is None and (name.startswith("np.random")
                                or name.startswith("numpy.random")):
                why = ("numpy RNG inside a traced body draws at TRACE "
                       "time: the value freezes into the program, keyed "
                       "to whatever call triggered the compile — "
                       "topology-dependent and invisible to seeds.  Use "
                       "jax.random with explicit keys")
            if why is not None:
                yield fc.finding(
                    "J203", node,
                    f"{name}() inside jitted function {fn.name!r}: "
                    f"{why}.")


def _folded_fields(project: Project) -> Set[str]:
    """keys of ops/grower.py's _FOLDED_FIELDS — the mode params
    canonical_params strips from the grower cache key."""
    fc = project.file("lightgbm_tpu/ops/grower.py")
    if fc is None:
        return set()
    for node in ast.walk(fc.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_FOLDED_FIELDS":
            v = node.value
            if isinstance(v, ast.Call):        # dict(a=..., b=...)
                return {kw.arg for kw in v.keywords if kw.arg}
            if isinstance(v, ast.Dict):
                return {k.value for k in v.keys
                        if isinstance(k, ast.Constant)}
    return set()


def _check_static_argnames(project: Project):
    folded = _folded_fields(project)
    if not folded:
        return
    for fc in project.files:
        if not in_package(fc.rel):
            continue
        for node in ast.walk(fc.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = dotted_name(node.func).rsplit(".", 1)[-1]
            if leaf not in ({"jit"} | _LEDGER_WRAPPERS):
                continue
            for kw in node.keywords:
                if kw.arg != "static_argnames":
                    continue
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str) and c.value in folded:
                        yield fc.finding(
                            "J204", node,
                            f"static_argnames names {c.value!r}, a mode "
                            "param canonical_params STRIPS from the "
                            "grower cache key: every distinct value "
                            "would compile a new program while the "
                            "params cache claims one.  Mode switches "
                            "ride the traced meta['mode_flags'] vector "
                            "instead (ops/grower.py).")


register(Rule(
    id="J201", name="unledgered-jax-jit", family="jit",
    summary=("Every jax.jit site must go through "
             "utils.compile_ledger.ledger_jit so the program zoo stays "
             "counted and attributable."),
    rationale=(
        "PR 6 halved compile latency by making every compiled program "
        "countable: `n_programs` is a gated bench metric and perf_probe "
        "retrace attributes compile wall per site.  A bare jax.jit is a "
        "program none of that sees — the zoo regrows invisibly.  "
        "Deliberately off-ledger sites (per-objective closures that "
        "re-trace in milliseconds) carry an inline suppression with the "
        "justification in the comment."),
    scope=in_package, check=lambda fc: _check_unledgered_jit(fc)))

register(Rule(
    id="J202", name="unledgered-shard-map", family="jit",
    summary=("shard_map programs must flow into ledger_jit (directly "
             "or via a wrapper that returns it)."),
    rationale=(
        "Sharded grower programs are the most expensive compiles in "
        "the process (minutes on a cold pod).  parallel/strategies.py "
        "routes every strategy through _strategy_jit -> ledger_jit; a "
        "new shard_map site that skips the ledger breaks the "
        "program-count gates the moment it re-traces."),
    scope=in_package, check=lambda fc: _check_unledgered_shard_map(fc)))

register(Rule(
    id="J203", name="host-call-in-jitted-body", family="jit",
    summary=("No time.time()/np.random/.item()/device_get inside "
             "functions that get jitted: host work either freezes at "
             "trace time or forces a sync."),
    rationale=(
        "A traced body runs ONCE per compile: `time.time()` bakes the "
        "trace-time wall clock into the program; `np.random` draws a "
        "constant keyed to whichever call happened to trigger the "
        "compile (topology-dependent, invisible to seeds — exactly the "
        "shape of PR-11's RNG root cause); `.item()`/`device_get` "
        "force device->host syncs that serialize the async dispatch "
        "pipeline the train loop depends on."),
    scope=in_package, check=lambda fc: _check_host_call_in_jit(fc)))

# ---------------------------------------------------------------------------
# J205: broad exception handlers on device-dispatch paths must route
# through the membudget OOM classifier (ISSUE 15)
# ---------------------------------------------------------------------------
_J205_SCOPE = re.compile(r"(^|/)lightgbm_tpu/(ops|models|serving)/")

#: callee leaves that reach the device from ops/models/serving — a try
#: body containing one of these is a device-dispatch path
_J205_DISPATCH = {"predict", "warmup", "_native_predict",
                  "forest_class_scores", "forest_leaf_values",
                  "bin_chunk", "bin_matrix", "bin_stream",
                  "block_until_ready", "device_put", "device_get"}

#: handler types broad enough to swallow an unclassified RESOURCE_
#: EXHAUSTED (specific handlers — ValueError, KeyError — cannot)
_J205_BROAD = {"Exception", "BaseException", "XlaRuntimeError",
               "JaxRuntimeError", "RuntimeError"}

#: names whose presence in a handler body means the error is routed
#: through the membudget classifier (or re-raised classified)
_J205_ROUTERS = {"membudget", "is_oom_error", "oom_guard",
                 "DeviceOutOfMemory", "MemoryLadderExhausted",
                 "ServingMemoryExhausted"}


def dispatch_scope(rel: str) -> bool:
    return bool(_J205_SCOPE.search(rel))


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return any(dotted_name(t).rsplit(".", 1)[-1] in _J205_BROAD
               for t in types)


def _handler_routes_or_reraises(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True  # bare re-raise: classification passes upward
        if isinstance(node, (ast.Name, ast.Attribute)):
            names = set(subtree_names(node)) | {dotted_name(node)
                                               .rsplit(".", 1)[-1]}
            if names & _J205_ROUTERS:
                return True
    return False


def _check_oom_classifier(fc: FileContext):
    for node in ast.walk(fc.tree):
        if not isinstance(node, ast.Try):
            continue
        dispatches = False
        for stmt in node.body:
            for call in ast.walk(stmt):
                if isinstance(call, ast.Call) and \
                        dotted_name(call.func).rsplit(".", 1)[-1] \
                        in _J205_DISPATCH:
                    dispatches = True
                    break
            if dispatches:
                break
        if not dispatches:
            continue
        for handler in node.handlers:
            if not _handler_is_broad(handler):
                continue
            if _handler_routes_or_reraises(handler):
                continue
            if handler.type is None:
                caught = "bare except"
            elif isinstance(handler.type, ast.Tuple):
                caught = "except (" + ", ".join(
                    dotted_name(t) for t in handler.type.elts) + ")"
            else:
                caught = f"except {dotted_name(handler.type)}"
            yield fc.finding(
                "J205", handler,
                f"{caught} on a device-dispatch path swallows "
                "unclassified RESOURCE_EXHAUSTED: route through the "
                "membudget classifier (membudget.is_oom_error / "
                "oom_guard) or re-raise bare, so a device OOM stays a "
                "counted, named, recoverable event instead of a "
                "silent fallback.")


register(Rule(
    id="J205", name="unclassified-oom-handler", family="jit",
    summary=("Broad except handlers (bare / Exception / "
             "XlaRuntimeError) on device-dispatch paths in ops/, "
             "models/, serving/ must route through the membudget OOM "
             "classifier or re-raise."),
    rationale=(
        "ISSUE 15 made device memory a budgeted, recoverable resource: "
        "every HBM exhaustion must classify into DeviceOutOfMemory so "
        "it is counted (lgbm_oom_events_total), noted in the flight "
        "recorder with a memory snapshot, and eligible for the "
        "degradation ladder / serving eviction.  A broad handler that "
        "swallows the raw XlaRuntimeError re-creates the pre-ISSUE-15 "
        "world: the OOM becomes an anonymous fallback and the pressure "
        "signal is lost.  Handlers that call membudget.is_oom_error, "
        "sit under an oom_guard re-raise, or re-raise bare are "
        "compliant; specific handlers (ValueError, KeyError) are "
        "outside the rule — they cannot catch an OOM."),
    scope=dispatch_scope, check=lambda fc: _check_oom_classifier(fc)))

register(Rule(
    id="J204", name="static-argname-of-folded-mode-param", family="jit",
    summary=("static_argnames must not name params canonical_params "
             "strips: folded mode fields ride the traced mode_flags "
             "vector, never the jit cache key."),
    rationale=(
        "canonical_params normalizes the folded mode fields "
        "(quant_round, quant_refit, cegb_*) so structurally identical "
        "configurations share ONE cached grower program; the actual "
        "values ride the traced meta['mode_flags'] vector.  Passing "
        "such a field as a static argname bypasses the fold: each "
        "value silently keys a fresh program while the memoized-grower "
        "cache (and the compile-stability gates) believe one exists."),
    project_check=lambda project: _check_static_argnames(project)))
