"""Config/docs drift rules (P4xx): every tpu_*/serving_* param read
somewhere and documented, and nothing documented that does not exist.

The config registry (`lightgbm_tpu/config.py` `_P`) is the single
source of truth; docs/Parameters.md is GENERATED from it
(tools/gen_params_doc.py, gated by tests/test_params_doc.py).  What the
generator cannot check is the third leg: that the code actually READS
each param.  A `tpu_*` knob nobody reads is worse than dead code — it
is a user-facing promise ("set this and behavior changes") that
silently does nothing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, Project, Rule, register

_PREFIX = re.compile(r"^(tpu_|serving_)")
_DOC_TOKEN = re.compile(r"\b((?:tpu|serving)_[a-z0-9_]+)\b")

# tokens that LOOK like params in docs prose but are not registry
# entries by design (each one justified here, not baselined):
#   tpu_bin_mappers — the saved-model trailer section name (PR 2), a
#       model-file format token, not a config knob
#   tpu_feature_profile — the model-health trailer section name
#       (ISSUE 14), same model-file format family as tpu_bin_mappers
#   serving_aot — the `<tpu_compile_cache_dir>/serving_aot` cache
#       SUBDIRECTORY named in serving_aot_cache_dir's default rule
#       (ISSUE 19), a filesystem path component, not a config knob
_DOC_TOKEN_ALLOWED = {"tpu_bin_mappers", "tpu_feature_profile",
                      "serving_aot"}


def _registry_params(project: Project) -> Dict[str, int]:
    """tpu_*/serving_* keys of config.py's _P literal -> lineno."""
    fc = project.file("lightgbm_tpu/config.py")
    if fc is None:
        return {}
    for node in ast.walk(fc.tree):
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "_P" and isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _PREFIX.match(k.value)}
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_P" and \
                isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _PREFIX.match(k.value)}
    return {}


def _usage_tokens(project: Project) -> Set[str]:
    """Every identifier-ish token that counts as 'reading' a param:
    attribute access (config.tpu_x), Name, keyword arg, or a string
    literal ("tpu_x" lookups / docstring references do NOT count —
    only code-position strings inside calls, e.g. .get("tpu_x"))."""
    used: Set[str] = set()
    # the lint file set usually covers only lightgbm_tpu/, but a param
    # legitimately consumed ONLY by tools/ or the bench/driver scripts
    # (serve_bench reads serving config) must not be reported dead —
    # the message says "package/tools", so the scan reads them too
    used |= _script_tokens(project)
    for fc in project.files:
        if fc.rel.endswith("lightgbm_tpu/config.py"):
            continue  # the registry defining a param is not a read
        for node in ast.walk(fc.tree):
            if isinstance(node, ast.Attribute):
                used.add(node.attr)
            elif isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.keyword) and node.arg:
                used.add(node.arg)
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                # string params surface as .get("tpu_x") / params
                # dict keys in tests and tools — count them, but only
                # exact identifier-shaped strings (not prose)
                v = node.value.strip()
                if _PREFIX.match(v) and re.fullmatch(r"[a-z0-9_]+", v):
                    used.add(v)
    return used


def _script_tokens(project: Project) -> Set[str]:
    """tpu_*/serving_* word tokens from the non-linted consumer
    scripts (tools/*.py, bench.py, __graft_entry__.py): a word-level
    scan — membership is all P401 needs, and these files may not be in
    the linted set at all."""
    import os

    out: Set[str] = set()
    paths = []
    tools_dir = os.path.join(project.root, "tools")
    if os.path.isdir(tools_dir):
        for dirpath, dirnames, filenames in os.walk(tools_dir):
            # graftlint itself is not a consumer: a param named in a
            # rule comment must not count as "read"
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "graftlint")]
            paths += [os.path.join(dirpath, f) for f in filenames
                      if f.endswith(".py")]
    for extra in ("bench.py", "__graft_entry__.py"):
        paths.append(os.path.join(project.root, extra))
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                out |= set(_DOC_TOKEN.findall(f.read()))
        except OSError:
            continue
    return out


def _facts(project: Project):
    """(params, doc, doc_tokens) computed once per Project — the three
    drift rules share the scan instead of re-parsing the registry and
    re-reading Parameters.md per rule."""
    cached = getattr(project, "_gl_drift_facts", None)
    if cached is None:
        params = _registry_params(project)
        doc = project.read_text("docs", "Parameters.md")
        doc_tokens = set(_DOC_TOKEN.findall(doc)) if doc else set()
        cached = project._gl_drift_facts = (params, doc, doc_tokens)
    return cached


def _check_param_drift(project: Project, which: str):
    """Shared scan; `which` selects the rule so each registered rule
    emits exactly its own findings (--rules P402 must run the P402
    check, and --rules P401 must NOT leak P402/P403 findings)."""
    params, doc, doc_tokens = _facts(project)
    if not params:
        return
    cfg = project.file("lightgbm_tpu/config.py")
    if which == "P401":
        used = _usage_tokens(project)
        for name, lineno in sorted(params.items()):
            if name not in used:
                yield cfg.finding(
                    "P401", lineno,
                    f"config param {name!r} is never read anywhere in "
                    "the package/tools: a knob that silently does "
                    "nothing is a broken user-facing promise.  Wire it "
                    "up or delete the registry entry (and regenerate "
                    "docs/Parameters.md).")
    elif which == "P402" and doc is not None:
        for name, lineno in sorted(params.items()):
            if name not in doc_tokens:
                yield cfg.finding(
                    "P402", lineno,
                    f"config param {name!r} missing from "
                    "docs/Parameters.md — run python "
                    "tools/gen_params_doc.py.")
    elif which == "P403" and doc is not None:
        # aliases and non-tpu params share the doc; only flag tokens
        # that CLAIM the tpu_/serving_ namespace without a registry row
        for tok in sorted(doc_tokens - set(params) - _DOC_TOKEN_ALLOWED):
            yield Finding(
                rule="P403", path="docs/Parameters.md", line=0,
                message=(f"{tok!r} appears in docs/Parameters.md but is "
                         "not a config-registry param: stale doc or a "
                         "typo'd name readers will copy into configs "
                         "that silently no-op.  Fix the doc (or extend "
                         "_DOC_TOKEN_ALLOWED with a justification)."),
                snippet=tok)


register(Rule(
    id="P401", name="param-never-read", family="drift",
    summary=("Every tpu_*/serving_* registry param must be read "
             "somewhere in the package or tools."),
    rationale=(
        "A config knob nobody reads is a silent lie: users set it, "
        "nothing changes, and the failure mode is indistinguishable "
        "from 'the feature is broken'.  The registry/doc generator "
        "keeps names and docs in sync mechanically; this closes the "
        "third leg (code actually consumes the param)."),
    project_check=lambda p: _check_param_drift(p, "P401")))

register(Rule(
    id="P402", name="param-undocumented", family="drift",
    summary="Every tpu_*/serving_* registry param appears in "
            "docs/Parameters.md.",
    rationale=(
        "docs/Parameters.md is generated from the registry "
        "(tools/gen_params_doc.py) and gated by tests/test_params_doc; "
        "this rule catches the window where a param landed without "
        "regenerating, from the lint gate that also runs outside "
        "pytest (multichip dryrun tail)."),
    project_check=lambda p: _check_param_drift(p, "P402")))

# ---------------------------------------------------------------------------
# P405: metric-name <-> USAGE.md metric-table drift (ISSUE 14)
# ---------------------------------------------------------------------------
# metric names never end in '_' — that shape is an f-string head
# (f"lgbm_serving_{counter}"), collected separately as a dyn prefix
_METRIC_LIT = re.compile(r"^lgbm_[a-z0-9_]*[a-z0-9]$")
_METRIC_DOC = re.compile(r"\blgbm_[a-z0-9_*]+")
# Prometheus exposition derives these suffixes from histogram families;
# a doc/code mention of either form documents the same metric
_HIST_SUFFIXES = ("", "_bucket", "_sum", "_count")


def _metric_facts(project: Project):
    """(code_names, dyn_prefixes, doc_tokens) shared by both directions
    of the P405 check.  code_names = full-match `lgbm_*` string
    literals anywhere in the linted package (direct registry names AND
    name-constant assignments like stats._LAT); dyn_prefixes = leading
    constants of f-strings that BUILD metric names (`f"lgbm_serving_
    {counter}"`), whose members a static scan cannot enumerate."""
    cached = getattr(project, "_gl_metric_facts", None)
    if cached is not None:
        return cached
    code: Dict[str, Tuple[str, int]] = {}
    dyn: Set[str] = set()
    for fc in project.files:
        for node in ast.walk(fc.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _METRIC_LIT.fullmatch(node.value):
                code.setdefault(node.value,
                                (fc.rel, getattr(node, "lineno", 0)))
            elif isinstance(node, ast.JoinedStr) and node.values:
                head = node.values[0]
                if isinstance(head, ast.Constant) and \
                        isinstance(head.value, str) and \
                        head.value.startswith("lgbm_"):
                    dyn.add(head.value)
    doc = project.read_text("docs", "USAGE.md")
    doc_tokens = set(_METRIC_DOC.findall(doc)) if doc is not None \
        else None
    cached = project._gl_metric_facts = (code, dyn, doc_tokens)
    return cached


def _doc_matches(token: str, name: str) -> bool:
    """Does one USAGE token (may contain ``*`` wildcards) document
    `name` (modulo the Prometheus histogram suffixes)?"""
    import fnmatch

    for suf in _HIST_SUFFIXES:
        if fnmatch.fnmatchcase(name + suf, token) or \
                fnmatch.fnmatchcase(name, token + suf):
            return True
    return False


def _check_metric_drift(project: Project):
    code, dyn, doc_tokens = _metric_facts(project)
    if doc_tokens is None or not code:
        return  # partial checkout (fixture trees): nothing to check
    for name, (rel, lineno) in sorted(code.items()):
        if not any(_doc_matches(tok, name) for tok in doc_tokens):
            fc = project.file(rel)
            src = fc.finding if fc is not None else None
            if src is not None:
                yield src("P405", lineno,
                          f"metric {name!r} is registered in code but "
                          "missing from docs/USAGE.md's metric-names "
                          "tables: an operator cannot alert on a metric "
                          "they cannot discover.  Add a table row (or a "
                          "covering wildcard like lgbm_serving_*_total).")
    for tok in sorted(doc_tokens):
        if any(_doc_matches(tok, name) for name in code):
            continue
        # dynamically-built families (f"lgbm_serving_{counter}"): a
        # token is legitimate when its literal head shares a prefix
        # with a dynamic name constructor — members are not statically
        # enumerable, so prefix compatibility is the checkable claim
        head = tok.split("*", 1)[0]
        if any(head.startswith(p) or p.startswith(head) for p in dyn):
            continue
        yield Finding(
            rule="P405", path="docs/USAGE.md", line=0,
            message=(f"{tok!r} appears in docs/USAGE.md but no code "
                     "registers a matching lgbm_* metric: a phantom "
                     "name readers will build dashboards on.  Fix the "
                     "doc or register the metric."),
            snippet=tok)


register(Rule(
    id="P405", name="metric-name-drift", family="drift",
    summary=("Every lgbm_* metric name registered in code appears in "
             "USAGE.md's metric-names tables, and no documented metric "
             "name is phantom."),
    rationale=(
        "The metric tables in docs/USAGE.md are the operator contract: "
        "dashboards and alerts are built from them, not from the "
        "source.  A metric the code emits but the doc omits is "
        "undiscoverable; a metric the doc names but nothing emits is a "
        "dashboard that silently flatlines.  Same shape as P402/P403 "
        "for params, applied to the `lgbm_*` namespace; wildcard "
        "tokens (lgbm_serving_*_total) cover dynamically-constructed "
        "families, matched by prefix against their f-string "
        "constructors."),
    project_check=_check_metric_drift))

register(Rule(
    id="P403", name="doc-param-phantom", family="drift",
    summary=("No tpu_*/serving_* token in docs/Parameters.md without a "
             "registry entry behind it."),
    rationale=(
        "The reverse direction of P402: a documented-but-nonexistent "
        "param is a name readers will copy into configs where it "
        "silently lands in Config.extra and does nothing.  Tokens that "
        "legitimately share the namespace (the tpu_bin_mappers model "
        "trailer) are allow-listed in the rule source with the "
        "justification."),
    project_check=lambda p: _check_param_drift(p, "P403")))
