"""Config/docs drift rules (P4xx): every tpu_*/serving_* param read
somewhere and documented, and nothing documented that does not exist.

The config registry (`lightgbm_tpu/config.py` `_P`) is the single
source of truth; docs/Parameters.md is GENERATED from it
(tools/gen_params_doc.py, gated by tests/test_params_doc.py).  What the
generator cannot check is the third leg: that the code actually READS
each param.  A `tpu_*` knob nobody reads is worse than dead code — it
is a user-facing promise ("set this and behavior changes") that
silently does nothing.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Finding, Project, Rule, register

_PREFIX = re.compile(r"^(tpu_|serving_)")
_DOC_TOKEN = re.compile(r"\b((?:tpu|serving)_[a-z0-9_]+)\b")

# tokens that LOOK like params in docs prose but are not registry
# entries by design (each one justified here, not baselined):
#   tpu_bin_mappers — the saved-model trailer section name (PR 2), a
#       model-file format token, not a config knob
_DOC_TOKEN_ALLOWED = {"tpu_bin_mappers"}


def _registry_params(project: Project) -> Dict[str, int]:
    """tpu_*/serving_* keys of config.py's _P literal -> lineno."""
    fc = project.file("lightgbm_tpu/config.py")
    if fc is None:
        return {}
    for node in ast.walk(fc.tree):
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "_P" and isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _PREFIX.match(k.value)}
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "_P" and \
                isinstance(node.value, ast.Dict):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                    and _PREFIX.match(k.value)}
    return {}


def _usage_tokens(project: Project) -> Set[str]:
    """Every identifier-ish token that counts as 'reading' a param:
    attribute access (config.tpu_x), Name, keyword arg, or a string
    literal ("tpu_x" lookups / docstring references do NOT count —
    only code-position strings inside calls, e.g. .get("tpu_x"))."""
    used: Set[str] = set()
    # the lint file set usually covers only lightgbm_tpu/, but a param
    # legitimately consumed ONLY by tools/ or the bench/driver scripts
    # (serve_bench reads serving config) must not be reported dead —
    # the message says "package/tools", so the scan reads them too
    used |= _script_tokens(project)
    for fc in project.files:
        if fc.rel.endswith("lightgbm_tpu/config.py"):
            continue  # the registry defining a param is not a read
        for node in ast.walk(fc.tree):
            if isinstance(node, ast.Attribute):
                used.add(node.attr)
            elif isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.keyword) and node.arg:
                used.add(node.arg)
            elif isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                # string params surface as .get("tpu_x") / params
                # dict keys in tests and tools — count them, but only
                # exact identifier-shaped strings (not prose)
                v = node.value.strip()
                if _PREFIX.match(v) and re.fullmatch(r"[a-z0-9_]+", v):
                    used.add(v)
    return used


def _script_tokens(project: Project) -> Set[str]:
    """tpu_*/serving_* word tokens from the non-linted consumer
    scripts (tools/*.py, bench.py, __graft_entry__.py): a word-level
    scan — membership is all P401 needs, and these files may not be in
    the linted set at all."""
    import os

    out: Set[str] = set()
    paths = []
    tools_dir = os.path.join(project.root, "tools")
    if os.path.isdir(tools_dir):
        for dirpath, dirnames, filenames in os.walk(tools_dir):
            # graftlint itself is not a consumer: a param named in a
            # rule comment must not count as "read"
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "graftlint")]
            paths += [os.path.join(dirpath, f) for f in filenames
                      if f.endswith(".py")]
    for extra in ("bench.py", "__graft_entry__.py"):
        paths.append(os.path.join(project.root, extra))
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                out |= set(_DOC_TOKEN.findall(f.read()))
        except OSError:
            continue
    return out


def _facts(project: Project):
    """(params, doc, doc_tokens) computed once per Project — the three
    drift rules share the scan instead of re-parsing the registry and
    re-reading Parameters.md per rule."""
    cached = getattr(project, "_gl_drift_facts", None)
    if cached is None:
        params = _registry_params(project)
        doc = project.read_text("docs", "Parameters.md")
        doc_tokens = set(_DOC_TOKEN.findall(doc)) if doc else set()
        cached = project._gl_drift_facts = (params, doc, doc_tokens)
    return cached


def _check_param_drift(project: Project, which: str):
    """Shared scan; `which` selects the rule so each registered rule
    emits exactly its own findings (--rules P402 must run the P402
    check, and --rules P401 must NOT leak P402/P403 findings)."""
    params, doc, doc_tokens = _facts(project)
    if not params:
        return
    cfg = project.file("lightgbm_tpu/config.py")
    if which == "P401":
        used = _usage_tokens(project)
        for name, lineno in sorted(params.items()):
            if name not in used:
                yield cfg.finding(
                    "P401", lineno,
                    f"config param {name!r} is never read anywhere in "
                    "the package/tools: a knob that silently does "
                    "nothing is a broken user-facing promise.  Wire it "
                    "up or delete the registry entry (and regenerate "
                    "docs/Parameters.md).")
    elif which == "P402" and doc is not None:
        for name, lineno in sorted(params.items()):
            if name not in doc_tokens:
                yield cfg.finding(
                    "P402", lineno,
                    f"config param {name!r} missing from "
                    "docs/Parameters.md — run python "
                    "tools/gen_params_doc.py.")
    elif which == "P403" and doc is not None:
        # aliases and non-tpu params share the doc; only flag tokens
        # that CLAIM the tpu_/serving_ namespace without a registry row
        for tok in sorted(doc_tokens - set(params) - _DOC_TOKEN_ALLOWED):
            yield Finding(
                rule="P403", path="docs/Parameters.md", line=0,
                message=(f"{tok!r} appears in docs/Parameters.md but is "
                         "not a config-registry param: stale doc or a "
                         "typo'd name readers will copy into configs "
                         "that silently no-op.  Fix the doc (or extend "
                         "_DOC_TOKEN_ALLOWED with a justification)."),
                snippet=tok)


register(Rule(
    id="P401", name="param-never-read", family="drift",
    summary=("Every tpu_*/serving_* registry param must be read "
             "somewhere in the package or tools."),
    rationale=(
        "A config knob nobody reads is a silent lie: users set it, "
        "nothing changes, and the failure mode is indistinguishable "
        "from 'the feature is broken'.  The registry/doc generator "
        "keeps names and docs in sync mechanically; this closes the "
        "third leg (code actually consumes the param)."),
    project_check=lambda p: _check_param_drift(p, "P401")))

register(Rule(
    id="P402", name="param-undocumented", family="drift",
    summary="Every tpu_*/serving_* registry param appears in "
            "docs/Parameters.md.",
    rationale=(
        "docs/Parameters.md is generated from the registry "
        "(tools/gen_params_doc.py) and gated by tests/test_params_doc; "
        "this rule catches the window where a param landed without "
        "regenerating, from the lint gate that also runs outside "
        "pytest (multichip dryrun tail)."),
    project_check=lambda p: _check_param_drift(p, "P402")))

register(Rule(
    id="P403", name="doc-param-phantom", family="drift",
    summary=("No tpu_*/serving_* token in docs/Parameters.md without a "
             "registry entry behind it."),
    rationale=(
        "The reverse direction of P402: a documented-but-nonexistent "
        "param is a name readers will copy into configs where it "
        "silently lands in Config.extra and does nothing.  Tokens that "
        "legitimately share the namespace (the tpu_bin_mappers model "
        "trailer) are allow-listed in the rule source with the "
        "justification."),
    project_check=lambda p: _check_param_drift(p, "P403")))
