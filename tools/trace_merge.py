"""Merge a multihost run's per-host telemetry streams into ONE
Perfetto-loadable Chrome trace.

Every host of a `tpu_telemetry=trace` run streams its spans/events as
``events-host<k>.jsonl`` under the shared ``tpu_trace_dir`` (the
incremental JSONL survives a host dying mid-run — exactly the runs
worth reading).  Rank 0 (or any machine that can see the shared
directory) merges them:

    python tools/trace_merge.py <tpu_trace_dir> [-o merged.json]

Each host becomes one Perfetto process row (pid = host index, named
``lightgbm_tpu host k``); span nesting/threads are preserved per host.
Host clocks are independent monotonic origins, so rows are aligned per
host, not globally — good enough to see which host stalled in which
collective, which is the question multihost traces exist to answer.
Malformed trailing lines (a host died mid-write) are skipped with a
count, never an error.
"""

import argparse
import glob
import json
import os
import re
import sys


def merge(trace_dir: str):
    """-> (chrome_trace_dict, per_host_line_counts, skipped_lines)."""
    paths = sorted(glob.glob(os.path.join(trace_dir, "events-host*.jsonl")))
    if not paths:
        raise FileNotFoundError(
            f"no events-host*.jsonl under {trace_dir!r} — was the run "
            "launched with tpu_telemetry=trace and tpu_trace_dir set?")
    events = []
    counts = {}
    skipped = 0
    for path in paths:
        m = re.search(r"events-host(\d+)\.jsonl$", path)
        host = int(m.group(1)) if m else 0
        events.append({"name": "process_name", "ph": "M", "pid": host,
                       "tid": 0, "args": {"name": f"lightgbm_tpu host {host}"}})
        n = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    skipped += 1  # torn tail of a dying host
                    continue
                rec = {"name": ev.get("name", "?"),
                       "ph": "X" if ev.get("kind") == "span" else "i",
                       "ts": float(ev.get("ts_us", 0.0)),
                       "pid": int(ev.get("host", host)),
                       "tid": int(ev.get("tid", 0)),
                       "args": dict(ev.get("tags") or {})}
                if rec["ph"] == "X":
                    rec["dur"] = float(ev.get("dur_us", 0.0))
                else:
                    rec["s"] = "t"
                events.append(rec)
                n += 1
        counts[host] = n
    return ({"traceEvents": events, "displayTimeUnit": "ms"},
            counts, skipped)


def merge_blackbox(trace_dir: str):
    """Overlay multiple hosts' ``blackbox-host<k>.json`` flight-recorder
    dumps (ISSUE 12) into one wall-clock timeline and answer "who hung
    first".

    Unlike the JSONL span streams (per-host monotonic origins), blackbox
    entries carry epoch seconds — directly comparable across hosts — so
    the overlay can order the LAST thing each host did globally.  The
    hang verdict: for each host, the newest ``span_begin`` with no later
    matching ``span_end`` is its in-flight site; the host whose
    in-flight site has the EARLIEST wall time hung first (its peers'
    later in-flight collectives are them waiting on it).

    -> (overlay dict, per-host verdicts, text report lines)
    """
    paths = sorted(glob.glob(os.path.join(trace_dir,
                                          "blackbox-host*.json")))
    if not paths:
        raise FileNotFoundError(
            f"no blackbox-host*.json under {trace_dir!r} — blackbox "
            "dumps land in tpu_obs_blackbox_dir / "
            "LIGHTGBM_TPU_BLACKBOX_DIR (default: the working directory)")
    hosts = {}
    timeline = []
    for path in paths:
        with open(path) as f:
            rec = json.load(f)
        host = int(rec.get("host", 0))
        entries = rec.get("entries", [])
        for e in entries:
            timeline.append({**e, "host": host})
        # in-flight: newest span_begin whose (name, tid) never ended
        in_flight = None
        ended = set()
        for e in reversed(entries):
            key = (e.get("name"), e.get("tid"))
            if e.get("kind") == "span_end":
                ended.add(key)
            elif e.get("kind") == "span_begin" and key not in ended:
                in_flight = e
                break
        hosts[host] = {"reason": rec.get("reason"),
                       "dump_t": rec.get("t"),
                       "entries": len(entries),
                       "in_flight": in_flight}
    timeline.sort(key=lambda e: e.get("t", 0.0))
    report = []
    # dumps overwrite in place per host, so a shared dir can hold a
    # STALE file from an earlier run; a wide dump-time spread means the
    # verdict below may be comparing different deaths
    dump_ts = [v["dump_t"] for v in hosts.values()
               if isinstance(v.get("dump_t"), (int, float))]
    if len(dump_ts) > 1 and max(dump_ts) - min(dump_ts) > 300.0:
        report.append(
            f"warning: host dump times differ by "
            f"{max(dump_ts) - min(dump_ts):.0f}s — a dump may be stale "
            "from an earlier run; treat the verdict accordingly")
    stuck = [(h, v["in_flight"]) for h, v in sorted(hosts.items())
             if v["in_flight"] is not None]
    for h, v in sorted(hosts.items()):
        flight = v["in_flight"]
        site = flight["name"] if flight else "(none in flight)"
        report.append(f"host {h}: dumped '{v['reason']}' with "
                      f"{v['entries']} entries; in flight: {site}")
    if stuck:
        first = min(stuck, key=lambda hv: hv[1].get("t", 0.0))
        report.append(
            f"verdict: host {first[0]} hung first — entered "
            f"{first[1]['name']!r} at t={first[1].get('t', 0.0):.3f} "
            "and never left; later in-flight sites on other hosts are "
            "peers waiting on it")
    else:
        report.append("verdict: no in-flight collective in any dump "
                      "(the deaths were not hangs)")
    return ({"hosts": hosts, "timeline": timeline}, hosts, report)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir", help="the run's tpu_trace_dir (or, with "
                                      "--blackbox, the blackbox dump dir)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <trace_dir>/merged.json)")
    ap.add_argument("--blackbox", action="store_true",
                    help="overlay blackbox-host*.json flight-recorder "
                         "dumps instead of JSONL span streams and print "
                         "the who-hung-first verdict")
    args = ap.parse_args(argv)
    if args.blackbox:
        out = args.out or os.path.join(args.trace_dir,
                                       "merged-blackbox.json")
        overlay, hosts, report = merge_blackbox(args.trace_dir)
        with open(out, "w") as f:
            json.dump(overlay, f)
        for line in report:
            print(line)
        print(f"overlaid {len(hosts)} host dump(s) -> {out}")
        return out
    out = args.out or os.path.join(args.trace_dir, "merged.json")
    trace, counts, skipped = merge(args.trace_dir)
    with open(out, "w") as f:
        json.dump(trace, f)
    hosts = ", ".join(f"host{k}: {n}" for k, n in sorted(counts.items()))
    print(f"merged {sum(counts.values())} events ({hosts}) -> {out}")
    if skipped:
        print(f"skipped {skipped} malformed line(s) (torn host tails)",
              file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
