"""Merge a multihost run's per-host telemetry streams into ONE
Perfetto-loadable Chrome trace.

Every host of a `tpu_telemetry=trace` run streams its spans/events as
``events-host<k>.jsonl`` under the shared ``tpu_trace_dir`` (the
incremental JSONL survives a host dying mid-run — exactly the runs
worth reading).  Rank 0 (or any machine that can see the shared
directory) merges them:

    python tools/trace_merge.py <tpu_trace_dir> [-o merged.json]

Each host becomes one Perfetto process row (pid = host index, named
``lightgbm_tpu host k``); span nesting/threads are preserved per host.
Host clocks are independent monotonic origins, so rows are aligned per
host, not globally — good enough to see which host stalled in which
collective, which is the question multihost traces exist to answer.
Malformed trailing lines (a host died mid-write) are skipped with a
count, never an error.
"""

import argparse
import glob
import json
import os
import re
import sys


def merge(trace_dir: str):
    """-> (chrome_trace_dict, per_host_line_counts, skipped_lines)."""
    paths = sorted(glob.glob(os.path.join(trace_dir, "events-host*.jsonl")))
    if not paths:
        raise FileNotFoundError(
            f"no events-host*.jsonl under {trace_dir!r} — was the run "
            "launched with tpu_telemetry=trace and tpu_trace_dir set?")
    events = []
    counts = {}
    skipped = 0
    for path in paths:
        m = re.search(r"events-host(\d+)\.jsonl$", path)
        host = int(m.group(1)) if m else 0
        events.append({"name": "process_name", "ph": "M", "pid": host,
                       "tid": 0, "args": {"name": f"lightgbm_tpu host {host}"}})
        n = 0
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    skipped += 1  # torn tail of a dying host
                    continue
                rec = {"name": ev.get("name", "?"),
                       "ph": "X" if ev.get("kind") == "span" else "i",
                       "ts": float(ev.get("ts_us", 0.0)),
                       "pid": int(ev.get("host", host)),
                       "tid": int(ev.get("tid", 0)),
                       "args": dict(ev.get("tags") or {})}
                if rec["ph"] == "X":
                    rec["dur"] = float(ev.get("dur_us", 0.0))
                else:
                    rec["s"] = "t"
                events.append(rec)
                n += 1
        counts[host] = n
    return ({"traceEvents": events, "displayTimeUnit": "ms"},
            counts, skipped)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_dir", help="the run's tpu_trace_dir")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <trace_dir>/merged.json)")
    args = ap.parse_args(argv)
    out = args.out or os.path.join(args.trace_dir, "merged.json")
    trace, counts, skipped = merge(args.trace_dir)
    with open(out, "w") as f:
        json.dump(trace, f)
    hosts = ", ".join(f"host{k}: {n}" for k, n in sorted(counts.items()))
    print(f"merged {sum(counts.values())} events ({hosts}) -> {out}")
    if skipped:
        print(f"skipped {skipped} malformed line(s) (torn host tails)",
              file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
