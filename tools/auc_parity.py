"""Full-scale AUC parity: ours vs the compiled reference on IDENTICAL data.

The north-star metric has two halves — speed (bench.py) and QUALITY: the
reference's published Higgs AUC is 0.845154 CPU / 0.845209-0.845239 GPU
(reference docs/Experiments.rst:127, docs/GPU-Performance.rst:139).  The
real Higgs cannot be fetched here (no egress), so this tool trains BOTH
frameworks on the same materialized dataset file (real data via
--data/LIGHTGBM_TPU_BENCH_DATA when available, else the bench's seeded
Higgs-shaped synthetic) and reports a GPU-Performance.rst-style table.

Usage:
    python tools/auc_parity.py [--rows 1000000] [--trees 500]
        [--leaves 255] [--data FILE] [--skip-ref] [--out docs/AUC_PARITY.md]

The reference runs through `.refbuild/lightgbm` with is_training_metric;
ours runs through the Python API on the identical matrix.  Both report the
final TRAIN AUC (the published Higgs experiments use train AUC, see
Experiments.rst "AUC on the training set").
"""

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
ORACLE = os.path.join(ROOT, ".refbuild", "lightgbm")


def _backend_or_cpu():
    """Probe the tunneled backend out-of-process; pin CPU if dead (the
    axon plugin hangs first-touch on a dead tunnel)."""
    from lightgbm_tpu.utils import backend as bk

    if bk.backend_health() != "ok":
        plat = bk.probe_default_backend(timeout_s=120)
        if plat != "tpu":
            bk.pin_cpu_backend()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--trees", type=int, default=500)
    ap.add_argument("--leaves", type=int, default=255)
    ap.add_argument("--max-bin", type=int, default=63)
    ap.add_argument("--data", default=os.environ.get(
        "LIGHTGBM_TPU_BENCH_DATA", ""))
    ap.add_argument("--skip-ref", action="store_true",
                    help="skip the reference run (ours-only JSON; no "
                         "parity table is written)")
    ap.add_argument("--out", default=os.path.join(ROOT, "docs",
                                                  "AUC_PARITY.md"))
    ap.add_argument("--workdir", default="/tmp/auc_parity")
    args = ap.parse_args()

    os.makedirs(args.workdir, exist_ok=True)
    _backend_or_cpu()
    from bench import make_data  # bench's data rules (real-file override)

    if args.data:
        if not os.path.exists(args.data):
            raise FileNotFoundError(f"--data {args.data!r} does not exist")
        os.environ["LIGHTGBM_TPU_BENCH_DATA"] = args.data
    X, y = make_data(args.rows, 28)
    src = args.data if args.data else f"synthetic(seed=42, n={args.rows})"

    # cache key includes the SOURCE so switching --data never reuses a
    # stale file; both frameworks then train from the same tsv (full
    # %.17g round-trip precision) so "identical data" is literal
    import hashlib

    tag = hashlib.sha1(src.encode()).hexdigest()[:10]
    data_file = os.path.join(args.workdir, f"train_{args.rows}_{tag}.tsv")
    if not os.path.exists(data_file):
        np.savetxt(data_file, np.column_stack([y, X]), delimiter="\t",
                   fmt="%.17g")
    del X, y
    raw = np.loadtxt(data_file, ndmin=2)
    y, X = raw[:, 0], np.ascontiguousarray(raw[:, 1:])
    del raw

    results = {}

    # ---- reference CLI -------------------------------------------------
    if not args.skip_ref:
        t0 = time.time()
        out = subprocess.run(
            [ORACLE, "task=train", f"data={data_file}", "objective=binary",
             f"num_trees={args.trees}", f"num_leaves={args.leaves}",
             "learning_rate=0.1", "min_data_in_leaf=20",
             f"max_bin={args.max_bin}", "metric=auc",
             "is_training_metric=true", "verbosity=2",
             f"output_model={args.workdir}/ref_model.txt"],
            capture_output=True, text=True, cwd=args.workdir,
            timeout=4 * 3600)
        ref_s = time.time() - t0
        assert out.returncode == 0, out.stderr[-800:]
        aucs = [float(ln.rsplit(":", 1)[1]) for ln in out.stdout.splitlines()
                if "auc" in ln and ":" in ln]
        results["ref"] = {"auc": aucs[-1], "seconds": round(ref_s, 1)}

    # ---- ours ----------------------------------------------------------
    import lightgbm_tpu as lgb

    t0 = time.time()
    ds = lgb.Dataset(X, label=y, params={"max_bin": args.max_bin})
    res = {}
    lgb.train({"objective": "binary", "num_leaves": args.leaves,
               "learning_rate": 0.1, "min_data_in_leaf": 20,
               "max_bin": args.max_bin, "metric": "auc",
               "verbosity": -1},
              ds, num_boost_round=args.trees, valid_sets=[ds],
              valid_names=["training"], verbose_eval=False,
              evals_result=res)
    our_s = time.time() - t0
    import jax

    results["ours"] = {"auc": float(res["training"]["auc"][-1]),
                       "seconds": round(our_s, 1),
                       "platform": jax.devices()[0].platform}

    line = {"tool": "auc_parity", "rows": args.rows, "trees": args.trees,
            "leaves": args.leaves, "data": src, **{
                f"{k}_{kk}": vv for k, v in results.items()
                for kk, vv in v.items()}}
    print(json.dumps(line))

    if "ref" in results:
        with open(args.out, "w") as f:
            f.write(
                "# AUC parity on identical data\n\n"
                "Style of reference docs/GPU-Performance.rst:139 "
                "(0.845209 vs 0.845239 on real Higgs).\n\n"
                f"Data: `{src}`  rows={args.rows}  trees={args.trees}  "
                f"leaves={args.leaves}  max_bin={args.max_bin}\n\n"
                "| framework | final train AUC | wall s |\n"
                "|---|---|---|\n"
                f"| reference CPU (.refbuild) | "
                f"{results['ref']['auc']:.6f} | "
                f"{results['ref']['seconds']} |\n"
                f"| lightgbm_tpu ({results['ours']['platform']}) | "
                f"{results['ours']['auc']:.6f} | "
                f"{results['ours']['seconds']} |\n")
            d = abs(results["ref"]["auc"] - results["ours"]["auc"])
            f.write(f"\nDelta: {d:.6f} "
                    f"(reference GPU-parity band is ~0.0001-0.001)\n")
        print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
