"""Generate docs/Parameters.md from the config registry.

The reference generates docs/Parameters.rst from config.h doc comments via
helpers/parameter_generator.py (reference src/io/config_auto.cpp:1-9); the
equivalent here reads `lightgbm_tpu/config.py`'s registry source — section
markers (`# --- name ---`) and the comment block directly above each entry
become the doc's sections and notes.

Usage: python tools/gen_params_doc.py   (rewrites docs/Parameters.md)
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu.config import _P  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def parse_sections_and_notes():
    """registry source -> ({param: section}, {param: note})."""
    src = open(os.path.join(REPO, "lightgbm_tpu", "config.py")).read()
    body = src[src.index("_P:"):]
    section, sections, notes = "", {}, {}
    pending = []
    for line in body.splitlines():
        stripped = line.strip()
        m = re.match(r"# --- (.+?) ---", stripped)
        if m:
            section = m.group(1)
            pending = []
            continue
        if stripped.startswith("#"):
            pending.append(stripped.lstrip("# ").rstrip())
            continue
        m = re.match(r'"(\w+)":\s*\(', stripped)
        if m:
            name = m.group(1)
            sections[name] = section
            if pending:
                notes[name] = " ".join(pending)
            pending = []
        elif not stripped:
            pending = []
        if stripped.startswith("}"):
            break
    return sections, notes


def fmt_default(v):
    if isinstance(v, str):
        return f'`"{v}"`' if v else "`\"\"`"
    if isinstance(v, list):
        return "`[]`" if not v else f"`{v}`"
    return f"`{v}`"


def main(out_path=None):
    sections, notes = parse_sections_and_notes()
    order = []  # section order of first appearance
    for name in _P:
        sec = sections.get(name, "other")
        if sec not in order:
            order.append(sec)

    out = [
        "# Parameters",
        "",
        "Generated from the `lightgbm_tpu/config.py` registry by "
        "`tools/gen_params_doc.py` — do not edit by hand.  Parameter names "
        "and aliases match the reference (LightGBM v2.3.2) parameter "
        "system; `tpu_*` entries are this framework's device knobs.",
        "",
    ]
    for sec in order:
        out.append(f"## {sec}")
        out.append("")
        out.append("| parameter | type | default | aliases | notes |")
        out.append("|---|---|---|---|---|")
        for name, (typ, default, aliases) in _P.items():
            if sections.get(name, "other") != sec:
                continue
            alias_s = ", ".join(f"`{a}`" for a in aliases) or "—"
            note = notes.get(name, "").replace("|", "\\|")
            out.append(f"| `{name}` | {typ} | {fmt_default(default)} | "
                       f"{alias_s} | {note} |")
        out.append("")
    path = out_path or os.path.join(REPO, "docs", "Parameters.md")
    with open(path, "w") as f:
        f.write("\n".join(out))
    print(f"wrote {path}: {len(_P)} parameters, {len(order)} sections")


if __name__ == "__main__":
    main()
