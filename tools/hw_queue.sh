#!/bin/sh
# Round-5 hardware queue: probe the tunneled TPU backend every ~5 min and,
# the moment it answers, run the queued hardware jobs in priority order.
# All results append to docs/HW_RESULTS_r5.log (durable, in-repo).
# Priority order follows VERDICT.md "Next round": official bench record
# first (the round's only non-negotiable), then fast default-validations,
# then profile/sweeps, then the long full-scale AUC parity run.
cd /root/repo
LOG=/root/repo/docs/HW_RESULTS_r5.log
while true; do
  # probe must see the real chip: with the axon factory registered, jax
  # init can "succeed" on the CPU fallback while the tunnel is down, so
  # a bare matmul is not evidence.  probe_default_backend already encodes
  # the throwaway-subprocess + timeout + platform-check logic — reuse it.
  while true; do
    timeout 130 python -c "
import sys
from lightgbm_tpu.utils.backend import probe_default_backend
p = probe_default_backend(timeout_s=110, retries=0)
print('probe ->', p)
sys.exit(0 if p == 'tpu' else 1)" >> /tmp/tunnel_probe.log 2>&1 && break
    sleep 300
  done
  # every job gets a hard timeout: a mid-run tunnel hang must not stall
  # the queue forever (bench's own probe window only bounds startup)
  timeout 5400 python -u bench.py > /tmp/bench_r1.json 2>&1
  timeout 5400 python -u bench.py > /tmp/bench_r2.json 2>&1
  if ! grep -q '"platform": "tpu"' /tmp/bench_r1.json \
     && ! grep -q '"platform": "tpu"' /tmp/bench_r2.json; then
    # nothing worth keeping — a one-line note, not two degraded records
    echo "probe saw TPU but both bench runs degraded; re-arming $(date -u)" >> "$LOG"
    sleep 300
    continue
  fi
  echo "tunnel up at $(date -u)" >> "$LOG"
  cat /tmp/bench_r1.json >> "$LOG"
  echo "--- run2 $(date -u)" >> "$LOG"
  cat /tmp/bench_r2.json >> "$LOG"
  if ! grep -q '"platform": "tpu"' /tmp/bench_r2.json; then
    # run1 reached TPU but the tunnel died mid-cycle; the extended queue
    # needs a live tunnel, so keep run1's record and re-arm the probe
    echo "run2 degraded after a TPU run1; re-arming probe loop $(date -u)" >> "$LOG"
    sleep 300
    continue
  fi
  if ! grep -q '"platform": "tpu"' /tmp/bench_r1.json; then
    # run 1 raced a recovering tunnel and fell back to CPU; take one more
    # TPU run so the log holds two on-chip records (cold-ish + warm)
    echo "--- run3 (run1 was degraded) $(date -u)" >> "$LOG"
    timeout 5400 python -u bench.py > /tmp/bench_r3.json 2>&1
    cat /tmp/bench_r3.json >> "$LOG"
    grep -q '"platform": "tpu"' /tmp/bench_r3.json \
      || echo "run3 also degraded — only one on-chip record this cycle $(date -u)" >> "$LOG"
  fi
  # profile/sweep tools print no platform themselves; stamp the live
  # platform immediately before each so a mid-queue tunnel drop cannot
  # contaminate the log with CPU timings posing as hardware records
  stamp() {
    timeout 130 python -c "
from lightgbm_tpu.utils.backend import probe_default_backend
print('platform-stamp:', probe_default_backend(timeout_s=110, retries=0))" \
      >> "$LOG" 2>&1
  }
  echo "--- packed/vselect TPU validation $(date -u)" >> "$LOG"
  timeout 1200 python -u tools/tpu_validate.py >> "$LOG" 2>&1
  echo "--- bucketed-default bench (BENCH_SHAPE_BUCKETS=32) $(date -u)" >> "$LOG"
  BENCH_SHAPE_BUCKETS=32 timeout 3600 python -u bench.py > /tmp/bench_bk.json 2>&1
  cat /tmp/bench_bk.json >> "$LOG"
  grep -q '"platform": "tpu"' /tmp/bench_bk.json \
    || echo "bucketed bench degraded (not a hardware record)" >> "$LOG"
  echo "--- profile $(date -u)" >> "$LOG"; stamp
  timeout 1800 python -u tools/profile_step.py >> "$LOG" 2>&1
  echo "--- round3 alpha sweep $(date -u)" >> "$LOG"; stamp
  timeout 3600 python -u tools/perf_probe.py round3 >> "$LOG" 2>&1
  echo "--- round4 partition sweep $(date -u)" >> "$LOG"; stamp
  timeout 2400 python -u tools/perf_probe.py round4 >> "$LOG" 2>&1
  echo "--- auc_parity full $(date -u)" >> "$LOG"; stamp
  timeout 10800 python -u tools/auc_parity.py >> "$LOG" 2>&1
  echo "--- decision triage $(date -u)" >> "$LOG"
  timeout 300 python -u tools/hw_decide.py >> "$LOG" 2>&1
  echo DONE >> "$LOG"
  break
done
