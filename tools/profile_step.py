"""Capture a TPU profile of a few boosting iterations and print the op
breakdown (self-time) so grower tuning targets measured hotspots.

Usage: python tools/profile_step.py [n_rows] [iters]
Writes the raw trace under /tmp/lgbm_trace and prints the hlo_op_profile
table parsed via xprof.
"""

import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.backend import host_sync
    from perf_probe import make_data

    X, y = make_data(n)

    ds = lgb.Dataset(X, label=y, params={"max_bin": 255})
    bst = lgb.Booster(params={
        "objective": "binary", "num_leaves": 255, "learning_rate": 0.1,
        "min_data_in_leaf": 20, "max_bin": 255,
        # match the BENCH program exactly (bench.py pins buckets off):
        # the point is attributing ITS ~170 ms/tree, not the bucketed
        # variant's
        "tpu_shape_buckets": 0,
        **json.loads(os.environ.get("EXTRA", "{}"))}, train_set=ds)
    for _ in range(2):  # compile + warm
        bst.update()
    host_sync(bst._driver.train_scores.scores)

    trace_dir = "/tmp/lgbm_trace"
    os.system(f"rm -rf {trace_dir}")
    jax.profiler.start_trace(trace_dir)
    t0 = time.time()
    for _ in range(iters):
        bst.update()
    host_sync(bst._driver.train_scores.scores)
    wall = time.time() - t0
    jax.profiler.stop_trace()
    print(f"{iters} iters in {wall:.2f}s = {iters / wall:.3f} it/s")

    xplanes = glob.glob(f"{trace_dir}/**/*.xplane.pb", recursive=True)
    print("xplane files:", xplanes)
    if not xplanes:
        return
    try:
        from xprof.convert import raw_to_tool_data as r
    except ImportError as exc:
        # the raw trace is still on disk for manual tensorboard use
        print(f"xprof unavailable ({exc}); raw trace kept at {trace_dir}")
        return

    for tool in ("framework_op_stats", "hlo_op_profile", "op_profile"):
        try:
            data, _ = r.xspace_to_tool_data(xplanes, tool, {})
            out = f"/tmp/lgbm_trace/{tool}.out"
            mode = "wb" if isinstance(data, bytes) else "w"
            with open(out, mode) as f:
                f.write(data)
            print(f"wrote {out} ({len(data)} bytes)")
        except Exception as exc:
            print(f"{tool}: {type(exc).__name__}: {str(exc)[:120]}")


if __name__ == "__main__":
    main()
