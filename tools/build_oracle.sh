#!/bin/sh
# Build the reference LightGBM oracle into .refbuild/ for parity tests.
#
# The reference CMake writes its outputs into the SOURCE tree
# (EXECUTABLE_OUTPUT_PATH), so the binaries are moved out afterwards to
# keep /root/reference pristine.
set -e
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
REF="${1:-/root/reference}"
OUT="$ROOT/.refbuild"
if [ -x "$OUT/lightgbm" ] && [ -e "$OUT/lib_lightgbm.so" ]; then
    echo "oracle already built at $OUT"
    exit 0
fi
mkdir -p "$OUT"
cd "$OUT"
cmake "$REF" -DCMAKE_BUILD_TYPE=Release > cmake.log 2>&1
make -j"$(nproc)" > make.log 2>&1 || true
for f in lightgbm lib_lightgbm.so; do
    if [ -e "$REF/$f" ]; then mv "$REF/$f" "$OUT/$f"; fi
done
test -x "$OUT/lightgbm" && test -e "$OUT/lib_lightgbm.so"
echo "oracle built at $OUT"
