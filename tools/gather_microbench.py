"""Microbenchmark TPU lowering of per-row small-table gathers vs rewrites.

The grower's partition step does several [K]- or [L]-table lookups indexed
by a [n] row vector.  XLA's TPU gather for this pattern can serialize; the
candidates below measure the alternatives used to pick the grower's
formulation:

  gather      x[idx] as written
  select      K-way where-select chain
  onehot_dot  one-hot [n, K] @ table [K] contraction
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=30):
    out = fn(*args)
    np.asarray(out)  # sync (tunneled backend: block_until_ready lies)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(out)
    return (time.time() - t0) / iters * 1e3


def main():
    n = 1 << 20
    rng = np.random.default_rng(0)

    for T in (25, 256):
        table = jnp.asarray(rng.normal(size=T).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, T, size=n), dtype=jnp.int32)

        g = jax.jit(lambda t, i: t[i])
        ms = timeit(g, table, idx)
        print(f"T={T:4d} gather      : {ms:8.3f} ms", flush=True)

        def sel(t, i):
            acc = jnp.zeros(n, jnp.float32)
            for k in range(T):
                acc = jnp.where(i == k, t[k], acc)
            return acc
        if T <= 32:
            ms = timeit(jax.jit(sel), table, idx)
            print(f"T={T:4d} select      : {ms:8.3f} ms", flush=True)

        def ohdot(t, i):
            oh = (i[:, None] == jnp.arange(T)).astype(jnp.bfloat16)
            return oh @ t.astype(jnp.bfloat16)
        ms = timeit(jax.jit(ohdot), table, idx)
        print(f"T={T:4d} onehot_dot  : {ms:8.3f} ms", flush=True)

    # take_along_axis pattern: bins_t [F, n], per-row feature index
    F = 28
    bins_t = jnp.asarray(rng.integers(0, 256, size=(F, n)), dtype=jnp.int32)
    f_r = jnp.asarray(rng.integers(0, F, size=n), dtype=jnp.int32)

    taa = jax.jit(lambda b, f: jnp.take_along_axis(b, f[None, :], axis=0)[0])
    ms = timeit(taa, bins_t, f_r)
    print(f"taa [F={F},n] gather   : {ms:8.3f} ms", flush=True)

    K = 25
    sel_feat = jnp.asarray(rng.integers(0, F, size=K), dtype=jnp.int32)
    kk_r = jnp.asarray(rng.integers(0, K, size=n), dtype=jnp.int32)

    def rows_then_select(b, sf, kk):
        rows = b[sf]                     # [K, n] contiguous row gather
        acc = jnp.zeros(n, jnp.int32)
        for k in range(K):
            acc = jnp.where(kk == k, rows[k], acc)
        return acc
    ms = timeit(jax.jit(rows_then_select), bins_t, sel_feat, kk_r)
    print(f"rows[K]+select chain   : {ms:8.3f} ms", flush=True)


if __name__ == "__main__":
    main()
