"""utils/lockcheck: the runtime half of the concurrency contract.

The static graftlint C3xx rules prove declared state is mutated under
its owning lock; lockcheck catches what a static map cannot — dynamic
lock-acquisition ORDER, dispatching with a lock held, and a thread
mutating guarded state without the lock at runtime.  These tests seed
each violation class deliberately (16-thread hammers for the racy
ones) and pin the disabled-mode contract: instrumented locks in the
serving/obs hot paths must be indistinguishable from bare
threading.Lock when the checker is off (the telemetry off-mode
overhead gate in test_telemetry.py covers the <1% end-to-end bound;
here we pin the mechanism).
"""

import threading
import time

import pytest

from lightgbm_tpu.utils import lockcheck


@pytest.fixture(autouse=True)
def _fresh():
    lockcheck.reset()
    lockcheck.enable(False)
    yield
    lockcheck.reset()
    lockcheck.enable(False)


# ---------------------------------------------------------------------------
# lock-order inversion
# ---------------------------------------------------------------------------
class TestLockOrder:
    def test_seeded_inversion_detected(self):
        a = lockcheck.make_lock("test.A")
        b = lockcheck.make_lock("test.B")
        lockcheck.enable()

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        # run sequentially (no real deadlock needed): the ORDER GRAPH
        # records A->B from thread 1, thread 2's B->A closes the cycle
        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
        kinds = [v["kind"] for v in lockcheck.violations()]
        assert "lock-order-inversion" in kinds
        detail = next(v["detail"] for v in lockcheck.violations()
                      if v["kind"] == "lock-order-inversion")
        assert "test.A" in detail and "test.B" in detail

    def test_same_named_distinct_instances_still_invert(self):
        """Two sessions share lock NAMES ('serving.stats'); an A/B vs
        B/A interleave between their DISTINCT locks is a real deadlock
        ingredient and must not hide behind the shared name."""
        a = lockcheck.make_lock("serving.stats")
        b = lockcheck.make_lock("serving.stats")
        lockcheck.enable()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        kinds = [v["kind"] for v in lockcheck.violations()]
        assert "lock-order-inversion" in kinds

    def test_instance_keyed_graph_no_cross_instance_conflation(self):
        """session-1 stats→admission and session-2 admission→stats use
        DIFFERENT lock instances: no inversion exists, none may be
        reported (the name-keyed graph regression)."""
        s1, a1 = (lockcheck.make_lock("serving.stats"),
                  lockcheck.make_lock("serving.admission"))
        s2, a2 = (lockcheck.make_lock("serving.stats"),
                  lockcheck.make_lock("serving.admission"))
        lockcheck.enable()
        with s1:
            with a1:
                pass
        with a2:
            with s2:
                pass
        assert lockcheck.violations() == []

    def test_failed_trylock_does_not_poison_graph(self):
        """trylock-with-backoff is a deadlock-AVOIDANCE pattern: a
        failed non-blocking acquire must not record an order edge, or
        the later legitimate reverse order reads as an inversion."""
        a = lockcheck.make_lock("test.try.A")
        b = lockcheck.make_lock("test.try.B")
        lockcheck.enable()
        holder = threading.Thread(target=lambda: (
            b.acquire(), time.sleep(0.2), b.release()))
        holder.start()
        time.sleep(0.05)
        with a:
            assert not b.acquire(blocking=False)   # busy: backs off
        holder.join()
        with b:                                    # reverse order, safe
            with a:
                pass
        assert lockcheck.violations() == []

    def test_consistent_order_clean(self):
        a = lockcheck.make_lock("test.A2")
        b = lockcheck.make_lock("test.B2")
        lockcheck.enable()
        for _ in range(3):
            with a:
                with b:
                    pass
        assert lockcheck.violations() == []

    def test_rlock_reentry_not_an_edge(self):
        r = lockcheck.make_rlock("test.R")
        lockcheck.enable()
        with r:
            with r:          # re-entry, not a second lock
                assert r.owned()
        assert lockcheck.violations() == []
        assert not r.owned()

    def test_strict_mode_raises_at_site(self):
        a = lockcheck.make_lock("test.A3")
        b = lockcheck.make_lock("test.B3")
        lockcheck.enable(strict=True)
        with a:
            with b:
                pass
        with pytest.raises(lockcheck.LockCheckError):
            with b:
                with a:
                    pass
        # the failed acquire path must not leave phantom held state
        lockcheck.enable(strict=False)
        assert lockcheck.held_names() == []


# ---------------------------------------------------------------------------
# mutation-without-lock: 16-thread hammer
# ---------------------------------------------------------------------------
class TestMutationOwnership:
    N_THREADS = 16
    N_OPS = 200

    class Guarded:
        """A structure following the serving convention: one owning
        lock, check_owned beside every mutation."""

        def __init__(self):
            self.lock = lockcheck.make_lock("test.guarded")
            self.items = []

        def add(self, x, *, honest=True):
            if honest:
                with self.lock:
                    lockcheck.check_owned(self.lock, "items")
                    self.items.append(x)
            else:
                lockcheck.check_owned(self.lock, "items")
                self.items.append(x)

    def test_hammer_honest_mutations_clean(self):
        g = self.Guarded()
        lockcheck.enable()
        threads = [threading.Thread(
            target=lambda: [g.add(i) for i in range(self.N_OPS)])
            for _ in range(self.N_THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(g.items) == self.N_THREADS * self.N_OPS
        assert lockcheck.violations() == []

    def test_hammer_with_seeded_racy_thread(self):
        """15 honest threads + 1 mutating WITHOUT the lock: exactly the
        racy thread's mutations are flagged, honest traffic stays
        clean."""
        g = self.Guarded()
        lockcheck.enable()
        threads = [threading.Thread(
            target=lambda: [g.add(i) for i in range(self.N_OPS)],
            name=f"honest-{k}") for k in range(self.N_THREADS - 1)]
        racy = threading.Thread(
            target=lambda: [g.add(i, honest=False) for i in range(7)],
            name="racy")
        for t in threads + [racy]:
            t.start()
        for t in threads + [racy]:
            t.join()
        vs = lockcheck.violations()
        assert len([v for v in vs
                    if v["kind"] == "mutation-without-lock"]) == 7
        assert all(v["thread"] == "racy" for v in vs)

    def test_check_owned_wrong_lock_object(self):
        lockcheck.enable()
        # a bare threading.Lock is not instrumentable: check_owned must
        # flag it rather than silently passing
        lockcheck.check_owned(threading.Lock(), "raw")
        assert lockcheck.violations()[0]["kind"] == "mutation-without-lock"


# ---------------------------------------------------------------------------
# hold-while-dispatching
# ---------------------------------------------------------------------------
class TestDispatchGuard:
    def test_dispatch_with_lock_held_flagged(self):
        lk = lockcheck.make_lock("test.dispatch")
        lockcheck.enable()
        with lk:
            lockcheck.check_dispatch("fixture.site")
        vs = lockcheck.violations()
        assert len(vs) == 1 and vs[0]["kind"] == "hold-while-dispatching"
        assert "test.dispatch" in vs[0]["detail"]
        assert "fixture.site" in vs[0]["detail"]

    def test_dispatch_without_locks_clean(self):
        lk = lockcheck.make_lock("test.dispatch2")
        lockcheck.enable()
        with lk:
            pass
        lockcheck.check_dispatch("fixture.site")
        assert lockcheck.violations() == []

    def test_serving_dispatch_sites_clean_under_checker(self):
        """The real serving path (registry.predict / batcher dispatch
        guards) runs with the checker armed: no lock is held across a
        dispatch, no inversion across the serving/obs lock set."""
        import numpy as np

        import lightgbm_tpu as lgb
        from lightgbm_tpu.serving import ServingSession

        rng = np.random.default_rng(5)
        X = rng.normal(size=(400, 4))
        y = (X[:, 0] > 0).astype(np.float64)
        params = {"objective": "binary", "num_leaves": 7,
                  "min_data_in_leaf": 5, "verbosity": -1,
                  "num_iterations": 3}
        bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                        num_boost_round=3)
        lockcheck.enable()
        try:
            sess = ServingSession({"serving_warmup": False,
                                   "serving_max_wait_ms": 0.5})
            sess.load("m", booster=bst)
            for _ in range(4):
                out = sess.predict("m", X[:32])
                assert out.shape[0] == 32
            sess.close()
        finally:
            lockcheck.enable(False)
        bad = [v for v in lockcheck.violations()
               if v["kind"] in ("hold-while-dispatching",
                                "lock-order-inversion")]
        assert bad == [], bad


# ---------------------------------------------------------------------------
# disabled-mode overhead mechanism
# ---------------------------------------------------------------------------
class TestDisabledOverhead:
    def test_disabled_acquire_is_delegation_only(self):
        """Disabled acquire/release must do no tracking work: no held
        stack, no owner, no graph edges."""
        lk = lockcheck.make_lock("test.off")
        with lk:
            assert lockcheck.held_names() == []
            assert not lk.owned()
        assert lockcheck.violations() == []

    def test_disabled_checks_are_noops(self):
        lk = lockcheck.make_lock("test.off2")
        lockcheck.check_owned(lk, "x")
        lockcheck.check_dispatch("site")
        assert lockcheck.violations() == []

    def test_disabled_cost_vs_bare_lock(self):
        """Mechanism bound (the end-to-end <1% bound lives in the
        telemetry off-mode gate, which times the REAL train loop): a
        disabled instrumented lock cycle is one flag load + two
        delegated calls.  Python-level __enter__ dispatch makes the
        ratio vs a C-level bare lock inherently noisy on a contended
        box, so the gate is EITHER within 12x of bare (interleaved
        min-of-7 washes drift) OR under an absolute 3us/cycle — a
        serving request does tens of lock cycles, so 3us keeps the
        whole lock bill microseconds against multi-ms requests."""
        bare = threading.Lock()
        inst = lockcheck.make_lock("test.bench")
        n = 20000

        def cycle(lock):
            t0 = time.perf_counter()
            for _ in range(n):
                with lock:
                    pass
            return time.perf_counter() - t0

        cycle(bare), cycle(inst)                     # warm
        bares, insts = [], []
        for _ in range(7):                           # interleaved arms
            bares.append(cycle(bare))
            insts.append(cycle(inst))
        t_bare, t_inst = min(bares), min(insts)
        assert t_inst < t_bare * 12 or t_inst / n < 3e-6, (
            f"disabled lockcheck cycle {t_inst / n * 1e9:.0f}ns vs bare "
            f"{t_bare / n * 1e9:.0f}ns")
