"""two_round streaming load vs the one-pass loader (reference
dataset_loader.cpp:188-216): identical bins when the sample covers the
file; valid training either way when it doesn't."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData

PATH = "/root/reference/examples/binary_classification/binary.train"


class TestTwoRound:
    def test_identical_when_sample_covers(self):
        one = TrainingData.from_file(PATH, Config({}))
        two = TrainingData._from_file_two_round(
            PATH, Config({"two_round": True}), None)
        np.testing.assert_array_equal(one.bins, two.bins)
        np.testing.assert_array_equal(one.metadata.label, two.metadata.label)
        assert [m.to_dict() for m in one.mappers] == \
            [m.to_dict() for m in two.mappers]

    def test_multichunk_identical(self):
        """Chunked streaming must not depend on the chunk size."""
        a = TrainingData._from_file_two_round(
            PATH, Config({"two_round": True}), None, chunk_rows=613)
        b = TrainingData._from_file_two_round(
            PATH, Config({"two_round": True}), None)
        np.testing.assert_array_equal(a.bins, b.bins)

    def test_reservoir_subsample_trains(self):
        """Sampled bin finding (sample < n) still yields a usable dataset
        and close bin boundaries."""
        full = TrainingData.from_file(PATH, Config({}))
        sub = TrainingData._from_file_two_round(
            PATH, Config({"two_round": True,
                          "bin_construct_sample_cnt": 800}), None,
            chunk_rows=977)
        assert sub.bins.shape == full.bins.shape
        # bins from an 800-row sample differ slightly but the row->bin map
        # must stay monotone per feature; spot-check rank correlation
        col = full.bins[:, 0].astype(np.int64)
        col2 = sub.bins[:, 0].astype(np.int64)
        assert np.corrcoef(col, col2)[0, 1] > 0.98

    def test_dataset_api_two_round(self, tmp_path):
        import lightgbm_tpu as lgb
        ds = lgb.Dataset(PATH, params={"two_round": True})
        bst = lgb.train({"objective": "binary", "num_leaves": 15},
                        ds, num_boost_round=5, verbose_eval=False)
        assert bst.num_trees() == 5
