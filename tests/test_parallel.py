"""Parallel tree-learner strategies on the 8-virtual-device CPU mesh.

The reference has NO automated multi-process tests (SURVEY.md §4); this
suite does better by validating all three parallel learners against the
serial grower on a virtual mesh — decision parity at the grower level and
metric parity end-to-end through the user API (the analog of the manual
examples/parallel_learning runbook).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner
from lightgbm_tpu.ops import grower as G


def _problem(n=4096, f=12, seed=7, **cfg):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "max_bin": 32, "num_leaves": 15,
              "min_data_in_leaf": 5, "tpu_block_rows": 512}
    params.update(cfg)
    config = Config(params)
    td = TrainingData.from_matrix(X, y, config)
    return config, td, rng


def _grow_records(config, td, seed=3):
    learner = TPUTreeLearner(config, td)
    rng = np.random.default_rng(seed)
    grad = rng.normal(size=learner.n).astype(np.float32)
    hess = np.abs(rng.normal(size=learner.n)).astype(np.float32) + 0.1
    tree, leaf_ids, out = learner.train(jnp.asarray(grad), jnp.asarray(hess))
    rec = np.asarray(jax.device_get(out["records"]))
    return rec, np.asarray(jax.device_get(leaf_ids)), tree


def _assert_decisions_close(rec_a, rec_b, min_agreement=0.85):
    # same number of splits
    np.testing.assert_array_equal(rec_a[:, G.REC_DID_SPLIT],
                                  rec_b[:, G.REC_DID_SPLIT])
    done = rec_a[:, G.REC_DID_SPLIT] > 0.5
    a = rec_a[done][:, [G.REC_LEAF, G.REC_FEATURE, G.REC_THRESHOLD]]
    b = rec_b[done][:, [G.REC_LEAF, G.REC_FEATURE, G.REC_THRESHOLD]]
    agreement = (a.astype(np.int64) == b.astype(np.int64)).mean()
    assert agreement >= min_agreement, f"decision agreement {agreement:.0%}"


@pytest.fixture
def _x64_reset():
    # deterministic mode flips jax_enable_x64 process-wide; undo so later
    # tests keep the default f32 promotion rules
    yield
    jax.config.update("jax_enable_x64", False)


@pytest.fixture(scope="module")
def serial_run():
    config, td, _ = _problem()
    return _grow_records(config, td), td


class TestStrategyParity:
    def test_data_parallel_matches_serial(self, serial_run):
        (rec_s, leaf_s, _), td = serial_run
        config, _, _ = _problem(tree_learner="data", num_machines=8)
        rec_d, leaf_d, _ = _grow_records(config, td)
        # psum reassociation causes rare f32 gain ties to break differently
        _assert_decisions_close(rec_s, rec_d, 0.85)

    def test_feature_parallel_matches_serial(self, serial_run):
        (rec_s, leaf_s, _), td = serial_run
        config, _, _ = _problem(tree_learner="feature", num_machines=4)
        rec_f, leaf_f, _ = _grow_records(config, td)
        # identical per-feature math + deterministic tie-breaks -> exact
        _assert_decisions_close(rec_s, rec_f, 1.0)
        np.testing.assert_array_equal(leaf_s, leaf_f)
        np.testing.assert_allclose(rec_s[:, G.REC_GAIN],
                                   rec_f[:, G.REC_GAIN], rtol=1e-5)

    def test_data_feature_2d_matches_serial(self, serial_run):
        """2-D (4 data x 2 feature) mesh: rows shard over 'data' with
        histogram psum, features over 'feature' with all_gather+argmax
        (reference parallel_tree_learner.h:25-187 composition)."""
        (rec_s, leaf_s, _), td = serial_run
        config, _, _ = _problem(tree_learner="data_feature", num_machines=8,
                                tpu_feature_shards=2)
        rec_2d, leaf_2d, _ = _grow_records(config, td)
        # the data-psum reassociation noise dominates, like 1-D data mode
        _assert_decisions_close(rec_s, rec_2d, 0.85)

    def test_deterministic_data_feature_2d_exact(self, _x64_reset):
        """f64 accumulation makes the 2-D composition EXACTLY serial:
        psum order stops mattering on the data axis and the feature-axis
        gather/argmax is already deterministic."""
        config_s, td, _ = _problem(deterministic=True)
        rec_s, leaf_s, _ = _grow_records(config_s, td)
        config_2, _, _ = _problem(tree_learner="data_feature",
                                  num_machines=8, tpu_feature_shards=2,
                                  deterministic=True)
        rec_2, leaf_2, _ = _grow_records(config_2, td)
        _assert_decisions_close(rec_s, rec_2, 1.0)
        np.testing.assert_array_equal(leaf_s, leaf_2)
        np.testing.assert_allclose(rec_s[:, G.REC_GAIN],
                                   rec_2[:, G.REC_GAIN], rtol=1e-12)

    def test_data_feature_bad_factorization_raises(self):
        config, td, _ = _problem(tree_learner="data_feature",
                                 num_machines=8, tpu_feature_shards=3)
        with pytest.raises(ValueError, match="tpu_feature_shards"):
            TPUTreeLearner(config, td)

    def test_data_feature_auto_degrades_on_two_machines(self, serial_run):
        # auto (tpu_feature_shards=0) on an unfactorable device count
        # degrades to a (n, 1) mesh instead of crashing
        (rec_s, _, _), td = serial_run
        config, _, _ = _problem(tree_learner="data_feature", num_machines=2)
        learner = TPUTreeLearner(config, td)
        assert (learner.d_shards, learner.f_shards) == (2, 1)
        rec_2, _, _ = _grow_records(config, td)
        _assert_decisions_close(rec_s, rec_2, 0.85)

    def test_deterministic_data_parallel_exact(self, _x64_reset):
        """deterministic=true (f64 accumulation end-to-end, the reference
        HistogramBinEntry representation, bin.h:33-40) makes data-parallel
        decisions EXACTLY match serial — reduction order stops mattering."""
        config_s, td, _ = _problem(deterministic=True)
        rec_s, leaf_s, _ = _grow_records(config_s, td)
        config_d, _, _ = _problem(tree_learner="data", num_machines=8,
                                  deterministic=True)
        rec_d, leaf_d, _ = _grow_records(config_d, td)
        _assert_decisions_close(rec_s, rec_d, 1.0)
        np.testing.assert_array_equal(leaf_s, leaf_d)
        np.testing.assert_allclose(rec_s[:, G.REC_GAIN],
                                   rec_d[:, G.REC_GAIN], rtol=1e-12)

    def test_voting_parallel_matches_data(self, serial_run):
        (rec_s, _, _), td = serial_run
        # top_k >= F: voting degenerates to full data-parallel aggregation
        config, _, _ = _problem(tree_learner="voting", num_machines=8,
                                top_k=12)
        rec_v, _, _ = _grow_records(config, td)
        _assert_decisions_close(rec_s, rec_v, 0.85)

    def test_voting_shard_histograms_sum_to_serial(self):
        """Histogram-level GPU_DEBUG_COMPARE (reference gpu_tree_learner.
        cpp:995-1020): the voting learner's per-shard LOCAL root
        histograms must psum to exactly the serial full histogram — a
        mis-aggregated voting path could still pass root-decision parity,
        this cannot."""
        import jax.numpy as jnp
        from lightgbm_tpu.parallel.strategies import make_strategy_grower

        config, td, rng = _problem(tree_learner="voting", num_machines=8,
                                   top_k=4)
        lv = TPUTreeLearner(config, td)
        ls = TPUTreeLearner(_problem()[0], td)
        grad = jnp.asarray(rng.normal(size=lv.n).astype(np.float32))
        hess = jnp.asarray(
            np.abs(rng.normal(size=lv.n)).astype(np.float32) + 0.1)
        fmask = jnp.ones(lv.f_pad, jnp.float32)
        key = jax.random.PRNGKey(0)

        gv = make_strategy_grower(lv.params, lv.f_pad, "voting", lv.mesh,
                                  voting_k=4, num_columns=lv.g_pad,
                                  debug_hist=True)
        gs = make_strategy_grower(ls.params, ls.f_pad, "serial", None,
                                  num_columns=ls.g_pad, debug_hist=True)
        gm = lv._ones_mask
        out_v = gv(lv.bins_t, lv.pad_vector(grad), lv.pad_vector(hess), gm,
                   fmask, lv.meta, key)
        out_s = gs(ls.bins_t, ls.pad_vector(grad), ls.pad_vector(hess),
                   ls._ones_mask, fmask, ls.meta, key)
        hv = np.asarray(jax.device_get(out_v["root_hist"]))
        hs = np.asarray(jax.device_get(out_s["root_hist"]))
        G_, B_, _ = hs.shape
        summed = hv.reshape(8, G_, B_, 3).sum(axis=0)
        # counts are integer-exact; grad/hess sums see f32 reassociation
        np.testing.assert_array_equal(summed[..., 2], hs[..., 2])
        np.testing.assert_allclose(summed, hs, rtol=2e-4, atol=2e-4)

    def test_voting_small_k_learns(self):
        config, td, _ = _problem(tree_learner="voting", num_machines=8,
                                 top_k=3)
        rec, _, tree = _grow_records(config, td)
        assert rec[0, G.REC_DID_SPLIT] > 0.5
        assert tree.num_leaves > 4

    def test_voting_realistic_k_tracks_serial(self):
        """PV-Tree at top_k < F is an approximation, not an equality: only
        the voted features' histograms are aggregated, so a shard-local
        favorite can displace the global winner and a single displaced
        split renumbers every later leaf (field-wise agreement cascades to
        noise; measured 10% at top_k=4 despite healthy trees).  The stable
        grower-level invariants: the ROOT decision — where the vote sees
        every shard's clear favorite — must match serial exactly, and the
        tree must grow to the same size.  Quality-tracking at realistic k
        is covered end-to-end by TestEndToEnd::test_train_api (top_k=10
        must reach AUC>0.75)."""
        # real logistic gradients (score=0), NOT the fixture's random ones:
        # random grads make every feature a near-tie and the vote a coin
        # flip, while a real objective gives feature 0 a dominant gain
        def real_grad_records(cfg_kw):
            config, td, _ = _problem(**cfg_kw)
            learner = TPUTreeLearner(config, td)
            y = np.asarray(td.metadata.label, np.float32)
            grad = (0.5 - y).astype(np.float32)
            hess = np.full_like(grad, 0.25)
            _, _, out = learner.train(jnp.asarray(grad), jnp.asarray(hess))
            return np.asarray(jax.device_get(out["records"]))

        rec_s = real_grad_records({})
        rec_v = real_grad_records(dict(tree_learner="voting",
                                       num_machines=8, top_k=4))
        np.testing.assert_array_equal(rec_s[:, G.REC_DID_SPLIT],
                                      rec_v[:, G.REC_DID_SPLIT])
        for fld in (G.REC_LEAF, G.REC_FEATURE, G.REC_THRESHOLD):
            assert rec_s[0, fld] == rec_v[0, fld], \
                f"root split field {fld}: {rec_s[0, fld]} vs {rec_v[0, fld]}"

    def test_serial_fallback_warns_on_one_machine(self):
        config, td, _ = _problem(tree_learner="data", num_machines=1)
        learner = TPUTreeLearner(config, td)
        assert learner.strategy == "serial"

    def test_too_many_machines_raises(self):
        config, td, _ = _problem(tree_learner="data", num_machines=64)
        with pytest.raises(ValueError, match="num_machines"):
            TPUTreeLearner(config, td)


class TestEndToEnd:
    """tree_learner config reaches the driver through the public API."""

    @pytest.mark.parametrize("learner_cfg", [
        {"tree_learner": "data", "num_machines": 8},
        {"tree_learner": "feature", "num_machines": 4},
        {"tree_learner": "voting", "num_machines": 8, "top_k": 10},
    ])
    def test_train_api(self, binary_example, learner_cfg):
        import lightgbm_tpu as lgb
        params = {"objective": "binary", "num_leaves": 15, "metric": "auc",
                  "verbosity": -1, "tpu_block_rows": 1024}
        params.update(learner_cfg)
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"])
        bst = lgb.train(params, ds, num_boost_round=15)
        from sklearn.metrics import roc_auc_score
        pred = bst.predict(binary_example["X_test"])
        auc = roc_auc_score(binary_example["y_test"], pred)
        assert auc > 0.75, f"{learner_cfg}: AUC {auc}"

    def test_data_parallel_auc_matches_serial(self, binary_example):
        import lightgbm_tpu as lgb
        from sklearn.metrics import roc_auc_score
        base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
                "tpu_block_rows": 1024}
        aucs = {}
        for name, extra in (("serial", {}),
                            ("data", {"tree_learner": "data",
                                      "num_machines": 8})):
            ds = lgb.Dataset(binary_example["X_train"],
                             label=binary_example["y_train"])
            bst = lgb.train({**base, **extra}, ds, num_boost_round=20)
            pred = bst.predict(binary_example["X_test"])
            aucs[name] = roc_auc_score(binary_example["y_test"], pred)
        assert abs(aucs["serial"] - aucs["data"]) < 0.01, aucs
