"""Plotting smoke tests (role of reference tests/python_package_test/
test_plotting.py): importance bars, metric curves, split-value histograms,
tree digraphs render without error on a trained model."""

import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")
matplotlib.use("Agg")

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(800, 5))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.2).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    res = {}
    vs = ds.create_valid(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "metric": "binary_logloss"}, ds, num_boost_round=5,
                    valid_sets=[vs], verbose_eval=False, evals_result=res)
    return bst, res


class TestPlotting:
    def test_plot_importance(self, trained):
        bst, _ = trained
        ax = lgb.plot_importance(bst)
        assert len(ax.patches) >= 1
        ax2 = lgb.plot_importance(bst, importance_type="gain",
                                  max_num_features=2)
        assert len(ax2.patches) <= 2

    def test_plot_metric(self, trained):
        _, res = trained
        ax = lgb.plot_metric(res)
        assert ax.get_ylabel() == "binary_logloss"
        assert len(ax.get_lines()) == 1

    def test_plot_split_value_histogram(self, trained):
        bst, _ = trained
        used = {int(f) for t in bst.dump_model()["tree_info"]
                if "split_feature" in t["tree_structure"]
                for f in [t["tree_structure"]["split_feature"]]}
        ax = lgb.plot_split_value_histogram(bst, feature=used.pop())
        assert len(ax.patches) >= 1

    def test_tree_digraph(self, trained):
        graphviz = pytest.importorskip("graphviz")
        bst, _ = trained
        g = lgb.create_tree_digraph(bst, tree_index=0)
        assert "yes" in g.source and "no" in g.source
