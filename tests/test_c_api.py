"""C API (ABI) tests: load lib_lightgbm_tpu.so via ctypes and exercise the
LGBM_* surface end to end, the analog of reference tests/c_api_test/
test_.py:12-46 (which loads lib_lightgbm.so directly and drives dataset
creation + booster train/predict at the ABI level)."""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB_PATH = os.path.join(ROOT, "build", "lib_lightgbm_tpu.so")

C_API_DTYPE_FLOAT32 = 0
C_API_DTYPE_FLOAT64 = 1
C_API_DTYPE_INT32 = 2
C_API_PREDICT_NORMAL = 0
C_API_PREDICT_RAW_SCORE = 1


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(LIB_PATH):
        os.makedirs(os.path.dirname(LIB_PATH), exist_ok=True)
        build = subprocess.run(
            [os.path.join(ROOT, "src", "capi", "build.sh"),
             os.path.dirname(LIB_PATH)],
            capture_output=True, text=True)
        if build.returncode != 0:
            pytest.skip(f"C API build failed: {build.stderr[-500:]}")
    os.environ["LIGHTGBM_TPU_PYROOT"] = ROOT
    L = ctypes.CDLL(LIB_PATH)
    L.LGBM_GetLastError.restype = ctypes.c_char_p
    return L


def _check(lib, ret):
    if ret != 0:
        raise RuntimeError(lib.LGBM_GetLastError().decode())


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    n, f = 1200, 6
    X = rng.normal(size=(n, f)).astype(np.float64)
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.4).astype(np.float32)
    return X, y


class TestCAPIDataset:
    def test_create_from_mat_and_fields(self, lib, data):
        X, y = data
        h = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int(1), b"max_bin=63", None, ctypes.byref(h)))
        assert h.value

        nd = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(h, ctypes.byref(nd)))
        assert nd.value == X.shape[0]
        nf = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumFeature(h, ctypes.byref(nf)))
        assert nf.value == X.shape[1]

        _check(lib, lib.LGBM_DatasetSetField(
            h, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y)), C_API_DTYPE_FLOAT32))

        out_len = ctypes.c_int()
        out_ptr = ctypes.c_void_p()
        out_type = ctypes.c_int()
        _check(lib, lib.LGBM_DatasetGetField(
            h, b"label", ctypes.byref(out_len), ctypes.byref(out_ptr),
            ctypes.byref(out_type)))
        assert out_len.value == len(y)
        assert out_type.value == C_API_DTYPE_FLOAT32
        got = np.ctypeslib.as_array(
            ctypes.cast(out_ptr, ctypes.POINTER(ctypes.c_float)),
            shape=(out_len.value,))
        np.testing.assert_allclose(got, y)
        _check(lib, lib.LGBM_DatasetFree(h))

    def test_create_from_file(self, lib):
        path = os.path.join("/root/reference/examples/binary_classification",
                            "binary.train")
        h = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromFile(
            path.encode(), b"max_bin=255", None, ctypes.byref(h)))
        nd = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(h, ctypes.byref(nd)))
        assert nd.value == 7000
        _check(lib, lib.LGBM_DatasetFree(h))

    def test_error_reporting(self, lib):
        h = ctypes.c_void_p()
        ret = lib.LGBM_DatasetCreateFromFile(
            b"/nonexistent/file.csv", b"", None, ctypes.byref(h))
        assert ret == -1
        assert len(lib.LGBM_GetLastError()) > 0


class TestCAPIBooster:
    def test_train_eval_predict_cycle(self, lib, data, tmp_path):
        X, y = data
        dh = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int(1), b"max_bin=63", None, ctypes.byref(dh)))
        _check(lib, lib.LGBM_DatasetSetField(
            dh, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y)), C_API_DTYPE_FLOAT32))

        bh = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            dh, b"objective=binary metric=binary_logloss num_leaves=15 "
                b"min_data_in_leaf=10 learning_rate=0.2",
            ctypes.byref(bh)))

        ncls = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetNumClasses(bh, ctypes.byref(ncls)))
        assert ncls.value == 1

        fin = ctypes.c_int()
        for _ in range(20):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)))
        it = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetCurrentIteration(bh, ctypes.byref(it)))
        assert it.value == 20

        cnt = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetEvalCounts(bh, ctypes.byref(cnt)))
        assert cnt.value == 1
        res = (ctypes.c_double * cnt.value)()
        out_len = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterGetEval(bh, 0, ctypes.byref(out_len), res))
        assert out_len.value == 1
        assert 0.0 < res[0] < 0.6  # training logloss after 20 iters

        n = X.shape[0]
        pred = (ctypes.c_double * n)()
        plen = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(n), ctypes.c_int32(X.shape[1]), ctypes.c_int(1),
            C_API_PREDICT_NORMAL, ctypes.c_int(0), b"",
            ctypes.byref(plen), pred))
        assert plen.value == n
        p = np.ctypeslib.as_array(pred)
        assert ((p > 0.5) == (y > 0.5)).mean() > 0.85

        model_file = str(tmp_path / "capi_model.txt").encode()
        _check(lib, lib.LGBM_BoosterSaveModel(bh, 0, model_file))
        assert os.path.exists(model_file.decode())

        # round-trip through the model file
        bh2 = ctypes.c_void_p()
        iters = ctypes.c_int()
        _check(lib, lib.LGBM_BoosterCreateFromModelfile(
            model_file, ctypes.byref(iters), ctypes.byref(bh2)))
        assert iters.value == 20
        pred2 = (ctypes.c_double * n)()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh2, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(n), ctypes.c_int32(X.shape[1]), ctypes.c_int(1),
            C_API_PREDICT_NORMAL, ctypes.c_int(0), b"",
            ctypes.byref(plen), pred2))
        np.testing.assert_allclose(np.ctypeslib.as_array(pred2), p,
                                   rtol=1e-6)

        _check(lib, lib.LGBM_BoosterFree(bh))
        _check(lib, lib.LGBM_BoosterFree(bh2))
        _check(lib, lib.LGBM_DatasetFree(dh))

    def test_custom_objective_update(self, lib, data):
        X, y = data
        dh = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int(1), b"max_bin=63", None, ctypes.byref(dh)))
        _check(lib, lib.LGBM_DatasetSetField(
            dh, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(len(y)), C_API_DTYPE_FLOAT32))
        bh = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            dh, b"objective=none num_leaves=15 min_data_in_leaf=10",
            ctypes.byref(bh)))
        n = X.shape[0]
        score = np.zeros(n, np.float64)
        fin = ctypes.c_int()
        for _ in range(5):
            p = 1.0 / (1.0 + np.exp(-score))
            grad = (p - y).astype(np.float32)
            hess = (p * (1 - p)).astype(np.float32)
            _check(lib, lib.LGBM_BoosterUpdateOneIterCustom(
                bh, grad.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                hess.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                ctypes.byref(fin)))
            pred = (ctypes.c_double * n)()
            plen = ctypes.c_int64()
            _check(lib, lib.LGBM_BoosterPredictForMat(
                bh, X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
                ctypes.c_int32(n), ctypes.c_int32(X.shape[1]),
                ctypes.c_int(1), C_API_PREDICT_RAW_SCORE, ctypes.c_int(0),
                b"", ctypes.byref(plen), pred))
            score = np.ctypeslib.as_array(pred).copy()
        acc = ((1 / (1 + np.exp(-score)) > 0.5) == (y > 0.5)).mean()
        assert acc > 0.8

    def test_network_init(self, lib):
        _check(lib, lib.LGBM_NetworkInit(b"127.0.0.1:12400", 12400, 120, 1))
        _check(lib, lib.LGBM_NetworkFree())
        # single-machine injected collectives are a no-op success
        assert lib.LGBM_NetworkInitWithFunctions(1, 0, None, None) == 0
        # real multi-machine injection must fail loudly
        assert lib.LGBM_NetworkInitWithFunctions(4, 0, None, None) == -1


class TestCAPIDatasetBinary:
    def test_save_binary(self, lib, data, tmp_path):
        X, y = data
        h = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int(1), b"max_bin=63", None, ctypes.byref(h)))
        out = str(tmp_path / "ds.bin").encode()
        _check(lib, lib.LGBM_DatasetSaveBinary(h, out))
        assert os.path.exists(out.decode())
        from lightgbm_tpu.io.dataset import TrainingData
        td = TrainingData.from_binary(out.decode())
        assert td.num_data == X.shape[0]
        _check(lib, lib.LGBM_DatasetFree(h))


class TestCAPIBreadth:
    """Round-3 additions: booster mutation, file predict, dataset subset
    and feature names (reference c_api.h:286-470,644-720,905-960)."""

    def _make_booster(self, lib, data, rounds=5):
        X, y = data
        dh = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            X.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[0]), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(1), b"max_bin=32", None, ctypes.byref(dh)))
        _check(lib, lib.LGBM_DatasetSetField(
            dh, b"label", y.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(len(y)), C_API_DTYPE_FLOAT32))
        bh = ctypes.c_void_p()
        _check(lib, lib.LGBM_BoosterCreate(
            dh, b"objective=binary num_leaves=7 min_data_in_leaf=5",
            ctypes.byref(bh)))
        fin = ctypes.c_int32()
        for _ in range(rounds):
            _check(lib, lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)))
        return dh, bh

    def test_leaf_value_get_set(self, lib, data):
        _, bh = self._make_booster(lib, data)
        val = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(bh, 0, 0,
                                                 ctypes.byref(val)))
        _check(lib, lib.LGBM_BoosterSetLeafValue(bh, 0, 0,
                                                 ctypes.c_double(1.25)))
        val2 = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(bh, 0, 0,
                                                 ctypes.byref(val2)))
        assert val2.value == 1.25 and val2.value != val.value

    def test_merge_and_shuffle(self, lib, data):
        _, bh1 = self._make_booster(lib, data, rounds=3)
        _, bh2 = self._make_booster(lib, data, rounds=2)
        n1, n2 = ctypes.c_int32(), ctypes.c_int32()
        _check(lib, lib.LGBM_BoosterNumberOfTotalModel(
            bh1, ctypes.byref(n1)))
        _check(lib, lib.LGBM_BoosterNumberOfTotalModel(
            bh2, ctypes.byref(n2)))
        _check(lib, lib.LGBM_BoosterMerge(bh1, bh2))
        n3 = ctypes.c_int32()
        _check(lib, lib.LGBM_BoosterNumberOfTotalModel(
            bh1, ctypes.byref(n3)))
        assert n3.value == n1.value + n2.value
        _check(lib, lib.LGBM_BoosterShuffleModels(bh1, 0, -1))

    def test_reset_parameter(self, lib, data):
        _, bh = self._make_booster(lib, data)
        _check(lib, lib.LGBM_BoosterResetParameter(
            bh, b"learning_rate=0.05"))

    def test_predict_for_file(self, lib, data, tmp_path):
        X, y = data
        _, bh = self._make_booster(lib, data)
        src = tmp_path / "pred_in.tsv"
        np.savetxt(src, np.column_stack([y, X]), delimiter="\t")
        out = tmp_path / "pred_out.txt"
        _check(lib, lib.LGBM_BoosterPredictForFile(
            bh, str(src).encode(), 0, C_API_PREDICT_NORMAL, -1, b"",
            str(out).encode()))
        got = np.loadtxt(out)
        assert got.shape == (len(y),)
        assert 0.0 <= got.min() and got.max() <= 1.0

    def test_feature_names_roundtrip(self, lib, data):
        dh, _ = self._make_booster(lib, data)
        names = [b"alpha", b"beta", b"gamma", b"delta", b"eps", b"zeta"]
        arr = (ctypes.c_char_p * len(names))(*names)
        _check(lib, lib.LGBM_DatasetSetFeatureNames(
            dh, arr, ctypes.c_int32(len(names))))
        bufs = [ctypes.create_string_buffer(64) for _ in names]
        ptrs = (ctypes.c_char_p * len(names))(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        cnt = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetFeatureNames(
            dh, ptrs, ctypes.byref(cnt)))
        assert cnt.value == len(names)
        assert [b.value for b in bufs] == names

    def test_dataset_subset(self, lib, data):
        dh, _ = self._make_booster(lib, data)
        idx = np.arange(0, 600, 2, dtype=np.int32)
        sub = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetGetSubset(
            dh, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(len(idx)), b"", ctypes.byref(sub)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(sub, ctypes.byref(n)))
        assert n.value == len(idx)


class TestCAPIBreadth2:
    """Second breadth batch: single-row / CSR predict, multi-mat dataset,
    booster introspection, SetLastError."""

    def test_set_last_error(self, lib):
        lib.LGBM_SetLastError(b"custom message")
        assert lib.LGBM_GetLastError() == b"custom message"

    def test_num_model_per_iteration_and_names(self, lib, data):
        helper = TestCAPIBreadth()
        _, bh = helper._make_booster(lib, data)
        k = ctypes.c_int32()
        _check(lib, lib.LGBM_BoosterNumModelPerIteration(bh, ctypes.byref(k)))
        assert k.value == 1
        bufs = [ctypes.create_string_buffer(64) for _ in range(6)]
        ptrs = (ctypes.c_char_p * 6)(
            *[ctypes.cast(b, ctypes.c_char_p) for b in bufs])
        cnt = ctypes.c_int32()
        # NOTE reference v2.3.2 order: (handle, out_len, out_strs)
        _check(lib, lib.LGBM_BoosterGetFeatureNames(bh, ctypes.byref(cnt),
                                                    ptrs))
        assert cnt.value == 6
        assert bufs[0].value == b"Column_0"

    def test_predict_single_row_and_csr(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        _, bh = helper._make_booster(lib, data)
        # dense single row
        row = np.ascontiguousarray(X[0])
        out_len = ctypes.c_int64()
        out = np.zeros(1, np.float64)
        _check(lib, lib.LGBM_BoosterPredictForMatSingleRow(
            bh, row.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1),
            C_API_PREDICT_NORMAL, -1, b"", ctypes.byref(out_len),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert out_len.value == 1
        # CSR of the first 5 rows must reproduce dense predictions
        import scipy.sparse as sp
        Xs = sp.csr_matrix(X[:5])
        out5 = np.zeros(5, np.float64)
        len5 = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForCSR(
            bh, Xs.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_INT32),
            Xs.indices.astype(np.int32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)),
            Xs.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_FLOAT64),
            ctypes.c_int64(len(Xs.indptr)), ctypes.c_int64(Xs.nnz),
            ctypes.c_int64(X.shape[1]), C_API_PREDICT_NORMAL, -1, b"",
            ctypes.byref(len5),
            out5.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert len5.value == 5
        dense_out = np.zeros(5, np.float64)
        dl = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh, np.ascontiguousarray(X[:5]).ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(5),
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1),
            C_API_PREDICT_NORMAL, -1, b"", ctypes.byref(dl),
            dense_out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        np.testing.assert_allclose(out5, dense_out, rtol=1e-12)

    def test_dataset_from_mats(self, lib, data):
        X, y = data
        a = np.ascontiguousarray(X[:400])
        b = np.ascontiguousarray(X[400:])
        ptrs = (ctypes.c_void_p * 2)(a.ctypes.data_as(ctypes.c_void_p),
                                     b.ctypes.data_as(ctypes.c_void_p))
        nrows = np.asarray([len(a), len(b)], np.int32)
        dh = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromMats(
            ctypes.c_int32(2), ptrs, C_API_DTYPE_FLOAT64,
            nrows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1), b"max_bin=32",
            None, ctypes.byref(dh)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(dh, ctypes.byref(n)))
        assert n.value == len(X)


class TestCAPIBreadth3:
    """Third batch: maintained-score retrieval, param updates, streaming
    row push, text dump."""

    def test_get_predict_matches_scores(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        dh, bh = helper._make_booster(lib, data)
        n_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterGetNumPredict(bh, 0,
                                                  ctypes.byref(n_len)))
        assert n_len.value == len(y)
        out = np.zeros(len(y), np.float64)
        got = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterGetPredict(
            bh, 0, ctypes.byref(got),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert got.value == len(y)
        # maintained train scores == raw predictions on training data
        pred = np.zeros(len(y), np.float64)
        pl = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh, np.ascontiguousarray(X).ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(len(y)),
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1),
            C_API_PREDICT_NORMAL, -1, b"", ctypes.byref(pl),
            pred.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        # GetPredict applies ConvertOutput (sigmoid here), like the
        # reference GBDT::GetPredictAt
        np.testing.assert_allclose(out, pred, rtol=1e-5, atol=1e-5)

    def test_update_param_guards_frozen_keys(self, lib, data):
        helper = TestCAPIBreadth()
        dh, _ = helper._make_booster(lib, data)
        _check(lib, lib.LGBM_DatasetUpdateParam(dh, b"learning_rate=0.2"))
        assert lib.LGBM_DatasetUpdateParam(dh, b"max_bin=64") != 0
        assert b"max_bin" in lib.LGBM_GetLastError()

    def test_push_rows_roundtrip(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        ref_dh, _ = helper._make_booster(lib, data)
        out = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateByReference(
            ref_dh, ctypes.c_int64(200), ctypes.byref(out)))
        a = np.ascontiguousarray(X[:120])
        b = np.ascontiguousarray(X[120:200])
        _check(lib, lib.LGBM_DatasetPushRows(
            out, a.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(120), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(0)))
        _check(lib, lib.LGBM_DatasetPushRows(
            out, b.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(80), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(120)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(out, ctypes.byref(n)))
        assert n.value == 200

    def test_push_rows_incomplete_rejected(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        ref_dh, _ = helper._make_booster(lib, data)
        out = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateByReference(
            ref_dh, ctypes.c_int64(100), ctypes.byref(out)))
        a = np.ascontiguousarray(X[:60])
        _check(lib, lib.LGBM_DatasetPushRows(
            out, a.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(60), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(0)))
        n = ctypes.c_int32()
        assert lib.LGBM_DatasetGetNumData(out, ctypes.byref(n)) != 0
        assert b"never pushed" in lib.LGBM_GetLastError()

    def test_dump_text(self, lib, data, tmp_path):
        helper = TestCAPIBreadth()
        dh, _ = helper._make_booster(lib, data)
        path = str(tmp_path / "dump.txt")
        _check(lib, lib.LGBM_DatasetDumpText(dh, path.encode()))
        lines = open(path).read().splitlines()
        assert lines[0].startswith("num_data: ")
        assert len(lines) == 3 + 1200


class TestCAPIBreadth4:
    """Fourth batch: CSC create/predict, single-row CSR, AddFeaturesFrom."""

    def test_csc_create_and_predict(self, lib, data):
        import scipy.sparse as sp
        X, y = data
        helper = TestCAPIBreadth()
        _, bh = helper._make_booster(lib, data)
        Xc = sp.csc_matrix(X[:50])
        out = np.zeros(50, np.float64)
        n = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForCSC(
            bh, Xc.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_INT32),
            Xc.indices.astype(np.int32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)),
            Xc.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_FLOAT64),
            ctypes.c_int64(len(Xc.indptr)), ctypes.c_int64(Xc.nnz),
            ctypes.c_int64(50), C_API_PREDICT_NORMAL, -1, b"",
            ctypes.byref(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert n.value == 50
        dense = np.zeros(50, np.float64)
        dl = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh, np.ascontiguousarray(X[:50]).ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(50),
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1),
            C_API_PREDICT_NORMAL, -1, b"", ctypes.byref(dl),
            dense.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        np.testing.assert_allclose(out, dense, rtol=1e-12)
        # dataset creation from the same CSC must match the mat dataset size
        dh = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromCSC(
            Xc.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_INT32),
            Xc.indices.astype(np.int32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)),
            Xc.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_FLOAT64),
            ctypes.c_int64(len(Xc.indptr)), ctypes.c_int64(Xc.nnz),
            ctypes.c_int64(50), b"max_bin=16", None, ctypes.byref(dh)))
        nd = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(dh, ctypes.byref(nd)))
        assert nd.value == 50

    def test_csr_single_row(self, lib, data):
        import scipy.sparse as sp
        X, y = data
        helper = TestCAPIBreadth()
        _, bh = helper._make_booster(lib, data)
        row = sp.csr_matrix(X[:1])
        out = np.zeros(1, np.float64)
        n = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForCSRSingleRow(
            bh, row.indptr.astype(np.int32).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_INT32),
            row.indices.astype(np.int32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)),
            row.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_FLOAT64),
            ctypes.c_int64(2), ctypes.c_int64(row.nnz),
            ctypes.c_int64(X.shape[1]), C_API_PREDICT_NORMAL, -1, b"",
            ctypes.byref(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert n.value == 1 and 0.0 <= out[0] <= 1.0

    def test_add_features_from(self, lib, data):
        X, y = data
        a1 = np.ascontiguousarray(X[:, :3])
        a2 = np.ascontiguousarray(X[:, 3:])
        handles = []
        for arr in (a1, a2):
            h = ctypes.c_void_p()
            _check(lib, lib.LGBM_DatasetCreateFromMat(
                arr.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
                ctypes.c_int32(arr.shape[0]), ctypes.c_int32(arr.shape[1]),
                ctypes.c_int32(1), b"max_bin=32", None, ctypes.byref(h)))
            handles.append(h)
        _check(lib, lib.LGBM_DatasetAddFeaturesFrom(handles[0], handles[1]))
        nf = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumFeature(handles[0],
                                                  ctypes.byref(nf)))
        assert nf.value == X.shape[1]


class TestCAPIBreadth5:
    """Fifth batch: reset training data (continued training on new rows),
    multi-matrix predict."""

    def test_reset_training_data_continues(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        dh, bh = helper._make_booster(lib, data, rounds=3)
        # new dataset aligned with the old one's mappers
        new = ctypes.c_void_p()
        half = np.ascontiguousarray(X[:600])
        _check(lib, lib.LGBM_DatasetCreateFromMat(
            half.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(600), ctypes.c_int32(X.shape[1]),
            ctypes.c_int32(1), b"max_bin=32", dh, ctypes.byref(new)))
        yh = np.ascontiguousarray(y[:600])
        _check(lib, lib.LGBM_DatasetSetField(
            new, b"label", yh.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(600), C_API_DTYPE_FLOAT32))
        _check(lib, lib.LGBM_BoosterResetTrainingData(bh, new))
        fin = ctypes.c_int32()
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bh, ctypes.byref(fin)))
        total = ctypes.c_int32()
        _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bh,
                                                       ctypes.byref(total)))
        assert total.value == 4
        n_len = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterGetNumPredict(bh, 0,
                                                  ctypes.byref(n_len)))
        assert n_len.value == 600

    def test_predict_for_mats(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        _, bh = helper._make_booster(lib, data)
        a = np.ascontiguousarray(X[:30])
        b = np.ascontiguousarray(X[30:80])
        ptrs = (ctypes.c_void_p * 2)(a.ctypes.data_as(ctypes.c_void_p),
                                     b.ctypes.data_as(ctypes.c_void_p))
        nrows = np.asarray([30, 50], np.int32)
        out = np.zeros(80, np.float64)
        n = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMats(
            bh, ptrs, C_API_DTYPE_FLOAT64, ctypes.c_int32(80),
            nrows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(2), ctypes.c_int32(X.shape[1]),
            C_API_PREDICT_NORMAL, -1, b"", ctypes.byref(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        assert n.value == 80
        dense = np.zeros(80, np.float64)
        dl = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh, np.ascontiguousarray(X[:80]).ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(80),
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1),
            C_API_PREDICT_NORMAL, -1, b"", ctypes.byref(dl),
            dense.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        np.testing.assert_allclose(out, dense, rtol=1e-12)


class TestCAPIBreadth6:
    """Final batch: leaf-pred refit, CSR row push, sampled-column
    creation, std::function CSR callback."""

    def test_refit_by_leaf_preds(self, lib, data):
        X, y = data
        helper = TestCAPIBreadth()
        dh, bh = helper._make_booster(lib, data, rounds=4)
        total = ctypes.c_int32()
        _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bh,
                                                       ctypes.byref(total)))
        # leaf assignment of the training rows under the current model
        leaves = np.zeros((len(y), total.value), np.float64)
        ll = ctypes.c_int64()
        _check(lib, lib.LGBM_BoosterPredictForMat(
            bh, np.ascontiguousarray(X).ctypes.data_as(ctypes.c_void_p),
            C_API_DTYPE_FLOAT64, ctypes.c_int32(len(y)),
            ctypes.c_int32(X.shape[1]), ctypes.c_int32(1),
            2, -1, b"", ctypes.byref(ll),  # 2 = leaf-index predict
            leaves.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
        lp = np.ascontiguousarray(leaves.astype(np.int32))
        v0 = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(bh, 0, 0,
                                                 ctypes.byref(v0)))
        _check(lib, lib.LGBM_BoosterRefit(
            bh, lp.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(len(y)), ctypes.c_int32(total.value)))
        v1 = ctypes.c_double()
        _check(lib, lib.LGBM_BoosterGetLeafValue(bh, 0, 0,
                                                 ctypes.byref(v1)))
        assert v0.value != v1.value  # decay-blended toward the refit value

    def test_push_rows_by_csr(self, lib, data):
        import scipy.sparse as sp
        X, y = data
        helper = TestCAPIBreadth()
        ref_dh, _ = helper._make_booster(lib, data)
        out = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateByReference(
            ref_dh, ctypes.c_int64(90), ctypes.byref(out)))
        blk = sp.csr_matrix(X[:90])
        _check(lib, lib.LGBM_DatasetPushRowsByCSR(
            out, blk.indptr.astype(np.int32).ctypes.data_as(
                ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_INT32),
            blk.indices.astype(np.int32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)),
            blk.data.astype(np.float64).ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(C_API_DTYPE_FLOAT64),
            ctypes.c_int64(len(blk.indptr)), ctypes.c_int64(blk.nnz),
            ctypes.c_int64(X.shape[1]), ctypes.c_int64(0)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(out, ctypes.byref(n)))
        assert n.value == 90

    def test_create_from_sampled_column(self, lib, data):
        X, y = data
        ncol = X.shape[1]
        nsample = 300
        cols = [np.ascontiguousarray(X[:nsample, c]) for c in range(ncol)]
        idxs = [np.arange(nsample, dtype=np.int32) for _ in range(ncol)]
        col_ptrs = (ctypes.c_void_p * ncol)(
            *[c.ctypes.data_as(ctypes.c_void_p) for c in cols])
        idx_ptrs = (ctypes.c_void_p * ncol)(
            *[i.ctypes.data_as(ctypes.c_void_p) for i in idxs])
        counts = np.full(ncol, nsample, np.int32)
        out = ctypes.c_void_p()
        _check(lib, lib.LGBM_DatasetCreateFromSampledColumn(
            col_ptrs, idx_ptrs, ctypes.c_int32(ncol),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_int32(nsample), ctypes.c_int32(500), b"max_bin=32",
            ctypes.byref(out)))
        blk = np.ascontiguousarray(X[:500])
        _check(lib, lib.LGBM_DatasetPushRows(
            out, blk.ctypes.data_as(ctypes.c_void_p), C_API_DTYPE_FLOAT64,
            ctypes.c_int32(500), ctypes.c_int32(ncol), ctypes.c_int32(0)))
        n = ctypes.c_int32()
        _check(lib, lib.LGBM_DatasetGetNumData(out, ctypes.byref(n)))
        assert n.value == 500
