"""Helpers to drive the compiled reference LightGBM as a parity oracle.

The reference binary/library is built out-of-tree into .refbuild/ by CI setup;
tests using it skip automatically when it is absent.  We only ever *run* the
reference — no reference code is copied.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Dict, List, Optional

import numpy as np

from .conftest import ORACLE_BIN, ORACLE_LIB


def run_cli(conf: Dict[str, str], cwd: str) -> str:
    """Run the reference CLI with the given config params; return stdout."""
    args = [ORACLE_BIN] + [f"{k}={v}" for k, v in conf.items()]
    out = subprocess.run(args, cwd=cwd, capture_output=True, text=True, timeout=600)
    if out.returncode != 0:
        raise RuntimeError(f"oracle failed: {out.stdout}\n{out.stderr}")
    return out.stdout


_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        _LIB = ctypes.CDLL(ORACLE_LIB)
    return _LIB


def dump_dataset_bins(data_file: str, params: str = "") -> Dict:
    """Bin a data file with the reference loader and parse the bin dump.

    Returns {"num_features", "num_data", "bins": [n, num_total_features] int
    array with -1 for unused (trivial) features}.
    """
    lib = _lib()
    handle = ctypes.c_void_p()
    ret = lib.LGBM_DatasetCreateFromFile(
        data_file.encode(), params.encode(), None, ctypes.byref(handle))
    if ret != 0:
        lib.LGBM_GetLastError.restype = ctypes.c_char_p
        raise RuntimeError(lib.LGBM_GetLastError().decode())
    with tempfile.NamedTemporaryFile(suffix=".txt", delete=False) as f:
        dump_path = f.name
    try:
        ret = lib.LGBM_DatasetDumpText(handle, dump_path.encode())
        assert ret == 0
        with open(dump_path) as f:
            text = f.read()
    finally:
        lib.LGBM_DatasetFree(handle)
        os.unlink(dump_path)

    lines = text.split("\n")
    meta = {}
    row_start = None
    for i, line in enumerate(lines):
        if line.startswith("num_features:"):
            meta["num_features"] = int(line.split(":")[1])
        elif line.startswith("num_total_features:"):
            meta["num_total_features"] = int(line.split(":")[1])
        elif line.startswith("num_data:"):
            meta["num_data"] = int(line.split(":")[1])
        elif line.startswith("feature "):
            row_start = i + 1  # forced_bins section is last before rows
    # data rows: after the forced_bins block, one comma-separated line per row,
    # 'NA' for trivial/unused features
    data_lines = [l for l in lines[row_start:] if l.strip()]
    rows = []
    for l in data_lines:
        toks = [t.strip() for t in l.split(",") if t.strip() != ""]
        rows.append([-1 if t == "NA" else int(t) for t in toks])
    bins = np.asarray(rows, dtype=np.int64)
    meta["bins"] = bins
    return meta


def train_cli_and_read_model(train_file: str, extra_conf: Dict[str, str],
                             valid_file: Optional[str] = None) -> Dict:
    """Train with the reference CLI; return parsed stdout metrics + model text."""
    with tempfile.TemporaryDirectory() as td:
        model_path = os.path.join(td, "model.txt")
        conf = {
            "task": "train",
            "data": train_file,
            "output_model": model_path,
            "verbosity": "1",
        }
        if valid_file:
            conf["valid_data"] = valid_file
        conf.update(extra_conf)
        stdout = run_cli(conf, td)
        with open(model_path) as f:
            model_text = f.read()
    return {"stdout": stdout, "model": model_text,
            "metrics": parse_cli_metrics(stdout)}


def parse_cli_metrics(stdout: str) -> Dict[str, List[float]]:
    """Parse '[LightGBM] [Info] Iteration:N, valid_1 auc : 0.83' lines."""
    out: Dict[str, List[float]] = {}
    for line in stdout.split("\n"):
        if "Iteration:" not in line or " : " not in line:
            continue
        try:
            head, val = line.rsplit(":", 1)
            value = float(val)
            key = head.split(",", 1)[1].strip()  # e.g. 'training binary_logloss'
            out.setdefault(key, []).append(value)
        except (ValueError, IndexError):
            continue
    return out
