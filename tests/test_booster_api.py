"""Booster API parity extras: attributes, pickling/copy, leaf access,
split-value histograms, trees_to_dataframe, model_from_string
(reference python-package/lightgbm/basic.py Booster surface)."""

import copy
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(17)
    X = rng.normal(size=(2000, 5))
    y = X[:, 0] * 2 - X[:, 2] + 0.1 * rng.normal(size=2000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=8)
    return bst, X


class TestBoosterExtras:
    def test_attr_roundtrip(self, trained):
        bst, _ = trained
        assert bst.attr("note") is None
        bst.set_attr(note="hello", other="x")
        assert bst.attr("note") == "hello"
        bst.set_attr(other=None)
        assert bst.attr("other") is None
        with pytest.raises(ValueError):
            bst.set_attr(bad=3)

    def test_pickle_and_copy(self, trained):
        bst, X = trained
        base = bst.predict(X)
        clone = pickle.loads(pickle.dumps(bst))
        np.testing.assert_allclose(clone.predict(X), base)
        dup = copy.deepcopy(bst)
        np.testing.assert_allclose(dup.predict(X), base)

    def test_get_leaf_output_matches_dump(self, trained):
        bst, _ = trained
        d = bst.dump_model()

        def first_leaf(node):
            while "leaf_index" not in node:
                node = node["left_child"]
            return node
        leaf = first_leaf(d["tree_info"][0]["tree_structure"])
        got = bst.get_leaf_output(0, leaf["leaf_index"])
        assert got == pytest.approx(leaf["leaf_value"])

    def test_split_value_histogram(self, trained):
        bst, _ = trained
        hist, edges = bst.get_split_value_histogram(0, bins=8)
        assert hist.sum() > 0 and len(edges) == len(hist) + 1
        xgb = bst.get_split_value_histogram(0, bins=8, xgboost_style=True)
        assert np.asarray(xgb).shape[1] == 2

    def test_trees_to_dataframe(self, trained):
        bst, _ = trained
        df = bst.trees_to_dataframe()
        assert list(df.columns) == [
            "tree_index", "node_depth", "node_index", "left_child",
            "right_child", "parent_index", "split_feature", "split_gain",
            "threshold", "decision_type", "missing_direction",
            "missing_type", "value", "weight", "count"]
        splits = df[df.split_feature.notna()]
        leaves = df[df.split_feature.isna()]
        assert len(splits) and len(leaves)
        # every non-root node's parent exists
        kids = df[df.parent_index.notna()]
        assert set(kids.parent_index) <= set(df.node_index)

    def test_model_from_string_replaces(self, trained):
        bst, X = trained
        other_text = bst.model_to_string()
        rng = np.random.default_rng(3)
        X2 = rng.normal(size=(500, 5))
        y2 = -X2[:, 1] + 0.1 * rng.normal(size=500)
        b2 = lgb.train({"objective": "regression", "num_leaves": 7,
                        "verbosity": -1},
                       lgb.Dataset(X2, label=y2), num_boost_round=2)
        b2.model_from_string(other_text)
        np.testing.assert_allclose(b2.predict(X), bst.predict(X))

    def test_sklearn_estimator_pickles(self):
        import pickle
        from lightgbm_tpu.sklearn import LGBMRegressor
        rng = np.random.default_rng(0)
        X = rng.normal(size=(800, 4))
        y = X[:, 0] + 0.1 * rng.normal(size=800)
        m = LGBMRegressor(n_estimators=5, num_leaves=7,
                          verbosity=-1).fit(X, y)
        m2 = pickle.loads(pickle.dumps(m))
        np.testing.assert_allclose(m2.predict(X), m.predict(X))


class TestAddFeaturesFrom:
    """Dataset.add_features_from (reference basic.py add_features_from /
    tests/python_package_test/test_basic.py equivalence check): training
    on A.add_features_from(B) must match training on the columns stacked
    up front."""

    def test_merged_training_matches_stacked(self):
        rng = np.random.default_rng(23)
        n = 1500
        Xa = rng.normal(size=(n, 3))
        Xb = rng.normal(size=(n, 2))
        y = Xa[:, 0] - 2 * Xb[:, 1] + 0.1 * rng.normal(size=n)
        params = {"objective": "regression", "num_leaves": 15,
                  "verbosity": -1}

        da = lgb.Dataset(Xa, label=y)
        da.add_features_from(lgb.Dataset(Xb, label=None))
        merged = lgb.train(params, da, num_boost_round=5)

        stacked = lgb.train(params, lgb.Dataset(
            np.column_stack([Xa, Xb]), label=y), num_boost_round=5)
        X = np.column_stack([Xa, Xb])
        np.testing.assert_allclose(merged.predict(X), stacked.predict(X))

    def test_row_count_mismatch_raises(self):
        rng = np.random.default_rng(24)
        da = lgb.Dataset(rng.normal(size=(100, 2)), label=rng.normal(size=100))
        db = lgb.Dataset(rng.normal(size=(101, 2)))
        with pytest.raises(ValueError, match="row counts"):
            da.add_features_from(db)


def test_dataset_accepts_list_of_row_chunks():
    """Reference basic.py accepts `data` as a list of 2-D row chunks;
    training on the chunk list must equal training on the stacked matrix."""
    rng = np.random.default_rng(29)
    chunks = [rng.normal(size=(100, 4)) for _ in range(3)]
    y = rng.normal(size=300)
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    a = lgb.train(params, lgb.Dataset(chunks, label=y), num_boost_round=3)
    b = lgb.train(params, lgb.Dataset(np.vstack(chunks), label=y),
                  num_boost_round=3)
    X = np.vstack(chunks)
    np.testing.assert_allclose(a.predict(X), b.predict(X))
    np.testing.assert_allclose(a.predict(chunks), b.predict(X))


def test_dataset_getters():
    """Reference Dataset getters: get_data (incl. subset slicing),
    get_monotone_constraints, get_feature_penalty, get_ref_chain."""
    rng = np.random.default_rng(41)
    X = rng.normal(size=(200, 3))
    y = rng.normal(size=200)
    ds = lgb.Dataset(X, label=y,
                     params={"monotone_constraints": [1, 0, -1]})
    vs = ds.create_valid(X, label=y)
    assert ds.get_data() is X
    np.testing.assert_array_equal(ds.get_monotone_constraints(), [1, 0, -1])
    assert ds.get_feature_penalty() is None
    assert {d for d in vs.get_ref_chain()} == {vs, ds}
    sub = ds.subset([0, 2, 5])
    np.testing.assert_allclose(sub.get_data(), X[[0, 2, 5]])
    # subset-of-subset composes used_indices through the chain
    sub2 = sub.subset([1, 2])
    np.testing.assert_allclose(sub2.get_data(), X[[2, 5]])
    # a freed chain raises instead of silently returning None
    ds.data = None
    ds.construct()
    with pytest.raises(lgb.LightGBMError, match="freed raw data"):
        sub2.get_data()
    with pytest.raises(lgb.LightGBMError, match="freed raw data"):
        ds.get_data()
    ds.data = X


def test_predict_shape_check(trained):
    """predict raises on feature-count mismatch unless
    predict_disable_shape_check (reference Parameters.rst)."""
    bst, X = trained
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        bst.predict(X[:, :3])
    # disabled: absent features predict as missing, extras are dropped
    p_full = bst.predict(X)
    p_short = bst.predict(X[:, :3], predict_disable_shape_check=True)
    assert p_short.shape == p_full.shape
    Xw = np.concatenate([X, X[:, :1]], axis=1)
    np.testing.assert_allclose(
        bst.predict(Xw, predict_disable_shape_check=True), p_full)
    # reference-style string values coerce through the config bool parser
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        bst.predict(X[:, :3], predict_disable_shape_check="false")
    assert bst.predict(X[:, :3],
                       predict_disable_shape_check="true").shape == p_full.shape


def test_sklearn_predict_forwards_kwargs(trained):
    """sklearn predict forwards **kwargs to Booster.predict (reference
    sklearn.py), so predict_disable_shape_check works through it."""
    from lightgbm_tpu.sklearn import LGBMRegressor

    bst, X = trained
    rng = np.random.default_rng(3)
    y = X[:, 0] - X[:, 1]
    est = LGBMRegressor(n_estimators=4, num_leaves=7).fit(X, y)
    with pytest.raises(lgb.LightGBMError, match="number of features"):
        est.predict(X[:, :3])
    out = est.predict(X[:, :3], predict_disable_shape_check=True)
    assert out.shape == (X.shape[0],)


def test_loaded_booster_merges_user_params(trained, tmp_path):
    """User params merge over a loaded model's stored params
    (reference basic.py Booster __init__ model_file path)."""
    bst, _ = trained
    f = tmp_path / "m.txt"
    bst.save_model(str(f))
    loaded = lgb.Booster(params={"num_threads": 2}, model_file=str(f))
    assert loaded.params["num_threads"] == 2
    assert loaded.params["objective"] == "regression"


class TestEvalForData:
    """Booster.eval on an AD-HOC dataset (reference c_api.cpp:207-230's
    AddValidData + Eval pair, transient here: gbdt.eval_for_data)."""

    def _setup(self):
        rng = np.random.default_rng(23)
        X = rng.normal(size=(1500, 6))
        y = (X[:, 0] - 0.8 * X[:, 1] + 0.3 * rng.normal(size=1500) > 0
             ).astype(np.float64)
        Xe = rng.normal(size=(500, 6))
        ye = (Xe[:, 0] - 0.8 * Xe[:, 1] > 0).astype(np.float64)
        p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
             "metric": ["binary_logloss", "auc"]}
        return X, y, Xe, ye, p

    def test_matches_registered_valid_set(self):
        X, y, Xe, ye, p = self._setup()
        ds = lgb.Dataset(X, label=y, params=p)
        dv = lgb.Dataset(Xe, label=ye, reference=ds, params=p)
        hist = {}
        bst = lgb.train(p, ds, num_boost_round=6, valid_sets=[dv],
                        valid_names=["holdout"],
                        callbacks=[lgb.record_evaluation(hist)])
        # a SECOND dataset over the same rows, evaluated ad hoc, must
        # reproduce the registered valid set's final metrics exactly
        dv2 = lgb.Dataset(Xe, label=ye, reference=ds, params=p)
        out = bst.eval(dv2, "holdout")
        got = {name: val for _, name, val, _ in out}
        assert got["binary_logloss"] == pytest.approx(
            hist["holdout"]["binary_logloss"][-1], rel=1e-6)
        assert got["auc"] == pytest.approx(
            hist["holdout"]["auc"][-1], rel=1e-6)
        # tuple layout matches eval_valid's (name, metric, value, hib)
        assert {t[0] for t in out} == {"holdout"}
        assert any(t[3] for t in out)  # auc reports higher_is_better

    def test_feval_and_repeat_calls(self):
        X, y, Xe, ye, p = self._setup()
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=4)
        dv = lgb.Dataset(Xe, label=ye, reference=ds, params=p)

        def feval(preds, data):
            return ("n_rows", float(len(preds)), True)

        out1 = bst.eval(dv, "e", feval=feval)
        out2 = bst.eval(dv, "e", feval=feval)
        # transient: repeated calls do not accumulate score state
        assert out1 == out2
        assert ("e", "n_rows", 500.0, True) in out1

    def test_unaligned_dataset_raises(self):
        X, y, Xe, ye, p = self._setup()
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=2)
        stray = lgb.Dataset(Xe, label=ye, params=p)  # no reference=
        with pytest.raises(ValueError, match="reference"):
            bst.eval(stray, "bad")
