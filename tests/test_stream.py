"""Out-of-core streamed training (ISSUE 16): the host-resident block
layout in ops/stream.py and its wiring through the learner factory,
the membudget planner, and the OOM degradation ladder.

What is pinned here:

1. **bitwise** — a streamed run produces a model BYTE-IDENTICAL to the
   resident layout for the integer histogram precisions (int8/int16),
   serial and against an int8 2-shard resident run.  The resident
   reference runs its SYNC path (fused-train-step disabled): the fused
   step computes gradients inside the jitted program and its float
   rounding differs from host-side gradients — a pre-existing
   fused-vs-sync divergence unrelated to streaming.  Streaming's own
   claim is exact: int32 histogram block sums are associative, so
   accumulating per stream block equals the one-shot contraction bit
   for bit.
2. **geometry** — the last partial block and the single-block
   degenerate case stream correctly, and `resolve_stream_rows` always
   returns a multiple of the inner histogram block.
3. **GOSS** — gradient-based block sampling is deterministic under
   re-run and invariant to perf-only knobs (double-buffering), because
   its uniforms are keyed on the GLOBAL row index of each block start,
   not on anything layout-dependent.
4. **selection** — `tpu_stream_mode=auto` picks the streamed layout
   exactly when the binned matrix would eat more than half the HBM
   budget, explicit pins are honored, and `plan_training` swaps the
   binned-matrix component for two double-buffer slots.
5. **ladder** — the recovery ladder's final rung degrades a resident
   run to streaming instead of raising MemoryLadderExhausted.
6. **checkpoint/resume** — a streamed run interrupted at the midpoint
   resumes to the same bytes as an uninterrupted streamed run.
7. **compile discipline** — streaming compiles a BOUNDED number of
   programs (one per distinct block width, i.e. at most two for the
   per-block sites); more iterations add zero recompiles.
8. **observability** — `stream_h2d` / `stream_block` spans land under
   `hist_build`, and the per-tree `stream_tree` event reports an
   overlap percentage > 0 when double-buffering is on.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.booster import Booster
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models import gbdt as gbdt_mod
from lightgbm_tpu.models.learner import (StreamedTreeLearner,
                                         TPUTreeLearner,
                                         make_tree_learner)
from lightgbm_tpu.ops.stream import (make_host_blocks,
                                     resolve_stream_rows,
                                     stream_supported)
from lightgbm_tpu.utils import faultline, membudget
from lightgbm_tpu.utils.compile_ledger import LEDGER

# int16 everywhere: the streamed-vs-resident bitwise contract holds
# for the integer histogram precisions (int32 partial sums are
# associative); float precisions reassociate across the block seam
_P = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
      "min_data_in_leaf": 5, "seed": 7, "verbosity": -1,
      "tpu_block_rows": 256, "tpu_hist_precision": "int16"}


def _data(n=1500, f=8, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f)).astype(np.float32)
    y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _model(bst):
    # strip the parameters echo: [tpu_stream_mode: ...] legitimately
    # differs between the two layouts of the same model
    return bst.model_to_string(num_iteration=-1).split("\nparameters:")[0]


def _train(params, X, y, rounds=5, **kw):
    ds = lgb.Dataset(X, label=y, params=params)
    return lgb.train(params, ds, num_boost_round=rounds,
                     keep_training_booster=True, verbose_eval=False,
                     **kw)


@pytest.fixture
def sync_resident(monkeypatch):
    """Pin the resident reference to the sync train path (see module
    docstring): the streamed layout always computes gradients on host,
    so bitwise comparisons must hold the resident side to the same."""
    monkeypatch.setattr(
        gbdt_mod.GBDT, "_maybe_make_train_step",
        lambda self: setattr(self, "_train_step", None))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultline.reset()
    yield
    faultline.reset()


def _learner_pair(X, y, stream_rows, seedp=None, **extra):
    """A resident and a streamed learner over the same binned data."""
    out = []
    for mode in ("resident", "streamed"):
        p = dict(_P, tpu_stream_mode=mode,
                 tpu_stream_block_rows=stream_rows, **(extra or {}))
        cfg = Config(p)
        td = TrainingData.from_matrix(X, y, cfg)
        cls = StreamedTreeLearner if mode == "streamed" else TPUTreeLearner
        out.append(cls(cfg, td))
    return out


def _grow_once(learner, grad, hess):
    import jax.numpy as jnp
    _, leaf_ids, out = learner.train(jnp.asarray(grad), jnp.asarray(hess))
    return np.asarray(out["records"]), np.asarray(leaf_ids)


# ---------------------------------------------------------------------------
# 1. bitwise streamed vs resident
# ---------------------------------------------------------------------------
class TestBitwise:
    @pytest.mark.parametrize("precision", ["int8", "int16"])
    def test_streamed_equals_resident_serial(self, sync_resident,
                                             precision):
        X, y = _data()
        p = dict(_P, tpu_hist_precision=precision,
                 tpu_stream_block_rows=512)
        ref = _model(_train(dict(p, tpu_stream_mode="resident"), X, y))
        got = _model(_train(dict(p, tpu_stream_mode="streamed"), X, y))
        assert got == ref

    def test_streamed_equals_resident_2shard_int8(self, sync_resident):
        """The ISSUE 16 acceptance triangle: serial-streamed must match
        the int8 2-shard resident run (which test_collective already
        pins to serial-resident)."""
        X, y = _data()
        p = dict(_P, tpu_hist_precision="int8",
                 tpu_quant_refit_leaves=False,
                 tpu_stream_block_rows=512)
        ref = _model(_train(dict(p, tpu_stream_mode="resident",
                                 tree_learner="data", num_machines=2),
                            X, y))
        got = _model(_train(dict(p, tpu_stream_mode="streamed"), X, y))
        assert got == ref

    def test_streamed_refuses_sharded_learner(self):
        X, y = _data(n=600)
        p = dict(_P, tpu_stream_mode="streamed", tree_learner="data",
                 num_machines=2)
        cfg = Config(p)
        td = TrainingData.from_matrix(X, y, cfg)
        with pytest.raises(NotImplementedError, match="serial"):
            StreamedTreeLearner(cfg, td)


# ---------------------------------------------------------------------------
# 2. block geometry
# ---------------------------------------------------------------------------
class TestBlockGeometry:
    def test_partial_tail_block(self):
        """n_pad not divisible by the stream width: the tail block is
        shorter, and the accumulated histograms still match resident
        bit for bit at the grower level."""
        X, y = _data()
        rng = np.random.default_rng(11)
        grad = rng.normal(size=len(y)).astype(np.float32)
        hess = np.abs(rng.normal(size=len(y))).astype(np.float32) + 0.1
        res, stream = _learner_pair(X, y, stream_rows=1024)
        widths = [b.shape[1] for b in stream._host_blocks]
        assert len(widths) >= 2 and widths[-1] < widths[0]
        assert sum(widths) == stream.n_pad
        r1, l1 = _grow_once(res, grad, hess)
        r2, l2 = _grow_once(stream, grad, hess)
        assert np.array_equal(r1, r2)
        assert np.array_equal(l1, l2)

    def test_single_block_degenerate(self):
        X, y = _data(n=600)
        rng = np.random.default_rng(12)
        grad = rng.normal(size=len(y)).astype(np.float32)
        hess = np.abs(rng.normal(size=len(y))).astype(np.float32) + 0.1
        res, stream = _learner_pair(X, y, stream_rows=10 ** 9)
        assert stream._stream.nbs == 1
        r1, l1 = _grow_once(res, grad, hess)
        r2, l2 = _grow_once(stream, grad, hess)
        assert np.array_equal(r1, r2)
        assert np.array_equal(l1, l2)

    def test_resolve_stream_rows_is_inner_block_multiple(self):
        for cfg_rows, n_pad, inner in ((0, 8192, 512), (700, 8192, 512),
                                       (512, 512, 512), (10 ** 9, 4096,
                                                         1024)):
            r = resolve_stream_rows(cfg_rows, n_pad, bytes_per_row=32,
                                    inner_block=inner)
            assert inner <= r <= n_pad
            assert r % inner == 0
        # budget-derived default: two slots must fit in 1/8 of budget
        r = resolve_stream_rows(0, 1 << 20, bytes_per_row=64,
                                inner_block=256,
                                budget_bytes=256 * (1 << 20))
        assert 2 * r * 64 <= (256 * (1 << 20)) // 8

    def test_host_blocks_cover_matrix(self):
        bins_t = np.arange(7 * 1280, dtype=np.uint8).reshape(7, 1280)
        blocks = make_host_blocks(bins_t, 512)
        assert [b.shape[1] for b in blocks] == [512, 512, 256]
        assert all(b.flags["C_CONTIGUOUS"] for b in blocks)
        assert np.array_equal(np.concatenate(blocks, axis=1), bins_t)

    def test_stream_supported_blockers(self):
        res, _ = _learner_pair(*_data(n=600), stream_rows=512)
        ok = res.params
        assert stream_supported(ok) is None
        assert "categorical" in stream_supported(
            ok._replace(has_cat=True))
        assert stream_supported(ok._replace(has_bundles=True))
        assert stream_supported(ok._replace(has_sparse=True))
        assert stream_supported(ok._replace(has_cegb=True))
        assert stream_supported(
            ok._replace(feature_fraction_bynode=0.5))


# ---------------------------------------------------------------------------
# 3. GOSS block sampling
# ---------------------------------------------------------------------------
class TestGoss:
    GOSS = dict(tpu_stream_goss_top=0.34, tpu_stream_goss_other=0.25,
                tpu_stream_block_rows=256)

    def test_rerun_is_deterministic(self):
        X, y = _data()
        p = dict(_P, tpu_stream_mode="streamed", **self.GOSS)
        a = _model(_train(p, X, y))
        b = _model(_train(p, X, y))
        assert a == b

    def test_goss_skips_blocks_and_stays_deterministic_at_learner(self):
        X, y = _data()
        rng = np.random.default_rng(13)
        grad = rng.normal(size=len(y)).astype(np.float32)
        hess = np.abs(rng.normal(size=len(y))).astype(np.float32) + 0.1
        outs = []
        for _ in range(2):
            _, stream = _learner_pair(X, y, stream_rows=256,
                                      tpu_stream_goss_top=0.34,
                                      tpu_stream_goss_other=0.25)
            outs.append(_grow_once(stream, grad, hess))
            assert stream.stream_stats["blocks_skipped"] > 0
        assert np.array_equal(outs[0][0], outs[1][0])
        assert np.array_equal(outs[0][1], outs[1][1])

    def test_invariant_under_double_buffer_knob(self):
        """Double-buffering is a perf knob: it must not leak into the
        sampled block set or the grown trees (the GOSS uniforms key on
        global row indices, not on copy scheduling)."""
        X, y = _data()
        p = dict(_P, tpu_stream_mode="streamed", **self.GOSS)
        a = _model(_train(dict(p, tpu_stream_double_buffer=True), X, y))
        b = _model(_train(dict(p, tpu_stream_double_buffer=False), X, y))
        assert a == b


# ---------------------------------------------------------------------------
# 4. layout selection + planner
# ---------------------------------------------------------------------------
class TestSelection:
    def _cfg_td(self, X, y, **extra):
        cfg = Config(dict(_P, **extra))
        return cfg, TrainingData.from_matrix(X, y, cfg)

    def test_auto_streams_over_budget(self):
        X, y = _data()
        cfg, td = self._cfg_td(X, y, tpu_stream_mode="auto",
                               tpu_hbm_budget_bytes=2 * len(y))
        assert membudget.select_layout(cfg, td) == "streamed"
        assert isinstance(make_tree_learner(cfg, td),
                          StreamedTreeLearner)

    def test_auto_resident_under_budget(self):
        X, y = _data()
        cfg, td = self._cfg_td(X, y, tpu_stream_mode="auto",
                               tpu_hbm_budget_bytes=1 << 32)
        assert membudget.select_layout(cfg, td) == "resident"
        assert isinstance(make_tree_learner(cfg, td), TPUTreeLearner)
        assert not isinstance(make_tree_learner(cfg, td),
                              StreamedTreeLearner)

    def test_explicit_pins_and_validation(self):
        X, y = _data(n=600)
        cfg, td = self._cfg_td(X, y, tpu_stream_mode="streamed")
        assert membudget.select_layout(cfg, td) == "streamed"
        cfg, td = self._cfg_td(X, y, tpu_stream_mode="resident",
                               tpu_hbm_budget_bytes=2 * len(y))
        assert membudget.select_layout(cfg, td) == "resident"
        cfg, _ = self._cfg_td(X, y)
        cfg.params["tpu_stream_mode"] = "bogus"
        with pytest.raises(ValueError, match="tpu_stream_mode"):
            membudget.select_layout(cfg, td)

    def test_config_blockers_force_resident(self):
        X, y = _data(n=600)
        cfg, td = self._cfg_td(X, y, tpu_stream_mode="auto",
                               tpu_hbm_budget_bytes=2 * len(y),
                               tree_learner="data", num_machines=2)
        assert membudget.stream_config_blockers(cfg)
        assert membudget.select_layout(cfg, td) == "resident"

    def test_plan_training_swaps_matrix_for_slots(self):
        X, y = _data()
        cfg, td = self._cfg_td(X, y, tpu_stream_mode="streamed",
                               tpu_stream_block_rows=512)
        lr = make_tree_learner(cfg, td)
        plan = membudget.plan_training(cfg, lr, 1)
        assert "binned_matrix" not in plan.components
        slots = plan.components["stream_slots"]
        biggest = max(b.nbytes for b in lr._host_blocks)
        assert slots == 2 * biggest


# ---------------------------------------------------------------------------
# 5. the ladder's final rung
# ---------------------------------------------------------------------------
class TestLadderDegrade:
    def test_degrades_to_streaming_instead_of_exhausting(self):
        """Six consecutive OOMs burn through every resident rung; the
        final rung swaps the layout to streaming, the retry succeeds,
        and training completes — no MemoryLadderExhausted."""
        X, y = _data(n=800, f=6, seed=0)
        p = dict(_P)
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        bst.update()
        faultline.arm("device_alloc", action="oom", times=6)
        bst.update()
        bst.update()
        faultline.reset()
        steps = bst._driver._mem_ladder.describe()
        assert steps[-1] == "stream_layout"
        assert str(bst._driver.config.tpu_stream_mode) == "streamed"
        assert isinstance(bst._driver.learner, StreamedTreeLearner)
        assert bst.current_iteration() == 3
        assert np.isfinite(bst.predict(X[:8], raw_score=True)).all()


# ---------------------------------------------------------------------------
# 6. checkpoint / resume mid-streamed-run
# ---------------------------------------------------------------------------
class TestCheckpointResume:
    def test_resume_mid_streamed_run_is_bitwise(self, tmp_path):
        X, y = _data()
        p = dict(_P, tpu_stream_mode="streamed",
                 tpu_stream_block_rows=512)
        base = _model(_train(p, X, y, rounds=6))
        pc = dict(p, tpu_checkpoint_dir=str(tmp_path),
                  tpu_checkpoint_interval=1)
        _train(pc, X, y, rounds=3)
        resumed = _train(pc, X, y, rounds=6, resume=True)
        assert isinstance(resumed._driver.learner, StreamedTreeLearner)
        assert _model(resumed) == base


# ---------------------------------------------------------------------------
# 7. compile discipline: no per-block retrace
# ---------------------------------------------------------------------------
class TestCompileLedger:
    def test_bounded_programs_across_blocks_and_rounds(self):
        """Per-block programs may see at most TWO operand shapes (the
        full stream width and the partial tail); everything else is one
        program.  Extra boosting rounds must add zero recompiles."""
        X, y = _data()
        p = dict(_P, tpu_stream_mode="streamed",
                 tpu_stream_block_rows=512)
        LEDGER.enable()
        LEDGER.reset()
        try:
            bst = _train(p, X, y, rounds=3)
            assert bst._driver.learner._stream.nbs >= 2
            for site in ("stream.root_block", "stream.block_step",
                         "stream.replay_block"):
                assert LEDGER.n_programs(site) <= 2, site
            for site in ("stream.prep", "stream.root_finish",
                         "stream.round_head", "stream.round_update",
                         "stream.finish"):
                assert LEDGER.n_programs(site) <= 1, site
            before = LEDGER.n_programs()
            bst.update()
            bst.update()
            assert LEDGER.n_programs() == before
        finally:
            LEDGER.enable(False)
            LEDGER.reset()


# ---------------------------------------------------------------------------
# 8. spans + overlap telemetry
# ---------------------------------------------------------------------------
class TestSpans:
    def test_stream_spans_nest_under_hist_build_with_overlap(self):
        X, y = _data()
        p = dict(_P, tpu_stream_mode="streamed",
                 tpu_stream_block_rows=512)
        obs.configure(mode="trace")
        obs.reset_events()
        try:
            _train(p, X, y, rounds=2)
            evs = obs.events()
        finally:
            obs.configure(mode="off", trace_dir="")
            obs.reset_events()
        spans = [e for e in evs if e["kind"] == "span"]
        blocks = [e for e in spans if e["name"] == "stream_block"]
        h2d = [e for e in spans if e["name"] == "stream_h2d"]
        assert blocks and h2d
        assert all(e["tags"]["parent"] == "hist_build" for e in blocks)
        assert any(e["tags"].get("streamed") for e in spans
                   if e["name"] == "hist_build")
        trees = [e for e in evs if e["kind"] == "event"
                 and e["name"] == "stream_tree"]
        assert trees
        assert any(t["tags"]["overlap_pct"] > 0 for t in trees)
        assert all(t["tags"]["rows_per_sec"] > 0 for t in trees)
