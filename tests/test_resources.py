"""Resource observability (ISSUE 12): device memory/cost accounting.

Covers the CPU memory_stats-None graceful fallback, phase watermarks,
process-runtime gauges on /stats and /metrics, the CompileLedger's
per-program cost capture (flops populated everywhere, memory fields
explicitly None on CPU unless forced), the configurable histogram
sample ring + truncation reporting, the serving registry's
serve_model_hbm_bytes gauge with bytes-freed eviction, and the tier-1
smoke that the bench record's resource fields exist (populated or
explicitly null on CPU).
"""

import json
import os
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.booster import Booster
from lightgbm_tpu.obs import metrics as obs_metrics
from lightgbm_tpu.obs import resources
from lightgbm_tpu.utils.compile_ledger import LEDGER, ledger_jit

_P = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
      "learning_rate": 0.1, "min_data_in_leaf": 5, "verbosity": -1}


def _problem(n=600, f=5, seed=1):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] - 0.3 * X[:, 1] > 0).astype(np.float64)
    return X, y


@pytest.fixture(autouse=True)
def _restore():
    prev_ring = obs_metrics.sample_ring()
    yield
    obs_metrics.set_sample_ring(prev_ring)
    resources.reset_phase_peaks()
    LEDGER.enable_capture(False)
    LEDGER.enable(False)
    LEDGER.reset()


# ---------------------------------------------------------------------------
# device memory: the CPU None contract
# ---------------------------------------------------------------------------
class TestDeviceMemory:
    def test_cpu_memory_stats_is_none(self):
        import jax

        if jax.devices()[0].platform != "cpu":
            pytest.skip("CPU-backend fallback contract")
        assert resources.device_memory_stats() is None
        assert resources.peak_hbm_bytes() is None
        assert resources.hbm_bytes_in_use() is None
        assert all(s is None
                   for s in resources.all_device_memory_stats())

    def test_phase_peak_graceful_on_cpu(self):
        """The bracket must run the body exactly once and record
        nothing when the backend reports no memory stats."""
        prev = obs.mode()
        obs.configure(mode="metrics")
        try:
            ran = []
            with resources.phase_peak("hist_build"):
                ran.append(1)
            assert ran == [1]
            assert resources.phase_peaks() == {}
        finally:
            obs.configure(mode=prev or "off")

    def test_phase_peak_noop_when_telemetry_off(self):
        assert obs.mode() == "off"
        with resources.phase_peak("predict"):
            pass
        assert resources.phase_peaks() == {}

    def test_watermark_bookkeeping(self):
        """The max-wins phase table + gauge, independent of backend."""
        resources._note_phase_peak("hist_build", 100)
        resources._note_phase_peak("hist_build", 50)   # not a new peak
        resources._note_phase_peak("ingest", 70)
        assert resources.phase_peaks() == {"hist_build": 100,
                                           "ingest": 70}
        assert obs.REGISTRY.value("lgbm_device_phase_peak_bytes",
                                  phase="hist_build") == 100
        resources.reset_phase_peaks()
        assert resources.phase_peaks() == {}


# ---------------------------------------------------------------------------
# process runtime stats
# ---------------------------------------------------------------------------
class TestProcessStats:
    def test_values_are_sane(self):
        st = resources.process_runtime_stats()
        assert st["process_rss_bytes"] > 1 << 20      # > 1 MiB
        assert st["process_uptime_s"] > 0
        assert st["process_threads"] >= 1
        assert st["process_open_fds"] >= 3            # stdio at least
        assert st["process_gc_collections"] >= 0

    def test_publish_gauges_exports_prometheus_text(self):
        reg = obs_metrics.MetricsRegistry()
        resources.publish_process_gauges(reg)
        text = reg.to_prometheus_text()
        for name in ("lgbm_process_resident_memory_bytes",
                     "lgbm_process_uptime_seconds",
                     "lgbm_process_threads",
                     "lgbm_process_open_fds",
                     "lgbm_process_gc_collections"):
            assert name in text


# ---------------------------------------------------------------------------
# compile-ledger cost capture
# ---------------------------------------------------------------------------
class TestLedgerCosts:
    def test_capture_and_analyze(self):
        import jax.numpy as jnp

        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        f = ledger_jit(lambda x: (x * 2.0) @ x.T, site="probe")
        f(jnp.ones((32, 8), jnp.float32))
        rows = LEDGER.cost_table(memory=True)
        assert len(rows) == 1
        r = rows[0]
        assert r["site"] == "probe"
        assert r["flops"] and r["flops"] > 0
        assert r["bytes_accessed"] > 0
        # forced memory analysis works even on CPU (AOT recompile)
        assert r["argument_bytes"] == 32 * 8 * 4
        assert r["output_bytes"] == 32 * 32 * 4
        assert r["temp_bytes"] is not None
        json.dumps(rows)  # bench embeds the table: must be JSON-safe

    def test_memory_fields_null_on_cpu_by_default(self):
        import jax
        import jax.numpy as jnp

        if jax.devices()[0].platform != "cpu":
            pytest.skip("CPU-backend auto policy")
        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        f = ledger_jit(lambda x: x + 1, site="cheap")
        f(jnp.ones((4,)))
        rows = LEDGER.cost_table()        # memory=None -> auto: off
        assert rows[0]["flops"] is not None
        assert rows[0]["temp_bytes"] is None
        assert rows[0]["argument_bytes"] is None

    def test_capture_survives_donated_buffers(self):
        import jax.numpy as jnp

        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        g = ledger_jit(lambda x: x * 3, site="donated",
                       donate_argnums=(0,))
        g(jnp.zeros((16,)))               # donation deletes the arg
        rows = LEDGER.cost_table(memory=True)
        assert rows[0]["flops"] is not None
        assert rows[0]["argument_bytes"] is not None

    def test_statics_stay_static_in_specs(self):
        import jax.numpy as jnp

        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        f = ledger_jit(lambda x, n: x * n, site="static",
                       static_argnames=("n",))
        f(jnp.ones((8,)), n=3)
        rows = LEDGER.cost_table(memory=True)
        assert rows[0]["flops"] is not None

    def test_forced_memory_after_auto_pass_fills_the_fields(self):
        """An auto (memory-off) analyze must not make a later explicit
        memory=True vacuous — the perf_probe 'forceable on CPU' path."""
        import jax.numpy as jnp

        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        f = ledger_jit(lambda x: x * 2, site="refill")
        f(jnp.ones((8,)))
        first = LEDGER.cost_table(memory=False)
        assert first[0]["temp_bytes"] is None
        forced = LEDGER.cost_table(memory=True)
        assert forced[0]["argument_bytes"] is not None

    def test_analyze_idempotent_and_no_capture_means_empty(self):
        import jax.numpy as jnp

        LEDGER.enable()
        LEDGER.enable_capture(False)
        LEDGER.reset()
        f = ledger_jit(lambda x: x - 1, site="plain")
        f(jnp.ones((8,)))
        rows = LEDGER.cost_table(memory=True)
        assert rows[0]["flops"] is None   # nothing captured to analyze
        assert LEDGER.cost_table(memory=True) == rows


# ---------------------------------------------------------------------------
# histogram sample ring (satellite)
# ---------------------------------------------------------------------------
class TestSampleRing:
    def test_configurable_ring_and_truncation_flag(self):
        reg = obs_metrics.MetricsRegistry()
        obs_metrics.set_sample_ring(4)
        for i in range(3):
            reg.observe("h", float(i), name="a")
        samples, trunc = reg.histogram_samples("h", with_truncated=True,
                                               name="a")
        assert samples == [0.0, 1.0, 2.0] and trunc is False
        for i in range(3, 10):
            reg.observe("h", float(i), name="a")
        samples, trunc = reg.histogram_samples("h", with_truncated=True,
                                               name="a")
        assert samples == [6.0, 7.0, 8.0, 9.0] and trunc is True
        # legacy single-value call keeps returning the bare list
        assert reg.histogram_samples("h", name="a") == samples

    def test_wired_from_config(self):
        from lightgbm_tpu.config import Config

        obs_metrics.set_sample_ring(obs_metrics.DEFAULT_SAMPLE_RING)
        obs.configure_from_config(Config({}))  # 0 = no clobber
        assert obs_metrics.sample_ring() == \
            obs_metrics.DEFAULT_SAMPLE_RING
        obs.configure_from_config(Config({"tpu_obs_ring_samples": 32}))
        assert obs_metrics.sample_ring() == 32


# ---------------------------------------------------------------------------
# serving: model HBM gauge + process gauges + blackbox route
# ---------------------------------------------------------------------------
@pytest.fixture(scope="class")
def served():
    from lightgbm_tpu.serving import ServingSession
    from lightgbm_tpu.serving.server import serve_http

    X, y = _problem()
    ds = lgb.Dataset(X, label=y, params=_P)
    bst = Booster(params=dict(_P), train_set=ds)
    for _ in range(3):
        bst.update()
    sess = ServingSession(params={"serving_max_batch_rows": 256,
                                  "serving_max_models": 2,
                                  "verbosity": -1})
    server = serve_http(sess, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    yield sess, bst, base, X
    server.shutdown()
    sess.close()


class TestServingResources:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()

    def test_model_hbm_gauge_set_on_load(self, served):
        sess, bst, base, X = served
        key = sess.load("m", booster=bst)
        entry = sess.registry.resolve("m")
        assert entry.hbm_bytes > 0   # packed tables exist (device path)
        gauge = sess._stats.registry.value(
            "lgbm_serving_model_hbm_bytes", model=key)
        assert gauge == entry.hbm_bytes
        models = {m["key"]: m for m in sess.models()}
        assert models[key]["hbm_bytes"] == entry.hbm_bytes
        total = sess._stats.registry.value(
            "lgbm_serving_models_hbm_bytes")
        assert total >= entry.hbm_bytes

    def test_eviction_zeroes_gauge_and_logs_bytes_freed(self, served):
        from lightgbm_tpu.utils.log import LOG_INFO, Log

        sess, bst, base, X = served
        k1 = sess.load("ev1", booster=bst)
        lines = []
        prev_level = Log.get_level()
        Log.reset_level(LOG_INFO)
        Log.reset_callback(lines.append)
        try:
            sess.load("ev2", booster=bst)
            sess.load("ev3", booster=bst)   # cap 2: evicts the LRU
        finally:
            Log.reset_callback(None)
            Log.reset_level(prev_level)
        resident = {m["key"] for m in sess.models()}
        evicted = {k1, "ev2@1", "ev3@1"} - resident
        assert evicted, "cap-2 registry must have evicted something"
        victim = next(iter(evicted))
        assert sess._stats.registry.value(
            "lgbm_serving_model_hbm_bytes", model=victim) == 0
        assert any("freed" in ln and "device bytes" in ln
                   for ln in lines)

    def test_stats_and_metrics_carry_process_gauges(self, served):
        sess, bst, base, X = served
        st = json.loads(self._get(base + "/stats")[1])
        assert st["process_rss_bytes"] > 0
        assert st["process_threads"] >= 1
        assert st["process_open_fds"] > 0
        assert "process_uptime_s" in st and "process_gc_collections" in st
        text = self._get(base + "/metrics")[1]
        assert "lgbm_process_resident_memory_bytes" in text
        assert "lgbm_process_open_fds" in text
        assert "lgbm_serving_model_hbm_bytes" in text

    def test_debug_blackbox_route(self, served):
        from lightgbm_tpu.obs import flightrecorder as fr

        sess, bst, base, X = served
        fr.note("test", "served_breadcrumb")
        status, body = self._get(base + "/debug/blackbox")
        assert status == 200
        rec = json.loads(body)
        assert rec["ring_depth"] >= 16
        assert any(e["name"] == "served_breadcrumb"
                   for e in rec["entries"])


# ---------------------------------------------------------------------------
# tier-1 smoke: the bench record's resource fields (satellite)
# ---------------------------------------------------------------------------
class TestBenchResourceSmoke:
    def test_bench_resource_metrics_populated_or_null_on_cpu(self):
        """A tiny train with capture armed must yield exactly the bench
        contract: program_costs populated with real flops,
        train_peak_hbm_bytes an explicit None on CPU (a number where a
        backend reports memory_stats)."""
        import jax

        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        resources.reset_phase_peaks()
        # a shape no other test in this process compiles: the ledger
        # records only NEW programs, and a cache-hot shape records none
        X, y = _problem(n=673, f=7, seed=9)
        bst = Booster(params=dict(_P),
                      train_set=lgb.Dataset(X, label=y, params=_P))
        for _ in range(2):
            bst.update()
        res = resources.bench_resource_metrics(LEDGER)
        assert set(res) == {"train_peak_hbm_bytes",
                            "phase_peak_hbm_bytes", "program_costs"}
        on_cpu = jax.devices()[0].platform == "cpu"
        if on_cpu:
            assert res["train_peak_hbm_bytes"] is None
            assert res["phase_peak_hbm_bytes"] is None
        else:
            assert res["train_peak_hbm_bytes"] > 0
        costs = res["program_costs"]
        assert costs and any(r["flops"] for r in costs)
        json.dumps(res)  # the bench embeds this verbatim

    def test_bench_emits_the_resource_fields(self):
        """The bench script itself wires the fields into its JSON
        record (the full run is exercised by the bench rounds; tier-1
        asserts the wiring exists)."""
        src = open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py")).read()
        for key in ('"train_peak_hbm_bytes"', '"phase_peak_hbm_bytes"',
                    '"serve_model_hbm_bytes"', '"program_costs"'):
            assert key in src, f"bench.py no longer records {key}"
