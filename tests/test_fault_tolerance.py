"""Fault-tolerant training (ISSUE 7): atomic checkpoint/resume, the
fault-injection harness, numeric guardrails, and the serving circuit
breaker.

The load-bearing guarantee under test: a training run interrupted at an
arbitrary iteration (injected device error, KeyboardInterrupt, SIGTERM)
and resumed from the newest VALID checkpoint produces a model
byte-identical to a never-interrupted run — serial and data-sharded,
float and quantized precisions.  Model comparisons strip the trailing
`parameters:` block (it legitimately embeds `tpu_checkpoint_dir`);
every tree byte and the mapper trailer are compared.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.booster import Booster
from lightgbm_tpu.models.gbdt import quant_headroom_check
from lightgbm_tpu.utils import faultline
from lightgbm_tpu.utils.checkpoint import CheckpointManager
from lightgbm_tpu.utils.log import LightGBMError

P = {"objective": "binary", "num_leaves": 13, "max_bin": 47,
     "min_data_in_leaf": 5, "bagging_fraction": 0.8, "bagging_freq": 1,
     "verbosity": -1}


def _data(n=1500, f=6, seed=11):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.4 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


def _model(bst) -> str:
    """Model bytes minus the parameters block (which embeds the
    checkpoint dir and so differs between runs by construction)."""
    return bst.model_to_string(num_iteration=-1).split("\nparameters:")[0]


def _train(params, X, y, rounds, **kw):
    ds = lgb.Dataset(X, label=y, params=params)
    return lgb.train(params, ds, num_boost_round=rounds,
                     keep_training_booster=True, **kw)


@pytest.fixture(autouse=True)
def _clean_faults():
    faultline.reset()
    yield
    faultline.reset()


X, Y = _data()


class TestFaultline:
    def test_unknown_point_and_action_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faultline.arm("nope")
        with pytest.raises(ValueError, match="unknown fault action"):
            faultline.arm("grow_step", action="explode")

    def test_at_and_times_addressing(self):
        faultline.arm("grow_step", action="poison", at=2, times=2)
        assert faultline.fire("grow_step") is None
        assert faultline.fire("grow_step") == "poison"
        assert faultline.fire("grow_step") == "poison"
        assert faultline.fire("grow_step") is None  # exhausted + disarmed
        assert faultline.hits("grow_step") == 4

    def test_raise_carries_context(self):
        faultline.arm("h2d_copy")
        with pytest.raises(faultline.FaultInjected, match="rows=7"):
            faultline.fire("h2d_copy", rows=7)

    def test_armed_context_manager(self):
        with faultline.armed("serve_dispatch"):
            with pytest.raises(faultline.FaultInjected):
                faultline.fire("serve_dispatch")
        assert faultline.fire("serve_dispatch") is None


class TestCheckpointManager:
    def test_atomic_bundle_and_retention(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for it in (1, 2, 3):
            mgr.save(it, f"model-{it}", {"iteration": it},
                     {"train_scores": np.full((1, 4), it, np.float32)})
        names = sorted(os.listdir(tmp_path))
        assert names == ["ckpt-00000002", "ckpt-00000003"]
        it, text, state, arrays, _ = mgr.load_latest()
        assert (it, text, state["iteration"]) == (3, "model-3", 3)
        np.testing.assert_array_equal(arrays["train_scores"],
                                      np.full((1, 4), 3, np.float32))

    def test_torn_manifest_and_truncated_payload_skipped(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=10)
        for it in (1, 2, 3):
            mgr.save(it, f"model-{it}", {"iteration": it},
                     {"a": np.zeros(2, np.float32)})
        # newest: unparseable manifest; second: torn payload
        with open(tmp_path / "ckpt-00000003" / "manifest.json", "w") as f:
            f.write("{torn")
        p = tmp_path / "ckpt-00000002" / "model.txt"
        p.write_bytes(p.read_bytes()[:3])
        it, text, _, _, _ = mgr.load_latest()
        assert (it, text) == (1, "model-1")

    def test_injected_truncation_fails_crc(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with faultline.armed("checkpoint_write", action="truncate"):
            mgr.save(1, "model body text", {"iteration": 1},
                     {"a": np.zeros(2, np.float32)})
        assert mgr.load_latest() is None  # torn write -> CRC mismatch

    def test_injected_raise_leaves_no_final_checkpoint(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with faultline.armed("checkpoint_write", action="raise"):
            with pytest.raises(faultline.FaultInjected):
                mgr.save(1, "m", {"iteration": 1},
                         {"a": np.zeros(2, np.float32)})
        assert mgr.load_latest() is None
        mgr.save(2, "m2", {"iteration": 2}, {"a": np.zeros(2, np.float32)})
        assert mgr.load_latest()[0] == 2  # temp debris cleaned, dir usable

    def test_sigterm_during_retention_keeps_newest_valid(self, tmp_path,
                                                         monkeypatch):
        """SIGTERM (the engine maps it to KeyboardInterrupt) landing
        inside keep-last-N pruning must never cost the newest valid
        bundle: deletions run oldest-first and the newest is excluded
        from the deletion list by construction."""
        import shutil as _shutil

        backlog = CheckpointManager(str(tmp_path), keep=10)
        for it in (1, 2, 3, 4):
            backlog.save(it, f"model-{it}", {"iteration": it},
                         {"a": np.zeros(2, np.float32)})
        mgr = CheckpointManager(str(tmp_path), keep=2)
        deleted = []
        real_rmtree = _shutil.rmtree

        def dying_rmtree(path, **kw):
            deleted.append(os.path.basename(str(path)))
            raise KeyboardInterrupt("SIGTERM")

        monkeypatch.setattr(_shutil, "rmtree", dying_rmtree)
        with pytest.raises(KeyboardInterrupt):
            mgr.save(5, "model-5", {"iteration": 5},
                     {"a": np.zeros(2, np.float32)})
        monkeypatch.setattr(_shutil, "rmtree", real_rmtree)
        # the interrupt hit the OLDEST prune candidate, and the newest
        # bundle (the one just written) survived, valid
        assert deleted == ["ckpt-00000001"]
        found = mgr.load_latest()
        assert found is not None and found[0] == 5
        assert mgr.validate(str(tmp_path / "ckpt-00000005"))

    def test_interrupted_prune_recovers_on_next_save(self, tmp_path,
                                                     monkeypatch):
        """Leftover over-retention bundles from an interrupted prune are
        collected by the next save's retention pass."""
        import shutil as _shutil

        backlog = CheckpointManager(str(tmp_path), keep=10)
        for it in (1, 2, 3, 4):
            backlog.save(it, f"model-{it}", {"iteration": it},
                         {"a": np.zeros(2, np.float32)})
        mgr = CheckpointManager(str(tmp_path), keep=2)
        hits = []

        def rmtree_once(path, **kw):
            hits.append(path)
            raise KeyboardInterrupt("SIGTERM")

        monkeypatch.setattr(_shutil, "rmtree", rmtree_once)
        with pytest.raises(KeyboardInterrupt):
            mgr.save(5, "model-5", {"iteration": 5},
                     {"a": np.zeros(2, np.float32)})
        monkeypatch.undo()
        mgr.save(6, "model-6", {"iteration": 6},
                 {"a": np.zeros(2, np.float32)})
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.startswith("ckpt-"))
        assert names == ["ckpt-00000005", "ckpt-00000006"]


class TestCheckpointResume:
    def test_checkpointing_is_bit_invisible(self, tmp_path):
        base = _model(_train(P, X, Y, 6))
        p = dict(P, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_interval=1, tpu_checkpoint_keep=2)
        bst = _train(p, X, Y, 6)
        assert _model(bst) == base
        assert sorted(os.listdir(tmp_path)) == \
            ["ckpt-00000005", "ckpt-00000006"]

    def test_round_trip_state_parity(self, tmp_path):
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        for _ in range(3):
            bst.update()
        bst.save_checkpoint(str(tmp_path))
        state_a, arrays_a = bst._driver.capture_train_state()

        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        assert bst2.resume_from_checkpoint(str(tmp_path)) == 3
        assert _model(bst2) == _model(bst)
        state_b, arrays_b = bst2._driver.capture_train_state()
        assert state_a == state_b
        for k in arrays_a:
            np.testing.assert_array_equal(arrays_a[k], arrays_b[k])

    @pytest.mark.parametrize("precision", ["hilo", "int8", "int16"])
    def test_resume_matches_uninterrupted_serial(self, tmp_path, precision):
        p = dict(P, tpu_hist_precision=precision)
        base = _model(_train(p, X, Y, 6))
        pc = dict(p, tpu_checkpoint_dir=str(tmp_path),
                  tpu_checkpoint_interval=1)
        _train(pc, X, Y, 3)
        resumed = _train(pc, X, Y, 6, resume=True)
        assert _model(resumed) == base

    def test_resume_matches_uninterrupted_int8_2shard(self, tmp_path):
        p = dict(P, tpu_hist_precision="int8", tree_learner="data",
                 num_machines=2, tpu_quant_refit_leaves=False)
        base = _model(_train(p, X, Y, 5))
        pc = dict(p, tpu_checkpoint_dir=str(tmp_path))
        _train(pc, X, Y, 2)
        assert _model(_train(pc, X, Y, 5, resume=True)) == base

    @pytest.mark.slow
    @pytest.mark.parametrize("precision", ["int8", "int16"])
    def test_resume_matches_uninterrupted_4shard(self, tmp_path, precision):
        p = dict(P, tpu_hist_precision=precision, tree_learner="data",
                 num_machines=4, tpu_quant_refit_leaves=False)
        base = _model(_train(p, X, Y, 5))
        pc = dict(p, tpu_checkpoint_dir=str(tmp_path))
        _train(pc, X, Y, 2)
        assert _model(_train(pc, X, Y, 5, resume=True)) == base

    def test_resume_without_checkpoints_trains_from_scratch(self, tmp_path):
        p = dict(P, tpu_checkpoint_dir=str(tmp_path / "empty"))
        bst = _train(p, X, Y, 4, resume=True)
        assert bst.num_trees() == 4

    def test_resume_needs_checkpoint_dir(self):
        with pytest.raises(ValueError, match="tpu_checkpoint_dir"):
            _train(dict(P), X, Y, 2, resume=True)

    def test_early_stopping_state_rides_the_bundle(self, tmp_path):
        Xv, Yv = _data(600, 6, seed=99)
        p = dict(P, tpu_checkpoint_dir=str(tmp_path))

        def run(rounds, resume=False):
            ds = lgb.Dataset(X, label=Y, params=p)
            vd = ds.create_valid(Xv, label=Yv)
            return lgb.train(p, ds, num_boost_round=rounds,
                             valid_sets=[vd], early_stopping_rounds=2,
                             verbose_eval=False, resume=resume,
                             keep_training_booster=True)

        full = run(12)
        import shutil

        shutil.rmtree(tmp_path)
        run(4)  # interrupted run: 4 iterations, checkpointed
        resumed = run(12, resume=True)
        assert resumed.best_iteration == full.best_iteration
        assert _model(resumed) == _model(full)


class TestInterruptSafety:
    def test_device_error_rolls_back_and_retrain_is_bitwise(self):
        base = _model(_train(P, X, Y, 5))
        ds = lgb.Dataset(X, label=Y, params=P)
        bst = Booster(params=P, train_set=ds)
        faultline.arm("grow_step", action="raise", at=3)
        errors = 0
        while bst.current_iteration() < 5:
            try:
                bst.update()
            except faultline.FaultInjected:
                errors += 1
                # rolled back to the last COMPLETE iteration, usable
                assert bst.current_iteration() == 2
                assert np.isfinite(
                    bst.predict(X[:16], raw_score=True)).all()
        assert errors == 1
        assert _model(bst) == base

    def test_interrupt_flushes_checkpoint_and_resume_is_bitwise(
            self, tmp_path):
        base = _model(_train(P, X, Y, 6))
        p = dict(P, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_interval=2)
        faultline.arm("grow_step", action="raise",
                      exc=KeyboardInterrupt("simulated preemption"), at=4)
        with pytest.raises(KeyboardInterrupt):
            _train(p, X, Y, 6)
        # iterations 0..2 completed; the flush wrote the off-interval 3
        assert CheckpointManager(str(tmp_path)).latest_iteration() == 3
        assert _model(_train(p, X, Y, 6, resume=True)) == base

    def test_sigterm_flushes_checkpoint(self, tmp_path):
        if threading.current_thread() is not threading.main_thread():
            pytest.skip("signal handlers need the main thread")
        p = dict(P, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_interval=100)  # only the flush writes

        class KillAt:
            order = 0
            before_iteration = True

            def __call__(self, env):
                if env.iteration == 3:
                    os.kill(os.getpid(), signal.SIGTERM)

        with pytest.raises(KeyboardInterrupt):
            _train(p, X, Y, 6, callbacks=[KillAt()])
        assert CheckpointManager(str(tmp_path)).latest_iteration() == 3
        assert signal.getsignal(signal.SIGTERM) in (
            signal.SIG_DFL, signal.default_int_handler)  # handler restored

    @pytest.mark.parametrize("point", ["grow_step", "h2d_copy",
                                       "checkpoint_write"])
    def test_booster_usable_after_interrupt_at_each_point(self, point,
                                                          tmp_path):
        p = dict(P, tpu_checkpoint_dir=str(tmp_path / point),
                 tpu_predict_device="true", tpu_predict_min_rows=1)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        bst.update()
        faultline.arm(point, action="raise",
                      exc=KeyboardInterrupt("simulated"))
        interrupted = False
        try:
            bst.update()                       # fires grow_step
            bst.save_checkpoint(str(tmp_path / point))  # checkpoint_write
            bst.predict(X[:64], raw_score=True,
                        device="tpu", tpu_predict_device="true")  # h2d
        except KeyboardInterrupt:
            interrupted = True
        faultline.reset()
        assert interrupted, point
        # after the interrupt the booster predicts AND keeps training
        assert np.isfinite(bst.predict(X[:16], raw_score=True)).all()
        before = bst.current_iteration()
        bst.update()
        assert bst.current_iteration() == before + 1


class TestRollbackEdgeCases:
    def test_dart_normalize_undone_on_rollback(self):
        """DART's _normalize mutates EXISTING trees in place
        (apply_shrinkage); a rolled-back iteration must undo that or the
        model is permanently corrupted."""
        p = dict(P, boosting="dart", skip_drop=0.0, drop_rate=0.5,
                 bagging_freq=0, bagging_fraction=1.0,
                 tpu_guard_numerics="raise")
        base = _model(_train(dict(p, tpu_guard_numerics="off"), X, Y, 5))
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        faultline.arm("grow_step", action="poison", at=3)
        done = errors = 0
        while done < 5:
            try:
                bst.update()
                done += 1
            except LightGBMError:
                errors += 1
        assert errors == 1
        assert _model(bst) == base, \
            "dropped trees stayed rescaled after rollback"

    def test_resume_with_init_model_trains_remaining_rounds(self, tmp_path):
        # bagging_freq=5 (refresh off-boundary) also covers the iter_
        # semantics: a mid-train materialize (checkpoint save) must not
        # shift the new-round counter by the init model's iterations, or
        # the continuation's bagging schedule drifts
        base = dict(P, bagging_freq=5)
        init = _train(base, X, Y, 3)
        init_str = init.model_to_string(num_iteration=-1)

        def cont(params, rounds, **kw):
            ds = lgb.Dataset(X, label=Y, params=params)
            return lgb.train(params, ds, num_boost_round=rounds,
                             init_model=lgb.Booster(model_str=init_str),
                             keep_training_booster=True, **kw)

        full = cont(dict(base), 6)
        assert full.num_trees() == 9
        p = dict(base, tpu_checkpoint_dir=str(tmp_path))
        cont(p, 3)  # interrupted: 3 of 6 continuation rounds
        resumed = cont(p, 6, resume=True)
        assert resumed.num_trees() == 9  # 3 init + 6 continuation
        assert _model(resumed) == _model(full)

    def test_flush_rewrites_torn_same_iteration_bundle(self, tmp_path):
        from lightgbm_tpu.utils.checkpoint import flush_checkpoint

        ds = lgb.Dataset(X, label=Y, params=P)
        bst = Booster(params=P, train_set=ds)
        bst.update()
        bst.update()
        mgr = CheckpointManager(str(tmp_path))
        bst.save_checkpoint(str(tmp_path))
        name = mgr.checkpoints()[0][1]
        with open(os.path.join(name, "manifest.json"), "w") as f:
            f.write("{torn")
        flush_checkpoint(bst, mgr)
        found = mgr.load_latest()
        assert found is not None and found[0] == 2


class TestNumericGuardrails:
    def _poisoned(self, mode, rounds=4, at=2):
        p = dict(P, tpu_guard_numerics=mode)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        faultline.arm("grow_step", action="poison", at=at)
        raised = None
        try:
            for _ in range(rounds):
                bst.update()
        except LightGBMError as exc:
            raised = exc
        return bst, raised

    def test_off_mode_propagates_silently(self):
        bst, raised = self._poisoned("off")
        assert raised is None
        assert not np.isfinite(
            bst._driver.train_scores.numpy()).all()

    def test_warn_mode_continues(self, capsys):
        bst, raised = self._poisoned("warn")
        assert raised is None
        assert "tpu_guard_numerics=warn" in capsys.readouterr().out

    def test_raise_mode_rolls_back_then_raises(self):
        bst, raised = self._poisoned("raise")
        assert raised is not None and "non-finite" in str(raised)
        # the poisoned iteration was rolled back: booster stays usable
        assert bst.current_iteration() == 1
        assert np.isfinite(bst.predict(X[:16], raw_score=True)).all()

    def test_skip_mode_drops_the_iteration_and_recovers(self):
        bst, raised = self._poisoned("skip", rounds=5)
        assert raised is None
        assert bst._driver._guard_skips_total == 1
        assert bst.current_iteration() == 4  # one update was dropped
        assert np.isfinite(bst._driver.train_scores.numpy()).all()
        assert np.isfinite(bst.predict(X[:16], raw_score=True)).all()

    def test_skip_mode_caps_consecutive_poison(self):
        p = dict(P, tpu_guard_numerics="skip")
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        faultline.arm("grow_step", action="poison", at=1, times=50)
        with pytest.raises(LightGBMError, match="consecutive poisoned"):
            for _ in range(20):
                bst.update()

    def test_skip_rebags_off_the_refresh_boundary(self):
        """A poisoned iteration that is NOT a bagging_freq boundary must
        still draw a FRESH mask on retry — otherwise the replay is
        bit-identical and the streak cap aborts deterministically."""
        p = dict(P, tpu_guard_numerics="skip", bagging_freq=5)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        faultline.arm("grow_step", action="poison", at=3)
        for _ in range(6):
            bst.update()
        assert bst._driver._guard_skips_total == 1
        assert np.isfinite(bst._driver.train_scores.numpy()).all()

    def test_skip_without_stochastic_lever_raises_immediately(self):
        p = dict(P, tpu_guard_numerics="skip", bagging_freq=0,
                 bagging_fraction=1.0)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        faultline.arm("grow_step", action="poison", at=2)
        with pytest.raises(LightGBMError, match="no stochastic lever"):
            for _ in range(4):
                bst.update()
        # raised after ONE detection, not after burning the streak
        assert bst._driver._guard_skips_total == 0
        assert np.isfinite(bst.predict(X[:16], raw_score=True)).all()

    def test_unknown_guard_mode_rejected(self):
        ds = lgb.Dataset(X, label=Y, params=P)
        with pytest.raises(ValueError, match="tpu_guard_numerics"):
            Booster(params=dict(P, tpu_guard_numerics="explode"),
                    train_set=ds)

    def test_quant_headroom_sentinel(self, capsys):
        # int16 narrows past ~65k rows: warn
        q = quant_headroom_check("int16", 200_000, "warn")
        assert q < 32767
        assert "histogram headroom" in capsys.readouterr().out
        # raise mode only fires once fewer than 7 bits of grid remain
        quant_headroom_check("int16", 10_000_000, "warn")
        with pytest.raises(LightGBMError, match="headroom"):
            quant_headroom_check("int16", 100_000_000, "raise")
        # no narrowing -> silent
        capsys.readouterr()
        quant_headroom_check("int16", 1000, "warn")
        assert "headroom" not in capsys.readouterr().out
        # int8's floor is precision-relative: a mild narrowing of an
        # essentially full grid must NOT raise (dtype max is only 127)
        assert quant_headroom_check("int8", 20_000_000, "raise") > 31


class TestServingBreaker:
    def _session(self, bst, **over):
        from lightgbm_tpu.serving import ServingSession

        params = {"serving_max_batch_rows": 512, "verbosity": -1,
                  "serving_breaker_failures": 2,
                  "serving_breaker_cooldown_ms": 80.0}
        params.update(over)
        sess = ServingSession(params=params)
        sess.load("m", booster=bst)
        return sess

    def test_open_halfopen_close_cycle(self):
        bst = _train(P, X, Y, 4)
        ref = bst.predict(X[:40], raw_score=True, device="cpu")
        sess = self._session(bst)
        try:
            faultline.arm("serve_dispatch", action="raise", times=10)
            # every request is served correctly via the walker fallback
            for _ in range(3):
                np.testing.assert_allclose(
                    sess.predict("m", X[:40], raw_score=True), ref,
                    rtol=0, atol=1e-6)
            st = sess.stats()
            assert st["breaker_open"] >= 1
            # request 3 short-circuited: only 2 device attempts failed
            assert st["device_fallbacks"] == 2
            assert [m["breaker"] for m in sess.models()] == ["open"]
            # OPEN: no device dispatch attempts at all
            h0 = faultline.hits("serve_dispatch")
            sess.predict("m", X[:40], raw_score=True)
            assert faultline.hits("serve_dispatch") == h0
            # cooldown elapses, fault cleared: half-open probe closes it
            time.sleep(0.12)
            faultline.disarm()
            np.testing.assert_allclose(
                sess.predict("m", X[:40], raw_score=True), ref,
                rtol=0, atol=1e-6)
            st = sess.stats()
            assert st["breaker_halfopen_probes"] >= 1
            assert [m["breaker"] for m in sess.models()] == ["closed"]
        finally:
            sess.close()

    def test_failed_probe_reopens(self):
        bst = _train(P, X, Y, 4)
        sess = self._session(bst, serving_breaker_cooldown_ms=40.0)
        try:
            faultline.arm("serve_dispatch", action="raise", times=100)
            for _ in range(2):
                sess.predict("m", X[:20], raw_score=True)
            assert [m["breaker"] for m in sess.models()] == ["open"]
            time.sleep(0.06)
            sess.predict("m", X[:20], raw_score=True)  # probe fails
            st = sess.stats()
            assert st["breaker_halfopen_probes"] >= 1
            assert [m["breaker"] for m in sess.models()] == ["open"]
            assert st["breaker_open"] >= 2  # re-opened after the probe
        finally:
            sess.close()

    def test_stuck_halfopen_probe_self_heals(self):
        """A probe that never reports back (a data error raises through
        BOTH predict paths before record_failure runs) must not wedge
        the breaker in half_open forever."""
        from lightgbm_tpu.serving import CircuitBreaker

        br = CircuitBreaker(threshold=1, cooldown_s=0.03)
        br.record_failure()
        assert br.state == "open"
        time.sleep(0.04)
        assert br.allow()               # the probe...
        assert br.state == "half_open"
        assert not br.allow()           # ...is exclusive while pending
        # probe vanished without record_success/record_failure: after
        # another cooldown a new probe is allowed
        time.sleep(0.04)
        assert br.allow()
        br.record_success()
        assert br.state == "closed"

    def test_fallback_results_stay_correct_under_injection(self):
        bst = _train(P, X, Y, 4)
        ref = bst.predict(X[:64], raw_score=True, device="cpu")
        sess = self._session(bst)
        try:
            faultline.arm("serve_dispatch", action="raise", times=1000)
            for _ in range(5):
                np.testing.assert_allclose(
                    sess.predict("m", X[:64], raw_score=True), ref,
                    rtol=0, atol=1e-6)
        finally:
            sess.close()
