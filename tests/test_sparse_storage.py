"""Sparse train-time storage (tpu_sparse_threshold; reference
OrderedSparseBin, src/io/ordered_sparse_bin.hpp / sparse_bin.hpp:73).

Contract: features below the nonzero-bin threshold are stored as padded
COO (row, bin) pairs; histograms come from an O(nnz) gather contraction
with the zero bin reconstructed from leaf totals (FixHistogram,
reference dataset.cpp:1044-1063), and partitions materialize the chosen
column on the fly.  Deterministic f64 runs must BIT-match dense storage
(the reconstruction stays in the accumulation dtype); default (hilo)
runs agree at decision level up to summation-order ulps.
"""

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture
def _x64_reset():
    # deterministic mode flips jax_enable_x64 process-wide; undo so later
    # tests keep the default f32 promotion rules
    yield
    jax.config.update("jax_enable_x64", False)


def _sparse_problem(n=4000, n_dense=4, n_sparse=8, density=0.03, seed=3):
    rng = np.random.default_rng(seed)
    F = n_dense + n_sparse
    X = np.zeros((n, F))
    X[:, :n_dense] = rng.normal(size=(n, n_dense))
    for f in range(n_dense, F):
        nz = rng.choice(n, size=max(4, int(n * density)), replace=False)
        X[nz, f] = rng.normal(size=len(nz)) + (f - F // 2) * 0.5
    y = (X[:, 0] + 2.0 * X[:, n_dense + 1] - 1.5 * X[:, F - 3]
         + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


BASE = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
        "min_data_in_leaf": 5, "verbosity": -1, "enable_bundle": False,
        "tpu_shape_buckets": 0}


def _model(params, X, y, rounds=5):
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.train(params, ds, num_boost_round=rounds,
                    keep_training_booster=True)
    return bst


class TestSparseStorageParity:
    def test_f64_bitmatch_select_and_vselect(self, _x64_reset):
        X, y = _sparse_problem()
        models = {}
        for tag, extra in (
                ("dense", {}),
                ("sparse", {"tpu_sparse_threshold": 0.2}),
                ("vsel", {"tpu_sparse_threshold": 0.2,
                          "tpu_partition_impl": "vselect"})):
            p = {**BASE, **extra, "deterministic": True}
            m = _model(p, X, y).model_to_string()
            models[tag] = m.split("\nparameters:")[0]
        assert models["sparse"] == models["dense"]
        assert models["vsel"] == models["dense"]

    def test_default_precision_decisions_agree(self):
        X, y = _sparse_problem()
        recs = {}
        for tag, extra in (("dense", {}),
                           ("sparse", {"tpu_sparse_threshold": 0.2})):
            p = {**BASE, **extra}
            bst = _model(p, X, y, rounds=3)
            d = bst.dump_model()
            feats = []
            for t in d["tree_info"]:
                def walk(nd):
                    if "split_feature" in nd:
                        feats.append((nd["split_feature"],
                                      nd.get("threshold")))
                        walk(nd["left_child"])
                        walk(nd["right_child"])
                walk(t["tree_structure"])
            recs[tag] = feats
        # identical split sets up to summation-order near-ties: demand
        # high overlap, not bit equality
        same = sum(a == b for a, b in zip(recs["dense"], recs["sparse"]))
        assert same / max(len(recs["dense"]), 1) >= 0.9, recs

    def test_sparse_train_auc_learns(self):
        X, y = _sparse_problem(density=0.02)
        p = {**BASE, "tpu_sparse_threshold": 0.2,
             "metric": ["auc"]}
        bst = _model(p, X, y, rounds=10)
        auc = dict((nm, v) for _, nm, v, _ in bst.eval_train())["auc"]
        assert auc > 0.85, auc


class TestSparseStorageGates:
    def test_rejects_feature_sharding(self):
        X, y = _sparse_problem(n=512)
        p = {**BASE, "tpu_sparse_threshold": 0.2,
             "tree_learner": "feature", "num_machines": 4}
        with pytest.raises(NotImplementedError, match="serial"):
            _model(p, X, y, rounds=1)

    def test_rejects_bundling(self):
        X, y = _sparse_problem(n=512)
        p = {**BASE, "tpu_sparse_threshold": 0.2, "enable_bundle": True}
        with pytest.raises(ValueError, match="enable_bundle"):
            _model(p, X, y, rounds=1)


@pytest.mark.slow
class TestBoschShapedMemory:
    """VERDICT r4 #7: the Bosch-shaped wide-sparse fixture must not pay
    dense HBM.  Scaled to 100k rows for the CPU tier; the storage-bytes
    assertion is shape-derived so it transfers to the 1.18M-row
    original (968 features at ~2% density)."""

    def test_storage_bound_and_training(self):
        n, F, density = 100_000, 968, 0.02
        rng = np.random.default_rng(7)
        rows = rng.integers(0, n, size=int(n * F * density))
        cols = rng.integers(8, F, size=len(rows))
        X = np.zeros((n, F), np.float32)
        X[rows, cols] = rng.normal(size=len(rows)).astype(np.float32)
        X[:, :8] = rng.normal(size=(n, 8)).astype(np.float32)
        y = ((X[:, 0] + X[:, 100] * 3 + X[:, 500] * 2) > 0
             ).astype(np.float64)
        p = {**BASE, "max_bin": 15, "tpu_sparse_threshold": 0.3,
             "num_leaves": 31}
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=2,
                        keep_training_booster=True)
        lr = bst._driver.learner
        assert lr.params.has_sparse
        # device-side bin storage: dense matrix + COO tables must be a
        # small fraction of the all-dense [F, n_pad] uint8 equivalent
        sidx = np.asarray(lr.meta["sparse_idx"])
        sbin = np.asarray(lr.meta["sparse_bin"])
        sparse_bytes = (lr.bins_t.size * lr.bins_t.dtype.itemsize
                        + sidx.nbytes + sbin.nbytes)
        dense_bytes = lr.g_pad * lr.n_pad  # uint8
        ratio = sparse_bytes / dense_bytes
        assert ratio < 0.25, (sparse_bytes, dense_bytes, ratio)
        # and the model actually trained on the sparse representation
        assert bst.num_trees() == 2
        assert "split_gain" in bst.model_to_string()


class TestSparseDataParallel:
    """Sparse storage composed with the data-parallel learner: per-shard
    COO tables ([d, Gs, M], shard-local row ids) sliced by axis_index
    inside the shard_map; the sparse contraction psums like the dense
    one and the zero bin reconstructs post-psum from global totals."""

    def test_f64_matches_serial(self, _x64_reset):
        X, y = _sparse_problem()
        p_ser = {**BASE, "deterministic": True,
                 "tpu_sparse_threshold": 0.2}
        p_par = {**p_ser, "tree_learner": "data", "num_machines": 8}
        models = {}
        for tag, p in (("serial", p_ser), ("data", p_par)):
            models[tag] = _model(p, X, y).model_to_string().split(
                "\nparameters:")[0]
        assert models["data"] == models["serial"]

    def test_default_precision_learns(self):
        X, y = _sparse_problem(density=0.02)
        p = {**BASE, "tpu_sparse_threshold": 0.2, "metric": ["auc"],
             "tree_learner": "data", "num_machines": 8}
        bst = _model(p, X, y, rounds=8)
        auc = dict((nm, v) for _, nm, v, _ in bst.eval_train())["auc"]
        assert auc > 0.85, auc

    def test_voting_sparse_parity_and_learns(self):
        """Voting composes with sparse storage: the local gain vote
        reconstructs zero bins from LOCAL totals, the voted aggregation
        from GLOBAL post-psum totals.  Voting is approximate by design,
        so the contract is root-decision parity with serial-sparse at a
        generous top_k plus end-to-end learning."""
        X, y = _sparse_problem(density=0.03)
        p_ser = {**BASE, "tpu_sparse_threshold": 0.2, "metric": ["auc"]}
        p_vot = {**p_ser, "tree_learner": "voting", "num_machines": 8,
                 "top_k": 8}
        roots = {}
        for tag, p in (("serial", p_ser), ("voting", p_vot)):
            bst = _model(p, X, y, rounds=6)
            d = bst.dump_model()["tree_info"][0]["tree_structure"]
            roots[tag] = (d["split_feature"], d["threshold"])
            if tag == "voting":
                auc = dict((nm, v)
                           for _, nm, v, _ in bst.eval_train())["auc"]
                assert auc > 0.85, auc
        assert roots["voting"] == roots["serial"], roots


class TestSparseEdgeCompositions:
    """Dense-vs-sparse f64 bit-parity under the features that interact
    with the COO path's masking and bin-space assumptions."""

    def _parity(self, X, y, extra=None, rounds=4, **data_kw):
        models = {}
        for tag, sp in (("dense", 0.0), ("sparse", 0.35)):
            p = {**BASE, "deterministic": True, "tpu_sparse_threshold": sp,
                 **(extra or {})}
            ds = lgb.Dataset(X, label=y, params=p, **data_kw)
            bst = lgb.train(p, ds, num_boost_round=rounds,
                            keep_training_booster=True)
            if tag == "sparse":
                assert bst._driver.learner.params.has_sparse
            models[tag] = bst.model_to_string().split("\nparameters:")[0]
        assert models["sparse"] == models["dense"]

    def test_categorical_sparse_column(self, _x64_reset):
        """A mostly-zero CATEGORICAL column stored sparse: the bin-space
        bitset decision and the cat split search must see the same
        histograms either way."""
        rng = np.random.default_rng(13)
        n = 3000
        X = np.zeros((n, 6))
        X[:, :3] = rng.normal(size=(n, 3))
        nz = rng.choice(n, size=200, replace=False)
        X[nz, 4] = rng.integers(1, 6, size=200)  # sparse categorical
        X[:, 5] = rng.integers(0, 4, size=n)     # dense categorical
        y = ((X[:, 0] > 0) ^ (X[:, 4] == 2)).astype(np.float64)
        self._parity(X, y, extra={"categorical_feature": "4,5"})

    def test_bagging_masks_sparse_rows(self, _x64_reset):
        """Bagging zeroes stats per row; the COO gather must respect the
        mask and the zero-bin reconstruction must use MASKED totals."""
        X, y = _sparse_problem()
        self._parity(X, y, extra={"bagging_fraction": 0.6,
                                  "bagging_freq": 1})

    def test_row_weights(self, _x64_reset):
        X, y = _sparse_problem()
        rng = np.random.default_rng(5)
        w = rng.random(len(y)) + 0.5
        self._parity(X, y, weight=w)
