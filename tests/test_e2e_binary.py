"""End-to-end binary classification vs the reference oracle (SURVEY.md §7 M2
acceptance: logloss/AUC curve matches reference CPU within tolerance)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

import lightgbm_tpu as lgb

from .conftest import has_oracle


@pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
class TestBinaryParity:
    @pytest.fixture(scope="class")
    def ref_metrics(self, binary_example):
        from .oracle import run_cli
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            stdout = run_cli({
                "task": "train",
                "data": binary_example["train_file"],
                "valid_data": binary_example["test_file"],
                "objective": "binary", "metric": "binary_logloss,auc",
                "num_trees": "50", "num_leaves": "31", "learning_rate": "0.1",
                "min_data_in_leaf": "20", "max_bin": "255",
                "is_training_metric": "true",
                "output_model": td + "/m.txt", "verbosity": "2"}, td)
        from .oracle import parse_cli_metrics
        return parse_cli_metrics(stdout)

    def test_metric_curves_match(self, binary_example, ref_metrics):
        # reference auto-loads the .weight sidecars next to the data files
        wtr = np.loadtxt(binary_example["train_file"] + ".weight")
        wte = np.loadtxt(binary_example["test_file"] + ".weight")
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"], weight=wtr,
                         params={"max_bin": 255})
        vs = ds.create_valid(binary_example["X_test"],
                             label=binary_example["y_test"], weight=wte)
        res = {}
        # tpu_split_batch=1: strict best-first split order for oracle parity
        lgb.train({"objective": "binary", "num_leaves": 31,
                   "learning_rate": 0.1, "min_data_in_leaf": 20,
                   "metric": ["binary_logloss", "auc"],
                   "tpu_split_batch": 1},
                  ds, num_boost_round=50, valid_sets=[ds, vs],
                  valid_names=["training", "valid_1"], verbose_eval=False,
                  evals_result=res)
        ref_tr_ll = ref_metrics["training binary_logloss"]
        my_tr_ll = res["training"]["binary_logloss"]
        # early iterations must track closely; later ones drift slowly as
        # f32-vs-f64 tie-breaks pick different (equally good) splits
        for i in (0, 4, 9):
            assert abs(my_tr_ll[i] - ref_tr_ll[i]) < 5e-3, \
                f"iter {i}: {my_tr_ll[i]} vs {ref_tr_ll[i]}"
        assert abs(my_tr_ll[49] - ref_tr_ll[49]) < 2e-2
        ref_va_auc = ref_metrics["valid_1 auc"][-1]
        my_va_auc = res["valid_1"]["auc"][-1]
        assert my_va_auc > ref_va_auc - 0.01, \
            f"valid auc {my_va_auc} vs ref {ref_va_auc}"

    def test_first_tree_structure_matches(self, binary_example):
        """With deterministic config the first tree should pick the same root
        split as the reference (bin parity => identical histograms)."""
        from .oracle import train_cli_and_read_model
        ref = train_cli_and_read_model(
            binary_example["train_file"],
            {"objective": "binary", "num_trees": "1", "num_leaves": "15",
             "learning_rate": "0.1", "min_data_in_leaf": "20",
             "verbosity": "-1"})
        ref_lines = dict(
            l.split("=", 1) for l in ref["model"].split("\n")
            if "=" in l and not l.startswith("["))
        ref_root_feature = int(ref_lines["split_feature"].split()[0])
        ref_root_threshold = float(ref_lines["threshold"].split()[0])

        wtr = np.loadtxt(binary_example["train_file"] + ".weight")
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"], weight=wtr,
                         params={"max_bin": 255})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "learning_rate": 0.1, "min_data_in_leaf": 20},
                        ds, num_boost_round=1, verbose_eval=False)
        d = bst.dump_model()
        root = d["tree_info"][0]["tree_structure"]
        assert root["split_feature"] == ref_root_feature
        assert root["threshold"] == pytest.approx(ref_root_threshold, abs=1e-9)


class TestTrainingBasics:
    def test_regression(self, regression_example):
        ds = lgb.Dataset(regression_example["X_train"],
                         label=regression_example["y_train"])
        vs = ds.create_valid(regression_example["X_test"],
                             label=regression_example["y_test"])
        res = {}
        lgb.train({"objective": "regression", "num_leaves": 31,
                   "learning_rate": 0.05, "metric": "l2"},
                  ds, num_boost_round=50, valid_sets=[vs],
                  verbose_eval=False, evals_result=res)
        curve = res["valid_0"]["l2"]
        assert curve[-1] < curve[0] * 0.8
        assert curve[-1] < 0.4  # reference example reaches ~0.2 area

    def test_early_stopping(self, binary_example):
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"])
        vs = ds.create_valid(binary_example["X_test"],
                             label=binary_example["y_test"])
        bst = lgb.train({"objective": "binary", "num_leaves": 127,
                         "learning_rate": 0.5, "metric": "binary_logloss"},
                        ds, num_boost_round=200, valid_sets=[vs],
                        early_stopping_rounds=5, verbose_eval=False)
        assert bst.best_iteration > 0
        assert bst.best_iteration < 200

    def test_init_score_continuation(self, binary_example):
        params = {"objective": "binary", "num_leaves": 15,
                  "min_data_in_leaf": 20, "learning_rate": 0.1}
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"])
        bst1 = lgb.train(params, ds, num_boost_round=5, verbose_eval=False)
        ds2 = lgb.Dataset(binary_example["X_train"],
                          label=binary_example["y_train"])
        bst2 = lgb.train(params, ds2, num_boost_round=5, verbose_eval=False,
                         init_model=bst1)
        assert bst2.num_trees() == 10
        # 5 + 5 continued must track a straight 10-iteration run: the loaded
        # trees' scores are replayed through the binned traversal
        ds3 = lgb.Dataset(binary_example["X_train"],
                          label=binary_example["y_train"])
        bst10 = lgb.train(params, ds3, num_boost_round=10, verbose_eval=False)
        p2 = bst2.predict(binary_example["X_test"], raw_score=True)
        p10 = bst10.predict(binary_example["X_test"], raw_score=True)
        assert np.abs(p2 - p10).max() < 1e-3

    def test_custom_objective(self, binary_example):
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"])

        def fobj(score, dataset):
            label = (binary_example["y_train"] > 0).astype(np.float64)
            p = 1.0 / (1.0 + np.exp(-score))
            return p - label, p * (1 - p)

        bst = lgb.train({"objective": "none", "num_leaves": 15,
                         "learning_rate": 0.1},
                        ds, num_boost_round=10, fobj=fobj, verbose_eval=False)
        p = bst.predict(binary_example["X_test"], raw_score=True)
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(binary_example["y_test"] > 0, p) > 0.75

    def test_bagging_and_feature_fraction(self, binary_example):
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"])
        bst = lgb.train({"objective": "binary", "num_leaves": 31,
                         "bagging_fraction": 0.5, "bagging_freq": 1,
                         "feature_fraction": 0.5, "seed": 7},
                        ds, num_boost_round=20, verbose_eval=False)
        p = bst.predict(binary_example["X_test"])
        from sklearn.metrics import roc_auc_score
        assert roc_auc_score(binary_example["y_test"] > 0, p) > 0.75
        # feature_fraction=0.5 must leave some features unused per tree
        d = bst.dump_model()
        feats_in_tree0 = set()
        def walk(nd):
            if "split_feature" in nd:
                feats_in_tree0.add(nd["split_feature"])
                walk(nd["left_child"]); walk(nd["right_child"])
        walk(d["tree_info"][0]["tree_structure"])
        assert len(feats_in_tree0) <= 14
