"""graftlint: the static-analysis suite gates every PR (ISSUE 13).

Three layers of proof:

1. **HEAD is clean** — the full suite over `lightgbm_tpu/` yields zero
   findings beyond the committed (empty) baseline.  This is the tier-1
   gate itself: a PR that re-introduces a PR-11 bug class fails here.
2. **Every rule fires** — fixture trees seed one violation per rule and
   the rule must flag it, including regression fixtures reproducing
   ALL THREE PR-11 root-cause patterns (shape-keyed RNG, fused
   mul+add on a score path, f32 reduction over dequantized values).
3. **The machinery works** — suppression comments, the baseline
   workflow, JSON/text reporters, --explain, and exit codes.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.graftlint import run_gate  # noqa: E402
from tools.graftlint.core import (RULES, apply_baseline, explain,  # noqa: E402
                                  load_baseline, run, to_json, to_text)

pytestmark = pytest.mark.graftlint


def _tree(tmp_path, files):
    """Materialize {relpath: source} under tmp_path as a mini repo."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        # package markers so the layout mirrors the real tree
        d = p.parent
        while d != tmp_path:
            init = d / "__init__.py"
            if not init.exists():
                init.write_text("")
            d = d.parent
    return str(tmp_path)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# 1. the gate: HEAD lints clean beyond the committed baseline
# ---------------------------------------------------------------------------
class TestHeadGate:
    def test_head_zero_findings_over_baseline(self):
        new, _all = run_gate(REPO)
        assert new == [], (
            "graftlint found NEW violations on HEAD:\n"
            + to_text(new)
            + "\nfix them or (exceptionally) add a justified baseline "
              "entry / inline suppression")

    def test_committed_baseline_is_empty_or_justified(self):
        entries = load_baseline(
            os.path.join(REPO, "tools", "graftlint", "baseline.json"))
        for e in entries:
            just = e.get("justification", "").strip()
            assert just and not just.startswith("TODO"), (
                f"baseline entry {e.get('rule')}@{e.get('path')} lacks a "
                "real justification")

    def test_cli_exits_zero_on_head(self):
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "lightgbm_tpu",
             "--format", "json"],
            cwd=REPO, capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stdout + out.stderr
        payload = json.loads(out.stdout)
        assert payload["new_findings"] == 0


# ---------------------------------------------------------------------------
# 2. determinism family: the three PR-11 root causes, as fixtures
# ---------------------------------------------------------------------------
class TestDeterminismRules:
    def test_pr11_root_cause_1_shape_keyed_rng(self, tmp_path):
        """Root cause #1: bagging masks drawn from shape-keyed threefry
        over the PADDED row axis."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bagging.py": """
            import jax
            def draw_mask(key, bins, n_pad):
                k = jax.random.fold_in(key, bins.shape[0])
                r = jax.random.PRNGKey(n_pad)
                return k, r
        """})
        fs = run(["lightgbm_tpu"], root)
        d101 = [f for f in fs if f.rule == "D101"]
        assert len(d101) == 2
        assert "topology-dependent" in d101[0].message

    def test_pr11_root_cause_2_fused_mul_add_score(self, tmp_path):
        """Root cause #2: gather*lr+scores contracted into an FMA
        differently between serial and shard_map programs."""
        root = _tree(tmp_path, {"lightgbm_tpu/models/learner.py": """
            def update(scores, leaf_output, leaf_ids, lr):
                scores = leaf_output[leaf_ids] * lr + scores
                return scores
            def update_aug(scores, leaf_output, ids, lr):
                scores += leaf_output[ids] * lr
                return scores
        """})
        fs = run(["lightgbm_tpu"], root)
        assert len([f for f in fs if f.rule == "D103"]) == 2

    def test_pr11_root_cause_3_f32_reduction(self, tmp_path):
        """Root cause #3: split-search cumsums on pre-dequantized f32
        where the exact int32 scan exists."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/split.py": """
            import jax.numpy as jnp
            def left_sums(hist_i32, scale):
                return jnp.cumsum(hist_i32.astype(jnp.float32) * scale)
            def left_sums_kwarg(hist_i32):
                # the dtype= spelling of the same dequantizing reduction
                return jnp.cumsum(hist_i32, dtype=jnp.float32)
        """})
        fs = run(["lightgbm_tpu"], root)
        assert _rules(fs) == ["D102"] and len(fs) == 2

    def test_pr11_fixed_idioms_stay_clean(self, tmp_path):
        """The PR-11 FIXES must not trip the rules that encode them."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/fixed.py": """
            import jax
            import jax.numpy as jnp
            def good(key, n_pad, hist_i32, scores, leaf_output, ids, lr,
                     any_split):
                # global-row-index hashing: iota LENGTH is n_pad but the
                # VALUES are global ids — not keying on the shape
                rows = jax.lax.iota(jnp.uint32, n_pad)
                # exact int32 scan, dequantize at the boundary
                left = jnp.cumsum(hist_i32)
                # pre-scaled leaf vector, gather + ONE rounded add
                scaled = jnp.where(any_split, leaf_output * lr, 0.0)
                new_scores = scores.at[0, :].add(scaled[ids])
                return rows, left, new_scores
        """})
        fs = run(["lightgbm_tpu"], root)
        assert fs == []

    def test_out_of_scope_module_not_flagged(self, tmp_path):
        """Determinism rules only bind the bitwise-critical modules."""
        root = _tree(tmp_path, {"lightgbm_tpu/plotting.py": """
            import jax
            def jitter(key, data):
                return jax.random.fold_in(key, data.shape[0])
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "D101"] == []


# ---------------------------------------------------------------------------
# 2b. jit-discipline family
# ---------------------------------------------------------------------------
class TestJitRules:
    def test_unledgered_jit_and_decorator(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/kernels.py": """
            import jax
            def f(x):
                return x + 1
            jf = jax.jit(f)
            @jax.jit
            def g(x):
                return x * 2
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J201"]
        assert len(fs) == 2

    def test_jit_alias_spellings_caught(self, tmp_path):
        """`from jax import jit`, `j = jax.jit` aliases, and
        partial(jax.jit, ...) must not evade the ledger gate."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/alias.py": """
            from functools import partial
            import jax
            from jax import jit
            my_jit = jax.jit
            def f(x):
                return x
            a = jit(f)
            b = my_jit(f)
            c = partial(jax.jit, static_argnames=("k",))(f)
            @jit
            def g(x):
                return x
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J201"]
        # four SITES: jit(f), my_jit(f), partial(jax.jit,...)(f), @jit
        # (the `my_jit = jax.jit` alias assignment is not itself a site)
        assert len(fs) == 4, [(f.line, f.snippet) for f in fs]
        assert {f.snippet for f in fs} == {
            "a = jit(f)", "b = my_jit(f)",
            'c = partial(jax.jit, static_argnames=("k",))(f)', "@jit"}

    def test_jit_via_module_alias_caught(self, tmp_path):
        """`import jax as jx; jx.jit(f)` must not evade J201."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/modalias.py": """
            import jax as jx
            def f(x):
                return x
            jf = jx.jit(f)
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J201"]
        assert len(fs) == 1 and fs[0].snippet == "jf = jx.jit(f)"

    def test_ledgered_jit_clean(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/kernels.py": """
            from ..utils.compile_ledger import ledger_jit
            @ledger_jit(site="k.f")
            def f(x):
                return x + 1
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J201"] == []

    def test_unledgered_shard_map(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/parallel/bad.py": """
            from jax.experimental.shard_map import shard_map
            def build(grow, mesh):
                fn = shard_map(grow, mesh=mesh)
                return fn
        """})
        fs = run(["lightgbm_tpu"], root)
        assert "J202" in _rules(fs)

    def test_shard_map_through_wrapper_clean(self, tmp_path):
        """The strategies.py pattern: shard_map result flows into a
        local wrapper that returns ledger_jit(...)."""
        root = _tree(tmp_path, {"lightgbm_tpu/parallel/good.py": """
            from jax.experimental.shard_map import shard_map
            from ..utils.compile_ledger import ledger_jit
            def _strategy_jit(fn, strategy):
                return ledger_jit(fn, site=strategy)
            def build(grow, mesh):
                fn = shard_map(grow, mesh=mesh)
                return _strategy_jit(fn, "data")
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J202"] == []

    def test_host_calls_in_jitted_body(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/traced.py": """
            import time
            import jax
            import numpy as np
            def body(x):
                t = time.time()
                r = np.random.uniform()
                v = x.item()
                h = jax.device_get(x)
                return x * t * r * v + h.sum()
            jf = jax.jit(body)  # graftlint: disable=J201 fixture
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J203"]
        assert len(fs) == 4

    def test_host_calls_outside_jit_clean(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/host.py": """
            import time
            def wall():
                return time.time()
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J203"] == []

    def test_static_argname_of_folded_mode_param(self, tmp_path):
        root = _tree(tmp_path, {
            "lightgbm_tpu/ops/grower.py": """
                _FOLDED_FIELDS = dict(quant_round="stochastic",
                                      quant_refit=False)
                def canonical_params(p):
                    return p._replace(**_FOLDED_FIELDS)
            """,
            "lightgbm_tpu/ops/bad_site.py": """
                from ..utils.compile_ledger import ledger_jit
                def f(x, quant_round="stochastic"):
                    return x
                jf = ledger_jit(f, site="bad",
                                static_argnames=("quant_round",))
            """})
        fs = run(["lightgbm_tpu"], root)
        assert "J204" in _rules(fs)
        # structural statics (shapes/dtypes/depth) stay allowed
        assert all("quant_round" in f.message for f in fs
                   if f.rule == "J204")


# ---------------------------------------------------------------------------
# 2b-ii. J205: OOM classification on device-dispatch paths (ISSUE 15)
# ---------------------------------------------------------------------------
class TestOOMClassifierRule:
    def test_broad_except_on_dispatch_path_fires(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/serving/bad.py": """
            def run(model, X):
                try:
                    return model.predict(X)
                except Exception:
                    return None
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J205"]
        assert len(fs) == 1 and "membudget" in fs[0].message

    def test_bare_except_and_xla_runtime_error_fire(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bad2.py": """
            from jaxlib.xla_extension import XlaRuntimeError
            def a(kernel, bins):
                try:
                    return kernel.block_until_ready()
                except:
                    return None
            def b(tables, bins, meta):
                try:
                    return forest_class_scores(tables, bins, meta)
                except XlaRuntimeError:
                    return None
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J205"]
        assert len(fs) == 2

    def test_tuple_handler_message_names_every_type(self, tmp_path):
        """A tuple handler is flagged AND its message names the caught
        types — dotted_name on the raw ast.Tuple would render ''."""
        root = _tree(tmp_path, {"lightgbm_tpu/serving/bad3.py": """
            def run(model, X):
                try:
                    return model.predict(X)
                except (RuntimeError, ValueError):
                    return None
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "J205"]
        assert len(fs) == 1
        assert "RuntimeError" in fs[0].message
        assert "ValueError" in fs[0].message

    def test_classifier_routed_handler_clean(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/serving/good.py": """
            from ..utils import membudget
            def run(model, X, stats):
                try:
                    return model.predict(X)
                except Exception as exc:
                    if membudget.is_oom_error(exc):
                        stats.count("dispatch_oom")
                    return None
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J205"] == []

    def test_bare_reraise_handler_clean(self, tmp_path):
        """A rollback-and-reraise handler passes the classified error
        upward unswallowed — the gbdt.train_one_iter shape."""
        root = _tree(tmp_path, {"lightgbm_tpu/models/good2.py": """
            def run(model, X, snap):
                try:
                    return model.predict(X)
                except BaseException:
                    restore(snap)
                    raise
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J205"] == []

    def test_specific_handlers_outside_the_rule(self, tmp_path):
        """ValueError/KeyError cannot catch an OOM; and broad handlers
        on NON-dispatch paths are someone else's problem."""
        root = _tree(tmp_path, {"lightgbm_tpu/serving/good3.py": """
            def run(model, X):
                try:
                    return model.predict(X)
                except (ValueError, KeyError):
                    return None
            def host_only(path):
                try:
                    return open(path).read()
                except Exception:
                    return None
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J205"] == []

    def test_outside_dispatch_modules_not_scoped(self, tmp_path):
        """utils/ and parallel/ are outside the rule's scope — the
        dispatch surface is ops/models/serving."""
        root = _tree(tmp_path, {"lightgbm_tpu/utils/helper.py": """
            def run(model, X):
                try:
                    return model.predict(X)
                except Exception:
                    return None
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "J205"] == []


# ---------------------------------------------------------------------------
# 2c. concurrency family
# ---------------------------------------------------------------------------
class TestConcurrencyRules:
    def test_mutation_outside_owning_lock(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/serving/registry.py": """
            import threading
            class ModelRegistry:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._entries = {}
                def racy(self, k, e):
                    self._entries[k] = e
                def fine(self, k, e):
                    with self._lock:
                        self._entries[k] = e
                def _evict_locked(self):
                    self._entries.clear()
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "C301"]
        assert len(fs) == 1 and "racy" not in fs[0].message
        assert fs[0].snippet == "self._entries[k] = e"

    def test_dispatch_under_lock(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/serving/registry.py": """
            import threading
            class ModelRegistry:
                def __init__(self):
                    self._lock = threading.RLock()
                def stall(self, entry, X):
                    with self._lock:
                        return entry.predict(X)
                def ok(self, entry, X):
                    return entry.predict(X)
        """})
        fs = [f for f in run(["lightgbm_tpu"], root) if f.rule == "C302"]
        assert len(fs) == 1

    def test_init_exempt(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/serving/batcher.py": """
            import threading
            class MicroBatcher:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._queues = {}
                    self._pending_rows = 0
        """})
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "C301"] == []


# ---------------------------------------------------------------------------
# 2d. config/docs drift family
# ---------------------------------------------------------------------------
class TestDriftRules:
    def _mini(self, tmp_path, doc):
        return _tree(tmp_path, {
            "lightgbm_tpu/config.py": """
                _P = {
                    "tpu_dead_knob": ("int", 0, ()),
                    "serving_live_knob": ("int", 1, ()),
                    "tpu_undocumented": ("int", 2, ()),
                    "max_bin": ("int", 255, ()),
                }
            """,
            "lightgbm_tpu/user.py": """
                def use(c):
                    return c.serving_live_knob + c.tpu_undocumented
            """,
            "docs/Parameters.md": doc})

    def test_dead_undocumented_and_phantom(self, tmp_path):
        root = self._mini(
            tmp_path,
            "`tpu_dead_knob` `serving_live_knob` `tpu_phantom_knob`\n")
        fs = run(["lightgbm_tpu"], root)
        by = {f.rule: f for f in fs}
        assert set(by) == {"P401", "P402", "P403"}
        assert "tpu_dead_knob" in by["P401"].message
        assert "tpu_undocumented" in by["P402"].message
        assert by["P403"].snippet == "tpu_phantom_knob"

    def test_param_read_only_by_tools_script_not_dead(self, tmp_path):
        """A param consumed only by tools/ or bench.py (serve_bench
        reads serving config) is NOT dead — the usage scan must cover
        the consumer scripts its message names."""
        root = self._mini(
            tmp_path,
            "`tpu_dead_knob` `serving_live_knob` `tpu_undocumented`\n")
        (tmp_path / "tools").mkdir()
        (tmp_path / "tools" / "serve_bench.py").write_text(
            'P = {"tpu_dead_knob": 7}\n')
        assert [f for f in run(["lightgbm_tpu"], root)
                if f.rule == "P401"] == []

    def test_clean_when_in_sync(self, tmp_path):
        root = self._mini(
            tmp_path,
            "`tpu_dead_knob` `serving_live_knob` `tpu_undocumented`\n")
        # make the dead knob live
        (tmp_path / "lightgbm_tpu" / "user2.py").write_text(
            "def f(c):\n    return c.tpu_dead_knob\n")
        assert run(["lightgbm_tpu"], root) == []


class TestMetricDrift:
    """P405 (ISSUE 14): lgbm_* metric names <-> USAGE.md tables."""

    def _tree_with(self, tmp_path, code, usage):
        return _tree(tmp_path, {
            "lightgbm_tpu/m.py": code,
            "docs/USAGE.md": usage})

    def test_undocumented_and_phantom(self, tmp_path):
        root = self._tree_with(
            tmp_path,
            """
            def f(r):
                r.inc("lgbm_hidden_total")
                r.observe("lgbm_known_seconds", 1.0)
            """,
            "| `lgbm_known_seconds` | histogram |\n"
            "| `lgbm_ghost_total` | counter |\n")
        fs = run(["lightgbm_tpu"], root, rules=["P405"])
        msgs = {f.snippet if f.path.endswith("USAGE.md")
                else "code": f for f in fs}
        assert any("lgbm_hidden_total" in f.message for f in fs), fs
        assert "lgbm_ghost_total" in msgs
        assert len(fs) == 2

    def test_wildcard_and_histogram_suffixes_cover(self, tmp_path):
        root = self._tree_with(
            tmp_path,
            """
            def f(r, c):
                r.inc(f"lgbm_serving_{c}")            # dynamic family
                r.observe("lgbm_lat_seconds", 1.0)
                r.inc("lgbm_serving_batches_total")   # wildcard-covered
            """,
            "| `lgbm_serving_*_total` | counter |\n"
            "| `lgbm_lat_seconds_bucket` | histogram |\n")
        assert run(["lightgbm_tpu"], root, rules=["P405"]) == []

    def test_fstring_head_is_not_a_code_name(self, tmp_path):
        # f"lgbm_serving_{x}" must register a dyn PREFIX, not a literal
        # metric called 'lgbm_serving_' that the doc then has to carry
        root = self._tree_with(
            tmp_path,
            'def f(r, x):\n    r.inc(f"lgbm_serving_{x}")\n',
            "`lgbm_serving_*_total` counters\n")
        assert run(["lightgbm_tpu"], root, rules=["P405"]) == []

    def test_skips_without_usage_doc(self, tmp_path):
        root = _tree(tmp_path, {
            "lightgbm_tpu/m.py":
                'def f(r):\n    r.inc("lgbm_orphan_total")\n'})
        assert run(["lightgbm_tpu"], root, rules=["P405"]) == []


# ---------------------------------------------------------------------------
# topology family: every collective is written once (ISSUE 20)
# ---------------------------------------------------------------------------
class TestTopologyRules:
    def test_raw_lax_collectives_fire(self, tmp_path):
        """Qualified calls, from-imports, and aliased-module spellings of
        the psum family outside parallel/topology.py are all findings."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/hist.py": """
            import jax
            from jax import lax
            from jax.lax import psum_scatter
            def agg(h):
                s = jax.lax.psum(h, "data")
                i = lax.axis_index(("hosts", "data"))
                return s, i
        """})
        fs = run(["lightgbm_tpu"], root, rules=["T501"])
        # from-import (psum_scatter) + two qualified calls
        assert len(fs) == 3
        assert all(f.rule == "T501" for f in fs)
        assert "parallel/topology.py" in fs[0].message

    def test_raw_process_allgather_fires(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/parallel/sync.py": """
            from jax.experimental import multihost_utils
            from jax.experimental.multihost_utils import process_allgather
            def pull(x):
                return multihost_utils.process_allgather(x, tiled=True)
        """})
        fs = run(["lightgbm_tpu"], root, rules=["T502"])
        assert len(fs) == 2  # the from-import and the qualified call
        assert all(f.rule == "T502" for f in fs)

    def test_topology_module_itself_exempt(self, tmp_path):
        """parallel/topology.py is the ONE module allowed to spell the
        raw primitives — the vocabulary has to be written somewhere."""
        root = _tree(tmp_path, {"lightgbm_tpu/parallel/topology.py": """
            import jax
            from jax.experimental.multihost_utils import process_allgather
            def axis_psum(x, axes):
                return jax.lax.psum(x, axes)
            def host_allgather(x):
                return process_allgather(x, tiled=True)
        """})
        assert run(["lightgbm_tpu"], root, rules=["T501", "T502"]) == []

    def test_axis_vocabulary_consumers_clean(self, tmp_path):
        """The ported idiom — axis_* helpers addressed by named axes —
        must NOT be flagged (bare names carry no lax qualifier)."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/grower.py": """
            from ..parallel.topology import (axis_index, axis_psum,
                                             axis_psum_scatter)
            def agg(h, data_axis):
                s = axis_psum_scatter(h, data_axis, scatter_dimension=0)
                return s + axis_psum(h, data_axis), axis_index(data_axis)
        """})
        assert run(["lightgbm_tpu"], root, rules=["T501", "T502"]) == []


# ---------------------------------------------------------------------------
# 3. machinery: suppressions, baseline, reporters, explain, CLI
# ---------------------------------------------------------------------------
class TestMachinery:
    BAD = {"lightgbm_tpu/ops/bad.py": """
        import jax
        def f(x):
            return x
        jf = jax.jit(f)
    """}

    def test_inline_suppression(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bad.py": """
            import jax
            def f(x):
                return x
            jf = jax.jit(f)  # graftlint: disable=J201 fixture says so
        """})
        assert run(["lightgbm_tpu"], root) == []

    def test_file_suppression_and_next_line(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bad.py": """
            # graftlint: disable-file=J201 whole file is a fixture
            import jax
            def f(x):
                return x
            jf = jax.jit(f)
            # graftlint: disable-next-line=J203
            # (no-op directive: nothing on the next line)
        """})
        assert run(["lightgbm_tpu"], root) == []

    def test_directive_in_docstring_is_not_a_suppression(self, tmp_path):
        """Documentation QUOTING the suppression syntax inside a
        string/docstring must not create real (file-wide!)
        suppressions — only comment tokens count."""
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bad.py": '''
            """Suppress findings like this:

                # graftlint: disable-file=J201 <why>
            """
            import jax
            def f(x):
                return x
            jf = jax.jit(f)
        '''})
        assert _rules(run(["lightgbm_tpu"], root)) == ["J201"]

    def test_suppression_comma_list(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bad.py": """
            import jax, time
            def f(x):
                return x + time.time()  # graftlint: disable=J203 fixture
            jf = jax.jit(f)  # graftlint: disable=J201, J204 list form with a why
        """})
        assert run(["lightgbm_tpu"], root) == []

    def test_suppression_is_per_rule(self, tmp_path):
        root = _tree(tmp_path, {"lightgbm_tpu/ops/bad.py": """
            import jax
            def f(x):
                return x
            jf = jax.jit(f)  # graftlint: disable=D101 wrong id
        """})
        assert _rules(run(["lightgbm_tpu"], root)) == ["J201"]

    def test_baseline_absorbs_then_pins(self, tmp_path):
        root = _tree(tmp_path, self.BAD)
        fs = run(["lightgbm_tpu"], root)
        assert len(fs) == 1
        entries = [{"rule": fs[0].rule, "path": fs[0].path,
                    "snippet": fs[0].snippet, "justification": "legacy"}]
        assert apply_baseline(fs, entries) == []
        assert fs[0].baselined
        # a SECOND, new violation is still caught
        (tmp_path / "lightgbm_tpu" / "ops" / "bad2.py").write_text(
            "import jax\njg = jax.jit(lambda x: x)\n")
        fs2 = run(["lightgbm_tpu"], root)
        new = apply_baseline(fs2, entries)
        assert len(new) == 1 and new[0].path.endswith("bad2.py")

    def test_baseline_keys_on_snippet_not_lineno(self, tmp_path):
        """Line drift above a baselined finding must not un-baseline
        it — the key is (rule, path, source line text)."""
        root = _tree(tmp_path, self.BAD)
        fs = run(["lightgbm_tpu"], root)
        entries = [{"rule": fs[0].rule, "path": fs[0].path,
                    "snippet": fs[0].snippet, "justification": "legacy"}]
        p = tmp_path / "lightgbm_tpu" / "ops" / "bad.py"
        p.write_text("# a new comment shifts every line\n"
                     + p.read_text())
        fs2 = run(["lightgbm_tpu"], root)
        assert fs2[0].line != fs[0].line
        assert apply_baseline(fs2, entries) == []

    def test_reporters(self, tmp_path):
        root = _tree(tmp_path, self.BAD)
        fs = run(["lightgbm_tpu"], root)
        text = to_text(fs)
        assert "J201" in text and "bad.py" in text
        payload = json.loads(to_json(fs, fs))
        assert payload["new_findings"] == 1
        assert payload["per_rule"] == {"J201": 1}
        assert payload["findings"][0]["snippet"] == "jf = jax.jit(f)"

    def test_explain_every_rule_points_home(self):
        for rid, rule in sorted(RULES.items()):
            text = explain(rid)
            assert text and rid in text and rule.summary in text
        # determinism explains cite the PR-11 postmortem (ROADMAP 7)
        for rid in ("D101", "D102", "D103"):
            assert "ROADMAP" in explain(rid) and "PR-11" in explain(rid)

    def test_cli_explain_and_exit_codes(self, tmp_path):
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--explain", "D101"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 0 and "PR-11" in out.stdout
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--explain", "NOPE"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 2
        root = _tree(tmp_path, self.BAD)
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "lightgbm_tpu",
             "--root", root, "--no-baseline", "--format", "json"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 1
        assert json.loads(out.stdout)["new_findings"] == 1

    def test_syntax_error_reported_not_crash(self, tmp_path):
        root = _tree(tmp_path,
                     {"lightgbm_tpu/ops/broken.py": "def f(:\n"})
        fs = run(["lightgbm_tpu"], root)
        assert len(fs) == 1 and fs[0].rule == "E000"

    def test_rules_filter_selects_each_drift_rule(self, tmp_path):
        """--rules P402 must RUN the P402 check, and --rules P401 must
        not leak P402/P403 findings (the shared-walk regression)."""
        root = _tree(tmp_path, {
            "lightgbm_tpu/config.py": """
                _P = {"tpu_undoc": ("int", 0, ())}
            """,
            "lightgbm_tpu/user.py": "def f(c):\n    return c.tpu_undoc\n",
            "docs/Parameters.md": "`tpu_phantom`\n"})
        assert _rules(run(["lightgbm_tpu"], root, rules=["P402"])) \
            == ["P402"]
        assert _rules(run(["lightgbm_tpu"], root, rules=["P403"])) \
            == ["P403"]
        assert _rules(run(["lightgbm_tpu"], root, rules=["P401"])) == []

    def test_no_matching_files_is_an_error_not_a_pass(self, tmp_path):
        """A typo'd path must not silently disable the gate."""
        with pytest.raises(OSError, match="no .py files matched"):
            run(["nonexistent_dir"], str(tmp_path))
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "nonexistent_dir",
             "--root", str(tmp_path)],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 2
        assert "no .py files matched" in out.stderr

    def test_write_baseline_refuses_subset_runs(self, tmp_path):
        """A --rules or path-subset --write-baseline would silently
        drop every other entry from the shared baseline file."""
        root = _tree(tmp_path, self.BAD)
        for extra in (["--rules", "J201"], ["lightgbm_tpu"]):
            out = subprocess.run(
                [sys.executable, "-m", "tools.graftlint", "--root", root,
                 "--baseline", str(tmp_path / "b.json"),
                 "--write-baseline"] + extra,
                cwd=REPO, capture_output=True, text=True, timeout=60)
            assert out.returncode == 2, (extra, out.stdout, out.stderr)
            assert "subset" in out.stderr
        # the full default run writes (and E000 entries are excluded)
        (tmp_path / "lightgbm_tpu" / "ops" / "broken.py").write_text(
            "def f(:\n")
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "--root", root,
             "--baseline", str(tmp_path / "b.json"), "--write-baseline"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        entries = json.loads((tmp_path / "b.json").read_text())["entries"]
        assert [e["rule"] for e in entries] == ["J201"]

    def test_corrupt_baseline_is_a_usage_error(self, tmp_path):
        root = _tree(tmp_path, self.BAD)
        bad_baseline = tmp_path / "baseline.json"
        bad_baseline.write_text("{not json<<<<")
        with pytest.raises(ValueError, match="not valid JSON"):
            load_baseline(str(bad_baseline))
        out = subprocess.run(
            [sys.executable, "-m", "tools.graftlint", "lightgbm_tpu",
             "--root", root, "--baseline", str(bad_baseline)],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert out.returncode == 2
        assert "not valid JSON" in out.stderr
        # absent baseline stays a valid (empty) state
        assert load_baseline(str(tmp_path / "missing.json")) == []
