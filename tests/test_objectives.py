"""Objective/metric zoo tests (M3): formula checks against hand-rolled
oracles plus small end-to-end runs for every model family."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata
from lightgbm_tpu.models.objectives import create_objective
from lightgbm_tpu.models import objectives_ext as oe


def _make_obj(name, n=64, seed=0, label=None, weight=None, group=None, **params):
    rng = np.random.default_rng(seed)
    if label is None:
        label = rng.normal(size=n).astype(np.float32) ** 2 + 0.1
    cfg = Config({"objective": name, **params})
    obj = create_objective(cfg)
    md = Metadata(len(label), label=label, weight=weight, group_sizes=group)
    obj.init(md, len(label))
    return obj, md


def _grads(obj, score):
    import jax
    g, h = obj.get_gradients(np.asarray(score, np.float32)[None, :])
    return np.asarray(jax.device_get(g)).reshape(-1), \
        np.asarray(jax.device_get(h)).reshape(-1)


class TestRegressionFamilyGradients:
    def test_l1(self):
        obj, md = _make_obj("regression_l1")
        s = np.linspace(-2, 2, 64)
        g, h = _grads(obj, s)
        np.testing.assert_allclose(g, np.sign(s - md.label), atol=1e-6)
        np.testing.assert_allclose(h, 1.0)

    def test_huber(self):
        obj, md = _make_obj("huber", alpha=0.5)
        s = np.linspace(-3, 3, 64)
        g, _ = _grads(obj, s)
        d = s - md.label
        expect = np.where(np.abs(d) <= 0.5, d, np.sign(d) * 0.5)
        np.testing.assert_allclose(g, expect, rtol=1e-5, atol=1e-6)

    def test_fair(self):
        obj, md = _make_obj("fair", fair_c=2.0)
        s = np.linspace(-3, 3, 64)
        g, h = _grads(obj, s)
        x = s - md.label
        np.testing.assert_allclose(g, 2 * x / (np.abs(x) + 2), rtol=1e-5)
        np.testing.assert_allclose(h, 4 / (np.abs(x) + 2) ** 2, rtol=1e-5)

    def test_poisson(self):
        obj, md = _make_obj("poisson", poisson_max_delta_step=0.7)
        s = np.linspace(-1, 1, 64)
        g, h = _grads(obj, s)
        np.testing.assert_allclose(g, np.exp(s) - md.label, rtol=1e-4)
        np.testing.assert_allclose(h, np.exp(s + 0.7), rtol=1e-4)

    def test_quantile(self):
        obj, md = _make_obj("quantile", alpha=0.3)
        s = np.linspace(-2, 2, 64)
        g, _ = _grads(obj, s)
        expect = np.where(s - md.label >= 0, 0.7, -0.3)
        np.testing.assert_allclose(g, expect, rtol=1e-5)

    def test_tweedie(self):
        obj, md = _make_obj("tweedie", tweedie_variance_power=1.3)
        s = np.linspace(-1, 1, 64)
        g, h = _grads(obj, s)
        y, rho = md.label, 1.3
        np.testing.assert_allclose(
            g, -y * np.exp((1 - rho) * s) + np.exp((2 - rho) * s), rtol=1e-4)

    def test_gamma_boost_from_score_is_log_mean(self):
        obj, md = _make_obj("gamma")
        assert obj.boost_from_score(0) == pytest.approx(
            np.log(np.asarray(md.label, np.float64).mean()), rel=1e-6)

    def test_poisson_rejects_negative_labels(self):
        with pytest.raises(ValueError):
            _make_obj("poisson", label=np.array([-1.0, 2.0], np.float32))


class TestPercentile:
    """percentile helpers match the reference PercentileFun semantics."""

    def test_median_odd(self):
        v = np.array([3.0, 1.0, 2.0])
        # float_pos = 1.5, pos = 1, bias = .5, desc = [3,2,1]: 3 - (3-2)*.5
        assert oe.percentile(v, 0.5) == pytest.approx(2.5)

    def test_alpha_extremes(self):
        v = np.arange(10.0)
        # alpha=0.95: float_pos=0.5 -> pos=0 < 1 -> max (ref PercentileFun)
        assert oe.percentile(v, 0.95) == 9.0
        # alpha=0.01: float_pos=9.9 -> pos=9, bias=0.9 -> desc[8]-(1)*0.9
        assert oe.percentile(v, 0.01) == pytest.approx(0.1)

    def test_weighted_equal_weights_matches_structure(self):
        v = np.array([1.0, 2.0, 3.0, 4.0])
        w = np.ones(4)
        # cdf=[1,2,3,4], thr=2 -> pos=2; cdf[3]-cdf[2]=1 >= 1 so the
        # reference interpolates (thr-cdf[pos])/(cdf[pos+1]-cdf[pos])
        # = (2-3)/1 -> v1 - (v2-v1) = 1.0 (WeightedPercentileFun quirk)
        assert oe.weighted_percentile(v, w, 0.5) == pytest.approx(1.0)


class TestRenewObjectivesE2E:
    @pytest.mark.parametrize("objective,metric", [
        ("regression_l1", "l1"), ("quantile", "quantile"), ("mape", "mape"),
        ("huber", "huber"), ("fair", "fair"),
    ])
    def test_training_reduces_loss(self, objective, metric):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(800, 6))
        y = X[:, 0] * 3 + np.abs(X[:, 1]) + rng.normal(size=800) * 0.1 + 5
        ds = lgb.Dataset(X, label=y)
        res = {}
        lgb.train({"objective": objective, "metric": metric,
                   "num_leaves": 15, "learning_rate": 0.2, "alpha": 0.5},
                  ds, num_boost_round=30, valid_sets=[ds],
                  valid_names=["training"], verbose_eval=False,
                  evals_result=res)
        curve = list(res["training"].values())[0]
        assert curve[-1] < curve[0] * 0.6, curve

    def test_poisson_gamma_tweedie(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(800, 5))
        rate = np.exp(0.5 * X[:, 0] + 0.2 * X[:, 1])
        y = rng.poisson(rate).astype(np.float64) + 0.1
        for objective in ("poisson", "gamma", "tweedie"):
            ds = lgb.Dataset(X, label=y)
            res = {}
            lgb.train({"objective": objective, "num_leaves": 15,
                       "learning_rate": 0.1},
                      ds, num_boost_round=30, valid_sets=[ds],
                      valid_names=["training"], verbose_eval=False,
                      evals_result=res)
            curve = list(res["training"].values())[0]
            assert curve[-1] < curve[0], (objective, curve[0], curve[-1])


class TestMulticlass:
    def test_softmax_gradients(self):
        n, k = 32, 3
        rng = np.random.default_rng(0)
        label = rng.integers(0, k, size=n).astype(np.float32)
        cfg = Config({"objective": "multiclass", "num_class": k})
        obj = create_objective(cfg)
        obj.init(Metadata(n, label=label), n)
        score = rng.normal(size=(k, n)).astype(np.float32)
        import jax
        g, h = obj.get_gradients(score)
        g = np.asarray(jax.device_get(g))
        h = np.asarray(jax.device_get(h))
        p = np.exp(score - score.max(0)) / np.exp(score - score.max(0)).sum(0)
        onehot = (label[None, :].astype(int) == np.arange(k)[:, None])
        np.testing.assert_allclose(g, p - onehot, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(h, 2 * p * (1 - p), rtol=1e-4, atol=1e-5)

    def test_e2e_multiclass(self, multiclass_example):
        X, y = multiclass_example["X_train"], multiclass_example["y_train"]
        ds = lgb.Dataset(X, label=y)
        vs = ds.create_valid(multiclass_example["X_test"],
                             label=multiclass_example["y_test"])
        res = {}
        bst = lgb.train({"objective": "multiclass", "num_class": 5,
                         "metric": ["multi_logloss", "multi_error"],
                         "num_leaves": 31, "learning_rate": 0.1},
                        ds, num_boost_round=30, valid_sets=[ds, vs],
                        valid_names=["training", "valid"],
                        verbose_eval=False, evals_result=res)
        # reference CLI reaches 1.110 at iter 30 on this config; we match it
        assert res["training"]["multi_logloss"][-1] < 1.15
        assert res["valid"]["multi_logloss"][-1] < \
            res["valid"]["multi_logloss"][0]
        pred = bst.predict(multiclass_example["X_test"])
        assert pred.shape == (len(multiclass_example["y_test"]), 5)
        np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
        acc = (pred.argmax(1) == multiclass_example["y_test"]).mean()
        # 5 classes, hard dataset (reference logloss is 1.11 at iter 30):
        # well above the 0.2 chance level is what 30 rounds buys
        assert acc > 0.4, acc

    def test_e2e_multiclassova(self, multiclass_example):
        X, y = multiclass_example["X_train"], multiclass_example["y_train"]
        ds = lgb.Dataset(X, label=y)
        res = {}
        bst = lgb.train({"objective": "multiclassova", "num_class": 5,
                         "metric": "multi_logloss",
                         "num_leaves": 15, "learning_rate": 0.1},
                        ds, num_boost_round=20, valid_sets=[ds],
                        valid_names=["training"], verbose_eval=False,
                        evals_result=res)
        curve = res["training"]["multi_logloss"]
        assert curve[-1] < curve[0]
        assert bst.num_trees() == 20 * 5

    def test_model_roundtrip_multiclass(self, multiclass_example):
        X, y = multiclass_example["X_train"][:500], multiclass_example["y_train"][:500]
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "multiclass", "num_class": 5,
                         "num_leaves": 7}, ds, num_boost_round=5,
                        verbose_eval=False)
        s = bst.model_to_string()
        bst2 = lgb.Booster(model_str=s)
        np.testing.assert_allclose(bst.predict(X[:50]), bst2.predict(X[:50]),
                                   rtol=1e-6)


class TestXentropy:
    def test_e2e(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(600, 5))
        p = 1 / (1 + np.exp(-(X[:, 0] + X[:, 1])))
        y = np.clip(p + rng.normal(size=600) * 0.05, 0, 1)
        for objective in ("cross_entropy", "cross_entropy_lambda"):
            ds = lgb.Dataset(X, label=y)
            res = {}
            lgb.train({"objective": objective, "num_leaves": 15,
                       "learning_rate": 0.1},
                      ds, num_boost_round=25, valid_sets=[ds],
                      valid_names=["training"], verbose_eval=False,
                      evals_result=res)
            curve = list(res["training"].values())[0]
            assert curve[-1] < curve[0], objective

    def test_kldiv_metric(self):
        rng = np.random.default_rng(4)
        X = rng.normal(size=(300, 4))
        y = np.clip(0.5 + 0.3 * np.tanh(X[:, 0]), 0, 1)
        ds = lgb.Dataset(X, label=y)
        res = {}
        lgb.train({"objective": "cross_entropy", "metric": "kldiv",
                   "num_leaves": 7}, ds, num_boost_round=15,
                  valid_sets=[ds], valid_names=["training"],
                  verbose_eval=False, evals_result=res)
        assert res["training"]["kldiv"][-1] < res["training"]["kldiv"][0]


class TestRanking:
    def test_lambdarank_gradient_signs(self):
        # two docs, label 1 ranked below label 0 by score -> the relevant doc
        # gets pushed up (negative lambda)
        cfg = Config({"objective": "lambdarank"})
        obj = create_objective(cfg)
        label = np.array([0.0, 1.0], np.float32)
        obj.init(Metadata(2, label=label, group_sizes=[2]), 2)
        g, h = obj.get_gradients(np.array([[1.0, -1.0]], np.float32))
        assert g[0, 1] < 0  # relevant doc pulled up
        assert g[0, 0] > 0  # irrelevant doc pushed down
        assert (h >= 0).all()

    def test_lambdarank_e2e(self, rank_example):
        ds = lgb.Dataset(rank_example["X_train"],
                         label=rank_example["y_train"],
                         group=rank_example["q_train"])
        vs = ds.create_valid(rank_example["X_test"],
                             label=rank_example["y_test"],
                             group=rank_example["q_test"])
        res = {}
        lgb.train({"objective": "lambdarank", "metric": "ndcg",
                   "num_leaves": 31, "learning_rate": 0.1,
                   "eval_at": [1, 3, 5], "min_data_in_leaf": 1},
                  ds, num_boost_round=30, valid_sets=[ds, vs],
                  valid_names=["training", "valid"], verbose_eval=False,
                  evals_result=res)
        assert "ndcg@1" in res["valid"]
        assert res["valid"]["ndcg@5"][-1] > 0.55
        assert res["training"]["ndcg@5"][-1] > res["training"]["ndcg@5"][0]

    def test_xendcg_e2e(self, rank_example):
        ds = lgb.Dataset(rank_example["X_train"],
                         label=rank_example["y_train"],
                         group=rank_example["q_train"])
        res = {}
        lgb.train({"objective": "rank_xendcg", "metric": "ndcg",
                   "num_leaves": 31, "learning_rate": 0.1,
                   "min_data_in_leaf": 1},
                  ds, num_boost_round=20, valid_sets=[ds],
                  valid_names=["training"], verbose_eval=False,
                  evals_result=res)
        assert res["training"]["ndcg@5"][-1] > res["training"]["ndcg@5"][0]

    def test_requires_group(self):
        X = np.random.default_rng(0).normal(size=(50, 3))
        y = np.zeros(50)
        ds = lgb.Dataset(X, label=y)
        with pytest.raises(ValueError):
            lgb.train({"objective": "lambdarank", "num_leaves": 7},
                      ds, num_boost_round=2, verbose_eval=False)


class TestMetricsAgainstSklearnStyleOracles:
    def test_ndcg_perfect_ranking_is_one(self):
        from lightgbm_tpu.models.metrics import create_metric
        cfg = Config({"eval_at": [3]})
        m = create_metric("ndcg", cfg)
        label = np.array([2, 1, 0, 0, 1, 2], np.float32)
        md = Metadata(6, label=label, group_sizes=[3, 3])
        m.init(md, 6)
        score = np.array([[3.0, 2.0, 1.0, 0.1, 0.5, 0.9]])
        out = dict(m.eval_all(score, None))
        assert out["ndcg@3"] == pytest.approx(1.0)

    def test_map_simple(self):
        from lightgbm_tpu.models.metrics import create_metric
        cfg = Config({"eval_at": [2]})
        m = create_metric("map", cfg)
        label = np.array([1, 0, 0, 1], np.float32)
        md = Metadata(4, label=label, group_sizes=[4])
        m.init(md, 4)
        # ranking: pos, neg, neg, pos -> AP@2 = (1/1) / min(2,2)... hits@2=1
        score = np.array([[4.0, 3.0, 2.0, 1.0]])
        out = dict(m.eval_all(score, None))
        assert out["map@2"] == pytest.approx(0.5)

    def test_auc_mu_separable(self):
        from lightgbm_tpu.models.metrics import create_metric
        cfg = Config({"objective": "multiclass", "num_class": 3})
        m = create_metric("auc_mu", cfg)
        label = np.array([0, 0, 1, 1, 2, 2], np.float32)
        md = Metadata(6, label=label)
        m.init(md, 6)
        # perfectly separable one-hot scores
        score = np.zeros((3, 6))
        for i, c in enumerate(label.astype(int)):
            score[c, i] = 10.0
        assert m.eval(score, None) == pytest.approx(1.0)

    def test_multi_error_topk(self):
        from lightgbm_tpu.models.metrics import create_metric
        cfg = Config({"objective": "multiclass", "num_class": 3,
              "multi_error_top_k": 2})
        m = create_metric("multi_error", cfg)
        label = np.array([0, 1, 2], np.float32)
        md = Metadata(3, label=label)
        m.init(md, 3)
        score = np.array([[0.5, 0.3, 0.2],
                          [0.4, 0.4, 0.3],
                          [0.1, 0.3, 0.5]])
        # row0: true class 0 has top score -> ok; row1: class1 tied top -> ok
        # row2: class2 top -> ok at k=2
        out = dict(m.eval_all(score, None))
        assert out["multi_error@2"] == pytest.approx(0.0)
