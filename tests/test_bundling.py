"""EFB exclusive feature bundling (reference FindGroups/FastFeatureBundling,
src/io/dataset.cpp:91-263 + FixHistogram, dataset.cpp:1044-1063)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # e2e trainings

import lightgbm_tpu as lgb
from lightgbm_tpu.io.bundling import apply_bundles, find_bundles


class TestBundlePlan:
    def test_exclusive_features_bundle(self):
        rng = np.random.default_rng(0)
        n = 2000
        which = rng.integers(0, 4, size=n)
        bins = np.zeros((n, 4), np.int32)
        for f in range(4):
            rows = which == f
            bins[rows, f] = rng.integers(1, 8, size=rows.sum())
        plan = find_bundles(bins, np.full(4, 8, np.int32),
                            np.ones(4, bool), 0.0, 64)
        assert plan.num_columns == 1
        assert len(plan.groups[0]) == 4
        bundled = apply_bundles(bins, plan)
        # zero-conflict bundling is lossless: round-trip every feature
        for f in range(4):
            off = plan.bin_offset[f]
            rel = bundled[:, 0] - off
            rec = np.where((rel >= 1) & (rel < 8), rel, 0)
            np.testing.assert_array_equal(rec, bins[:, f])

    def test_conflict_budget_respected(self):
        rng = np.random.default_rng(1)
        n = 1000
        bins = rng.integers(0, 2, size=(n, 3)).astype(np.int32)  # ~50% dense
        plan = find_bundles(bins, np.full(3, 2, np.int32),
                            np.ones(3, bool), 0.0, 64)
        # heavy mutual conflicts + zero budget: nothing may bundle
        assert plan.is_trivial

    def test_capacity_cap(self):
        bins = np.zeros((100, 3), np.int32)
        bins[0, 0] = 1; bins[1, 1] = 1; bins[2, 2] = 1
        plan = find_bundles(bins, np.full(3, 60, np.int32),
                            np.ones(3, bool), 0.0, 100)
        # 3 x 59 nonzero bins don't fit 100: at most 1 pair bundles
        for g, nb in zip(plan.groups, plan.num_bin):
            assert nb <= 100


class TestBundledTraining:
    @pytest.fixture(scope="class")
    def sparse_xy(self):
        rng = np.random.default_rng(0)
        n = 6000
        cat = rng.integers(0, 30, size=n)
        # binary indicators: 2 bins each, so dozens fit in one bundle
        onehot = np.zeros((n, 30))
        onehot[np.arange(n), cat] = 1.0
        dense = rng.normal(size=(n, 4))
        X = np.column_stack([onehot, dense])
        y = ((cat % 3 == 0).astype(float) + 0.5 * dense[:, 0]
             + 0.3 * rng.normal(size=n) > 0.6).astype(float)
        return X, y

    def test_quality_matches_unbundled(self, sparse_xy):
        from sklearn.metrics import roc_auc_score
        X, y = sparse_xy
        params = {"objective": "binary", "num_leaves": 31,
                  "min_data_in_leaf": 10, "max_bin": 63}
        ds1 = lgb.Dataset(X, label=y)
        b1 = lgb.train(params, ds1, num_boost_round=15, verbose_eval=False,
                       keep_training_booster=True)
        ds2 = lgb.Dataset(X, label=y)
        b2 = lgb.train({**params, "enable_bundle": False}, ds2,
                       num_boost_round=15, verbose_eval=False)
        lrn = b1._driver.learner
        assert lrn.num_columns < lrn.num_features
        auc1 = roc_auc_score(y, b1.predict(X))
        auc2 = roc_auc_score(y, b2.predict(X))
        assert abs(auc1 - auc2) < 0.01

    def test_model_io_and_predict_unaffected(self, sparse_xy, tmp_path):
        """Bundling is a training-time representation: saved models and
        predictions speak original feature space."""
        X, y = sparse_xy
        ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
        bst = lgb.train({"objective": "binary", "num_leaves": 15},
                        ds, num_boost_round=5, verbose_eval=False)
        p = bst.predict(X[:100])
        bst.save_model(str(tmp_path / "m.txt"))
        re = lgb.Booster(model_file=str(tmp_path / "m.txt"))
        np.testing.assert_allclose(re.predict(X[:100]), p, rtol=1e-6)

    def test_dense_data_not_bundled(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(2000, 8))
        y = (X[:, 0] > 0).astype(float)
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "binary", "num_leaves": 15},
                        ds, num_boost_round=2, verbose_eval=False,
                        keep_training_booster=True)
        lrn = bst._driver.learner
        assert lrn.num_columns == lrn.num_features


class TestMultihostTransport:
    """find_bundles_multihost ships bin-id samples across ranks; the
    transport dtype must hold every bin id (uint16 silently truncates
    past 65535)."""

    def _fake_world(self, monkeypatch, seen):
        import jax
        from jax.experimental import multihost_utils

        monkeypatch.setattr(jax, "process_count", lambda: 2)

        def gather(a):
            seen.append(np.array(a, copy=True))
            return np.stack([a, a])

        monkeypatch.setattr(multihost_utils, "process_allgather", gather)

    def test_wide_bins_ride_uint32(self, monkeypatch):
        from lightgbm_tpu.io.bundling import find_bundles_multihost

        rng = np.random.default_rng(0)
        n, F = 64, 3
        num_bin = np.array([70_000, 5, 5], np.int64)
        bins = np.zeros((n, F), np.int32)
        bins[:, 0] = rng.integers(60_000, 70_000, size=n)  # > uint16 range
        bins[:, 1] = rng.integers(0, 5, size=n)
        seen = []
        self._fake_world(monkeypatch, seen)
        find_bundles_multihost(bins, num_bin, np.zeros(F), n,
                               sparse_threshold=0.9, max_conflict_rate=0.0,
                               max_bundle_bins=256)
        samples = [a for a in seen if a.ndim == 2]
        assert samples, "no sample payload was gathered"
        assert samples[0].dtype == np.uint32
        assert int(samples[0][:, 0].max()) >= 60_000, \
            "bin ids were truncated in transport"

    def test_narrow_bins_keep_uint16(self, monkeypatch):
        from lightgbm_tpu.io.bundling import find_bundles_multihost

        rng = np.random.default_rng(1)
        n, F = 64, 3
        num_bin = np.array([255, 5, 5], np.int64)
        bins = (rng.integers(0, 5, size=(n, F))).astype(np.uint16)
        seen = []
        self._fake_world(monkeypatch, seen)
        find_bundles_multihost(bins, num_bin, np.zeros(F), n,
                               sparse_threshold=0.9, max_conflict_rate=0.0,
                               max_bundle_bins=256)
        samples = [a for a in seen if a.ndim == 2]
        assert samples and samples[0].dtype == np.uint16
