"""Perf-regression sentinel (ISSUE 12): tools/bench_diff.py.

Exit-code contract: 0 = comparable + clean, 1 = regression, 2 =
refused (cross-backend / degraded / crash record — the comparisons the
r04->r05 postmortem proved are fiction), 3 = usage error.  Plus the
blackbox overlay mode of tools/trace_merge.py (who hung first).
"""

import importlib.util
import json
import os

import pytest

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"_{name}", os.path.join(_TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bd = _load("bench_diff")
tm = _load("trace_merge")


def _rec(**over):
    base = {"metric": "higgs1m_boosting_iters_per_sec", "value": 1.0,
            "train_auc": 0.81, "compile_s": 30.0, "n_programs": 10,
            "predict_rows_per_sec": 1e6, "serve_p99_ms": 5.0,
            "backend": "tpu", "degraded": False}
    base.update(over)
    return base


def _write(tmp_path, name, rec):
    p = tmp_path / name
    p.write_text(json.dumps(rec))
    return str(p)


class TestDiff:
    def test_clean_comparison_exits_zero(self, tmp_path):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json", _rec(value=1.02))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_OK
        assert "no regressions" in text

    def test_throughput_drop_is_a_regression(self, tmp_path):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json", _rec(value=0.5))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_REGRESSION
        assert "REGRESSION" in text and "value" in text

    def test_lower_better_direction(self, tmp_path):
        """compile_s GROWING is a regression; compile_s shrinking by
        the same ratio is an improvement, not a regression."""
        a = _write(tmp_path, "a.json", _rec())
        worse = _write(tmp_path, "w.json", _rec(compile_s=60.0))
        better = _write(tmp_path, "b.json", _rec(compile_s=15.0))
        assert bd.run(old_path=a, new_path=worse)[0] == \
            bd.EXIT_REGRESSION
        code, text = bd.run(old_path=a, new_path=better)
        assert code == bd.EXIT_OK and "improved" in text

    def test_within_tolerance_is_ok(self, tmp_path):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json", _rec(value=0.9))  # -10% < 15% tol
        assert bd.run(old_path=a, new_path=b)[0] == bd.EXIT_OK

    def test_program_zoo_gate_is_exact(self, tmp_path):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json", _rec(n_programs=11))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_REGRESSION and "n_programs" in text

    def test_hbm_metrics_participate(self, tmp_path):
        a = _write(tmp_path, "a.json",
                   _rec(train_peak_hbm_bytes=1_000_000))
        b = _write(tmp_path, "b.json",
                   _rec(train_peak_hbm_bytes=2_000_000))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_REGRESSION
        assert "train_peak_hbm_bytes" in text

    def test_zero_baseline_never_regresses(self, tmp_path):
        """A 0.0 baseline gives the relative tolerance no scale: a
        0.0 -> 0.01 serve_shed_pct move is noise, surfaced as
        new-nonzero, never a gate failure."""
        a = _write(tmp_path, "a.json", _rec(serve_shed_pct=0.0))
        b = _write(tmp_path, "b.json", _rec(serve_shed_pct=0.01))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_OK and "new-nonzero" in text
        same = _write(tmp_path, "s.json", _rec(serve_shed_pct=0.0))
        assert bd.run(old_path=a, new_path=same)[0] == bd.EXIT_OK

    def test_null_metrics_are_skipped(self, tmp_path):
        """Explicit nulls (CPU rounds) drop out of the diff instead of
        crashing or comparing against numbers."""
        a = _write(tmp_path, "a.json", _rec(train_peak_hbm_bytes=None))
        b = _write(tmp_path, "b.json", _rec(train_peak_hbm_bytes=None))
        assert bd.run(old_path=a, new_path=b)[0] == bd.EXIT_OK


class TestRefusal:
    def test_cross_backend_refused_with_distinct_exit_code(self,
                                                           tmp_path):
        """The acceptance scenario: TPU-vs-degraded-CPU is refused
        loudly with an exit code DISTINCT from the regression one."""
        a = _write(tmp_path, "a.json", _rec(backend="tpu"))
        b = _write(tmp_path, "b.json",
                   _rec(backend="cpu", degraded=True, value=0.1))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_REFUSED
        assert code != bd.EXIT_REGRESSION
        assert "REFUSED" in text and "cross-backend" in text

    def test_degraded_refused_by_default_allowed_explicitly(self,
                                                            tmp_path):
        a = _write(tmp_path, "a.json", _rec(backend="cpu",
                                            degraded=True))
        b = _write(tmp_path, "b.json", _rec(backend="cpu",
                                            degraded=True, value=1.01))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_REFUSED and "degraded" in text
        code, text = bd.run(old_path=a, new_path=b, allow_degraded=True)
        assert code == bd.EXIT_OK

    def test_unreadable_record_is_a_usage_error_not_a_regression(
            self, tmp_path):
        """A missing/corrupt record must exit EXIT_ERROR (3), never the
        regression code 1 — CI treating them distinctly must not
        misreport a typo'd path as a perf regression."""
        a = _write(tmp_path, "a.json", _rec())
        code, text = bd.run(old_path=a,
                            new_path=str(tmp_path / "missing.json"))
        assert code == bd.EXIT_ERROR and "cannot read" in text
        code, _ = bd.run(head=str(tmp_path / "missing.json"))
        assert code == bd.EXIT_ERROR
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert bd.run(old_path=a, new_path=str(bad))[0] == bd.EXIT_ERROR
        assert bd.main([a, str(bad)]) == bd.EXIT_ERROR

    def test_crash_record_refused(self, tmp_path):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json",
                   _rec(value=0.0, error="RuntimeError: boom"))
        code, text = bd.run(old_path=a, new_path=b)
        assert code == bd.EXIT_REFUSED and "CRASH" in text

    def test_committed_rounds_refuse_by_default(self):
        """The repo's own newest rounds (r04/r05) are degraded CPU
        runs: the default committed-vs-committed diff must refuse —
        exactly the honest verdict the r04->r05 postmortem reached by
        hand."""
        code, text = bd.run()
        assert code == bd.EXIT_REFUSED


class TestHeadMode:
    def test_head_vs_newest_committed(self, tmp_path):
        """--head compares a fresh record against the newest committed
        round (r05: degraded cpu), so a matching degraded-cpu HEAD
        refuses by default and diffs under --allow-degraded."""
        committed = bd.committed_records()
        assert committed, "repo has committed BENCH rounds"
        newest = committed[0][1]
        head = _write(tmp_path, "head.json", {
            **{k: v for k, v in newest.items()
               if isinstance(v, (int, float, str, bool))},
        })
        code, _ = bd.run(head=head)
        assert code == bd.EXIT_REFUSED     # r05 is degraded
        code, text = bd.run(head=head, allow_degraded=True)
        assert code == bd.EXIT_OK          # identical record: clean


class TestCLI:
    def test_main_exit_codes(self, tmp_path, capsys):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json", _rec(value=0.4))
        assert bd.main([a, b]) == bd.EXIT_REGRESSION
        assert "REGRESSION" in capsys.readouterr().out
        assert bd.main(["--gate", a, b]) == bd.EXIT_REGRESSION
        ok = _write(tmp_path, "ok.json", _rec())
        assert bd.main([a, ok]) == bd.EXIT_OK

    def test_tolerance_scale(self, tmp_path):
        a = _write(tmp_path, "a.json", _rec())
        b = _write(tmp_path, "b.json", _rec(value=0.75))  # -25%
        assert bd.run(old_path=a, new_path=b)[0] == bd.EXIT_REGRESSION
        assert bd.run(old_path=a, new_path=b,
                      tolerance_scale=2.0)[0] == bd.EXIT_OK


# ---------------------------------------------------------------------------
# blackbox overlay (tools/trace_merge.py --blackbox)
# ---------------------------------------------------------------------------
class TestBlackboxOverlay:
    def _dump(self, tmp_path, host, entries, reason="collective_timeout"):
        rec = {"reason": reason, "host": host, "pid": 1, "t": 100.0,
               "ring_depth": 512, "entries": entries, "metrics": {}}
        (tmp_path / f"blackbox-host{host}.json").write_text(
            json.dumps(rec))

    def test_who_hung_first(self, tmp_path):
        """Host 0 entered its collective first and never left; host 1's
        later in-flight collective is it waiting on host 0 — the
        verdict must name host 0."""
        self._dump(tmp_path, 0, [
            {"t": 10.0, "kind": "span_begin", "name": "collective/eval",
             "tid": 1},
        ])
        self._dump(tmp_path, 1, [
            {"t": 9.0, "kind": "span_begin", "name": "collective/eval",
             "tid": 1},
            {"t": 9.5, "kind": "span_end", "name": "collective/eval",
             "tid": 1},
            {"t": 12.0, "kind": "span_begin",
             "name": "collective/checkpoint_barrier", "tid": 1},
        ])
        overlay, hosts, report = tm.merge_blackbox(str(tmp_path))
        assert hosts[0]["in_flight"]["name"] == "collective/eval"
        assert hosts[1]["in_flight"]["name"] == \
            "collective/checkpoint_barrier"
        verdict = report[-1]
        assert "host 0 hung first" in verdict
        assert "collective/eval" in verdict
        # overlay timeline is globally wall-clock ordered
        ts = [e["t"] for e in overlay["timeline"]]
        assert ts == sorted(ts)

    def test_no_hang_verdict(self, tmp_path):
        self._dump(tmp_path, 0, [
            {"t": 1.0, "kind": "span_begin", "name": "collective/x",
             "tid": 1},
            {"t": 2.0, "kind": "span_end", "name": "collective/x",
             "tid": 1},
        ], reason="guard_raise")
        _, hosts, report = tm.merge_blackbox(str(tmp_path))
        assert hosts[0]["in_flight"] is None
        assert "no in-flight collective" in report[-1]

    def test_missing_dir_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            tm.merge_blackbox(str(tmp_path))

    def test_cli_blackbox_mode(self, tmp_path, capsys):
        self._dump(tmp_path, 0, [
            {"t": 5.0, "kind": "span_begin", "name": "collective/sync",
             "tid": 1},
        ])
        out = tm.main([str(tmp_path), "--blackbox"])
        assert os.path.exists(out)
        assert "hung first" in capsys.readouterr().out
