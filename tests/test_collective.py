"""Distributed fault tolerance (ISSUE 8): collective watchdogs,
deterministic (host, call-index) fault addressing, multihost-consistent
checkpoint groups, and elastic resume across shard topologies.

The load-bearing guarantees under test:

* a hung host-level collective becomes a structured `CollectiveTimeout`
  after the configured deadline — and an injected timeout mid-train
  still ends in a flushed, valid checkpoint and a predict-usable
  booster (the degradation path the reference's all-or-nothing
  `Network::Allreduce` lacks);
* a global checkpoint manifest only commits when EVERY host's bundle is
  durable at the SAME iteration, and resume refuses torn or
  mixed-iteration groups;
* a checkpoint taken at P shards/hosts resumes at P' (including 1) with
  int8/int16 models byte-identical to uninterrupted runs — scores are
  global f32 buffers and quantized rounding keys on the GLOBAL row
  index.
"""

import json
import os
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.booster import Booster
from lightgbm_tpu.parallel import collective
from lightgbm_tpu.parallel.collective import (CollectiveTimeout,
                                              HostDropped,
                                              guarded_collective)
from lightgbm_tpu.parallel.mesh import row_offsets
from lightgbm_tpu.utils import faultline
from lightgbm_tpu.utils.checkpoint import (CheckpointManager,
                                           _params_fingerprint,
                                           params_diff, save_checkpoint)

P = {"objective": "binary", "num_leaves": 13, "max_bin": 47,
     "min_data_in_leaf": 5, "bagging_fraction": 0.8, "bagging_freq": 1,
     "verbosity": -1}


def _data(n=1500, f=6, seed=11):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.4 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


X, Y = _data()


def _model(bst) -> str:
    return bst.model_to_string(num_iteration=-1).split("\nparameters:")[0]


def _train(params, rounds, **kw):
    ds = lgb.Dataset(X, label=Y, params=params)
    return lgb.train(params, ds, num_boost_round=rounds,
                     keep_training_booster=True, **kw)


@pytest.fixture(autouse=True)
def _clean():
    faultline.reset()
    collective.configure(timeout_s=0.0, retries=1, backoff_s=0.25)
    yield
    faultline.reset()
    collective.configure(timeout_s=0.0, retries=1, backoff_s=0.25)


# ---------------------------------------------------------------------------
class TestGuardedCollective:
    def test_passthrough(self):
        assert guarded_collective(lambda a, b: a + b, 2, 3,
                                  name="t") == 5

    def test_deadline_expiry_is_structured(self):
        with pytest.raises(CollectiveTimeout) as ei:
            guarded_collective(lambda: time.sleep(5.0), name="slow",
                               timeout_s=0.05)
        assert ei.value.name == "slow"
        assert ei.value.timeout_s == pytest.approx(0.05)
        assert ei.value.attempts == 1

    def test_transient_failure_retries_with_backoff(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise OSError("transient DCN hiccup")
            return "ok"

        t0 = time.time()
        assert guarded_collective(flaky, name="t", retries=2,
                                  backoff_s=0.05) == "ok"
        assert len(calls) == 2
        assert time.time() - t0 >= 0.05  # backoff actually waited

    def test_retry_budget_exhausts(self):
        def broken():
            raise OSError("still down")

        with pytest.raises(OSError):
            guarded_collective(broken, name="t", retries=1, backoff_s=0.0)

    def test_injected_raise_is_retried_as_transient(self):
        faultline.arm("collective_sync", action="raise", times=1)
        assert guarded_collective(lambda: 7, name="t", retries=1,
                                  backoff_s=0.0) == 7
        assert faultline.hits("collective_sync") == 2  # one per attempt

    def test_injected_hang_times_out_via_real_deadline(self):
        faultline.arm("collective_sync", action="hang")
        with pytest.raises(CollectiveTimeout):
            guarded_collective(lambda: 1, name="t", timeout_s=0.05,
                               retries=3)

    def test_injected_hang_on_local_identity(self):
        faultline.arm("collective_sync", action="hang")
        with pytest.raises(CollectiveTimeout):
            guarded_collective(lambda: 1, name="t", local=True)

    def test_timeout_never_retries(self):
        faultline.arm("collective_sync", action="hang", times=5)
        with pytest.raises(CollectiveTimeout) as ei:
            guarded_collective(lambda: 1, name="t", timeout_s=0.05,
                               retries=5)
        assert ei.value.attempts == 1

    def test_host_drop_bypasses_retry(self):
        faultline.arm("host_drop", action="raise", times=5)
        with pytest.raises(HostDropped):
            guarded_collective(lambda: 1, name="t", retries=5,
                               backoff_s=0.0)
        assert faultline.hits("host_drop") == 1

    def test_host_drop_custom_exc_still_bypasses_retry(self):
        """An armed host_drop with a custom exception type (e.g. a real
        transport error class) must normalize to HostDropped, not slip
        into the transient-retry branch."""
        faultline.arm("host_drop", action="raise",
                      exc=ConnectionError("peer died"))
        with pytest.raises(HostDropped):
            guarded_collective(lambda: 1, name="t", retries=5,
                               backoff_s=0.0)
        assert faultline.hits("host_drop") == 1

    def test_configure_sets_process_defaults(self):
        collective.configure(timeout_s=12.5, retries=4)
        d = collective.defaults()
        assert d["timeout_s"] == 12.5 and d["retries"] == 4

    def test_default_params_booster_does_not_disarm_watchdog(self):
        collective.configure(timeout_s=60.0)
        ds = lgb.Dataset(X, label=Y, params=dict(P))
        Booster(params=dict(P), train_set=ds)  # unset (-1): no clobber
        assert collective.defaults()["timeout_s"] == 60.0
        p2 = dict(P, tpu_collective_timeout_s=5.0)
        Booster(params=p2, train_set=lgb.Dataset(X, label=Y, params=p2))
        assert collective.defaults()["timeout_s"] == 5.0
        # explicit 0 really disables (unlike the -1 unset default)
        p3 = dict(P, tpu_collective_timeout_s=0)
        Booster(params=p3, train_set=lgb.Dataset(X, label=Y, params=p3))
        assert collective.defaults()["timeout_s"] == 0.0


# ---------------------------------------------------------------------------
class TestFaultlineAddressing:
    def test_host_addressed_spec_only_fires_on_that_host(self):
        faultline.set_host_index(1)
        faultline.arm("collective_sync", action="raise", host=0)
        assert faultline.fire("collective_sync") is None  # host 1: no-op
        faultline.set_host_index(0)
        with pytest.raises(faultline.FaultInjected):
            faultline.fire("collective_sync")

    def test_absolute_call_index_is_arm_time_independent(self):
        for _ in range(3):
            faultline.fire("collective_sync")
        # absolute index 2 already passed: the coordinate names ONE call
        # in the execution, so a spec armed after it must never fire —
        # not drift onto a later call like relative arming would
        faultline.arm("collective_sync", action="raise", at=2,
                      absolute=True, times=1)
        for _ in range(4):
            assert faultline.fire("collective_sync") is None

    def test_absolute_addressing_reproducible_after_reset(self):
        faultline.reset()
        faultline.arm("collective_sync", action="raise", at=2,
                      absolute=True)
        assert faultline.fire("collective_sync") is None  # call 1
        with pytest.raises(faultline.FaultInjected):
            faultline.fire("collective_sync")             # call 2

    def test_reset_clears_host_override(self):
        faultline.set_host_index(3)
        assert faultline.host_index() == 3
        faultline.reset()
        assert faultline.host_index() != 3 or \
            os.environ.get("LIGHTGBM_TPU_FAULT_HOST") == "3"

    def test_host_and_absolute_compose(self):
        faultline.set_host_index(2)
        faultline.arm("host_drop", action="raise", at=3, absolute=True,
                      host=2)
        faultline.arm("host_drop", action="raise", at=1, absolute=True,
                      host=0)  # other host: must never fire here
        assert faultline.fire("host_drop") is None
        assert faultline.fire("host_drop") is None
        with pytest.raises(faultline.FaultInjected):
            faultline.fire("host_drop")


# ---------------------------------------------------------------------------
class TestWatchdogDegradation:
    def test_timeout_mid_eval_leaves_booster_usable(self):
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        vX, vY = _data(400, 6, seed=5)
        bst.add_valid(lgb.Dataset(vX, label=vY, reference=ds, params=p),
                      "v")
        for _ in range(3):
            bst.update()
        faultline.arm("collective_sync", action="hang")
        with pytest.raises(CollectiveTimeout):
            bst.eval_valid()
        faultline.reset()
        # degraded, not dead: predict, eval, and continued training work
        assert np.isfinite(bst.predict(X[:64], raw_score=True)).all()
        bst.update()
        assert bst.current_iteration() == 4

    def test_timeout_mid_train_flushes_checkpoint_then_bitwise_resume(
            self, tmp_path):
        base = _model(_train(dict(P), 6))
        p = dict(P, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_interval=1)
        vX, vY = _data(400, 6, seed=5)

        def run(rounds, arm_at=None, resume=False):
            ds = lgb.Dataset(X, label=Y, params=p)
            dv = lgb.Dataset(vX, label=vY, reference=ds, params=p)
            if arm_at is not None:
                # each iteration's eval syncs once per metric: the N-th
                # collective call lands mid-train deterministically
                faultline.arm("collective_sync", action="hang",
                              at=arm_at, absolute=True)
            return lgb.train(p, ds, num_boost_round=rounds,
                             valid_sets=[dv], valid_names=["v"],
                             keep_training_booster=True, resume=resume,
                             verbose_eval=False)

        with pytest.raises(CollectiveTimeout):
            run(6, arm_at=4)
        faultline.reset()
        # the engine flushed a final checkpoint before re-raising
        mgr = CheckpointManager(str(tmp_path))
        found = mgr.load_latest()
        assert found is not None and 1 <= found[0] < 6
        # resume reproduces the uninterrupted bytes
        assert _model(run(6, resume=True)) == base


# ---------------------------------------------------------------------------
def _fake_barrier(entries):
    """A barrier stub standing in for process_allgather in a simulated
    host group: returns the given per-host [iteration, crc, rows]
    triples."""
    return lambda vec: [np.asarray(e, np.int64) for e in entries]


def _save_host_bundles(root, iteration, host_payloads, keep=3):
    """Write one bundle per simulated host; returns the managers."""
    mgrs = []
    for k, (model_text, state, arrays) in enumerate(host_payloads):
        m = CheckpointManager(str(root), keep=keep, host_index=k,
                              host_count=len(host_payloads))
        m.save(iteration, model_text, state, arrays)
        mgrs.append(m)
    return mgrs


def _set_bundle_host_count(bundle_dir, hc):
    """Stamp a saved bundle's recorded topology host_count (manifest
    CRC refreshed) — simulates a bundle written by an hc-host group."""
    import zlib as _zlib

    st_path = os.path.join(str(bundle_dir), "state.json")
    with open(st_path) as f:
        st = json.load(f)
    st.setdefault("topology", {})["host_count"] = hc
    raw = json.dumps(st, sort_keys=True).encode()
    with open(st_path, "wb") as f:
        f.write(raw)
    man_path = os.path.join(str(bundle_dir), "manifest.json")
    with open(man_path) as f:
        man = json.load(f)
    man["files"]["state.json"] = {"crc32": _zlib.crc32(raw),
                                  "bytes": len(raw)}
    with open(man_path, "w") as f:
        json.dump(man, f)


def _bundle(rows, host, hosts, it=3):
    state = {"iteration": it,
             "topology": {"rows": rows, "host_count": hosts,
                          "host_index": host, "partitioned": True}}
    arrays = {"train_scores":
              np.full((1, rows), float(host), np.float32)}
    return f"model-{it}", state, arrays


class TestMultihostCheckpointGroup:
    def test_commit_requires_all_hosts_same_iteration(self, tmp_path):
        mgrs = _save_host_bundles(tmp_path, 3,
                                  [_bundle(100, 0, 2), _bundle(80, 1, 2)])
        crc1 = mgrs[1].manifest_crc(mgrs[1].host_bundle_path(1, 3))
        crc0 = mgrs[0].manifest_crc(mgrs[0].host_bundle_path(0, 3))
        # mixed iterations at the barrier: the commit must refuse
        with pytest.raises(ValueError, match="mixed-iteration"):
            mgrs[0].commit_global(3, barrier=_fake_barrier(
                [[3, crc0, 100], [2, crc1, 80]]))
        assert mgrs[0].group_manifests() == []
        # a consistent barrier commits (rank 0 only)
        path = mgrs[0].commit_global(3, barrier=_fake_barrier(
            [[3, crc0, 100], [3, crc1, 80]]))
        assert path and os.path.exists(path)
        assert mgrs[1].commit_global(3, barrier=_fake_barrier(
            [[3, crc1, 80], [3, crc1, 80]])) is None  # non-zero rank

    def test_group_validation_refuses_torn_sets(self, tmp_path):
        mgrs = _save_host_bundles(tmp_path, 3,
                                  [_bundle(100, 0, 2), _bundle(80, 1, 2)])
        crcs = [m.manifest_crc(m.host_bundle_path(m.host_index, 3))
                for m in mgrs]
        mgrs[0].commit_global(3, barrier=_fake_barrier(
            [[3, crcs[0], 100], [3, crcs[1], 80]]))
        it, manifest = mgrs[0].load_latest_group()
        assert it == 3 and mgrs[0].validate_group(manifest)
        # tear host 1's bundle: the group must stop validating and
        # load_latest_group must skip it
        victim = os.path.join(mgrs[1].host_bundle_path(1, 3),
                              "arrays.npz")
        with open(victim, "r+b") as f:
            f.truncate(8)
        assert not mgrs[0].validate_group(manifest)
        assert mgrs[0].load_latest_group() is None

    def test_refuses_commit_on_torn_local_bundle(self, tmp_path):
        """A torn local bundle still ENTERS the barrier (raising before
        it would strand the healthy peers inside the allgather) and the
        whole group refuses via the sentinel."""
        m = CheckpointManager(str(tmp_path), host_index=0, host_count=2)
        seen = []

        def barrier(vec):
            seen.append(np.asarray(vec).tolist())
            return [vec, np.asarray([9, 123, 80], np.int64)]

        with pytest.raises(ValueError, match="torn/missing"):
            m.commit_global(9, barrier=barrier)
        # this host reached the barrier and contributed the sentinel
        assert seen == [[-1, 0, 0]]
        assert m.group_manifests() == []

    def test_group_manifest_retention(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep=2, host_index=0,
                              host_count=1)
        # host_count=1 writes flat; drive commit bookkeeping directly
        for it in (1, 2, 3, 4):
            m.save(it, f"model-{it}", {"iteration": it},
                   {"train_scores": np.zeros((1, 4), np.float32)})
            crc = m.manifest_crc(m.host_bundle_path(0, it))
            m.commit_global(it, barrier=_fake_barrier([[it, crc, 4]]))
        assert [it for it, _ in m.group_manifests()] == [4, 3]

    def test_elastic_resume_from_partitioned_group(self, tmp_path):
        """A 2-host partitioned checkpoint group resumes on ONE process
        bitwise: global buffers reassemble in process order."""
        base = _model(_train(dict(P), 6))
        # build the "2-host" group from a real single-host checkpoint:
        # slice its global arrays into per-host halves
        solo = tmp_path / "solo"
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        for _ in range(3):
            bst.update()
        save_checkpoint(bst, CheckpointManager(str(solo)))
        it, model_text, state, arrays, _ = \
            CheckpointManager(str(solo)).load_latest()
        n = arrays["train_scores"].shape[1]
        n0 = n // 2
        group = tmp_path / "group"
        payloads = []
        for k, (lo, hi) in enumerate(((0, n0), (n0, n))):
            st = json.loads(json.dumps(state))  # deep copy
            st["topology"] = {"rows": hi - lo, "host_count": 2,
                              "host_index": k, "partitioned": True}
            arr = {"train_scores":
                   np.ascontiguousarray(arrays["train_scores"][:, lo:hi])}
            if "bag_mask" in arrays:
                arr["bag_mask"] = np.ascontiguousarray(
                    arrays["bag_mask"][lo:hi])
            payloads.append((model_text, st, arr))
        mgrs = _save_host_bundles(group, it, payloads)
        crcs = [m.manifest_crc(m.host_bundle_path(m.host_index, it))
                for m in mgrs]
        mgrs[0].commit_global(it, barrier=_fake_barrier(
            [[it, crcs[0], n0], [it, crcs[1], n - n0]]),
            topology=payloads[0][1]["topology"])
        # resume on the live single-process topology: the loader must
        # reassemble host slices into the global buffers
        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        assert bst2.resume_from_checkpoint(str(group)) == 3
        for _ in range(3):
            bst2.update()
        assert _model(bst2) == base

    def test_malformed_group_manifest_is_skipped_not_fatal(self,
                                                           tmp_path):
        """A manifest that parses as JSON but has malformed hosts
        entries must read as invalid (skip-with-warning), not crash the
        resume — and an older valid group must still be found."""
        mgrs = _save_host_bundles(tmp_path, 3,
                                  [_bundle(100, 0, 2), _bundle(80, 1, 2)])
        crcs = [m.manifest_crc(m.host_bundle_path(m.host_index, 3))
                for m in mgrs]
        mgrs[0].commit_global(3, barrier=_fake_barrier(
            [[3, crcs[0], 100], [3, crcs[1], 80]]))
        for bad in ({"iteration": 9, "host_count": 2, "hosts": 7},
                    {"iteration": 9, "host_count": 2,
                     "hosts": [{"index": 0}, {"index": 1}]},
                    {"iteration": 9, "host_count": 2,
                     "hosts": [0, 1]}):
            assert mgrs[0].validate_group(bad) is False
        # a newer malformed manifest on disk: walked past, older used
        with open(tmp_path / "global-00000009.json", "w") as f:
            json.dump({"iteration": 9, "host_count": 2, "hosts": 7}, f)
        it, manifest = mgrs[0].load_latest_group()
        assert it == 3

    def test_stale_global_temp_files_are_swept(self, tmp_path):
        m = CheckpointManager(str(tmp_path), keep=2, host_index=0,
                              host_count=1)
        debris = tmp_path / ".tmp-global-00000001.json-999"
        debris.write_text("{}")
        m.save(2, "model-2", {"iteration": 2},
               {"train_scores": np.zeros((1, 4), np.float32)})
        crc = m.manifest_crc(m.host_bundle_path(0, 2))
        m.commit_global(2, barrier=_fake_barrier([[2, crc, 4]]))
        assert not debris.exists()

    def test_uncommitted_set_at_changed_host_count_falls_back(
            self, tmp_path, monkeypatch):
        """Uncommitted bundles written at P hosts cannot be used by a
        P'-host group (no committed manifest to re-shard from): resume
        must fall back to the older flat checkpoint, not hand each live
        host a stale slice."""
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        ckdir = tmp_path / "grp"
        for _ in range(2):
            bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir)))  # flat @2
        bst.update()
        # "4-host" uncommitted bundle on host 0 at iteration 3
        save_checkpoint(bst, CheckpointManager(str(ckdir / "host-00000")))
        _set_bundle_host_count(ckdir / "host-00000" / "ckpt-00000003", 4)
        mgr = CheckpointManager(str(ckdir), host_index=0, host_count=2)
        monkeypatch.setattr(
            CheckpointManager, "_default_barrier",
            lambda self, vec: [vec, np.asarray([3, 0, 0], np.int64)])
        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        from lightgbm_tpu.utils.checkpoint import restore_checkpoint
        state = restore_checkpoint(bst2, mgr)
        assert state is not None and int(state["iteration"]) == 2

    def test_row_offsets_helper(self):
        offs, total = row_offsets([100, 80, 120])
        np.testing.assert_array_equal(offs, [0, 100, 180])
        assert total == 300

    def test_uncommitted_group_resumes_min_common_iteration(
            self, tmp_path, monkeypatch):
        """No committed global manifest: the hosts must agree on the
        MIN-COMMON locally-valid iteration — each picking its own newest
        would desync the group's collective streams."""
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        ckdir = tmp_path / "grp"
        for _ in range(2):
            bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir / "host-00000"),
                                               keep=10))
        bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir / "host-00000"),
                                               keep=10))
        for it in (2, 3):
            _set_bundle_host_count(
                ckdir / "host-00000" / f"ckpt-{it:08d}", 2)
        # "host 1" (simulated at the barrier) only reached iteration 2
        mgr = CheckpointManager(str(ckdir), keep=10, host_index=0,
                                host_count=2)
        monkeypatch.setattr(
            CheckpointManager, "_default_barrier",
            lambda self, vec: [vec, np.asarray([2, 0, 0], np.int64)])
        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        from lightgbm_tpu.utils.checkpoint import restore_checkpoint
        state = restore_checkpoint(bst2, mgr)
        assert state is not None and int(state["iteration"]) == 2

    def test_uncommitted_host_bundles_outrank_stale_flat_root(
            self, tmp_path, monkeypatch):
        """The group's newest durable state (uncommitted per-host
        bundles) must win over an older flat root checkpoint left from
        a single-host run the pod resumed from."""
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        ckdir = tmp_path / "grp"
        for _ in range(2):
            bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir)))  # flat @2
        bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir / "host-00000")))
        _set_bundle_host_count(ckdir / "host-00000" / "ckpt-00000003", 2)
        mgr = CheckpointManager(str(ckdir), host_index=0, host_count=2)
        monkeypatch.setattr(
            CheckpointManager, "_default_barrier",
            lambda self, vec: [vec, np.asarray([3, 0, 0], np.int64)])
        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        from lightgbm_tpu.utils.checkpoint import restore_checkpoint
        state = restore_checkpoint(bst2, mgr)
        assert state is not None and int(state["iteration"]) == 3

    def test_newer_flat_checkpoint_outranks_older_committed_group(
            self, tmp_path):
        """A committed group manifest must not shadow NEWER durable
        progress (e.g. the pod run was elastically resumed single-host
        and trained further before dying again)."""
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        ckdir = tmp_path / "grp"
        for _ in range(2):
            bst.update()
        # committed "1-host group" at iteration 2: host dir + manifest
        hmgr = CheckpointManager(str(ckdir), host_index=0, host_count=1)
        save_checkpoint(bst, hmgr)
        crc = hmgr.manifest_crc(hmgr.host_bundle_path(0, 2))
        hmgr.commit_global(2, barrier=_fake_barrier([[2, crc, len(Y)]]))
        # newer flat checkpoint at iteration 3
        bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir)))
        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        assert bst2.resume_from_checkpoint(str(ckdir)) == 3

    def test_uncommitted_group_with_bundleless_host_refuses(
            self, tmp_path, monkeypatch):
        p = dict(P)
        ds = lgb.Dataset(X, label=Y, params=p)
        bst = Booster(params=p, train_set=ds)
        ckdir = tmp_path / "grp"
        bst.update()
        save_checkpoint(bst, CheckpointManager(str(ckdir / "host-00000")))
        mgr = CheckpointManager(str(ckdir), host_index=0, host_count=2)
        monkeypatch.setattr(
            CheckpointManager, "_default_barrier",
            lambda self, vec: [vec, np.asarray([-1, 0, 0], np.int64)])
        ds2 = lgb.Dataset(X, label=Y, params=p)
        bst2 = Booster(params=p, train_set=ds2)
        from lightgbm_tpu.utils.checkpoint import restore_checkpoint
        with pytest.raises(ValueError, match="cannot resume consistently"):
            restore_checkpoint(bst2, mgr)


# ---------------------------------------------------------------------------
class TestElasticResume:
    """Device-shard elastic resume: checkpoint at P data shards, resume
    at P' — models must stay byte-identical for quantized precisions
    (the dryrun sweeps the full (P, P') matrix; tier-1 covers one
    direction each way)."""

    @pytest.mark.parametrize("p1,p2", [(2, 4), (4, 1)])
    def test_int8_bitwise_across_shard_counts(self, tmp_path, p1, p2):
        q = dict(P, tpu_hist_precision="int8", tree_learner="data",
                 tpu_quant_refit_leaves=False)
        base = _model(_train(dict(q, num_machines=1), 6))
        pc = dict(q, tpu_checkpoint_dir=str(tmp_path))
        _train(dict(pc, num_machines=p1), 3)
        resumed = _train(dict(pc, num_machines=p2), 6, resume=True)
        assert _model(resumed) == base

    def test_elastic_refused_when_disabled(self, tmp_path):
        q = dict(P, tree_learner="data",
                 tpu_checkpoint_dir=str(tmp_path))
        _train(dict(q, num_machines=2), 3)
        with pytest.raises(ValueError, match="tpu_resume_elastic"):
            _train(dict(q, num_machines=4, tpu_resume_elastic=False), 6,
                   resume=True)

    def test_elastic_refusal_survives_material_mismatch(self, tmp_path):
        """A co-occurring material param change must not smuggle a
        refused re-shard past tpu_resume_elastic=false."""
        q = dict(P, tree_learner="data",
                 tpu_checkpoint_dir=str(tmp_path))
        _train(dict(q, num_machines=2), 3)
        with pytest.raises(ValueError, match="tpu_resume_elastic"):
            _train(dict(q, num_machines=4, learning_rate=0.2,
                        tpu_resume_elastic=False), 6, resume=True)

    def test_material_params_mismatch_names_keys(self, tmp_path, capsys):
        q = dict(P, tpu_checkpoint_dir=str(tmp_path))
        _train(q, 3)
        _train(dict(q, learning_rate=0.2), 6, resume=True)
        captured = capsys.readouterr()
        out = captured.out + captured.err
        assert "learning_rate" in out and "0.2" in out

    def test_strict_mode_raises_with_named_keys(self, tmp_path):
        q = dict(P, tpu_checkpoint_dir=str(tmp_path))
        _train(q, 3)
        with pytest.raises(ValueError, match="learning_rate"):
            _train(dict(q, learning_rate=0.2, tpu_resume_strict=True), 6,
                   resume=True)

    def test_params_diff_classification(self):
        stored = {"learning_rate": "0.1", "num_machines": "4",
                  "max_bin": "47"}
        live = {"learning_rate": "0.1", "num_machines": "2",
                "max_bin": "63"}
        elastic, material = params_diff(stored, live)
        assert [k for k, _, _ in elastic] == ["num_machines"]
        assert [k for k, _, _ in material] == ["max_bin"]

    def test_fingerprint_ignores_topology_keys(self):
        a = _params_fingerprint({"learning_rate": 0.1, "num_machines": 4,
                                 "workers": "a:1,b:2"})
        b = _params_fingerprint({"learning_rate": 0.1, "num_machines": 1})
        c = _params_fingerprint({"learning_rate": 0.2, "num_machines": 4})
        assert a == b
        assert a != c
