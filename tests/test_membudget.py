"""Memory pressure (ISSUE 15): the HBM budget planner, OOM
classification + recovery ladder, and pressure-aware serving eviction.

Five layers of proof:

1. **classification** — RESOURCE_EXHAUSTED-shaped errors (including the
   faultline ``oom`` action's realistic injection) classify into
   `DeviceOutOfMemory` naming the guarded site; non-OOM errors never
   do.
2. **planner math** — the preflight plan's pool/bins components equal
   the LIVE learner buffers byte-for-byte, the serving plan equals the
   actually-uploaded packed-table bytes, and the CompileLedger's
   independent ``memory_analysis()`` oracle is covered by the plan.
3. **recovery** — an injected mid-train OOM at EVERY guarded site rolls
   back, descends the deterministic ladder, and completes with a model
   BYTE-IDENTICAL to an undisturbed run (serial + int8 2-shard); ladder
   exhaustion leaves a valid final checkpoint, a usable booster, and a
   blackbox dump naming the site.
4. **serving pressure** — over-budget loads refuse with the structured
   507 instead of warming into a crash, sustained pressure evicts cold
   LRU versions, and a dispatch-path OOM is served via walker failover
   with zero errors to accepted requests.
5. **surfaces** — /stats, /healthz and /metrics carry the budget and
   pressure numbers; bench_diff knows the new fields' directions.
"""

import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.booster import Booster
from lightgbm_tpu.obs import REGISTRY, flightrecorder
from lightgbm_tpu.serving import ServingSession
from lightgbm_tpu.serving.server import serve_http
from lightgbm_tpu.utils import faultline, membudget
from lightgbm_tpu.utils.checkpoint import CheckpointManager
from lightgbm_tpu.utils.log import LightGBMError

BASE = {"objective": "binary", "num_leaves": 7, "max_bin": 15,
        "min_data_in_leaf": 5, "verbosity": -1}


def make_xy(n=800, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def model_str(bst):
    return bst.model_to_string(num_iteration=-1).split("\nparameters:")[0]


def train(params, X, y, rounds=3, **kw):
    ds = lgb.Dataset(X, label=y, params=params)
    return lgb.train(params, ds, num_boost_round=rounds,
                     keep_training_booster=True, verbose_eval=False,
                     **kw)


def counter(metric, **labels):
    return float(REGISTRY.value(metric, **labels))


@pytest.fixture(autouse=True)
def _clean_faults():
    faultline.reset()
    yield
    faultline.reset()


# ---------------------------------------------------------------------------
# 1. classification
# ---------------------------------------------------------------------------
class TestClassifier:
    def test_resource_exhausted_shapes_classify(self):
        for msg in (
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                "allocate 17179869184 bytes.",
                "Resource exhausted: Failed to allocate request for "
                "2.0GiB",
                "Execution failed: OOM when allocating tensor",
                "Out of memory allocating 123 bytes"):
            assert membudget.is_oom_error(RuntimeError(msg)), msg
        assert membudget.is_oom_error(MemoryError())

    def test_non_oom_never_classifies(self):
        assert not membudget.is_oom_error(ValueError("RESOURCE_EXHAUSTED"))
        assert not membudget.is_oom_error(RuntimeError("shape mismatch"))
        assert not membudget.is_oom_error(KeyError("x"))
        # a generic injected fault is NOT an OOM — only the oom action
        assert not membudget.is_oom_error(
            faultline.FaultInjected("RESOURCE_EXHAUSTED lookalike"))
        # the bare acronym matches only as an UPPER-CASE whole word: a
        # substring/case-folded match would misclassify ordinary words
        for msg in ("no room left in the queue", "zoom level invalid",
                    "boom: handler crashed", "the bathroom is closed"):
            assert not membudget.is_oom_error(RuntimeError(msg)), msg

    def test_faultline_oom_action_is_realistic(self):
        faultline.arm("device_alloc", action="oom")
        with pytest.raises(Exception) as ei:
            faultline.fire("device_alloc", site="test")
        exc = ei.value
        assert not isinstance(exc, faultline.FaultInjected)
        assert "RESOURCE_EXHAUSTED" in str(exc)
        assert membudget.is_oom_error(exc)

    def test_oom_guard_classifies_and_names_site(self):
        before = counter("lgbm_oom_events_total", site="predict_chunk")
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            with membudget.oom_guard("predict_chunk", rows=7):
                raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory")
        assert ei.value.site == "predict_chunk"
        assert counter("lgbm_oom_events_total",
                       site="predict_chunk") == before + 1
        # and the flight-recorder ring names the site
        ent = [e for e in flightrecorder.entries()
               if e["kind"] == "oom" and e["name"] == "device_oom"]
        assert ent and ent[-1]["fields"]["site"] == "predict_chunk"

    def test_oom_guard_passes_other_errors_through(self):
        with pytest.raises(ValueError):
            with membudget.oom_guard("train_step"):
                raise ValueError("not a memory problem")

    def test_inner_site_name_wins_through_nested_guards(self):
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            with membudget.oom_guard("train_step"):
                with membudget.oom_guard("score_replay"):
                    raise RuntimeError("RESOURCE_EXHAUSTED: oom")
        assert ei.value.site == "score_replay"


# ---------------------------------------------------------------------------
# 2. budget resolution + planner math
# ---------------------------------------------------------------------------
class TestBudget:
    def test_explicit_bytes_honored_on_any_backend(self):
        from lightgbm_tpu.config import Config

        cfg = Config({"tpu_hbm_budget_bytes": 12345})
        assert membudget.budget_bytes(cfg) == 12345

    def test_auto_budget_scales_capacity(self, monkeypatch):
        from lightgbm_tpu.config import Config

        monkeypatch.setattr(membudget, "device_capacity_bytes",
                            lambda: 1000)
        cfg = Config({"tpu_hbm_budget_frac": 0.5})
        assert membudget.budget_bytes(cfg) == 500

    def test_no_budget_on_nonreporting_backend(self):
        from lightgbm_tpu.config import Config

        # CPU reports no memory_stats: nothing resolves, None not 0
        assert membudget.budget_bytes(Config({})) is None

    def test_serving_budget_falls_back_to_training(self):
        from lightgbm_tpu.config import Config

        cfg = Config({"tpu_hbm_budget_bytes": 777})
        assert membudget.serving_budget_bytes(cfg) == 777
        cfg2 = Config({"tpu_hbm_budget_bytes": 777,
                       "serving_hbm_budget_bytes": 55})
        assert membudget.serving_budget_bytes(cfg2) == 55

    def test_device_capacity_memoized_once(self, monkeypatch):
        """Capacity is static per process: the devices are queried ONCE
        and the answer memoized — /healthz probes and locked eviction
        paths must not pay device round-trips to re-derive a constant.
        An unknown answer (backend not up yet) is never pinned."""
        import lightgbm_tpu.obs.resources as resources

        calls = []

        def stats():
            calls.append(1)
            return [{"bytes_limit": 1000}]

        monkeypatch.setattr(membudget, "_capacity_memo", [])
        monkeypatch.setattr(resources, "_devices", lambda: ["d0"])
        monkeypatch.setattr(resources, "all_device_memory_stats", stats)
        assert membudget.device_capacity_bytes() == 1000
        assert membudget.device_capacity_bytes() == 1000
        assert len(calls) == 1
        # no devices yet -> None returned but NOT cached; the first
        # post-init call still resolves the real capacity
        monkeypatch.setattr(membudget, "_capacity_memo", [])
        monkeypatch.setattr(resources, "_devices", lambda: [])
        assert membudget.device_capacity_bytes() is None
        monkeypatch.setattr(resources, "_devices", lambda: ["d0"])
        assert membudget.device_capacity_bytes() == 1000


class TestPlanner:
    @pytest.fixture(scope="class")
    def trained(self):
        X, y = make_xy()
        bst = train(dict(BASE), X, y, rounds=2)
        return bst, X, y

    def test_pool_and_bins_components_exact(self, trained):
        bst, _, _ = trained
        drv = bst._driver
        plan = membudget.plan_training(drv.config, drv.learner,
                                       drv.num_tree_per_iteration)
        assert plan.components["histogram_pool"] == \
            int(drv.learner._pool.nbytes)
        assert plan.components["binned_matrix"] == \
            int(drv.learner.bins_t.nbytes)
        # every named component is a positive itemized number
        for name in ("stats_planes", "score_buffers", "packed_forest",
                     "ingest_scratch", "predict_scratch"):
            assert plan.components[name] > 0, name

    def test_plan_fits_semantics_and_table(self, trained):
        bst, _, _ = trained
        drv = bst._driver
        plan = membudget.plan_training(drv.config, drv.learner, 1)
        assert plan.fits is None          # no budget on CPU
        from lightgbm_tpu.config import Config

        cfg = Config({**BASE, "tpu_hbm_budget_bytes": 10})
        tight = membudget.plan_training(cfg, drv.learner, 1)
        assert tight.fits is False and tight.headroom < 0
        msg = tight.refuse_message("test")
        assert "histogram_pool" in msg and "budget" in msg

    def test_plan_vs_ledger_memory_analysis_oracle(self):
        """The independent oracle: the CompileLedger's captured
        memory_analysis (forced on CPU) for the grow program must be
        COVERED by the plan — the plan itemizes every argument buffer
        XLA counts, plus consumers outside any one program."""
        from lightgbm_tpu.utils.compile_ledger import LEDGER

        # a UNIQUE shape: the memoized grower + jit cache would satisfy
        # an already-seen shape without compiling (= nothing captured)
        X, y = make_xy(n=900, f=7, seed=3)
        LEDGER.enable()
        LEDGER.enable_capture()
        LEDGER.reset()
        try:
            bst = train(dict(BASE), X, y, rounds=2)
            drv = bst._driver
            plan = membudget.plan_training(drv.config, drv.learner,
                                           drv.num_tree_per_iteration)
            check = membudget.ledger_cross_check(plan, site="grow")
            assert check is not None, "no analyzed grow program captured"
            assert check["ledger_argument_bytes"] > 0
            assert check["covered"], check
        finally:
            LEDGER.enable_capture(False)
            LEDGER.enable(False)
            LEDGER.reset()

    def test_serving_plan_matches_uploaded_bytes(self, trained):
        bst, _, _ = trained
        from lightgbm_tpu.config import Config

        cfg = Config({"verbosity": -1})
        plan = membudget.plan_model_load(bst, cfg)
        assert plan is not None
        sess = ServingSession(params={"verbosity": -1})
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            assert plan.components["packed_tables"] == entry.hbm_bytes
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# 3. preflight
# ---------------------------------------------------------------------------
class TestPreflight:
    def test_raise_refuses_with_itemized_plan(self):
        X, y = make_xy()
        p = dict(BASE, tpu_hbm_budget_bytes=100, tpu_hbm_preflight="raise")
        with pytest.raises(LightGBMError) as ei:
            train(p, X, y, rounds=1)
        msg = str(ei.value)
        assert "histogram_pool" in msg and "headroom" in msg

    def test_warn_proceeds(self):
        X, y = make_xy()
        p = dict(BASE, tpu_hbm_budget_bytes=100, tpu_hbm_preflight="warn")
        before = counter("lgbm_log_warnings_total")
        bst = train(p, X, y, rounds=1)
        assert bst.current_iteration() == 1
        assert counter("lgbm_log_warnings_total") > before

    def test_degrade_fits_and_stays_bitwise(self):
        X, y = make_xy()
        ref = model_str(train(dict(BASE), X, y, rounds=3))
        drv = train(dict(BASE), X, y, rounds=1)._driver
        full = membudget.plan_training(drv.config, drv.learner,
                                       drv.num_tree_per_iteration).total
        p = dict(BASE, tpu_hbm_budget_bytes=full - 1000,
                 tpu_hbm_preflight="degrade")
        before = counter("lgbm_oom_ladder_steps_total",
                         step="shrink_chunk_rows")
        bst = train(p, X, y, rounds=3)
        assert model_str(bst) == ref
        assert counter("lgbm_oom_ladder_steps_total",
                       step="shrink_chunk_rows") > before
        # the settled config is visible on the driver
        assert int(bst._driver.config.tpu_ingest_chunk_rows) < 65536

    def test_degrade_exhausted_refuses(self):
        X, y = make_xy()
        p = dict(BASE, tpu_hbm_budget_bytes=50,
                 tpu_hbm_preflight="degrade")
        with pytest.raises(LightGBMError):
            train(p, X, y, rounds=1)

    def test_invalid_mode_rejected_at_init(self):
        X, y = make_xy()
        p = dict(BASE, tpu_hbm_preflight="definitely")
        with pytest.raises(ValueError):
            train(p, X, y, rounds=1)

    def test_budget_gauge_published(self):
        X, y = make_xy()
        p = dict(BASE, tpu_hbm_budget_bytes=10 ** 9)
        train(p, X, y, rounds=1)
        assert counter("lgbm_hbm_budget_bytes", scope="training") \
            == 10 ** 9


# ---------------------------------------------------------------------------
# 4. mid-train recovery + the ladder
# ---------------------------------------------------------------------------
class TestMidTrainRecovery:
    def test_injected_oom_recovers_bitwise(self):
        X, y = make_xy()
        ref = model_str(train(dict(BASE), X, y, rounds=4))
        p = dict(BASE)
        ds = lgb.Dataset(X, label=y, params=p)
        bst = Booster(params=p, train_set=ds)
        before = counter("lgbm_oom_recoveries_total", site="train_step")
        for it in range(4):
            if it == 2:
                faultline.arm("device_alloc", action="oom", at=1)
            bst.update()
        assert model_str(bst) == ref
        assert counter("lgbm_oom_recoveries_total",
                       site="train_step") == before + 1
        steps = bst._driver._mem_ladder.describe()
        assert steps == ["shrink_chunk_rows"]
        # flight recorder carries the ladder transition
        ent = [e for e in flightrecorder.entries()
               if e["kind"] == "oom" and e["name"] == "ladder_step"]
        assert ent and ent[-1]["fields"]["site"] == "train_step"

    def test_repeated_oom_descends_deterministically(self):
        X, y = make_xy()
        ref = model_str(train(dict(BASE), X, y, rounds=3))
        p = dict(BASE)
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        bst.update()
        faultline.arm("device_alloc", action="oom", times=5)
        bst.update()        # 5 consecutive OOMs -> 5 ladder steps
        bst.update()
        assert model_str(bst) == ref
        steps = bst._driver._mem_ladder.describe()
        # deterministic order: chunk halvings to the floor, then the
        # fine bucket policy (no data axis -> no scatter step here)
        assert steps == ["shrink_chunk_rows"] * 4 + ["bucket_policy_fine"]
        assert int(bst._driver.config.tpu_predict_chunk_rows) == \
            membudget.CHUNK_FLOOR
        assert str(bst._driver.config.tpu_bucket_policy) == "fine"

    def test_recovery_disabled_propagates_structured(self):
        X, y = make_xy()
        p = dict(BASE, tpu_oom_recovery=False)
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        bst.update()
        faultline.arm("device_alloc", action="oom", at=1)
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            bst.update()
        # propagates AS the classified error, NOT as exhaustion: the
        # ladder was never tried and must not be blamed
        assert not isinstance(ei.value, membudget.MemoryLadderExhausted)
        assert ei.value.site == "train_step"
        # the rollback left the booster usable
        assert bst.current_iteration() == 1
        assert np.isfinite(bst.predict(X[:8], raw_score=True)).all()

    def test_ladder_rebuild_oom_is_classified(self):
        """An allocation failure during the ladder's learner REBUILD
        (agg/policy steps re-create the pool + transposed bins) is
        classified and named like any other train-step OOM — a raw
        XlaRuntimeError escaping the recovery path unnamed would be
        exactly the pre-ISSUE-15 failure the ladder exists to prevent."""
        X, y = make_xy()
        p = dict(BASE)
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        bst.update()
        faultline.arm("device_alloc", action="oom", at=1)
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            bst._driver.apply_memory_degradation(
                {"tpu_bucket_policy": "fine"})
        assert ei.value.site == "train_step"

    def test_exhaustion_checkpoint_booster_and_blackbox(self, tmp_path):
        X, y = make_xy()
        flightrecorder.configure(dump_dir=str(tmp_path))
        try:
            p = dict(BASE, tpu_checkpoint_dir=str(tmp_path / "ck"))
            ds = lgb.Dataset(X, label=y, params=p)
            faultline.arm("device_alloc", action="oom", at=3, times=10 ** 6)
            with pytest.raises(membudget.MemoryLadderExhausted):
                lgb.train(p, ds, num_boost_round=6, verbose_eval=False)
            faultline.reset()
            # a valid final checkpoint covers the last COMPLETE iteration
            found = CheckpointManager(str(tmp_path / "ck")).load_latest()
            assert found is not None and found[0] >= 1
            # the blackbox dump names the failing site (the exhaustion
            # dump lands first; engine.train's post-checkpoint dump
            # overwrites the reason but keeps the same oom ring)
            dump = json.load(open(tmp_path / "blackbox-host0.json"))
            assert dump["reason"] in (
                "oom_ladder_exhausted",
                "train_interrupt:MemoryLadderExhausted")
            oom = [e for e in dump["entries"] if e["kind"] == "oom"]
            assert any(e["fields"].get("site") == "train_step"
                       for e in oom if e.get("fields"))
            assert any(e["name"] == "ladder_exhausted" for e in oom)
            # resume trains on from the flushed checkpoint
            bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                            num_boost_round=6, resume=True,
                            verbose_eval=False,
                            keep_training_booster=True)
            assert bst.current_iteration() == 6
        finally:
            flightrecorder.configure(dump_dir="")

    def test_continue_training_after_rebuild_oom_exhaustion(self):
        """A rebuild-OOM exhaustion parks the learner reference; a
        later update() retries the rebuild — once pressure subsides,
        continue-training works instead of dying on an unstructured
        AttributeError, and the carried RNG state is restored."""
        X, y = make_xy()
        p = dict(BASE)
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        bst.update()
        faultline.arm("device_alloc", action="oom", at=1, times=10 ** 6)
        with pytest.raises(membudget.MemoryLadderExhausted):
            bst.update()
        faultline.reset()
        # the ladder's final rebuild OOMed: the learner is parked
        assert bst._driver.learner is None
        assert bst._driver._ladder_carry is not None
        bst.update()   # pressure subsided: lazy rebuild + train on
        assert bst.current_iteration() == 2
        assert bst._driver.learner is not None
        assert np.isfinite(bst.predict(X[:8], raw_score=True)).all()

    @pytest.mark.slow
    def test_int8_2shard_recovery_bitwise(self):
        X, y = make_xy(n=1200, f=8, seed=11)
        p = dict(BASE, tpu_hist_precision="int8", tree_learner="data",
                 num_machines=2, num_leaves=13, max_bin=31,
                 tpu_quant_refit_leaves=False, tpu_hist_agg="psum")
        ref = model_str(train(dict(p), X, y, rounds=5))
        bst = Booster(params=dict(p),
                      train_set=lgb.Dataset(X, label=y, params=dict(p)))
        for it in range(5):
            if it == 2:
                # push past the chunk floor INTO the scatter switch:
                # 4 halvings + hist_agg_scatter + one clean retry
                faultline.arm("device_alloc", action="oom", times=5)
            bst.update()
        faultline.reset()
        assert model_str(bst) == ref
        steps = bst._driver._mem_ladder.describe()
        assert "hist_agg_scatter" in steps
        assert bst._driver.learner.hist_agg == "scatter"


# ---------------------------------------------------------------------------
# 5. the other guarded sites
# ---------------------------------------------------------------------------
class TestChunkSites:
    def test_ingest_oom_recovers_bitwise(self):
        X, y = make_xy(n=1000)
        ref = lgb.Dataset(X, label=y, params=dict(BASE))
        ref.construct()
        p = dict(BASE, tpu_ingest_device="true", tpu_ingest_min_rows=1,
                 tpu_ingest_chunk_rows=2048)
        faultline.arm("device_alloc", action="oom", at=1)
        dev = lgb.Dataset(X, label=y, params=p)
        dev.construct()
        assert np.array_equal(np.asarray(ref._inner.bins),
                              np.asarray(dev._inner.bins))

    def test_ingest_oom_multichunk_no_row_duplication(self):
        """Regression: a chunk shrink on chunk i must not re-slice the
        stream with the NEW chunk size — rows the shrunk call already
        binned would re-enter the pending buffer and the dataset would
        silently grow (reproduced: 6024 rows from a 5000-row matrix)."""
        X, y = make_xy(n=5000, f=4, seed=7)
        ref = lgb.Dataset(X, label=y, params=dict(BASE))
        ref.construct()
        p = dict(BASE, tpu_ingest_device="true", tpu_ingest_min_rows=1,
                 tpu_ingest_chunk_rows=2048)
        faultline.arm("device_alloc", action="oom", at=1)
        dev = lgb.Dataset(X, label=y, params=p)
        dev.construct()
        assert np.asarray(dev._inner.bins).shape[0] == 5000
        assert np.array_equal(np.asarray(ref._inner.bins),
                              np.asarray(dev._inner.bins))

    def test_ingest_reassemble_oom_is_classified(self):
        """The multi-part reassembly concatenate — the single largest
        ingest allocation, reached exactly when a shrink just proved
        the device nearly full — classifies instead of escaping raw.
        Fire 1 = first launch (OOM -> shrink), 2-3 = halved launches,
        4 = the reassemble guard."""
        X, y = make_xy(n=4000, f=4, seed=9)
        p = dict(BASE, tpu_ingest_device="true", tpu_ingest_min_rows=1,
                 tpu_ingest_chunk_rows=4096)
        faultline.arm("device_alloc", action="oom", at=1)
        faultline.arm("device_alloc", action="oom", at=4)
        ds = lgb.Dataset(X, label=y, params=p)
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            ds.construct()
        assert ei.value.site == "ingest_chunk"
        assert ei.value.info.get("stage") == "reassemble"

    def test_ingest_floor_propagates_structured(self):
        X, y = make_xy(n=1000)
        p = dict(BASE, tpu_ingest_device="true", tpu_ingest_min_rows=1,
                 tpu_ingest_chunk_rows=256)
        faultline.arm("device_alloc", action="oom", times=10)
        ds = lgb.Dataset(X, label=y, params=p)
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            ds.construct()
        assert ei.value.site == "ingest_chunk"

    def test_predict_chunk_oom_recovers_identically(self):
        X, y = make_xy()
        p = dict(BASE, tpu_predict_chunk_rows=16384)
        bst = train(p, X, y, rounds=2)
        # device-vs-device is the bitwise claim (chunk invariance);
        # the native walker accumulates in f64 and is only close
        dev_ref = bst.predict(X, raw_score=True, device="tpu",
                              tpu_predict_device="true")
        faultline.arm("device_alloc", action="oom", at=1)
        dev = bst.predict(X, raw_score=True, device="tpu",
                          tpu_predict_device="true")
        np.testing.assert_array_equal(dev_ref, dev)
        np.testing.assert_allclose(bst.predict(X, raw_score=True), dev,
                                   rtol=1e-6, atol=1e-6)
        assert int(bst._driver.config.tpu_predict_chunk_rows) == 8192

    def test_score_replay_oom_is_classified(self):
        X, y = make_xy()
        p = dict(BASE, tpu_predict_device="true")
        bst = train(dict(p), X, y, rounds=2)
        # re-open a training context so add_valid replays on device
        ds = lgb.Dataset(X, label=y, params=dict(p))
        b2 = Booster(params=dict(p), train_set=ds)
        for _ in range(2):
            b2.update()
        b2.current_iteration()  # materialize the pending trees
        faultline.arm("device_alloc", action="oom", times=100)
        vs = lgb.Dataset(X[:256], label=y[:256], reference=ds,
                         params=dict(p))
        with pytest.raises(membudget.DeviceOutOfMemory) as ei:
            b2.add_valid(vs, "v")
        assert ei.value.site in ("score_replay", "train_step")
        del bst

    def test_every_guarded_site_has_a_chaos_path(self):
        """The OOM_SITES vocabulary is covered: each site either has a
        dedicated test above/below or is exercised here via the label
        on lgbm_oom_events_total after this module ran its course —
        the vocabulary itself must not drift silently."""
        assert set(membudget.OOM_SITES) == {
            "train_step", "ingest_chunk", "predict_chunk",
            "score_replay", "registry_load", "registry_warmup",
            "serve_dispatch"}


# ---------------------------------------------------------------------------
# 6. pressure-aware serving
# ---------------------------------------------------------------------------
class TestServingPressure:
    @pytest.fixture()
    def booster(self):
        X, y = make_xy()
        return train(dict(BASE), X, y, rounds=2), X

    def test_over_budget_load_refused_507(self, booster):
        bst, _ = booster
        sess = ServingSession(params={"verbosity": -1,
                                      "serving_hbm_budget_bytes": 64})
        try:
            before = sess.stats()["models_refused_hbm"]
            with pytest.raises(membudget.ServingMemoryExhausted) as ei:
                sess.load("m", booster=bst)
            assert getattr(ei.value, "http_status", None) == 507
            assert "packed_tables" in str(ei.value)
            st = sess.stats()
            assert st["models_refused_hbm"] == before + 1
            assert st["hbm_budget_bytes"] == 64
            # nothing was registered: the name stays unknown
            with pytest.raises(KeyError):
                sess.registry.resolve("m")
        finally:
            sess.close()

    def test_pressure_evicts_cold_version_for_new_load(self, booster):
        bst, X = booster
        from lightgbm_tpu.config import Config

        # a small batch bound keeps launch scratch from dwarfing the
        # packed tables (the quantity pressure eviction manages)
        base_cfg = {"verbosity": -1, "serving_max_batch_rows": 16}
        plan = membudget.plan_model_load(bst, Config(base_cfg))
        tables = plan.components["packed_tables"]
        # budget fits both loads at preflight; the pressure threshold
        # sits between one and two resident models' packed bytes, so
        # registering v2 pushes past it and the (now-cold) v1 yields
        budget = plan.total * 3
        frac = (tables * 1.5) / budget
        assert frac >= 0.05  # below the clamp the threshold never bites
        sess = ServingSession(params={
            **base_cfg,
            "serving_hbm_budget_bytes": budget,
            "serving_hbm_pressure_frac": frac})
        try:
            sess.load("m", booster=bst)          # v1
            before = sess.stats()["evictions_pressure"]
            sess.load("m", booster=bst)          # v2: v1 must yield
            st = sess.stats()
            assert st["evictions_pressure"] >= before + 1
            keys = [m["key"] for m in sess.models()]
            assert "m@2" in keys and "m@1" not in keys
            out = sess.predict("m", np.nan_to_num(X[:8]), raw_score=True)
            assert np.isfinite(np.asarray(out)).all()
        finally:
            sess.close()

    def test_relieve_pressure_one_victim_and_skips_walker_only(
            self, booster):
        """relieve_pressure(0) evicts exactly ONE device-backed cold
        entry; zero-byte (walker-only) entries are never pressure
        victims — evicting them frees no HBM."""
        bst, _ = booster
        sess = ServingSession(params={"verbosity": -1,
                                      "serving_max_models": 10})
        try:
            txt = bst.model_to_string()
            walker = {"tpu_predict_device": "false", "verbosity": -1}
            sess.load("w", model_str=txt, params=walker)   # w@1: 0 bytes
            sess.load("w", model_str=txt, params=walker)   # w@1 cold
            assert sess.registry.resolve("w@1").hbm_bytes == 0
            sess.load("d", booster=bst)                    # d@1
            sess.load("d", booster=bst)                    # d@1 cold
            sess.load("d", booster=bst)                    # d@2 cold too
            freed = sess.registry.relieve_pressure()
            assert freed > 0
            keys = [m["key"] for m in sess.models()]
            # exactly one device-backed cold victim left; the walker-
            # only cold version survived untouched
            assert "w@1" in keys
            assert sum(k in ("d@1", "d@2") for k in keys) == 1
        finally:
            sess.close()

    def test_same_key_reload_in_place_near_budget(self, booster):
        """Replacing name@N IN PLACE must not double-count the
        departing copy: its bytes leave as the new ones land, so a
        reload of the current version fits a budget sized for ONE
        resident model instead of being refused 507 with a message
        blaming a concurrent load."""
        bst, X = booster
        from lightgbm_tpu.config import Config

        base_cfg = {"verbosity": -1, "serving_max_batch_rows": 16}
        plan = membudget.plan_model_load(bst, Config(base_cfg))
        budget = plan.total + 1   # room for one copy, never two
        sess = ServingSession(params={
            **base_cfg, "serving_hbm_budget_bytes": budget})
        try:
            sess.load("m", booster=bst, version=3)
            sess.load("m", booster=bst, version=3)   # in-place reload
            assert [m["key"] for m in sess.models()] == ["m@3"]
            out = sess.predict("m", np.nan_to_num(X[:8]), raw_score=True)
            assert np.isfinite(np.asarray(out)).all()
        finally:
            sess.close()

    def test_walker_only_model_admits_under_tiny_budget(self, booster):
        """An explicit tpu_predict_device=false model uploads nothing:
        the preflight plan is None, so it admits under ANY budget
        instead of being refused 507 (and evicting device-backed
        models) for packed bytes it will never upload."""
        bst, X = booster
        txt = bst.model_to_string()
        sess = ServingSession(params={"verbosity": -1,
                                      "serving_hbm_budget_bytes": 64})
        try:
            sess.load("w", model_str=txt,
                      params={"tpu_predict_device": "false",
                              "verbosity": -1})
            assert sess.registry.resolve("w").hbm_bytes == 0
            out = sess.predict("w", np.nan_to_num(X[:8]), raw_score=True)
            assert np.isfinite(np.asarray(out)).all()
        finally:
            sess.close()

    def test_early_stopped_entry_bytes_match_plan(self, booster):
        """PackedForest.device() uploads and retains the FULL pack
        regardless of the best_iteration slice a request resolves to:
        the entry's hbm_bytes must report that full residency, equal to
        the preflight plan's packed_tables — a sliced undercount would
        let admissions pass preflight on one number and occupy another."""
        bst, _ = booster
        bst.best_iteration = 1   # early-stopped: slice < full pack
        from lightgbm_tpu.config import Config

        plan = membudget.plan_model_load(bst, Config({"verbosity": -1}))
        sess = ServingSession(params={"verbosity": -1})
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            assert entry.hbm_bytes > 0
            assert entry.hbm_bytes == plan.components["packed_tables"]
        finally:
            sess.close()

    def test_uncontended_load_never_hits_the_concurrency_wall(self):
        """Preflight and the under-lock wall apply the SAME formula
        (resident tables + new tables + MAX launch scratch across
        entries): a load the wall would refuse is refused at preflight,
        BEFORE any upload or warmup — a formula mismatch would let an
        uncontended load burn the upload and then be refused with a
        message falsely blaming a concurrent load."""
        from lightgbm_tpu.config import Config

        Xw, yw = make_xy(f=20, seed=3)
        wide = train(dict(BASE), Xw, yw, rounds=2)
        Xs, ys = make_xy(f=2, seed=4)
        small = train(dict(BASE), Xs, ys, rounds=2)
        base_cfg = {"verbosity": -1, "serving_max_batch_rows": 8}
        cfg = Config(base_cfg)
        pa, pb = (membudget.plan_model_load(b, cfg) for b in (wide, small))
        ta, sa = (pa.components[k] for k in ("packed_tables",
                                             "launch_scratch"))
        tb, sb = (pb.components[k] for k in ("packed_tables",
                                             "launch_scratch"))
        # the discriminating budget: admitting `small` fits with its
        # OWN scratch but not with the wide resident's larger scratch
        assert sa > sb + 1
        budget = ta + tb + (sa + sb) // 2
        assert budget >= ta + sa    # `wide` alone admits cleanly
        sess = ServingSession(params={
            **base_cfg, "serving_hbm_budget_bytes": budget})
        try:
            sess.load("wide", booster=wide)
            before = sess.stats()["models_loaded"]
            with pytest.raises(membudget.ServingMemoryExhausted) as ei:
                sess.load("small", booster=small)
            # refused by the itemized PREFLIGHT plan, not the wall's
            # concurrent-load diagnosis (no concurrency happened)
            assert "packed_tables" in str(ei.value)
            assert "concurrent" not in str(ei.value)
            assert sess.stats()["models_loaded"] == before
            with pytest.raises(KeyError):
                sess.registry.resolve("small")
        finally:
            sess.close()

    def test_concurrent_admission_wall_holds_under_lock(self, booster):
        """The check-then-act race: the budget wall is re-checked at
        registration (under the lock), so racing loads cannot jointly
        breach it even though the preflight read was lock-free."""
        bst, _ = booster
        from lightgbm_tpu.config import Config

        base_cfg = {"verbosity": -1, "serving_max_batch_rows": 16}
        plan = membudget.plan_model_load(bst, Config(base_cfg))
        tables = plan.components["packed_tables"]
        # room for ONE resident model (+ its launch scratch), not two
        budget = plan.total + tables // 2
        sess = ServingSession(params={
            **base_cfg, "serving_hbm_budget_bytes": budget})
        try:
            results, errors = [], []

            def one(name):
                try:
                    results.append(sess.load(name, booster=bst))
                except membudget.ServingMemoryExhausted as exc:
                    errors.append(exc)

            ts = [threading.Thread(target=one, args=(n,))
                  for n in ("a", "b")]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            # both current aliases -> neither is cold-evictable, so at
            # most one admission fits the wall; the other refused
            resident = sum(m["hbm_bytes"] for m in sess.models())
            assert resident <= budget
            assert len(results) == 1 and len(errors) == 1, \
                (results, errors)
        finally:
            sess.close()

    def test_load_oom_retries_after_eviction_then_507(self, booster):
        bst, _ = booster
        sess = ServingSession(params={"verbosity": -1})
        try:
            sess.load("a", booster=bst)
            sess.load("a", booster=bst)   # a@1 becomes cold
            # one injected OOM at the upload: eviction frees a@1 and
            # the retry succeeds — a recovery, not a refusal
            faultline.arm("device_alloc", action="oom", at=1)
            sess.load("b", booster=bst)
            assert any(m["key"] == "b@1" for m in sess.models())
            # with nothing cold left, a persistent OOM is a 507
            faultline.arm("device_alloc", action="oom", times=10 ** 6)
            with pytest.raises(membudget.ServingMemoryExhausted):
                sess.load("c", booster=bst)
            faultline.reset()
        finally:
            sess.close()

    def test_dispatch_oom_zero_errors_to_accepted(self, booster):
        bst, X = booster
        sess = ServingSession(params={"verbosity": -1,
                                      "serving_max_batch_rows": 256})
        try:
            sess.load("m", booster=bst)
            Xq = np.nan_to_num(X[:64])
            want = sess.predict("m", Xq, raw_score=True)
            faultline.arm("device_alloc", action="oom", times=3)
            errors, outs = [], []

            def hit():
                try:
                    outs.append(sess.predict("m", Xq, raw_score=True))
                except Exception as exc:  # pragma: no cover - the bug
                    errors.append(exc)

            threads = [threading.Thread(target=hit) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            faultline.reset()
            assert not errors, errors
            assert len(outs) == 6
            for out in outs:
                # walker-served batches accumulate in f64 (vs the
                # device's f32): equal values, not equal bytes
                np.testing.assert_allclose(np.asarray(out),
                                           np.asarray(want),
                                           rtol=1e-6, atol=1e-6)
            st = sess.stats()
            assert st["dispatch_oom"] >= 1
            assert st["device_fallbacks"] >= 1
        finally:
            sess.close()

    def test_warmup_oom_refuses_instead_of_walking(self, booster):
        bst, _ = booster
        sess = ServingSession(params={"verbosity": -1})
        try:
            # the upload survives, every warmup launch OOMs: the load
            # must refuse, not admit a model that can only walk
            faultline.arm("device_alloc", action="oom", at=2,
                          times=10 ** 6)
            with pytest.raises(membudget.ServingMemoryExhausted) as ei:
                sess.load("m", booster=bst)
            assert ei.value.site in ("registry_warmup", "predict_chunk")
            faultline.reset()
        finally:
            sess.close()


class TestHTTPSurfaces:
    @pytest.fixture()
    def served(self):
        X, y = make_xy()
        bst = train(dict(BASE), X, y, rounds=2)
        sess = ServingSession(params={"verbosity": -1,
                                      "serving_hbm_budget_bytes": 64})
        server = serve_http(sess, "127.0.0.1", 0)
        port = server.server_address[1]
        yield f"http://127.0.0.1:{port}", sess, bst
        server.shutdown()
        sess.close()

    @staticmethod
    def _post(url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())

    def test_load_maps_to_507_with_code_memory(self, served, tmp_path):
        base, _sess, bst = served
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(base + "/load", {"name": "m", "model_file": path})
        assert ei.value.code == 507
        body = json.loads(ei.value.read())
        assert body["code"] == "memory"
        assert "packed_tables" in body["error"]

    def test_healthz_and_stats_carry_pressure(self, served):
        base, _sess, _bst = served
        with urllib.request.urlopen(base + "/healthz") as resp:
            hz = json.loads(resp.read())
        assert hz["ok"] is True
        assert hz["hbm_budget_bytes"] == 64
        assert "hbm_pressure" in hz and "hbm_models_bytes" in hz
        with urllib.request.urlopen(base + "/stats") as resp:
            st = json.loads(resp.read())
        for key in ("hbm_budget_bytes", "hbm_models_bytes",
                    "hbm_pressure", "models_refused_hbm",
                    "dispatch_oom", "evictions_pressure"):
            assert key in st, key


# ---------------------------------------------------------------------------
# 7. bench_diff knows the new fields
# ---------------------------------------------------------------------------
class TestBenchDiffFields:
    def test_directions_and_tolerances(self):
        import tools.bench_diff as bd

        direction, tol = bd.METRICS["oom_recovery_s"]
        assert direction == -1 and tol > 0
        direction, tol = bd.METRICS["hbm_budget_headroom_bytes"]
        assert direction == +1 and tol > 0

    def test_bench_emits_the_oom_fields(self):
        src = open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py")).read()
        for key in ('"oom_recovery_s"', '"hbm_budget_headroom_bytes"'):
            assert key in src, f"bench.py no longer records {key}"
