"""Multi-host mesh mapping (the Linkers rendezvous role,
reference src/network/linkers_socket.cpp:165-220 -> jax.distributed).

TestMultihostMapping covers the config-mapping logic in-process; the
TestTwoProcessRendezvous smoke test spawns a REAL 2-process
jax.distributed group (gloo CPU collectives) that runs init_multihost ->
global 8-device mesh -> one data-parallel tree, asserting identical
split records on both ranks — the automated stand-in for the reference's
manual parallel_learning runbook (linkers_socket.cpp:165-220).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from lightgbm_tpu.parallel import mesh


class TestMultihostMapping:
    def test_single_machine_skips(self):
        assert mesh.init_multihost("", 0, 1) is False
        assert mesh.init_multihost("127.0.0.1:12400", 12400, 1) is False

    def test_unresolvable_process_id_raises(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_HOST_IP", raising=False)
        monkeypatch.delenv("LIGHTGBM_TPU_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="position"):
            mesh.init_multihost("10.0.0.1:12400,10.0.0.2:12400", 12400, 2)

    def test_process_id_from_host_ip(self, monkeypatch):
        """The pid resolution finds this host in the machine list; the
        jax.distributed.initialize call itself is stubbed (no cluster)."""
        calls = {}

        def fake_init(coordinator_address, num_processes, process_id):
            calls.update(coordinator=coordinator_address,
                         n=num_processes, pid=process_id)

        import jax

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setenv("LIGHTGBM_TPU_HOST_IP", "10.0.0.2")
        mesh._distributed_initialized = False
        try:
            assert mesh.init_multihost(
                "10.0.0.1:12400,10.0.0.2:12400,10.0.0.3:12400", 12400, 3)
            assert calls == {"coordinator": "10.0.0.1:12400", "n": 3,
                             "pid": 1}
        finally:
            mesh._distributed_initialized = False


_WORKER_SRC = """
import os, sys, importlib.util
root = {root!r}
sys.path.insert(0, root)
spec = importlib.util.spec_from_file_location(
    "_boot", os.path.join(root, "lightgbm_tpu", "utils", "backend.py"))
_b = importlib.util.module_from_spec(spec)
spec.loader.exec_module(_b)
_b.pin_cpu_backend(force_device_count=4)
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner

pid = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
# every rank loads the SAME data (the reference's all-data-on-all-machines
# mode; pre-partitioned loading is a separate path)
rng = np.random.default_rng(7)
X = rng.normal(size=(2048, 10))
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
cfg = Config({{"objective": "binary", "max_bin": 16, "num_leaves": 7,
              "min_data_in_leaf": 5, "tpu_block_rows": 256,
              "tree_learner": "data", "num_machines": 8,
              "machines": {machines!r}}})
td = TrainingData.from_matrix(X, y, cfg)
learner = TPUTreeLearner(cfg, td)   # init_multihost runs in here
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8
grad = rng.normal(size=2048).astype(np.float32)
hess = np.abs(rng.normal(size=2048)).astype(np.float32) + 0.1
tree, _, out = learner.train(grad, hess)
rec = np.asarray(jax.device_get(out["records"]))
assert rec[0, 14] > 0.5, "no split grown"
np.save({outfile!r}, rec)
print(f"rank {{pid}}: {{int(rec[:, 14].sum())}} splits", flush=True)

# FULL training through the public API on the same global mesh: the GBDT
# driver routes multi-process learners through the sync path (local score
# state, allgathered leaf ids) — every rank must produce the same model
import lightgbm_tpu as lgb
ds = lgb.Dataset(X, label=y, params=dict(cfg.params))
bst = lgb.train({{**dict(cfg.params), "verbosity": -1}}, ds,
                num_boost_round=3)
model = bst.model_to_string().split("\\nparameters:")[0]
with open({outfile!r} + ".model", "w") as f:
    f.write(model)
print(f"rank {{pid}}: trained {{bst.num_trees()}} trees", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
class TestTwoProcessRendezvous:
    def test_two_process_data_parallel_tree(self, tmp_path):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        machines = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
        procs, outs = [], []
        for pid in range(2):
            outfile = str(tmp_path / f"rec_{pid}.npy")
            outs.append(outfile)
            src = _WORKER_SRC.format(root=root, machines=machines,
                                     outfile=outfile)
            env = dict(os.environ,
                       LIGHTGBM_TPU_PROCESS_ID=str(pid))
            # the workers pin their own backend; drop the parent's
            # virtual-device flags so they don't fight the pin
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", src], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        logs = []
        for p in procs:
            try:
                log, _ = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            logs.append(log)
        for pid, (p, log) in enumerate(zip(procs, logs)):
            assert p.returncode == 0, f"rank {pid} failed:\n{log[-4000:]}"
        rec0 = np.load(outs[0])
        rec1 = np.load(outs[1])
        # both ranks must materialize IDENTICAL split records: the grower
        # output is replicated, so any divergence means the collective
        # ran inconsistently
        np.testing.assert_array_equal(rec0, rec1)
        assert rec0[:, 14].sum() >= 3
        # full lgb.train over the 2-process mesh: identical models
        m0 = open(outs[0] + ".model").read()
        m1 = open(outs[1] + ".model").read()
        assert m0 == m1 and "tree" in m0
