"""Multi-host mesh mapping (the Linkers rendezvous role,
reference src/network/linkers_socket.cpp:165-220 -> jax.distributed).

TestMultihostMapping covers the config-mapping logic in-process; the
TestTwoProcessRendezvous smoke test spawns a REAL 2-process
jax.distributed group (gloo CPU collectives) that runs init_multihost ->
global 8-device mesh -> one data-parallel tree, asserting identical
split records on both ranks — the automated stand-in for the reference's
manual parallel_learning runbook (linkers_socket.cpp:165-220).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from lightgbm_tpu.parallel import mesh


class TestMultihostMapping:
    def test_single_machine_skips(self):
        assert mesh.init_multihost("", 0, 1) is False
        assert mesh.init_multihost("127.0.0.1:12400", 12400, 1) is False

    def test_unresolvable_process_id_raises(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_HOST_IP", raising=False)
        monkeypatch.delenv("LIGHTGBM_TPU_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="position"):
            mesh.init_multihost("10.0.0.1:12400,10.0.0.2:12400", 12400, 2)

    def test_process_id_from_host_ip(self, monkeypatch):
        """The pid resolution finds this host in the machine list; the
        jax.distributed.initialize call itself is stubbed (no cluster)."""
        calls = {}

        def fake_init(coordinator_address, num_processes, process_id):
            calls.update(coordinator=coordinator_address,
                         n=num_processes, pid=process_id)

        import jax

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setenv("LIGHTGBM_TPU_HOST_IP", "10.0.0.2")
        mesh._distributed_initialized = False
        try:
            assert mesh.init_multihost(
                "10.0.0.1:12400,10.0.0.2:12400,10.0.0.3:12400", 12400, 3)
            assert calls == {"coordinator": "10.0.0.1:12400", "n": 3,
                             "pid": 1}
        finally:
            mesh._distributed_initialized = False


_WORKER_SRC = """
import os, sys, importlib.util
root = {root!r}
sys.path.insert(0, root)
spec = importlib.util.spec_from_file_location(
    "_boot", os.path.join(root, "lightgbm_tpu", "utils", "backend.py"))
_b = importlib.util.module_from_spec(spec)
spec.loader.exec_module(_b)
_b.pin_cpu_backend(force_device_count=4)
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner

pid = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
# every rank loads the SAME data (the reference's all-data-on-all-machines
# mode; pre-partitioned loading is a separate path)
rng = np.random.default_rng(7)
X = rng.normal(size=(2048, 10))
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
cfg = Config({{"objective": "binary", "max_bin": 16, "num_leaves": 7,
              "min_data_in_leaf": 5, "tpu_block_rows": 256,
              "tree_learner": "data", "num_machines": 8,
              "machines": {machines!r}}})
td = TrainingData.from_matrix(X, y, cfg)
learner = TPUTreeLearner(cfg, td)   # init_multihost runs in here
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8
grad = rng.normal(size=2048).astype(np.float32)
hess = np.abs(rng.normal(size=2048)).astype(np.float32) + 0.1
tree, _, out = learner.train(grad, hess)
rec = np.asarray(jax.device_get(out["records"]))
assert rec[0, 14] > 0.5, "no split grown"
np.save({outfile!r}, rec)
print(f"rank {{pid}}: {{int(rec[:, 14].sum())}} splits", flush=True)

# FULL training through the public API on the same global mesh: the GBDT
# driver routes multi-process learners through the sync path (local score
# state, allgathered leaf ids) — every rank must produce the same model
import lightgbm_tpu as lgb
ds = lgb.Dataset(X, label=y, params=dict(cfg.params))
bst = lgb.train({{**dict(cfg.params), "verbosity": -1,
                 "num_iterations": 3}}, ds, num_boost_round=3)
model = bst.model_to_string().split("\\nparameters:")[0]
with open({outfile!r} + ".model", "w") as f:
    f.write(model)
print(f"rank {{pid}}: trained {{bst.num_trees()}} trees", flush=True)

# ---- distributed metrics + early stopping on a PARTITIONED valid set:
# each rank holds only HALF the validation rows, so a host-local metric
# would differ across ranks; the metric_sync reduction must make every
# rank report the GLOBAL value and stop at the SAME iteration
import json
rngv = np.random.default_rng(21)
Xv = rngv.normal(size=(1024, 10))
yv = (Xv[:, 0] + 0.5 * Xv[:, 1]
      + rngv.normal(scale=0.7, size=1024) > 0).astype(np.float64)
half = 512
lo, hi = pid * half, (pid + 1) * half
p_es = dict(cfg.params)
p_es["verbosity"] = -1
p_es["num_iterations"] = 12
p_es["metric"] = ["binary_logloss", "auc"]
dtr = lgb.Dataset(X, label=y, params=p_es)
dval = lgb.Dataset(Xv[lo:hi], label=yv[lo:hi], reference=dtr, params=p_es)
hist = {{}}
bst3 = lgb.train(p_es, dtr, num_boost_round=12,
                 valid_sets=[dval], valid_names=["part"],
                 callbacks=[lgb.early_stopping(2, verbose=False),
                            lgb.record_evaluation(hist)])
n_it = bst3.current_iteration()
# independent expected values: plain numpy on the FULL valid set (no
# collectives, identical on both ranks), predictions from the model
margin = bst3.predict(Xv, num_iteration=n_it, raw_score=True)
pm = np.clip(1.0 / (1.0 + np.exp(-margin)), 1e-15, 1.0 - 1e-15)
exp_ll = float(-(yv * np.log(pm) + (1.0 - yv) * np.log(1.0 - pm)).mean())
order = np.argsort(margin, kind="stable")
ss = margin[order]
pos = (yv[order] > 0).astype(np.float64)
neg = 1.0 - pos
bnd = np.flatnonzero(np.diff(ss)) + 1
gid = np.zeros(len(ss), np.int64)
gid[bnd] = 1
gid = np.cumsum(gid)
ng = int(gid[-1]) + 1
posg = np.bincount(gid, weights=pos, minlength=ng)
negg = np.bincount(gid, weights=neg, minlength=ng)
negb = np.concatenate([[0.0], np.cumsum(negg)[:-1]])
exp_auc = float((posg * (negb + 0.5 * negg)).sum()
                / (pos.sum() * neg.sum()))
rec2 = {{"best_iter": int(bst3.best_iteration),
         "n_iter": int(n_it),
         "curve_ll": hist["part"]["binary_logloss"],
         "curve_auc": hist["part"]["auc"],
         "expected_ll": exp_ll, "expected_auc": exp_auc}}
with open({outfile!r} + ".esjson", "w") as f:
    json.dump(rec2, f)
print(f"rank {{pid}}: es best_iter={{bst3.best_iteration}}", flush=True)

# ---- pre-partitioned TRAINING rows (reference loader pre_partition):
# each rank holds only its HALF of the training rows; bin finding runs
# feature-sharded + allgather, rows place as process-local shards, and
# metrics/boost-from-average reduce globally.  Deterministic f64 with
# identical global row order => the model must BIT-match a serial
# full-data run in the same bin space.
p_pt = dict(cfg.params)
# boost_from_average=false: the distributed init is the MEAN of the
# per-rank inits (reference GlobalSyncUpByMean), which legitimately
# differs from a centralized full-data init on imbalanced halves —
# bit-matching serial requires removing that known semantic difference
p_pt.update(verbosity=-1, deterministic=True, pre_partition=True,
            metric=["auc"], tpu_shape_buckets=0, num_iterations=3,
            boost_from_average=False)
half_t = 1024
ds_pt = lgb.Dataset(X[pid * half_t:(pid + 1) * half_t],
                    label=y[pid * half_t:(pid + 1) * half_t],
                    params=p_pt)
bst_pt = lgb.train(p_pt, ds_pt, num_boost_round=3,
                   keep_training_booster=True)
m_pt = bst_pt.model_to_string().split("\\nparameters:")[0]
auc_pt = dict((nm, v) for _, nm, v, _ in bst_pt.eval_train())["auc"]
# serial full-data reference in the SAME bin space (shared mappers)
p_sr = {{k: v for k, v in p_pt.items()
         if k not in ("machines", "num_machines", "pre_partition")}}
p_sr["tree_learner"] = "serial"
ds_sr = lgb.Dataset(X, label=y, reference=ds_pt, params=p_sr)
bst_sr = lgb.train(p_sr, ds_sr, num_boost_round=3,
                   keep_training_booster=True)
m_sr = bst_sr.model_to_string().split("\\nparameters:")[0]
auc_sr = dict((nm, v) for _, nm, v, _ in bst_sr.eval_train())["auc"]

# psum partial-sum order differs from the serial block scan by f64
# ulps, and the f32 leaf-value downcast can flip at a rounding
# boundary — so the contract is STRUCTURAL exactness (every split
# line identical) + numeric closeness on the value lines
def split_lines(m):
    keep = ("split_feature=", "threshold=", "left_child=", "right_child=")
    out = [l for l in m.splitlines() if l.startswith(keep)]
    for l in m.splitlines():
        # default-left (bit 2) may flip on direction-gain ties under a
        # different reduction order; everything else must be identical
        if l.startswith("decision_type="):
            out.append(" ".join(str(int(v) & ~2)
                                for v in l.split("=")[1].split()))
    return out
def value_rows(m):
    out = []
    for l in m.splitlines():
        if l.startswith(("leaf_value=", "internal_value=",
                         "split_gain=")):
            out.extend(float(v) for v in l.split("=")[1].split())
    return np.asarray(out)
struct_ok = split_lines(m_pt) == split_lines(m_sr)
v_pt, v_sr = value_rows(m_pt), value_rows(m_sr)
val_delta = (float(np.max(np.abs(v_pt - v_sr)))
             if len(v_pt) == len(v_sr) else float("inf"))
with open({outfile!r} + ".ptmodel", "w") as f:
    f.write(m_pt)
with open({outfile!r} + ".srmodel", "w") as f:
    f.write(m_sr)
with open({outfile!r} + ".ptjson", "w") as f:
    json.dump({{"auc_pt": auc_pt, "auc_sr": auc_sr,
               "struct_ok": bool(struct_ok),
               "val_delta": val_delta}}, f)
print(f"rank {{pid}}: partitioned-train auc={{auc_pt:.4f}} "
      f"struct_ok={{struct_ok}} val_delta={{val_delta:.2e}}", flush=True)

# ---- sparse COO storage x pre_partition: the sparse-feature decision
# comes from GLOBAL nonzero fractions, each process builds only its own
# shards' tables, and the partitioned model must structurally match a
# serial-sparse full-data run in the same bin space
rngs = np.random.default_rng(33)
Xs_full = np.zeros((2048, 12))
Xs_full[:, :4] = rngs.normal(size=(2048, 4))
for f in range(4, 12):
    nzr = rngs.choice(2048, size=64, replace=False)
    Xs_full[nzr, f] = rngs.normal(size=64) + 1.0
ys_full = (Xs_full[:, 0] + 2.0 * Xs_full[:, 5] > 0).astype(np.float64)
p_sp = dict(p_pt)
p_sp.update(enable_bundle=False, tpu_sparse_threshold=0.2,
            num_iterations=2)
# scipy ingest composes with the distributed (feature-sharded) bin
# finding: the CSC columns ride the same collective as dense input
import scipy.sparse as sps
ds_sp = lgb.Dataset(sps.csr_matrix(Xs_full[pid * half_t:(pid + 1) * half_t]),
                    label=ys_full[pid * half_t:(pid + 1) * half_t],
                    params=p_sp)
bst_sp = lgb.train(p_sp, ds_sp, num_boost_round=2,
                   keep_training_booster=True)
assert bst_sp._driver.learner.params.has_sparse, "sparse did not engage"
m_sp = bst_sp.model_to_string().split("\\nparameters:")[0]
p_ss = {{k: v for k, v in p_sp.items()
         if k not in ("machines", "num_machines", "pre_partition")}}
p_ss["tree_learner"] = "serial"
ds_ss = lgb.Dataset(Xs_full, label=ys_full, reference=ds_sp, params=p_ss)
bst_ss = lgb.train(p_ss, ds_ss, num_boost_round=2,
                   keep_training_booster=True)
m_ss = bst_ss.model_to_string().split("\\nparameters:")[0]
sp_struct = split_lines(m_sp) == split_lines(m_ss)
v_sp, v_ss = value_rows(m_sp), value_rows(m_ss)
sp_delta = (float(np.max(np.abs(v_sp - v_ss)))
            if len(v_sp) == len(v_ss) else float("inf"))
with open({outfile!r} + ".spjson", "w") as f:
    json.dump({{"struct_ok": bool(sp_struct), "val_delta": sp_delta,
               "model": m_sp}}, f)
print(f"rank {{pid}}: sparse x pre_partition struct_ok={{sp_struct}} "
      f"val_delta={{sp_delta:.2e}}", flush=True)

# ---- GOSS x pre_partition: the threshold/sample run over LOCAL rows
# (the reference's distributed behavior — each machine subsets its own
# data); every rank must still produce the identical global model
p_go = dict(p_pt)
p_go.update(boosting="goss", top_rate=0.3, other_rate=0.2,
            learning_rate=1.0, num_iterations=3)
ds_go = lgb.Dataset(X[pid * half_t:(pid + 1) * half_t],
                    label=y[pid * half_t:(pid + 1) * half_t],
                    params=p_go)
bst_go = lgb.train(p_go, ds_go, num_boost_round=3)
m_go = bst_go.model_to_string().split("\\nparameters:")[0]
with open({outfile!r} + ".gossmodel", "w") as f:
    f.write(m_go)
print(f"rank {{pid}}: goss x pre_partition trained "
      f"{{bst_go.num_trees()}} trees", flush=True)

# ---- lambdarank x pre_partition: per-query lambdas run over LOCAL
# queries (queries live whole on one rank — the reference's distributed
# ranking semantics), histograms aggregate globally, and the NDCG train
# metric reduces across ranks.  Deterministic f64: structural parity
# with serial full-data training, identical global NDCG.
rngr = np.random.default_rng(44)
Xr2 = rngr.normal(size=(2048, 10))
rel2 = np.minimum((np.abs(Xr2[:, 0]) * 2).astype(np.int64), 3)
qsz = 16
p_lr = dict(p_pt)
p_lr.update(objective="lambdarank", metric=["ndcg"], eval_at=[3],
            num_iterations=2, label_gain=",".join(
                str((1 << i) - 1) for i in range(4)))
ds_lr = lgb.Dataset(Xr2[pid * half_t:(pid + 1) * half_t],
                    label=rel2[pid * half_t:(pid + 1) * half_t],
                    group=[qsz] * (half_t // qsz), params=p_lr)
bst_lr = lgb.train(p_lr, ds_lr, num_boost_round=2,
                   keep_training_booster=True)
m_lr = bst_lr.model_to_string().split("\\nparameters:")[0]
ndcg_lr = bst_lr.eval_train()[0][2]
p_ls = {{k: v for k, v in p_lr.items()
         if k not in ("machines", "num_machines", "pre_partition")}}
p_ls["tree_learner"] = "serial"
ds_ls = lgb.Dataset(Xr2, label=rel2, group=[qsz] * (2048 // qsz),
                    reference=ds_lr, params=p_ls)
bst_ls = lgb.train(p_ls, ds_ls, num_boost_round=2,
                   keep_training_booster=True)
m_ls = bst_ls.model_to_string().split("\\nparameters:")[0]
ndcg_ls = bst_ls.eval_train()[0][2]
lr_struct = split_lines(m_lr) == split_lines(m_ls)
with open({outfile!r} + ".lrjson", "w") as f:
    json.dump({{"struct_ok": bool(lr_struct),
               "ndcg_pt": ndcg_lr, "ndcg_sr": ndcg_ls}}, f)
print(f"rank {{pid}}: lambdarank x pre_partition struct_ok={{lr_struct}} "
      f"ndcg={{ndcg_lr:.4f}}", flush=True)

# ---- percentile-renew x pre_partition: each rank refits leaf outputs
# from its LOCAL rows' percentiles; the driver then averages per leaf
# over contributing machines (the reference's GlobalSum scheme,
# serial_tree_learner.cpp:865-891).  Both ranks must agree bitwise and
# the l1 train metric (globally reduced) must beat the constant model.
p_q = dict(p_pt)
p_q.update(objective="regression_l1", metric=["l1"], num_iterations=3,
           learning_rate=0.5)
yq = X[:, 0] * 2.0 + 0.3 * rng.normal(size=2048)
ds_q = lgb.Dataset(X[pid * half_t:(pid + 1) * half_t],
                   label=yq[pid * half_t:(pid + 1) * half_t],
                   params=p_q)
bst_q = lgb.train(p_q, ds_q, num_boost_round=3,
                  keep_training_booster=True)
m_q = bst_q.model_to_string().split("\\nparameters:")[0]
l1_q = bst_q.eval_train()[0][2]
base_l1 = float(np.abs(yq - np.median(yq)).mean())
with open({outfile!r} + ".qjson", "w") as f:
    json.dump({{"model": m_q, "l1": l1_q, "base_l1": base_l1}}, f)
print(f"rank {{pid}}: renew x pre_partition l1={{l1_q:.4f}} "
      f"(const model {{base_l1:.4f}})", flush=True)

# ---- EFB x pre_partition: the bundling plan is found from a globally
# allgathered row sample (and globally reduced zero fractions), so
# every rank greedy-groups identically; with the full data inside the
# sample quota the plan equals the serial full-data one -> structural
# parity in deterministic f64
rngb = np.random.default_rng(55)
Xb = np.zeros((2048, 10))
Xb[:, :2] = rngb.normal(size=(2048, 2))
owner = rngb.integers(2, 10, size=2048)
for f in range(2, 10):
    rows_f = np.flatnonzero(owner == f)
    # strictly positive stored values keep 0.0 in bin 0 (the
    # bundling heuristic keys on the bin-0 default) and a handful of
    # DISTINCT levels keeps each feature's bin count small enough for
    # several features to share one bundle's bin budget
    Xb[rows_f, f] = rngb.integers(1, 6, size=len(rows_f)).astype(float)
yb = ((Xb[:, 0] > 0) ^ (owner % 2 == 0)).astype(np.float64)
p_b = dict(p_pt)
# max_bin=64: at the worker default of 16 a bundle cannot hold two
# 16-bin features (budget is max_bundle_bins-1), so no plan would form
p_b.update(enable_bundle=True, num_iterations=2, max_bin=64)
ds_b = lgb.Dataset(Xb[pid * half_t:(pid + 1) * half_t],
                   label=yb[pid * half_t:(pid + 1) * half_t], params=p_b)
bst_b = lgb.train(p_b, ds_b, num_boost_round=2,
                  keep_training_booster=True)
assert bst_b._driver.learner.bundle_plan is not None, "EFB did not engage"
m_b = bst_b.model_to_string().split("\\nparameters:")[0]
p_bs = {{k: v for k, v in p_b.items()
         if k not in ("machines", "num_machines", "pre_partition")}}
p_bs["tree_learner"] = "serial"
ds_bs = lgb.Dataset(Xb, label=yb, reference=ds_b, params=p_bs)
bst_bs = lgb.train(p_bs, ds_bs, num_boost_round=2,
                   keep_training_booster=True)
m_bs = bst_bs.model_to_string().split("\\nparameters:")[0]
b_struct = split_lines(m_b) == split_lines(m_bs)
with open({outfile!r} + ".efbjson", "w") as f:
    json.dump({{"struct_ok": bool(b_struct), "model": m_b}}, f)
print(f"rank {{pid}}: efb x pre_partition struct_ok={{b_struct}}",
      flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
class TestTwoProcessRendezvous:
    def test_two_process_data_parallel_tree(self, tmp_path):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        machines = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
        procs, outs = [], []
        for pid in range(2):
            outfile = str(tmp_path / f"rec_{pid}.npy")
            outs.append(outfile)
            src = _WORKER_SRC.format(root=root, machines=machines,
                                     outfile=outfile)
            env = dict(os.environ,
                       LIGHTGBM_TPU_PROCESS_ID=str(pid))
            # the workers pin their own backend; drop the parent's
            # virtual-device flags so they don't fight the pin
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", src], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        logs = []
        for p in procs:
            try:
                log, _ = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            logs.append(log)
        for pid, (p, log) in enumerate(zip(procs, logs)):
            assert p.returncode == 0, f"rank {pid} failed:\n{log[-4000:]}"
        rec0 = np.load(outs[0])
        rec1 = np.load(outs[1])
        # both ranks must materialize IDENTICAL split records: the grower
        # output is replicated, so any divergence means the collective
        # ran inconsistently
        np.testing.assert_array_equal(rec0, rec1)
        assert rec0[:, 14].sum() >= 3
        # full lgb.train over the 2-process mesh: identical models
        m0 = open(outs[0] + ".model").read()
        m1 = open(outs[1] + ".model").read()
        assert m0 == m1 and "tree" in m0
        # distributed metrics over the partitioned valid set: both ranks
        # must report BITWISE-identical metric curves (same collective,
        # same arithmetic order) and stop at the same iteration...
        import json
        es0 = json.load(open(outs[0] + ".esjson"))
        es1 = json.load(open(outs[1] + ".esjson"))
        assert es0 == es1, "ranks diverged on metrics/early stopping"
        assert es0["best_iter"] == es1["best_iter"]
        # ...and the reported value must be the GLOBAL metric: the last
        # curve entry equals the numpy full-valid-set computation (f32
        # score-state accumulation vs the predictor's f64 sum bounds the
        # tolerance)
        assert es0["curve_ll"][-1] == pytest.approx(es0["expected_ll"],
                                                    abs=2e-4)
        assert es0["curve_auc"][-1] == pytest.approx(es0["expected_auc"],
                                                     abs=2e-4)
        # early stopping actually engaged (12 rounds max, patience 2)
        assert 1 <= es0["best_iter"] <= es0["n_iter"] <= 12
        # pre-partitioned TRAINING: identical models on both ranks, and
        # (deterministic f64, same global row order) bit-equal to the
        # serial full-data model; the distributed train-AUC is the
        # GLOBAL statistic so it matches the serial run's exactly
        pt0 = open(outs[0] + ".ptmodel").read()
        pt1 = open(outs[1] + ".ptmodel").read()
        assert pt0 == pt1 and "tree" in pt0
        ptj0 = json.load(open(outs[0] + ".ptjson"))
        ptj1 = json.load(open(outs[1] + ".ptjson"))
        assert ptj0 == ptj1
        # every split decision identical to serial full-data training;
        # value lines within the f32-downcast rounding band
        assert ptj0["struct_ok"], "partitioned splits diverged from serial"
        # value lines print 6-digit-rounded; one print digit = 1e-6
        assert ptj0["val_delta"] < 1e-5, ptj0
        assert ptj0["auc_pt"] == pytest.approx(ptj0["auc_sr"], abs=1e-6)
        assert ptj0["auc_pt"] > 0.9
        # sparse COO x pre_partition: both ranks identical, structurally
        # equal to serial-sparse full-data training
        spj0 = json.load(open(outs[0] + ".spjson"))
        spj1 = json.load(open(outs[1] + ".spjson"))
        assert spj0 == spj1
        assert spj0["struct_ok"], "sparse partitioned diverged from serial"
        assert spj0["val_delta"] < 1e-5, spj0
        assert "tree" in spj0["model"]
        # GOSS x pre_partition: per-machine sampling, identical global
        # model on both ranks
        g0 = open(outs[0] + ".gossmodel").read()
        g1 = open(outs[1] + ".gossmodel").read()
        assert g0 == g1 and "tree" in g0
        # lambdarank x pre_partition: local per-query lambdas, global
        # histograms and a globally-reduced NDCG — structural parity
        # with serial full-data and matching metric
        lr0 = json.load(open(outs[0] + ".lrjson"))
        lr1 = json.load(open(outs[1] + ".lrjson"))
        assert lr0 == lr1
        assert lr0["struct_ok"], "lambdarank partitioned diverged"
        assert lr0["ndcg_pt"] == pytest.approx(lr0["ndcg_sr"], abs=1e-6)
        # percentile-renew x pre_partition: bitwise rank agreement (the
        # leaf averaging is a collective) and the refit actually learns
        q0 = json.load(open(outs[0] + ".qjson"))
        q1 = json.load(open(outs[1] + ".qjson"))
        assert q0 == q1, "renew ranks diverged"
        assert "tree" in q0["model"]
        assert q0["l1"] < 0.7 * q0["base_l1"], q0  # 3 trees at lr 0.5
        # EFB x pre_partition: globally-agreed plan, identical ranks,
        # structural parity with the serial full-data plan
        e0 = json.load(open(outs[0] + ".efbjson"))
        e1 = json.load(open(outs[1] + ".efbjson"))
        assert e0 == e1, "EFB ranks diverged"
        assert e0["struct_ok"], "EFB partitioned diverged from serial"
        assert "tree" in e0["model"]
