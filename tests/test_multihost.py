"""Multi-host mesh mapping (the Linkers rendezvous role,
reference src/network/linkers_socket.cpp:165-220 -> jax.distributed).

Real multi-process initialization cannot run in a single-process CI; these
tests cover the config-mapping logic and the single-process skip path.
The in-process 8-device mesh tests (test_parallel.py) exercise the same
sharded growers that a global mesh would run.
"""

import pytest

from lightgbm_tpu.parallel import mesh


class TestMultihostMapping:
    def test_single_machine_skips(self):
        assert mesh.init_multihost("", 0, 1) is False
        assert mesh.init_multihost("127.0.0.1:12400", 12400, 1) is False

    def test_unresolvable_process_id_raises(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_HOST_IP", raising=False)
        monkeypatch.delenv("LIGHTGBM_TPU_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="position"):
            mesh.init_multihost("10.0.0.1:12400,10.0.0.2:12400", 12400, 2)

    def test_process_id_from_host_ip(self, monkeypatch):
        """The pid resolution finds this host in the machine list; the
        jax.distributed.initialize call itself is stubbed (no cluster)."""
        calls = {}

        def fake_init(coordinator_address, num_processes, process_id):
            calls.update(coordinator=coordinator_address,
                         n=num_processes, pid=process_id)

        import jax

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setenv("LIGHTGBM_TPU_HOST_IP", "10.0.0.2")
        mesh._distributed_initialized = False
        try:
            assert mesh.init_multihost(
                "10.0.0.1:12400,10.0.0.2:12400,10.0.0.3:12400", 12400, 3)
            assert calls == {"coordinator": "10.0.0.1:12400", "n": 3,
                             "pid": 1}
        finally:
            mesh._distributed_initialized = False
