"""Multi-host mesh mapping (the Linkers rendezvous role,
reference src/network/linkers_socket.cpp:165-220 -> jax.distributed).

TestMultihostMapping covers the config-mapping logic in-process; the
TestTwoProcessRendezvous smoke test spawns a REAL 2-process
jax.distributed group (gloo CPU collectives) that runs init_multihost ->
global 8-device mesh -> one data-parallel tree, asserting identical
split records on both ranks — the automated stand-in for the reference's
manual parallel_learning runbook (linkers_socket.cpp:165-220).
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from lightgbm_tpu.parallel import mesh


class TestMultihostMapping:
    def test_single_machine_skips(self):
        assert mesh.init_multihost("", 0, 1) is False
        assert mesh.init_multihost("127.0.0.1:12400", 12400, 1) is False

    def test_unresolvable_process_id_raises(self, monkeypatch):
        monkeypatch.delenv("LIGHTGBM_TPU_HOST_IP", raising=False)
        monkeypatch.delenv("LIGHTGBM_TPU_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="position"):
            mesh.init_multihost("10.0.0.1:12400,10.0.0.2:12400", 12400, 2)

    def test_process_id_from_host_ip(self, monkeypatch):
        """The pid resolution finds this host in the machine list; the
        jax.distributed.initialize call itself is stubbed (no cluster)."""
        calls = {}

        def fake_init(coordinator_address, num_processes, process_id):
            calls.update(coordinator=coordinator_address,
                         n=num_processes, pid=process_id)

        import jax

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        monkeypatch.setenv("LIGHTGBM_TPU_HOST_IP", "10.0.0.2")
        mesh._distributed_initialized = False
        try:
            assert mesh.init_multihost(
                "10.0.0.1:12400,10.0.0.2:12400,10.0.0.3:12400", 12400, 3)
            assert calls == {"coordinator": "10.0.0.1:12400", "n": 3,
                             "pid": 1}
        finally:
            mesh._distributed_initialized = False


_WORKER_SRC = """
import os, sys, importlib.util
root = {root!r}
sys.path.insert(0, root)
spec = importlib.util.spec_from_file_location(
    "_boot", os.path.join(root, "lightgbm_tpu", "utils", "backend.py"))
_b = importlib.util.module_from_spec(spec)
spec.loader.exec_module(_b)
_b.pin_cpu_backend(force_device_count=4)
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner

pid = int(os.environ["LIGHTGBM_TPU_PROCESS_ID"])
# every rank loads the SAME data (the reference's all-data-on-all-machines
# mode; pre-partitioned loading is a separate path)
rng = np.random.default_rng(7)
X = rng.normal(size=(2048, 10))
y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
cfg = Config({{"objective": "binary", "max_bin": 16, "num_leaves": 7,
              "min_data_in_leaf": 5, "tpu_block_rows": 256,
              "tree_learner": "data", "num_machines": 8,
              "machines": {machines!r}}})
td = TrainingData.from_matrix(X, y, cfg)
learner = TPUTreeLearner(cfg, td)   # init_multihost runs in here
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8
grad = rng.normal(size=2048).astype(np.float32)
hess = np.abs(rng.normal(size=2048)).astype(np.float32) + 0.1
tree, _, out = learner.train(grad, hess)
rec = np.asarray(jax.device_get(out["records"]))
assert rec[0, 14] > 0.5, "no split grown"
np.save({outfile!r}, rec)
print(f"rank {{pid}}: {{int(rec[:, 14].sum())}} splits", flush=True)

# FULL training through the public API on the same global mesh: the GBDT
# driver routes multi-process learners through the sync path (local score
# state, allgathered leaf ids) — every rank must produce the same model
import lightgbm_tpu as lgb
ds = lgb.Dataset(X, label=y, params=dict(cfg.params))
bst = lgb.train({{**dict(cfg.params), "verbosity": -1}}, ds,
                num_boost_round=3)
model = bst.model_to_string().split("\\nparameters:")[0]
with open({outfile!r} + ".model", "w") as f:
    f.write(model)
print(f"rank {{pid}}: trained {{bst.num_trees()}} trees", flush=True)

# ---- distributed metrics + early stopping on a PARTITIONED valid set:
# each rank holds only HALF the validation rows, so a host-local metric
# would differ across ranks; the metric_sync reduction must make every
# rank report the GLOBAL value and stop at the SAME iteration
import json
rngv = np.random.default_rng(21)
Xv = rngv.normal(size=(1024, 10))
yv = (Xv[:, 0] + 0.5 * Xv[:, 1]
      + rngv.normal(scale=0.7, size=1024) > 0).astype(np.float64)
half = 512
lo, hi = pid * half, (pid + 1) * half
p_es = dict(cfg.params)
p_es["verbosity"] = -1
p_es["metric"] = ["binary_logloss", "auc"]
dtr = lgb.Dataset(X, label=y, params=p_es)
dval = lgb.Dataset(Xv[lo:hi], label=yv[lo:hi], reference=dtr, params=p_es)
hist = {{}}
bst3 = lgb.train(p_es, dtr, num_boost_round=12,
                 valid_sets=[dval], valid_names=["part"],
                 callbacks=[lgb.early_stopping(2, verbose=False),
                            lgb.record_evaluation(hist)])
n_it = bst3.current_iteration()
# independent expected values: plain numpy on the FULL valid set (no
# collectives, identical on both ranks), predictions from the model
margin = bst3.predict(Xv, num_iteration=n_it, raw_score=True)
pm = np.clip(1.0 / (1.0 + np.exp(-margin)), 1e-15, 1.0 - 1e-15)
exp_ll = float(-(yv * np.log(pm) + (1.0 - yv) * np.log(1.0 - pm)).mean())
order = np.argsort(margin, kind="stable")
ss = margin[order]
pos = (yv[order] > 0).astype(np.float64)
neg = 1.0 - pos
bnd = np.flatnonzero(np.diff(ss)) + 1
gid = np.zeros(len(ss), np.int64)
gid[bnd] = 1
gid = np.cumsum(gid)
ng = int(gid[-1]) + 1
posg = np.bincount(gid, weights=pos, minlength=ng)
negg = np.bincount(gid, weights=neg, minlength=ng)
negb = np.concatenate([[0.0], np.cumsum(negg)[:-1]])
exp_auc = float((posg * (negb + 0.5 * negg)).sum()
                / (pos.sum() * neg.sum()))
rec2 = {{"best_iter": int(bst3.best_iteration),
         "n_iter": int(n_it),
         "curve_ll": hist["part"]["binary_logloss"],
         "curve_auc": hist["part"]["auc"],
         "expected_ll": exp_ll, "expected_auc": exp_auc}}
with open({outfile!r} + ".esjson", "w") as f:
    json.dump(rec2, f)
print(f"rank {{pid}}: es best_iter={{bst3.best_iteration}}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.mark.slow
class TestTwoProcessRendezvous:
    def test_two_process_data_parallel_tree(self, tmp_path):
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        machines = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
        procs, outs = [], []
        for pid in range(2):
            outfile = str(tmp_path / f"rec_{pid}.npy")
            outs.append(outfile)
            src = _WORKER_SRC.format(root=root, machines=machines,
                                     outfile=outfile)
            env = dict(os.environ,
                       LIGHTGBM_TPU_PROCESS_ID=str(pid))
            # the workers pin their own backend; drop the parent's
            # virtual-device flags so they don't fight the pin
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", src], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True))
        logs = []
        for p in procs:
            try:
                log, _ = p.communicate(timeout=600)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            logs.append(log)
        for pid, (p, log) in enumerate(zip(procs, logs)):
            assert p.returncode == 0, f"rank {pid} failed:\n{log[-4000:]}"
        rec0 = np.load(outs[0])
        rec1 = np.load(outs[1])
        # both ranks must materialize IDENTICAL split records: the grower
        # output is replicated, so any divergence means the collective
        # ran inconsistently
        np.testing.assert_array_equal(rec0, rec1)
        assert rec0[:, 14].sum() >= 3
        # full lgb.train over the 2-process mesh: identical models
        m0 = open(outs[0] + ".model").read()
        m1 = open(outs[1] + ".model").read()
        assert m0 == m1 and "tree" in m0
        # distributed metrics over the partitioned valid set: both ranks
        # must report BITWISE-identical metric curves (same collective,
        # same arithmetic order) and stop at the same iteration...
        import json
        es0 = json.load(open(outs[0] + ".esjson"))
        es1 = json.load(open(outs[1] + ".esjson"))
        assert es0 == es1, "ranks diverged on metrics/early stopping"
        assert es0["best_iter"] == es1["best_iter"]
        # ...and the reported value must be the GLOBAL metric: the last
        # curve entry equals the numpy full-valid-set computation (f32
        # score-state accumulation vs the predictor's f64 sum bounds the
        # tolerance)
        assert es0["curve_ll"][-1] == pytest.approx(es0["expected_ll"],
                                                    abs=2e-4)
        assert es0["curve_auc"][-1] == pytest.approx(es0["expected_auc"],
                                                     abs=2e-4)
        # early stopping actually engaged (12 rounds max, patience 2)
        assert 1 <= es0["best_iter"] <= es0["n_iter"] <= 12
