"""Boosting-mode tests (M4): GOSS, DART, RF, rollback, model round-trips."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def binary_data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(2000, 8))
    y = (X[:, 0] + 0.5 * X[:, 1] ** 2 > 0.5).astype(float)
    return X, y


class TestGOSS:
    def test_trains_and_learns(self, binary_data):
        X, y = binary_data
        res = {}
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"boosting": "goss", "objective": "binary",
                         "metric": "binary_logloss", "num_leaves": 15,
                         "learning_rate": 0.5, "top_rate": 0.2,
                         "other_rate": 0.1},
                        ds, num_boost_round=15,
                        valid_sets=[ds], valid_names=["training"],
                        verbose_eval=False, evals_result=res)
        curve = res["training"]["binary_logloss"]
        assert curve[-1] < curve[0] * 0.7
        acc = ((bst.predict(X) > 0.5) == y).mean()
        assert acc > 0.85

    def test_rejects_bagging(self, binary_data):
        X, y = binary_data
        with pytest.raises(ValueError, match="bagging"):
            lgb.train({"boosting": "goss", "objective": "binary",
                       "bagging_freq": 1, "bagging_fraction": 0.5},
                      lgb.Dataset(X, label=y), num_boost_round=2,
                      verbose_eval=False)

    def test_rejects_bad_rates(self, binary_data):
        X, y = binary_data
        with pytest.raises(ValueError, match="top_rate"):
            lgb.train({"boosting": "goss", "objective": "binary",
                       "top_rate": 0.8, "other_rate": 0.4},
                      lgb.Dataset(X, label=y), num_boost_round=2,
                      verbose_eval=False)

    def test_goss_with_renew_objective(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(1000, 5))
        y = X[:, 0] * 2 + rng.normal(size=1000) * 0.1
        res = {}
        ds = lgb.Dataset(X, label=y)
        lgb.train({"boosting": "goss", "objective": "regression_l1",
                   "metric": "l1", "num_leaves": 15, "learning_rate": 0.3},
                  ds, num_boost_round=15,
                  valid_sets=[ds],
                  valid_names=["training"], verbose_eval=False,
                  evals_result=res)
        curve = res["training"]["l1"]
        assert curve[-1] < curve[0] * 0.8


class TestDART:
    def test_trains_and_learns(self, binary_data):
        X, y = binary_data
        res = {}
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"boosting": "dart", "objective": "binary",
                         "metric": "binary_logloss", "num_leaves": 15,
                         "learning_rate": 0.15, "drop_rate": 0.5,
                         "skip_drop": 0.0},
                        ds, num_boost_round=15,
                        valid_sets=[ds], valid_names=["training"],
                        verbose_eval=False, evals_result=res)
        curve = res["training"]["binary_logloss"]
        assert curve[-1] < curve[0]
        acc = ((bst.predict(X) > 0.5) == y).mean()
        assert acc > 0.8

    def test_scores_consistent_with_model(self, binary_data):
        """After DART's drop/normalize dance, the maintained train scores
        must equal the sum of the (rescaled) model trees."""
        X, y = binary_data
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"boosting": "dart", "objective": "binary",
                         "num_leaves": 7, "learning_rate": 0.3,
                         "drop_rate": 0.6, "skip_drop": 0.0},
                        ds, num_boost_round=8, verbose_eval=False,
                        keep_training_booster=True)
        drv = bst._driver
        drv._materialize()
        maintained = drv.train_scores.numpy()[0]
        replayed = drv.predict_raw(X)[0]
        np.testing.assert_allclose(maintained, replayed, atol=2e-4)

    def test_uniform_drop(self, binary_data):
        X, y = binary_data
        bst = lgb.train({"boosting": "dart", "objective": "binary",
                         "num_leaves": 7, "uniform_drop": True,
                         "drop_rate": 0.3, "skip_drop": 0.2},
                        lgb.Dataset(X, label=y), num_boost_round=10,
                        verbose_eval=False)
        assert bst.num_trees() == 10

    def test_xgboost_dart_mode(self, binary_data):
        X, y = binary_data
        bst = lgb.train({"boosting": "dart", "objective": "binary",
                         "num_leaves": 7, "xgboost_dart_mode": True,
                         "drop_rate": 0.5, "skip_drop": 0.0},
                        lgb.Dataset(X, label=y), num_boost_round=8,
                        verbose_eval=False,
                        keep_training_booster=True)
        drv = bst._driver
        drv._materialize()
        np.testing.assert_allclose(drv.train_scores.numpy()[0],
                                   drv.predict_raw(X)[0], atol=2e-4)


class TestRF:
    def test_trains_and_learns(self, binary_data):
        X, y = binary_data
        res = {}
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"boosting": "rf", "objective": "binary",
                         "metric": "binary_logloss", "num_leaves": 31,
                         "bagging_freq": 1, "bagging_fraction": 0.7,
                         "feature_fraction": 0.8},
                        ds, num_boost_round=10,
                        valid_sets=[ds],
                        valid_names=["training"], verbose_eval=False,
                        evals_result=res)
        acc = ((bst.predict(X) > 0.5) == y).mean()
        assert acc > 0.85

    def test_requires_bagging(self, binary_data):
        X, y = binary_data
        with pytest.raises(ValueError, match="bagging"):
            lgb.train({"boosting": "rf", "objective": "binary"},
                      lgb.Dataset(X, label=y), num_boost_round=2,
                      verbose_eval=False)

    def test_average_output_round_trip(self, binary_data):
        X, y = binary_data
        bst = lgb.train({"boosting": "rf", "objective": "binary",
                         "num_leaves": 15, "bagging_freq": 1,
                         "bagging_fraction": 0.6},
                        lgb.Dataset(X, label=y), num_boost_round=5,
                        verbose_eval=False)
        s = bst.model_to_string()
        assert "\naverage_output\n" in s
        bst2 = lgb.Booster(model_str=s)
        np.testing.assert_allclose(bst.predict(X[:100]),
                                   bst2.predict(X[:100]), rtol=1e-6)

    def test_scores_are_averaged(self, binary_data):
        """Maintained scores equal mean of tree outputs (+bias)."""
        X, y = binary_data
        bst = lgb.train({"boosting": "rf", "objective": "binary",
                         "num_leaves": 15, "bagging_freq": 1,
                         "bagging_fraction": 0.6},
                        lgb.Dataset(X, label=y), num_boost_round=6,
                        verbose_eval=False,
                        keep_training_booster=True)
        drv = bst._driver
        maintained = drv.train_scores.numpy()[0]
        replayed = drv.predict_raw(X)[0]  # predict_raw averages for RF
        np.testing.assert_allclose(maintained, replayed, atol=2e-4)


class TestRollbackAndSnapshots:
    def test_rollback(self, binary_data):
        X, y = binary_data
        ds = lgb.Dataset(X, label=y)
        bst = lgb.Booster(params={"objective": "binary", "num_leaves": 7},
                          train_set=ds)
        for _ in range(5):
            bst.update()
        assert bst.current_iteration() == 5
        scores_before = bst._driver.train_scores.numpy().copy()
        bst.update()
        bst.rollback_one_iter()
        assert bst.current_iteration() == 5
        np.testing.assert_allclose(bst._driver.train_scores.numpy(),
                                   scores_before, atol=1e-5)
