"""Fleet-scale serving (ISSUE 19): mesh-replicated dispatch, AOT cold
starts, quantized serving tables.

Contracts under test:
* a `serving_devices=N` load places one replica per device (distinct
  jax devices, placement table row, per-device HBM gauges that sum to
  `hbm_total_bytes`) and replicated predicts stay value-correct;
* concurrent traffic spreads across dispatch workers (least-loaded
  routing, per-device rows counters);
* pressure-evicting a replicated model frees bytes on EVERY device —
  the per-device gauges drop together, not just the summary gauge;
* a single device's injected `device_alloc` OOM fails over to the
  surviving replicas with ZERO caller-visible errors
  (`replica_failovers` counts it; the native walker is never needed);
* `serving_table_precision=bf16` cuts per-model serving bytes >= 40%
  with a bounded raw-score delta; `int16` keeps the decision path
  EXACTLY (thresholds/ids/codes quantize losslessly) so the score
  delta is leaf-rounding only;
* an AOT cache dir makes the SECOND load reach a full request-size
  sweep with zero new jitted programs and zero warmup compiles
  (`aot_cache_hits` ledger-asserted); a corrupt `.aotx` degrades to a
  logged warm compile, never a failed load.

Everything runs under JAX_PLATFORMS=cpu with 8 virtual devices
(tests/conftest.py pins `--xla_force_host_platform_device_count`).
"""

import glob
import threading

import numpy as np
import pytest

from .conftest import *  # noqa: F401,F403  (cpu backend pin)

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import ServingSession
from lightgbm_tpu.utils import faultline, membudget

PARAMS = {"objective": "binary", "num_leaves": 15,
          "tpu_predict_device": "true", "verbose": -1}


@pytest.fixture(autouse=True)
def _clean_faults():
    faultline.reset()
    yield
    faultline.reset()


def _make_data(n=3000, f=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) < 0.08] = np.nan
    y = (np.nansum(X[:, :3], axis=1) > 0).astype(float)
    return X, y


def _train(X, y, rounds=8):
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    return lgb.train(dict(PARAMS), ds, num_boost_round=rounds,
                     verbose_eval=False)


@pytest.fixture(scope="module")
def booster():
    X, y = _make_data()
    return _train(X, y), X


def _session(devices=0, **params):
    p = {"serving_max_batch_rows": 1024, "serving_max_wait_ms": 1.0,
         "verbosity": -1, **params}
    if devices:
        p["serving_devices"] = devices
    return ServingSession(params=p)


def _gauge(sess, name, **labels):
    return float(sess._stats.registry.value(name, **labels))


# ---------------------------------------------------------------------------
# 1. replicated placement + routing
# ---------------------------------------------------------------------------
class TestReplicatedDispatch:
    def test_replicas_land_on_distinct_devices_with_gauges(self, booster):
        bst, X = booster
        sess = _session(devices=4)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            assert len(entry.replicas) == 4
            devs = [r.device for r in entry.replicas]
            assert len(set(devs)) == 4
            assert tuple(sess.registry.placement.devices_for(entry.key)) \
                == (0, 1, 2, 3)
            per_dev = [_gauge(sess, "lgbm_serving_device_hbm_bytes",
                              device=str(i)) for i in range(4)]
            assert all(g > 0 for g in per_dev)
            assert int(sum(per_dev)) == int(entry.hbm_total_bytes)
            # the per-device budget unit stays ONE replica's bytes
            assert entry.hbm_bytes == entry.replicas[0].nbytes
        finally:
            sess.close()

    def test_replicated_predict_matches_native(self, booster):
        bst, X = booster
        sess = _session(devices=4)
        try:
            sess.load("m", booster=bst)
            got = sess.predict("m", X[:700], raw_score=True)
            ref = bst.predict(X[:700], raw_score=True, device="cpu")
            np.testing.assert_allclose(got, ref, rtol=0, atol=1e-5)
        finally:
            sess.close()

    def test_concurrent_load_spreads_across_devices(self, booster):
        bst, X = booster
        sess = _session(devices=4)
        try:
            sess.load("m", booster=bst)
            errs = []

            def worker(i):
                try:
                    for j in range(6):
                        sess.predict("m", X[(i * 37 + j) % 512:][:64],
                                     raw_score=True)
                except Exception as exc:  # pragma: no cover - fail loud
                    errs.append(exc)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(16)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs
            rows = [_gauge(sess, "lgbm_serving_device_rows_total",
                           device=str(i)) for i in range(4)]
            assert sum(1 for r in rows if r > 0) >= 2, \
                f"least-loaded routing never left device 0: {rows}"
            snap = sess.batcher.device_snapshot()
            assert [d["device"] for d in snap] == [0, 1, 2, 3]
            assert sum(d["rows"] for d in snap) == sum(rows)
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# 2. pressure eviction frees the whole fleet's bytes
# ---------------------------------------------------------------------------
class TestFleetEviction:
    def test_pressure_eviction_frees_bytes_on_every_device(self, booster):
        bst, X = booster
        from lightgbm_tpu.config import Config

        base_cfg = {"verbosity": -1, "serving_max_batch_rows": 16,
                    "serving_devices": 2}
        plan = membudget.plan_model_load(bst, Config(base_cfg))
        tables = plan.components["packed_tables"]
        budget = plan.total * 3
        frac = (tables * 1.5) / budget
        assert frac >= 0.05
        sess = ServingSession(params={
            **base_cfg, "serving_hbm_budget_bytes": budget,
            "serving_hbm_pressure_frac": frac})
        try:
            sess.load("m", booster=bst)          # v1 on devices {0, 1}
            v1 = sess.registry.resolve("m")
            before = [_gauge(sess, "lgbm_serving_device_hbm_bytes",
                             device=str(i)) for i in range(2)]
            assert all(b >= v1.replicas[i].nbytes
                       for i, b in enumerate(before))
            sess.load("m", booster=bst)          # v2: v1 must yield
            st = sess.stats()
            assert st["evictions_pressure"] >= 1
            v2 = sess.registry.resolve("m")
            assert v2.key != v1.key
            after = [_gauge(sess, "lgbm_serving_device_hbm_bytes",
                            device=str(i)) for i in range(2)]
            # EVERY device's gauge dropped to exactly v2's replica bytes
            for i in range(2):
                assert int(after[i]) == int(v2.replicas[i].nbytes), \
                    (i, before, after)
            assert not sess.registry.placement.devices_for(v1.key)
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# 3. single-device OOM chaos -> sibling failover, zero errors
# ---------------------------------------------------------------------------
class TestSingleDeviceFailover:
    def test_device0_oom_fails_over_with_zero_errors(self, booster):
        bst, X = booster
        sess = _session(devices=2)
        try:
            sess.load("m", booster=bst)
            ref = bst.predict(X[:64], raw_score=True, device="cpu")
            # only device 0's dispatch allocations fail; loads/warmups
            # and device 1 stay healthy (the `where` faultline filter)
            faultline.arm("device_alloc", action="oom", times=10 ** 6,
                          where={"site": "serve_dispatch", "device": 0})
            for _ in range(6):
                got = sess.predict("m", X[:64], raw_score=True)
                np.testing.assert_allclose(got, ref, rtol=0, atol=1e-5)
            st = sess.stats()
            assert st["replica_failovers"] >= 1
            assert st["dispatch_oom"] >= 1
            # the walker escape hatch was never needed: siblings served
            assert st["device_fallbacks"] == 0
            entry = sess.registry.resolve("m")
            assert entry.healthy  # device 1 keeps the model routable
            faultline.reset()
            got = sess.predict("m", X[:64], raw_score=True)
            np.testing.assert_allclose(got, ref, rtol=0, atol=1e-5)
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# 4. quantized serving tables
# ---------------------------------------------------------------------------
class TestQuantizedTables:
    def _pack_host(self, bst):
        return bst._driver._packed_forest().host()

    def test_bf16_cuts_model_bytes_40pct_with_bounded_scores(self, booster):
        bst, X = booster
        f32 = _session(**{"serving_table_precision": "f32"})
        bf16 = _session(**{"serving_table_precision": "bf16"})
        try:
            f32.load("m", booster=bst)
            bf16.load("m", booster=bst)
            b_f32 = f32.registry.resolve("m").hbm_bytes
            b_bf16 = bf16.registry.resolve("m").hbm_bytes
            assert b_bf16 <= 0.6 * b_f32, (b_f32, b_bf16)
            a = f32.predict("m", X[:800], raw_score=True)
            b = bf16.predict("m", X[:800], raw_score=True)
            # bf16 has 8 head bits of mantissa: each tree's leaf errs
            # <= 2^-9 relative, so the documented sum-of-trees bound
            lv = np.asarray(self._pack_host(bst)["leaf_value"],
                            np.float64)
            bound = np.abs(lv).max(axis=1).sum() * 2.0 ** -8
            assert float(np.abs(a - b).max()) <= bound, \
                (float(np.abs(a - b).max()), bound)
        finally:
            f32.close()
            bf16.close()

    def test_int16_decision_path_parity_exact(self, booster):
        bst, X = booster
        from lightgbm_tpu.ops.predict import _NODE_KEYS, quantize_tables

        host = self._pack_host(bst)
        q = quantize_tables(host, "int16")
        # structural proof: every node table quantized LOSSLESSLY, so
        # traversal decisions are the same integer comparisons
        for key in _NODE_KEYS + ("init_node",):
            assert q[key].dtype == np.int16, key
            assert np.array_equal(q[key].astype(np.int64),
                                  host[key].astype(np.int64)), key
        i16 = _session(**{"serving_table_precision": "int16"})
        f32 = _session()
        try:
            i16.load("m", booster=bst)
            f32.load("m", booster=bst)
            a = f32.predict("m", X[:800], raw_score=True)
            b = i16.predict("m", X[:800], raw_score=True)
            # identical decision path => the delta is per-tree leaf
            # rounding only: half a quantization step per tree
            bound = float(q["leaf_scale"].astype(np.float64).sum()) \
                * 0.51 + 1e-7
            assert float(np.abs(a - b).max()) <= bound, \
                (float(np.abs(a - b).max()), bound)
        finally:
            i16.close()
            f32.close()

    def test_plan_model_load_prices_quantized_tables(self, booster):
        bst, _ = booster
        from lightgbm_tpu.config import Config

        base = {"verbosity": -1, "serving_max_batch_rows": 16}
        p_f32 = membudget.plan_model_load(bst, Config(base))
        p_bf16 = membudget.plan_model_load(
            bst, Config({**base, "serving_table_precision": "bf16"}))
        assert p_bf16.components["packed_tables"] <= \
            0.6 * p_f32.components["packed_tables"]
        # the preflight number matches what the load actually puts on
        # each device (the budget unit stays truthful under precision)
        sess = _session(**{"serving_table_precision": "bf16",
                           "serving_max_batch_rows": 16})
        try:
            sess.load("m", booster=bst)
            assert sess.registry.resolve("m").hbm_bytes == \
                p_bf16.components["packed_tables"]
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# 5. AOT-compiled cold starts
# ---------------------------------------------------------------------------
class TestAOTColdStart:
    def test_second_load_serves_sweep_with_zero_new_programs(
            self, booster, tmp_path):
        bst, X = booster
        cache = str(tmp_path / "aot")
        params = {"serving_aot_cache_dir": cache,
                  "serving_max_batch_rows": 1024}
        warm = _session(**params)
        try:
            warm.load("m", booster=bst)
            st = warm.stats()
            assert st["aot_cache_misses"] >= 1  # first load compiles
            assert glob.glob(cache + "/*.aotx")
        finally:
            warm.close()
        from lightgbm_tpu.ops.predict import _class_scores_kernel

        jit_before = (_class_scores_kernel._cache_size()
                      if hasattr(_class_scores_kernel, "_cache_size")
                      else None)
        cold = _session(**params)
        try:
            cold.load("m", booster=bst)
            st0 = cold.stats()
            assert st0["aot_cache_hits"] >= 1
            assert st0["aot_cache_misses"] == 0
            # the compile ledger: a cold replica reaches a full
            # request-size sweep with ZERO jit-compiled programs
            assert st0["compiles_warmup"] == 0
            ref = bst.predict(X[:900], raw_score=True, device="cpu")
            for sz in (1, 7, 64, 513, 900):
                got = cold.predict("m", X[:sz], raw_score=True)
                np.testing.assert_allclose(got, ref[:sz], rtol=0,
                                           atol=1e-5)
            st = cold.stats()
            assert st["compile_cache_misses"] == 0
            if jit_before is not None:
                assert _class_scores_kernel._cache_size() == jit_before, \
                    "cold start compiled a jitted program after all"
        finally:
            cold.close()

    def test_corrupt_aot_blob_degrades_to_warm_compile(self, booster,
                                                       tmp_path):
        bst, X = booster
        cache = str(tmp_path / "aot")
        params = {"serving_aot_cache_dir": cache,
                  "serving_max_batch_rows": 1024}
        warm = _session(**params)
        try:
            warm.load("m", booster=bst)
        finally:
            warm.close()
        blobs = sorted(glob.glob(cache + "/*.aotx"))
        assert blobs
        with open(blobs[0], "wb") as f:
            f.write(b"not an executable")
        sess = _session(**params)
        try:
            sess.load("m", booster=bst)   # must not raise
            st = sess.stats()
            assert st["aot_cache_misses"] >= 1   # the corrupt bucket
            ref = bst.predict(X[:256], raw_score=True, device="cpu")
            got = sess.predict("m", X[:256], raw_score=True)
            np.testing.assert_allclose(got, ref, rtol=0, atol=1e-5)
        finally:
            sess.close()
