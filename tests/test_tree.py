"""Tree model: structure, serialization, prediction (SURVEY.md §2.1 Tree)."""

import numpy as np
import pytest

from lightgbm_tpu.models.tree import Tree
from lightgbm_tpu.models.gbdt import GBDT

from .conftest import has_oracle


def _small_tree():
    t = Tree(4)
    # root split on feature 0 @ 0.5
    t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=10,
            threshold_double=0.5, left_value=-1.0, right_value=1.0,
            left_cnt=60, right_cnt=40, left_weight=6.0, right_weight=4.0,
            gain=10.0, missing_type=0, default_left=True)
    # split left leaf on feature 1 @ -0.2
    t.split(leaf=0, feature_inner=1, real_feature=1, threshold_bin=5,
            threshold_double=-0.2, left_value=-2.0, right_value=-0.5,
            left_cnt=30, right_cnt=30, left_weight=3.0, right_weight=3.0,
            gain=5.0, missing_type=0, default_left=True)
    return t


class TestTreeStructure:
    def test_split_bookkeeping(self):
        t = _small_tree()
        assert t.num_leaves == 3
        # node 0: children = node 1 (left, was leaf 0) and ~1 (right leaf)
        assert t.left_child[0] == 1
        assert t.right_child[0] == ~1
        assert t.left_child[1] == ~0
        assert t.right_child[1] == ~2
        assert t.internal_count[0] == 100
        assert t.leaf_depth[0] == 2 and t.leaf_depth[2] == 2

    def test_predict(self):
        t = _small_tree()
        X = np.array([[0.0, -0.5], [0.0, 0.0], [1.0, 0.0]])
        np.testing.assert_allclose(t.predict(X), [-2.0, -0.5, 1.0])
        assert list(t.predict_leaf(X)) == [0, 2, 1]

    def test_shrinkage_and_bias(self):
        t = _small_tree()
        t.apply_shrinkage(0.1)
        # leaf order: 0 = left of 2nd split, 1 = right of 1st, 2 = right of 2nd
        np.testing.assert_allclose(t.leaf_value[:3], [-0.2, 0.1, -0.05])
        assert t.shrinkage == pytest.approx(0.1)
        t.add_bias(1.0)
        np.testing.assert_allclose(t.leaf_value[:3], [0.8, 1.1, 0.95])
        assert t.shrinkage == 1.0

    def test_string_roundtrip(self):
        t = _small_tree()
        t2 = Tree.from_string(t.to_string())
        X = np.random.default_rng(0).normal(size=(50, 2))
        np.testing.assert_allclose(t.predict(X), t2.predict(X))
        assert t2.num_leaves == 3

    def test_missing_nan_default_left(self):
        t = Tree(2)
        t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                threshold_double=0.5, left_value=-1.0, right_value=1.0,
                left_cnt=50, right_cnt=50, left_weight=5.0, right_weight=5.0,
                gain=1.0, missing_type=2, default_left=True)
        X = np.array([[np.nan], [0.2], [0.9]])
        np.testing.assert_allclose(t.predict(X), [-1.0, -1.0, 1.0])
        # default right
        t.decision_type[0] = int(t.decision_type[0]) & ~2
        np.testing.assert_allclose(t.predict(X), [1.0, -1.0, 1.0])


@pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
class TestModelInterchange:
    """Model files interchange with the reference bit-exactly (SURVEY.md §2.2)."""

    @pytest.fixture(scope="class")
    def ref_model(self, binary_example, tmp_path_factory):
        from .oracle import train_cli_and_read_model
        return train_cli_and_read_model(
            binary_example["train_file"],
            {"objective": "binary", "num_trees": "10", "num_leaves": "31",
             "learning_rate": "0.1", "min_data_in_leaf": "20",
             "verbosity": "-1"})

    def test_load_reference_model_and_predict(self, ref_model, binary_example,
                                              tmp_path):
        import subprocess
        from .conftest import ORACLE_BIN
        g = GBDT.from_model_string(ref_model["model"])
        assert len(g.models) == 10
        mine = g.predict(binary_example["X_test"])
        model_path = tmp_path / "m.txt"
        model_path.write_text(ref_model["model"])
        out_path = tmp_path / "p.txt"
        subprocess.run([ORACLE_BIN, "task=predict",
                        f"data={binary_example['test_file']}",
                        f"input_model={model_path}",
                        f"output_result={out_path}", "verbosity=-1"],
                       check=True, capture_output=True)
        ref = np.loadtxt(out_path)
        np.testing.assert_allclose(mine, ref, atol=1e-12)

    def test_reference_loads_our_model(self, binary_example, tmp_path):
        import subprocess
        import lightgbm_tpu as lgb
        from .conftest import ORACLE_BIN
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"],
                         params={"max_bin": 255})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "learning_rate": 0.1, "min_data_in_leaf": 20},
                        ds, num_boost_round=5, verbose_eval=False)
        mine = bst.predict(binary_example["X_test"])
        model_path = tmp_path / "m.txt"
        bst.save_model(str(model_path))
        out_path = tmp_path / "p.txt"
        subprocess.run([ORACLE_BIN, "task=predict",
                        f"data={binary_example['test_file']}",
                        f"input_model={model_path}",
                        f"output_result={out_path}", "verbosity=-1"],
                       check=True, capture_output=True)
        ref = np.loadtxt(out_path)
        np.testing.assert_allclose(mine, ref, atol=1e-12)

    def test_our_string_roundtrip(self, binary_example):
        import lightgbm_tpu as lgb
        from lightgbm_tpu.booster import Booster
        ds = lgb.Dataset(binary_example["X_train"],
                         label=binary_example["y_train"])
        bst = lgb.train({"objective": "binary", "num_leaves": 7},
                        ds, num_boost_round=3, verbose_eval=False)
        s = bst.model_to_string()
        bst2 = Booster(model_str=s)
        np.testing.assert_allclose(bst.predict(binary_example["X_test"]),
                                   bst2.predict(binary_example["X_test"]))
