"""Sharded histogram aggregation (tpu_hist_agg=scatter): psum_scatter
feature slices, per-shard split search, best-split sync.

The contract under test (ops/grower.py, parallel/strategies.py):

* scatter and psum make IDENTICAL split decisions — bitwise for the
  quantized precisions (int8/int16: associative int32 sums + the shared
  tie-break), decision-parity for f32/hilo (different reduction orders);
* no shard ever materializes the global histogram: the per-shard pool /
  root histogram is the F/P feature slice (the no-global-histogram
  assertion, via the debug_hist root_hist shard shapes);
* the shared deterministic tie-break (split.argbest: highest gain, then
  lowest global feature id, then lowest bin) makes equal-gain decisions
  identical across psum, scatter, feature, and voting paths at every
  shard count;
* F not divisible by the shard count pads transparently (trivial
  padding features can never split).

Runs on the 8-virtual-device CPU mesh (tests/conftest.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner
from lightgbm_tpu.ops import grower as G
from lightgbm_tpu.ops.split import argbest


def _problem(n=4096, f=10, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


def _grow_records(X, y, grad_seed=3, **cfg):
    params = {"objective": "binary", "max_bin": 63, "num_leaves": 15,
              "min_data_in_leaf": 5, "tpu_block_rows": 512,
              "verbosity": -1}
    params.update(cfg)
    config = Config(params)
    td = TrainingData.from_matrix(X, y, config)
    learner = TPUTreeLearner(config, td)
    r = np.random.default_rng(grad_seed)
    grad = r.normal(size=learner.n).astype(np.float32)
    hess = np.abs(r.normal(size=learner.n)).astype(np.float32) + 0.1
    tree, leaf_ids, out = learner.train(jnp.asarray(grad),
                                        jnp.asarray(hess))
    return (np.asarray(jax.device_get(out["records"])),
            np.asarray(jax.device_get(leaf_ids)), learner)


def _train_model_text(X, y, rounds=3, **cfg):
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5, "tpu_block_rows": 512,
              "verbosity": -1, "tpu_shape_buckets": 0}
    params.update(cfg)
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.train(params, ds, num_boost_round=rounds,
                    keep_training_booster=True)
    text = bst.model_to_string().split("\nparameters:")[0]
    return text, bst


class TestResolution:
    def test_auto_is_scatter_on_a_real_data_axis(self):
        X, y = _problem(n=1024)
        _, _, l = _grow_records(X, y, tree_learner="data", num_machines=4)
        assert l.hist_agg == "scatter"
        assert l.params.hist_agg == "scatter"

    def test_serial_and_feature_stay_psum(self):
        X, y = _problem(n=1024)
        _, _, ls = _grow_records(X, y)
        assert ls.hist_agg == "psum"
        _, _, lf = _grow_records(X, y, tree_learner="feature",
                                 num_machines=2)
        assert lf.hist_agg == "psum"

    def test_explicit_psum_honored(self):
        X, y = _problem(n=1024)
        _, _, l = _grow_records(X, y, tree_learner="data", num_machines=4,
                                tpu_hist_agg="psum")
        assert l.hist_agg == "psum"

    def test_bad_value_rejected(self):
        X, y = _problem(n=512)
        config = Config({"objective": "binary", "tpu_hist_agg": "ring"})
        td = TrainingData.from_matrix(X, y, config)
        with pytest.raises(ValueError, match="tpu_hist_agg"):
            TPUTreeLearner(config, td)


class TestRecordsBitwise:
    """int8 grower records bitwise-identical: serial vs scatter at 2/4/8
    shards vs psum — the PR-4 cross-shard-count guarantee must survive
    the scattered topology (associative int32 psum_scatter + shared
    tie-break)."""

    def test_scatter_matches_serial_and_psum(self):
        X, y = _problem()
        q = {"tpu_hist_precision": "int8"}
        rec_s, leaf_s, _ = _grow_records(X, y, **q)
        for shards in (2, 4, 8):
            rec_c, leaf_c, l = _grow_records(
                X, y, tree_learner="data", num_machines=shards, **q)
            assert l.hist_agg == "scatter"
            np.testing.assert_array_equal(rec_s, rec_c)
            np.testing.assert_array_equal(leaf_s, leaf_c)
        rec_p, leaf_p, _ = _grow_records(
            X, y, tree_learner="data", num_machines=4,
            tpu_hist_agg="psum", **q)
        np.testing.assert_array_equal(rec_s, rec_p)


class TestNoGlobalHistogram:
    """The acceptance hook: under scatter each shard's root histogram /
    pool slice is [G/P, B, 3] — the global histogram never materializes
    on any one shard (per-shard pool HBM drops by the data-axis
    factor)."""

    def test_per_shard_slice_is_f_over_p(self):
        from lightgbm_tpu.parallel.strategies import make_strategy_grower

        X, y = _problem(n=2048, f=8)
        config = Config({"objective": "binary", "max_bin": 63,
                         "num_leaves": 15, "min_data_in_leaf": 5,
                         "tpu_block_rows": 512, "verbosity": -1,
                         "tree_learner": "data", "num_machines": 4})
        td = TrainingData.from_matrix(X, y, config)
        l = TPUTreeLearner(config, td)
        grow = make_strategy_grower(l.params, l.f_pad, "data", l.mesh,
                                    num_columns=l.g_pad, debug_hist=True)
        r = np.random.default_rng(0)
        grad = jnp.asarray(r.normal(size=l.n_pad).astype(np.float32))
        hess = jnp.asarray(
            np.abs(r.normal(size=l.n_pad)).astype(np.float32))
        out = grow(l.bins_t, grad, hess, l._ones_mask,
                   jnp.ones(l.f_pad, jnp.float32), l.meta,
                   jax.random.PRNGKey(0))
        rh = out["root_hist"]
        # global reassembly is [G, B, 3]; each ADDRESSABLE SHARD holds
        # only its G/P slice
        assert rh.shape[0] == l.g_pad
        shard_rows = {s.data.shape[0] for s in rh.addressable_shards}
        assert shard_rows == {l.g_pad // 4}, shard_rows
        # and the stacked slices ARE the psum histogram
        grow_p = make_strategy_grower(
            l.params._replace(hist_agg="psum"), l.f_pad, "data", l.mesh,
            num_columns=l.g_pad, debug_hist=True)
        out_p = grow_p(l.bins_t, grad, hess, l._ones_mask,
                       jnp.ones(l.f_pad, jnp.float32), l.meta,
                       jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(rh), np.asarray(
            out_p["root_hist"]), rtol=2e-4, atol=2e-4)


class TestPaddingEdges:
    """F not divisible by P: the learner pads the feature axis to a
    shard multiple; padding features are trivial and can never split."""

    @pytest.mark.parametrize("f", [9, 13])
    def test_int8_bitwise_with_padding(self, f):
        X, y = _problem(n=4096, f=f)
        q = {"tpu_hist_precision": "int8"}
        rec_s, leaf_s, _ = _grow_records(X, y, **q)
        rec_c, leaf_c, l = _grow_records(
            X, y, tree_learner="data", num_machines=8, **q)
        assert l.f_pad % 8 == 0 and l.f_pad >= f
        np.testing.assert_array_equal(rec_s, rec_c)
        np.testing.assert_array_equal(leaf_s, leaf_c)


class TestFloatDecisionParity:
    """f32/hilo: psum vs scatter reduction orders differ by design, so
    the bar is decision parity (the same 0.85 agreement bound the psum
    mode holds against serial), not bitwise equality."""

    def test_f32_scatter_vs_psum(self):
        X, y = _problem()
        kw = dict(tree_learner="data", num_machines=8,
                  tpu_hist_precision="f32")
        rec_c, _, _ = _grow_records(X, y, **kw)
        rec_p, _, _ = _grow_records(X, y, tpu_hist_agg="psum", **kw)
        np.testing.assert_array_equal(rec_c[:, G.REC_DID_SPLIT],
                                      rec_p[:, G.REC_DID_SPLIT])
        done = rec_c[:, G.REC_DID_SPLIT] > 0.5
        cols = [G.REC_LEAF, G.REC_FEATURE, G.REC_THRESHOLD]
        agree = (rec_c[done][:, cols].astype(np.int64)
                 == rec_p[done][:, cols].astype(np.int64)).mean()
        assert agree >= 0.85, f"decision agreement {agree:.0%}"


class TestTieBreak:
    """Duplicated columns force exact gain ties: every path must pick the
    LOWEST feature id (the shared argbest rule), at every shard count."""

    def _tie_problem(self):
        rng = np.random.default_rng(11)
        X = rng.normal(size=(2048, 8))
        X[:, 5] = X[:, 0]  # exact duplicate -> bitwise-equal gains
        y = (X[:, 0] > 0.3).astype(np.float64)
        return X, y

    def _tie_records(self, X, y, **cfg):
        # like _grow_records, but with y-DERIVED gradients (logistic at
        # score 0) so the duplicated pair 0/5 carries the dominant gain
        # and the 0-vs-5 tie is actually exercised at the winner level —
        # random gradients would leave both duplicates losing every leaf
        params = {"objective": "binary", "max_bin": 63, "num_leaves": 15,
                  "min_data_in_leaf": 5, "tpu_block_rows": 512,
                  "verbosity": -1}
        params.update(cfg)
        config = Config(params)
        td = TrainingData.from_matrix(X, y, config)
        learner = TPUTreeLearner(config, td)
        yp = np.zeros(learner.n, np.float32)
        yp[:len(y)] = y
        grad = (0.5 - yp).astype(np.float32)
        hess = np.full(learner.n, 0.25, np.float32)
        _, _, out = learner.train(jnp.asarray(grad), jnp.asarray(hess))
        return np.asarray(jax.device_get(out["records"]))

    @pytest.mark.parametrize("cfg", [
        {},                                                # serial argmax
        {"tree_learner": "data", "num_machines": 4},       # scatter sync
        {"tree_learner": "data", "num_machines": 4,
         "tpu_hist_agg": "psum"},                          # psum argmax
        {"tree_learner": "feature", "num_machines": 4},    # feature sync
        {"tree_learner": "voting", "num_machines": 4,
         "top_k": 6},                                      # voting argbest
    ])
    def test_lowest_feature_wins(self, cfg):
        X, y = self._tie_problem()
        rec = self._tie_records(X, y, tpu_hist_precision="int16", **cfg)
        done = rec[:, G.REC_DID_SPLIT] > 0.5
        feats = rec[done][:, G.REC_FEATURE].astype(np.int64)
        # feature 5 is a bitwise duplicate of feature 0: the winner of
        # any 0-vs-5 tie must be 0, so 5 may never appear
        assert 5 not in feats, feats
        assert 0 in feats

    def test_argbest_unit(self):
        g = jnp.asarray([1.0, 3.0, 3.0, 2.0])
        f = jnp.asarray([7, 4, 2, 0], jnp.int32)
        t = jnp.asarray([1, 1, 9, 0], jnp.int32)
        assert int(argbest(g, f, t)) == 2          # max gain, lowest feat
        f2 = jnp.asarray([7, 2, 2, 0], jnp.int32)
        assert int(argbest(g, f2, t)) == 1         # feat tie -> lowest bin
        assert int(argbest(g, f2)) == 1            # no bins: first lowest


@pytest.mark.slow
class TestModelFileBitwise:
    """End-to-end acceptance sweep: scatter model files bitwise-equal to
    psum AND serial for int8/int16 at 1/2/4/8 shards (refit off: the
    refit leaf psum is the one f32 reduction whose shard-order ulps may
    reach the model)."""

    @pytest.mark.parametrize("prec", ["int8", "int16"])
    def test_sweep(self, prec):
        X, y = _problem()
        q = {"tpu_hist_precision": prec, "tpu_quant_refit_leaves": False}
        ref, _ = _train_model_text(X, y, **q)
        for shards in (1, 2, 4, 8):
            cfg = dict(q)
            if shards > 1:
                cfg.update(tree_learner="data", num_machines=shards)
            got_sc, b = _train_model_text(X, y, **cfg)
            assert got_sc == ref, f"{prec} scatter@{shards} != serial"
            if shards > 1:
                assert b._driver.learner.hist_agg == "scatter"
                got_ps, _ = _train_model_text(
                    X, y, tpu_hist_agg="psum", **cfg)
                assert got_ps == ref, f"{prec} psum@{shards} != serial"


@pytest.mark.slow
class TestVotingScatter:
    """Voting mode: the voted [k, B, 3] aggregation scatters instead of
    the (local) pool; decisions must bit-match the psum voting path."""

    def test_int16_model_bitwise_vs_psum(self):
        X, y = _problem(f=12)
        kw = dict(tree_learner="voting", num_machines=8, top_k=5,
                  tpu_hist_precision="int16",
                  tpu_quant_refit_leaves=False)
        m_sc, b = _train_model_text(X, y, **kw)
        assert b._driver.learner.hist_agg == "scatter"
        m_ps, _ = _train_model_text(X, y, tpu_hist_agg="psum", **kw)
        assert m_sc == m_ps

    def test_topk_smaller_than_shards_pads(self):
        # kk=2 < P=8: the voted set pads with masked duplicates
        X, y = _problem(f=12)
        kw = dict(tree_learner="voting", num_machines=8, top_k=2,
                  tpu_hist_precision="int16",
                  tpu_quant_refit_leaves=False)
        m_sc, _ = _train_model_text(X, y, **kw)
        m_ps, _ = _train_model_text(X, y, tpu_hist_agg="psum", **kw)
        assert m_sc == m_ps


@pytest.mark.slow
class TestDataFeature2D:
    """2-D mesh: the scatter slice composes under the feature axis —
    histograms psum_scatter over 'data' within each feature shard, then
    the winner syncs over 'data' and 'feature' in turn."""

    def test_int8_bitwise_vs_serial(self):
        X, y = _problem(f=12)
        q = {"tpu_hist_precision": "int8",
             "tpu_quant_refit_leaves": False}
        ref, _ = _train_model_text(X, y, **q)
        got, b = _train_model_text(
            X, y, tree_learner="data_feature", num_machines=8,
            tpu_feature_shards=2, **q)
        assert b._driver.learner.hist_agg == "scatter"
        assert got == ref

    def test_f32_decision_parity_vs_psum(self):
        X, y = _problem(f=12)
        kw = dict(tree_learner="data_feature", num_machines=8,
                  tpu_feature_shards=2, tpu_hist_precision="f32")
        rec_c, _, _ = _grow_records(X, y, **kw)
        rec_p, _, _ = _grow_records(X, y, tpu_hist_agg="psum", **kw)
        done = rec_c[:, G.REC_DID_SPLIT] > 0.5
        cols = [G.REC_LEAF, G.REC_FEATURE, G.REC_THRESHOLD]
        agree = (rec_c[done][:, cols].astype(np.int64)
                 == rec_p[done][:, cols].astype(np.int64)).mean()
        assert agree >= 0.85


@pytest.mark.slow
class TestBundlesScatter:
    """EFB + scatter: bundle COLUMNS scatter; each shard searches exactly
    the features bundled into its column slice (scatter_feat table) and
    expands them from the local slice."""

    def _bundle_problem(self):
        rng = np.random.default_rng(0)
        n = 3000
        cat = rng.integers(0, 30, size=n)
        onehot = np.zeros((n, 30))
        onehot[np.arange(n), cat] = 1.0
        dense = rng.normal(size=(n, 4))
        X = np.column_stack([onehot, dense])
        y = ((cat % 3 == 0).astype(float) + 0.5 * dense[:, 0]
             + 0.3 * rng.normal(size=n) > 0.6).astype(float)
        return X, y

    def test_int16_model_bitwise_vs_psum(self):
        X, y = self._bundle_problem()
        kw = dict(tree_learner="data", num_machines=8,
                  tpu_hist_precision="int16",
                  tpu_quant_refit_leaves=False, min_data_in_leaf=10)
        m_sc, b = _train_model_text(X, y, **kw)
        l = b._driver.learner
        assert l.params.has_bundles, "EFB did not engage"
        assert l.hist_agg == "scatter"
        assert "scatter_feat" in l.meta
        sf = np.asarray(l.meta["scatter_feat"])
        assert sf.shape[0] == 8
        # every real feature appears exactly once across the shard table
        real = np.sort(sf[sf >= 0])
        np.testing.assert_array_equal(real, np.arange(l.num_features))
        m_ps, _ = _train_model_text(X, y, tpu_hist_agg="psum", **kw)
        assert m_sc == m_ps

    def test_hilo_decision_parity_vs_psum(self):
        X, y = self._bundle_problem()
        kw = dict(tree_learner="data", num_machines=4,
                  min_data_in_leaf=10)
        m_sc, _ = _train_model_text(X, y, **kw)
        m_ps, _ = _train_model_text(X, y, tpu_hist_agg="psum", **kw)
        assert m_sc == m_ps  # held exactly on this fixture


@pytest.mark.slow
class TestSparseScatter:
    """Sparse COO storage + scatter: zero-bin reconstruction on the
    slice rides the exact threaded leaf totals; deterministic f64 must
    bit-match serial-sparse."""

    def test_f64_model_bitwise_vs_serial(self):
        rng = np.random.default_rng(7)
        n = 2048
        X = np.zeros((n, 12))
        X[:, :4] = rng.normal(size=(n, 4))
        for f in range(4, 12):
            nzr = rng.choice(n, size=80, replace=False)
            X[nzr, f] = rng.normal(size=80) + 1.0
        y = (X[:, 0] + 2.0 * X[:, 5] > 0).astype(np.float64)
        kw = dict(enable_bundle=False, deterministic=True,
                  tpu_sparse_threshold=0.2, tpu_block_rows=256,
                  num_leaves=7, max_bin=16, rounds=2)
        try:
            m_ser, b1 = _train_model_text(X, y, **kw)
            assert b1._driver.learner.params.has_sparse
            m_sc, b2 = _train_model_text(
                X, y, tree_learner="data", num_machines=8, **kw)
            assert b2._driver.learner.hist_agg == "scatter"
            assert b2._driver.learner.params.has_sparse
            assert m_sc == m_ser
        finally:
            jax.config.update("jax_enable_x64", False)


@pytest.mark.slow
class TestCrossShardInt16OpenItem6:
    """ROADMAP open item 7 (née 6), FIXED (ISSUE 11): the bagged family
    violated the PR-5 cross-shard bitwise claim.  Three stacked root
    causes, none of them the suspected min_data counting:

    1. bagging/GOSS masks were drawn with shape-keyed
       `jax.random.uniform(key, (n_pad,))` — threefry counters pair
       across array halves, so every row's draw changes with the TOTAL
       padded length, and n_pad is topology-dependent (serial pads 2000
       rows to a 2048 block multiple; 4 x 500-row shards need none).
       Masks now come from the PCG hash over GLOBAL row indices, like
       the PR-4 quantization rounding.
    2. the fused step's score update `leaf_output[ids] * lr + scores`
       was a mul+add chain XLA/LLVM could contract into an FMA — and
       contracted DIFFERENTLY in the serial vs shard_map programs,
       drifting scores one ulp apart under identical trees.  The update
       now pre-scales the [L] leaf vector so the per-row path is
       gather + one correctly-rounded add.
    3. the split-search bin cumsums ran over pre-dequantized f32
       histograms; for quantized precisions they now run in exact int32
       and dequantize at the scan boundary (reassociation-proof).

    These tests are the former strict-xfail repro flipped to the
    passing gate, widened to the whole family probing found broken
    (int8 under bagging, int16 at num_leaves=7, pos/neg bagging) at 2
    AND 4 shards.  PR 8's elastic-resume matrix inherits the widened
    contract."""

    @staticmethod
    def _family_data():
        rng = np.random.default_rng(7)
        X = rng.normal(size=(2000, 8))
        y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
        return X, y

    @pytest.mark.parametrize("shards", [2, 4])
    def test_serial_vs_sharded_bagged_round6_bitwise(self, shards):
        X, y = self._family_data()
        q = dict(tpu_hist_precision="int16", tpu_quant_refit_leaves=False,
                 bagging_fraction=0.8, bagging_freq=1)
        m_serial, _ = _train_model_text(X, y, rounds=6, **q)
        m_shard, bst = _train_model_text(
            X, y, rounds=6, tree_learner="data", num_machines=shards, **q)
        assert bst._driver.learner.hist_agg == "scatter"
        assert m_serial == m_shard

    @pytest.mark.parametrize("q", [
        dict(tpu_hist_precision="int8", bagging_fraction=0.8,
             bagging_freq=1),
        dict(tpu_hist_precision="int16", num_leaves=7,
             bagging_fraction=0.8, bagging_freq=1),
        dict(tpu_hist_precision="int16", pos_bagging_fraction=0.7,
             neg_bagging_fraction=0.9, bagging_freq=1),
    ], ids=["int8-bagged", "int16-leaves7", "int16-posneg"])
    def test_widened_family_bitwise(self, q):
        X, y = self._family_data()
        q = dict(tpu_quant_refit_leaves=False, **q)
        m_serial, _ = _train_model_text(X, y, rounds=4, **q)
        m_shard, _ = _train_model_text(
            X, y, rounds=4, tree_learner="data", num_machines=4, **q)
        assert m_serial == m_shard

    def test_same_data_without_bagging_still_holds(self):
        """Bracketing control from the xfail era: the SAME
        data/precision WITHOUT bagging — the committed PR-5 contract
        itself."""
        X, y = self._family_data()
        q = dict(tpu_hist_precision="int16", tpu_quant_refit_leaves=False)
        m_serial, _ = _train_model_text(X, y, rounds=3, **q)
        m_shard, _ = _train_model_text(
            X, y, rounds=3, tree_learner="data", num_machines=4, **q)
        assert m_serial == m_shard
