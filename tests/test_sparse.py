"""Sparse (CSR/CSC) ingest: O(nnz) binning parity with the dense path.

The reference stores sparse features delta-encoded end to end (reference
src/io/sparse_bin.hpp:73, include/LightGBM/bin.h:472-508).  Here the
TPU core is a dense [n, F] int8 matrix, so the contract under test is
different: sparse input must produce EXACTLY the bins the densified
matrix would, while never materializing the [n, F] f64 intermediate
(peak-RSS assertion in TestBoschShapedMemory).
"""

import subprocess
import sys
import textwrap

import numpy as np
import pytest
from scipy import sparse as sps

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData


def _random_sparse(n, f, density, seed=0, fmt="csr"):
    rng = np.random.default_rng(seed)
    m = sps.random(n, f, density=density, format=fmt, random_state=seed,
                   data_rvs=lambda k: rng.normal(size=k))
    return m


class TestSparseBinParity:
    @pytest.mark.parametrize("fmt", ["csr", "csc"])
    def test_bins_match_dense(self, fmt):
        sp = _random_sparse(400, 12, 0.15, seed=3, fmt=fmt)
        dense = sp.toarray()
        cfg = Config({"max_bin": 63})
        td_sp = TrainingData.from_sparse(sp, config=cfg)
        td_de = TrainingData.from_matrix(dense, config=cfg)
        assert td_sp.used_feature_idx == td_de.used_feature_idx
        np.testing.assert_array_equal(td_sp.bins, td_de.bins)

    def test_bins_match_dense_zero_as_missing(self):
        sp = _random_sparse(300, 8, 0.2, seed=5)
        cfg = Config({"max_bin": 31, "zero_as_missing": True})
        np.testing.assert_array_equal(
            TrainingData.from_sparse(sp, config=cfg).bins,
            TrainingData.from_matrix(sp.toarray(), config=cfg).bins)

    def test_bins_match_dense_with_sampling(self):
        # sample_cnt < n exercises the CSC row-subsample branch
        sp = _random_sparse(2000, 6, 0.1, seed=7)
        cfg = Config({"max_bin": 15, "bin_construct_sample_cnt": 500})
        np.testing.assert_array_equal(
            TrainingData.from_sparse(sp, config=cfg).bins,
            TrainingData.from_matrix(sp.toarray(), config=cfg).bins)

    def test_valid_set_aligns_to_reference_mappers(self):
        tr = _random_sparse(400, 10, 0.15, seed=11)
        va = _random_sparse(100, 10, 0.15, seed=13)
        cfg = Config({"max_bin": 63})
        td = TrainingData.from_sparse(tr, config=cfg)
        tv_sp = TrainingData.from_sparse(va, config=cfg, reference=td)
        tv_de = TrainingData.from_matrix(va.toarray(), config=cfg,
                                         reference=td)
        np.testing.assert_array_equal(tv_sp.bins, tv_de.bins)
        # create_valid dispatches sparse input to from_sparse
        np.testing.assert_array_equal(td.create_valid(va).bins, tv_sp.bins)

    def test_wide_input_predict_stays_sparse(self):
        # extra columns are dropped while still sparse; a [chunk, 10^6]
        # densify would OOM — keep the width trim O(nnz)
        sp = _random_sparse(300, 10, 0.2, seed=37)
        y = np.asarray(sp.sum(axis=1)).ravel()
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1},
                        lgb.Dataset(sp, label=y), num_boost_round=3)
        wide = sps.hstack([sp, sps.csr_matrix((300, 1_000_000))]).tocsr()
        np.testing.assert_allclose(
            bst.predict(wide, predict_disable_shape_check=True),
            bst.predict(sp))

    def test_explicit_stored_zeros_match_dense(self):
        # stored zeros in the sparse structure must bin like implicit ones
        sp = _random_sparse(200, 5, 0.3, seed=17).tocsr()
        sp.data[::4] = 0.0  # stored zeros, NOT eliminated
        np.testing.assert_array_equal(
            TrainingData.from_sparse(sp).bins,
            TrainingData.from_matrix(sp.toarray()).bins)


class TestSparseTrainPredict:
    def test_train_model_identical_to_dense(self):
        sp = _random_sparse(600, 15, 0.2, seed=23)
        y = (np.asarray(sp.sum(axis=1)).ravel() > 0).astype(np.float64)
        params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                  "min_data_in_leaf": 5}
        b_sp = lgb.train(params, lgb.Dataset(sp, label=y), num_boost_round=8)
        b_de = lgb.train(params, lgb.Dataset(sp.toarray(), label=y),
                         num_boost_round=8)
        assert b_sp.model_to_string() == b_de.model_to_string()

    def test_sparse_predict_matches_dense(self):
        sp = _random_sparse(500, 15, 0.2, seed=29)
        y = (np.asarray(sp.sum(axis=1)).ravel() > 0).astype(np.float64)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1},
                        lgb.Dataset(sp, label=y), num_boost_round=5)
        p_dense = bst.predict(sp.toarray())
        np.testing.assert_allclose(bst.predict(sp), p_dense)
        np.testing.assert_allclose(bst.predict(sp.tocsc()), p_dense)
        # chunked path with several chunks
        chunked = bst._predict_sparse_chunked(
            sp.tocsr(), None, False, False, False, {}, chunk_rows=128)
        np.testing.assert_allclose(chunked, p_dense)
        # n-first outputs concatenate for leaf/contrib too
        np.testing.assert_allclose(
            bst._predict_sparse_chunked(sp.tocsr(), None, False, True,
                                        False, {}, chunk_rows=128),
            bst.predict(sp.toarray(), pred_leaf=True))
        np.testing.assert_allclose(
            bst._predict_sparse_chunked(sp.tocsr(), None, False, False,
                                        True, {}, chunk_rows=128),
            bst.predict(sp.toarray(), pred_contrib=True), atol=1e-12)

    def test_sparse_predict_shape_check(self):
        sp = _random_sparse(200, 10, 0.2, seed=31)
        y = np.asarray(sp.sum(axis=1)).ravel()
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1},
                        lgb.Dataset(sp, label=y), num_boost_round=3)
        with pytest.raises(lgb.LightGBMError, match="number of features"):
            bst.predict(sp[:, :6])
        out = bst.predict(sp[:, :6], predict_disable_shape_check=True)
        assert out.shape == (200,)

    def test_distributed_binning_degrades_to_local(self):
        """Sparse ingest joins the collective bin-finding path; in a
        single-process group it degrades to the plain local find and
        must produce the same mappers as dense input."""
        sp = _random_sparse(300, 4, 0.2)
        cfg = Config({"pre_partition": True, "num_machines": 2})
        td_sp = TrainingData.from_sparse(sp, config=cfg)
        td_de = TrainingData.from_matrix(np.asarray(sp.todense()),
                                         config=Config({}))
        for a, b in zip(td_sp.mappers, td_de.mappers):
            assert a.to_dict() == b.to_dict()


@pytest.mark.slow
class TestBoschShapedMemory:
    def test_bosch_shaped_ingest_is_o_nnz(self):
        """1M x 968 at ~2% nnz builds a Dataset without the [n, F] f64
        blow-up: the f64 matrix alone would be 7.7 GB; bins (uint8) are
        ~0.97 GB.  Asserts peak RSS < 4 GB in a fresh subprocess
        (VERDICT r3 item 5; reference src/io/sparse_bin.hpp:73)."""
        code = textwrap.dedent("""
            import resource, sys
            sys.path.insert(0, %r)
            from lightgbm_tpu.utils.backend import pin_cpu_backend
            pin_cpu_backend()
            import numpy as np
            from scipy import sparse as sps
            from lightgbm_tpu.config import Config
            from lightgbm_tpu.io.dataset import TrainingData

            n, f = 1_000_000, 968
            rng = np.random.default_rng(0)
            nnz_per_row = 19  # ~2%%
            rows = np.repeat(np.arange(n), nnz_per_row)
            cols = rng.integers(0, f, size=n * nnz_per_row).astype(np.int32)
            vals = rng.normal(size=n * nnz_per_row)
            sp = sps.csr_matrix((vals, (rows, cols)), shape=(n, f))
            del rows, cols, vals
            td = TrainingData.from_sparse(
                sp, config=Config({"max_bin": 63,
                                   "bin_construct_sample_cnt": 50000}))
            assert td.bins.shape[0] == n
            assert td.bins.dtype == np.uint8
            peak_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
            print(f"PEAK_GB={peak_gb:.2f}")
            assert peak_gb < 4.0, f"peak RSS {peak_gb:.2f} GB is not O(nnz)"
        """) % (str(__import__("pathlib").Path(__file__).parent.parent),)
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=1200)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "PEAK_GB=" in r.stdout
