"""Serving runtime (lightgbm_tpu/serving): registry + micro-batching.

Contracts under test:
* `ServingSession.predict` is BITWISE-identical to a direct
  `Booster.predict` through the same device path for every
  missing-type/categorical/dtype case — batching, coalescing, and
  launch padding never change a row's value.
* concurrency: a 64-thread hammer sees zero cross-request bleed.
* admission control sheds deterministically; timeouts raise.
* registry warmup bounds compiles: a request-size sweep 1..4096 after
  load triggers ZERO new jit compilations.
* hot-swap flips atomically; LRU evicts non-current versions.

Everything runs under JAX_PLATFORMS=cpu (tier-1).
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from .conftest import *  # noqa: F401,F403  (cpu backend pin)

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (MicroBatcher, ServingQueueFull,
                                  ServingSession, ServingStats,
                                  ServingTimeout, serve_http)

PARAMS = {"objective": "binary", "num_leaves": 15,
          "tpu_predict_device": "true", "verbose": -1}


def _make_data(n=4500, f=6, seed=0, with_nan=True, with_zero=True,
               with_cat=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    if with_nan:
        X[rng.random((n, f)) < 0.12] = np.nan
    if with_zero:
        X[:, 2] = np.where(rng.random(n) < 0.55, 0.0, X[:, 2])
    cat_cols = []
    if with_cat:
        X[:, f - 1] = rng.integers(0, 14, size=n).astype(float)
        cat_cols = [f - 1]
    y = (np.nansum(X[:, :3], axis=1)
         + (X[:, f - 1] % 3 == 0 if with_cat else 0) > 0).astype(float)
    return X, y, cat_cols


def _train(X, y, cat_cols, params=None, rounds=8):
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                     categorical_feature=cat_cols or "auto")
    return lgb.train({**PARAMS, **(params or {})}, ds,
                     num_boost_round=rounds, verbose_eval=False)


@pytest.fixture(scope="module")
def served():
    """One trained model loaded into a running session."""
    X, y, cats = _make_data()
    bst = _train(X, y, cats)
    sess = ServingSession(params={"serving_max_batch_rows": 4096,
                                  "serving_max_wait_ms": 2.0})
    sess.load("m", booster=bst)
    yield sess, bst, X
    sess.close()


class TestServingParity:
    @pytest.mark.parametrize("with_nan,with_zero,with_cat",
                             [(True, True, True), (True, False, False),
                              (False, True, True), (False, False, False)])
    def test_bitwise_vs_direct_predict(self, with_nan, with_zero, with_cat):
        X, y, cats = _make_data(n=1500, with_nan=with_nan,
                                with_zero=with_zero, with_cat=with_cat)
        bst = _train(X, y, cats)
        sess = ServingSession()
        sess.load("m", booster=bst)
        try:
            for sz in (1, 3, 97, 700):
                got = sess.predict("m", X[:sz])
                solo = bst.predict(X[:sz], device="tpu")
                np.testing.assert_array_equal(
                    got, solo, err_msg=f"size {sz} diverged from direct "
                    "Booster.predict")
        finally:
            sess.close()

    def test_dtype_cases(self, served):
        sess, bst, X = served
        for cast in (np.float32, np.float64):
            Xc = X[:64].astype(cast)
            np.testing.assert_array_equal(
                sess.predict("m", Xc), bst.predict(Xc, device="tpu"),
                err_msg=f"dtype {cast} diverged")
        Xi = np.nan_to_num(X[:64], nan=0.0).astype(np.int64)
        np.testing.assert_array_equal(sess.predict("m", Xi),
                                      bst.predict(Xi, device="tpu"))
        # 1-d single row
        row = X[5]
        np.testing.assert_array_equal(sess.predict("m", row),
                                      bst.predict(row[None], device="tpu"))

    def test_raw_score_and_num_iteration(self, served):
        sess, bst, X = served
        got = sess.predict("m", X[:50], raw_score=True, num_iteration=3)
        solo = bst.predict(X[:50], raw_score=True, num_iteration=3,
                           device="tpu")
        np.testing.assert_array_equal(got, solo)

    def test_best_iteration_honored_by_default(self):
        """num_iteration=None must resolve to best_iteration exactly
        like direct Booster.predict (early-stopped models) — and warmup
        must pre-compile THAT subset's shapes, not the full forest's."""
        X, y, cats = _make_data(n=1200)
        bst = _train(X, y, cats, rounds=8)
        bst.best_iteration = 3
        sess = ServingSession()  # warmup ON
        sess.load("es", booster=bst)
        try:
            np.testing.assert_array_equal(
                sess.predict("es", X[:40]),
                bst.predict(X[:40], device="tpu"))
            # and that is genuinely the 3-iteration subset
            np.testing.assert_array_equal(
                sess.predict("es", X[:40]),
                bst.predict(X[:40], num_iteration=3, device="tpu"))
            assert sess.stats()["compile_cache_misses"] == 0, \
                "warmup compiled the wrong num_iteration subset"
        finally:
            sess.close()

    def test_multiclass_scatter(self):
        X, y, _ = _make_data(n=1200, with_cat=False)
        y3 = (np.abs(y * 2 + (X[:, 0] > 0)) % 3).astype(float)
        bst = _train(X, y3, [], params={"objective": "multiclass",
                                        "num_class": 3})
        sess = ServingSession()
        sess.load("mc", booster=bst)
        try:
            got = sess.predict("mc", X[:41])
            assert got.shape == (41, 3)
            np.testing.assert_array_equal(got,
                                          bst.predict(X[:41], device="tpu"))
        finally:
            sess.close()

    def test_pandas_frame_requests(self):
        pd = pytest.importorskip("pandas")
        rng = np.random.default_rng(5)
        n = 1500
        df = pd.DataFrame({
            "x0": rng.normal(size=n),
            "x1": rng.normal(size=n),
            "color": pd.Categorical.from_codes(
                rng.integers(0, 4, size=n),
                ["red", "green", "blue", "violet"]),
        })
        y = (df["x0"].to_numpy() + (df["color"].cat.codes.to_numpy() == 1)
             > 0).astype(float)
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train(PARAMS, ds, num_boost_round=5, verbose_eval=False)
        sess = ServingSession()
        sess.load("pd", booster=bst)
        try:
            got = sess.predict("pd", df.iloc[:77])
            solo = bst.predict(df.iloc[:77], device="tpu")
            np.testing.assert_array_equal(got, solo)
        finally:
            sess.close()


class TestConcurrency:
    def test_64_thread_hammer_zero_bleed(self, served):
        sess, bst, X = served
        n_threads, reqs = 64, 3
        rng = np.random.default_rng(1000)
        # per-thread request slices + solo oracle answers, computed
        # sequentially up front so the hammer itself only exercises the
        # serving path
        plans = []
        for i in range(n_threads):
            plan = []
            for _ in range(reqs):
                sz = int(rng.integers(1, 60))
                lo = int(rng.integers(0, X.shape[0] - sz))
                Xi = X[lo:lo + sz]
                plan.append((Xi, bst.predict(Xi, device="tpu")))
            plans.append(plan)
        failures = []
        barrier = threading.Barrier(n_threads)

        def worker(i):
            barrier.wait()
            for r, (Xi, solo) in enumerate(plans[i]):
                try:
                    got = sess.predict("m", Xi)
                except Exception as exc:
                    failures.append((i, r, repr(exc)))
                    continue
                if got.shape != solo.shape or not np.array_equal(got, solo):
                    failures.append((i, r, "result bleed"))

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not failures, failures[:5]
        st = sess.stats()
        # the hammer must actually have exercised coalescing
        assert st["batches_total"] < st["requests_total"]

    def test_padded_rows_never_leak(self, served):
        sess, bst, X = served
        for sz in (1, 2, 3, 5):
            got = sess.predict("m", X[:sz])
            assert got.shape == (sz,)
            np.testing.assert_array_equal(got,
                                          bst.predict(X[:sz], device="tpu"))


class TestAdmissionControl:
    def test_queue_full_sheds_deterministically(self):
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=50.0,
                         queue_rows=100, stats=stats)  # worker NOT started
        runner = lambda Xb: Xb[:, 0]  # noqa: E731
        b.submit("k", runner, np.zeros((60, 2)))
        b.submit("k", runner, np.zeros((40, 2)))   # exactly at capacity
        with pytest.raises(ServingQueueFull):
            b.submit("k", runner, np.zeros((1, 2)))
        snap = stats.snapshot()
        assert snap["requests_shed"] == 1
        assert snap["requests_total"] == 2
        assert snap["queue_depth_rows"] == 100

    def test_timeout_raises(self):
        X, y, cats = _make_data(n=600)
        bst = _train(X, y, cats, rounds=2)
        sess = ServingSession(params={"serving_warmup": False},
                              start=False)  # no worker -> guaranteed stall
        sess.load("m", booster=bst)
        try:
            with pytest.raises(ServingTimeout):
                sess.predict("m", X[:4], timeout_ms=50)
            assert sess.stats()["requests_timeout"] == 1
        finally:
            sess.close()

    def test_wrong_width_request_fails_alone(self, served):
        """Feature width is part of the batch key: a malformed request
        errors by itself and never poisons well-formed traffic."""
        sess, bst, X = served
        from lightgbm_tpu.utils.log import LightGBMError

        with pytest.raises(LightGBMError, match="number of features"):
            sess.predict("m", X[:8, :3])
        np.testing.assert_array_equal(sess.predict("m", X[:8]),
                                      bst.predict(X[:8], device="tpu"))

    def test_drained_queue_releases_runner(self):
        """Runner closures must not outlive their queue — a retained one
        would pin an LRU-evicted model's packed forest forever."""
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=0.0)
        b.start()
        try:
            r = b.submit("k", lambda Xb: Xb[:, 0], np.zeros((3, 2)))
            b.wait(r, 5.0)
            with b._cv:
                assert not b._runners and not b._queues
        finally:
            b.close()

    def test_empty_submit_rejected_and_errors_stay_out_of_latency(self):
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=0.0, stats=stats)
        with pytest.raises(ValueError, match="at least one slice"):
            b.submit_many("k", lambda Xb: Xb, [])
        b.start()
        try:

            def boom(Xb):
                raise RuntimeError("nope")

            r = b.submit("k", boom, np.zeros((2, 2)))
            with pytest.raises(RuntimeError):
                b.wait(r, 5.0)
            assert stats.snapshot()["latency_window"] == 0, \
                "failed request polluted the latency percentiles"
            # the worker survived the empty-submit attempt and the error
            ok = b.submit("k2", lambda Xb: Xb[:, 0], np.zeros((3, 2)))
            assert b.wait(ok, 5.0).shape == (3,)
        finally:
            b.close()

    def test_abandoned_requests_are_shed_not_computed(self):
        """Slices whose caller already timed out must never reach the
        runner — wasted device work under overload kills goodput."""
        ran = []
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=0.0)

        def runner(Xb):
            ran.append(Xb.shape[0])
            return Xb[:, 0]

        r1 = b.submit("k", runner, np.zeros((3, 2)))
        r1.abandoned = True              # caller departed before start()
        r2 = b.submit("k", runner, np.zeros((5, 2)))
        b.start()
        try:
            out = b.wait(r2, 5.0)
            assert out.shape == (5,)
            assert ran == [5], "abandoned slice was computed"
            with b._cv:
                assert b._pending_rows == 0
        finally:
            b.close()

    def test_runner_error_delivered_to_all_waiters(self):
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=1.0, stats=stats)
        b.start()

        def boom(Xb):
            raise RuntimeError("kernel exploded")

        try:
            r1 = b.submit("k", boom, np.zeros((3, 2)))
            r2 = b.submit("k", boom, np.zeros((4, 2)))
            for r in (r1, r2):
                with pytest.raises(RuntimeError, match="kernel exploded"):
                    b.wait(r, 5.0)
        finally:
            b.close()


class TestWarmupBoundsCompiles:
    def test_sweep_1_to_4096_zero_new_compiles(self):
        X, y, cats = _make_data(n=4500)
        bst = _train(X, y, cats)
        sess = ServingSession(params={"serving_max_batch_rows": 4096})
        sess.load("m", booster=bst)
        try:
            st0 = sess.stats()
            # warmup pre-compiles exactly the policy's bucket ladder
            # (one bucket at the default "wide" policy and 4096 max
            # rows; 1024/2048/4096 under "fine")
            from lightgbm_tpu.ops.predict import predict_row_buckets

            drv = bst._driver
            expect = len(predict_row_buckets(4096, drv.predict_chunk_rows(),
                                             policy=drv.bucket_policy()))
            assert st0["compiles_warmup"] == expect
            assert st0["compile_cache_misses"] == 0
            from lightgbm_tpu.ops.predict import _class_scores_kernel

            jit_before = (_class_scores_kernel._cache_size()
                          if hasattr(_class_scores_kernel, "_cache_size")
                          else None)
            for sz in (1, 2, 3, 7, 64, 100, 513, 1024, 1025, 2048, 2049,
                       3000, 4095, 4096):
                sess.predict("m", X[:sz])
            st = sess.stats()
            assert st["compile_cache_misses"] == 0, \
                "request-size sweep hit a cold compile after warmup"
            assert st["compile_cache_hits"] >= 14
            if jit_before is not None:
                assert _class_scores_kernel._cache_size() == jit_before, \
                    "the jit cache itself grew during the sweep"
            # oversize requests split into warmed max_batch_rows slices
            # instead of hitting a cold 8192-row bucket
            Xbig = np.concatenate([X, X[:1500]], axis=0)  # 6000 rows
            got = sess.predict("m", Xbig)
            assert got.shape == (6000,)
            assert sess.stats()["compile_cache_misses"] == 0
            if jit_before is not None:
                assert _class_scores_kernel._cache_size() == jit_before
            # value check against the native walker (a solo 6000-row
            # DEVICE predict would itself compile the 8192 bucket)
            np.testing.assert_allclose(
                got, bst.predict(Xbig, device="cpu"), rtol=0, atol=1e-5)
        finally:
            sess.close()


class TestRegistry:
    def test_hot_swap_flips_atomically(self):
        X, y, cats = _make_data(n=900, seed=1)
        bst_a = _train(X, y, cats, rounds=3)
        bst_b = _train(X, y, cats, rounds=7)
        sess = ServingSession(params={"serving_warmup": False})
        try:
            k1 = sess.load("m", booster=bst_a)
            assert k1 == "m@1"
            np.testing.assert_array_equal(sess.predict("m", X[:30]),
                                          bst_a.predict(X[:30],
                                                        device="tpu"))
            k2 = sess.load("m", booster=bst_b)  # hot-swap
            assert k2 == "m@2"
            np.testing.assert_array_equal(sess.predict("m", X[:30]),
                                          bst_b.predict(X[:30],
                                                        device="tpu"))
            # the retired version stays addressable by full key
            np.testing.assert_array_equal(sess.predict("m@1", X[:30]),
                                          bst_a.predict(X[:30],
                                                        device="tpu"))
        finally:
            sess.close()

    def test_hot_swap_never_flips_backwards(self):
        """Concurrent loads finish warmup in arbitrary order; a slower
        OLDER version must not steal the alias back from a newer one."""
        X, y, cats = _make_data(n=700, seed=6)
        bst_a = _train(X, y, cats, rounds=2)
        bst_b = _train(X, y, cats, rounds=5)
        sess = ServingSession(params={"serving_warmup": False})
        try:
            sess.load("m", booster=bst_b, version=2)  # newer lands first
            sess.load("m", booster=bst_a, version=1)  # stale finisher
            np.testing.assert_array_equal(
                sess.predict("m", X[:20]),
                bst_b.predict(X[:20], device="tpu"))
            # the stale version is still resident under its full key
            np.testing.assert_array_equal(
                sess.predict("m@1", X[:20]),
                bst_a.predict(X[:20], device="tpu"))
        finally:
            sess.close()

    def test_lru_evicts_non_current_versions(self):
        X, y, cats = _make_data(n=900, seed=2)
        boosters = [_train(X, y, cats, rounds=2) for _ in range(3)]
        sess = ServingSession(params={"serving_max_models": 2,
                                      "serving_warmup": False})
        try:
            sess.load("m", booster=boosters[0])      # m@1
            sess.load("m", booster=boosters[1])      # m@2 (current)
            sess.load("other", booster=boosters[2])  # forces eviction
            with pytest.raises(KeyError):
                sess.predict("m@1", X[:5])
            # current versions survive
            sess.predict("m", X[:5])
            sess.predict("other", X[:5])
            st = sess.stats()
            assert st["models_loaded"] == 3 and st["models_evicted"] == 1
        finally:
            sess.close()

    def test_load_does_not_mutate_adopted_booster(self):
        """Serving pins the device path per CALL; the user's booster
        must behave exactly as before outside the session."""
        X, y, cats = _make_data(n=700, seed=9)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbose": -1}, ds, num_boost_round=3,
                        verbose_eval=False)  # note: no tpu_predict_device
        before = dict(bst.params)
        p_before = bst.predict(X[:30])
        sess = ServingSession(params={"serving_warmup": False})
        try:
            sess.load("m", booster=bst)
            sess.predict("m", X[:10])
            assert bst.params == before
            np.testing.assert_array_equal(bst.predict(X[:30]), p_before)
        finally:
            sess.close()

    def test_unload_current_version_realises_rollback(self):
        """Unloading the bad current version re-points the bare name at
        the newest surviving version instead of going dark."""
        X, y, cats = _make_data(n=700, seed=10)
        bst_a = _train(X, y, cats, rounds=2)
        bst_b = _train(X, y, cats, rounds=4)
        sess = ServingSession(params={"serving_warmup": False})
        try:
            sess.load("m", booster=bst_a)   # m@1
            sess.load("m", booster=bst_b)   # m@2 current
            sess.unload("m@2")              # roll back the bad deploy
            np.testing.assert_array_equal(
                sess.predict("m", X[:10]),
                bst_a.predict(X[:10], device="tpu"))
        finally:
            sess.close()

    def test_mixed_explicit_implicit_versions_never_collide(self):
        X, y, cats = _make_data(n=700, seed=7)
        bst_a = _train(X, y, cats, rounds=2)
        bst_b = _train(X, y, cats, rounds=4)
        sess = ServingSession(params={"serving_warmup": False})
        try:
            sess.load("m", booster=bst_a, version=2)
            key = sess.load("m", booster=bst_b)  # implicit: must NOT be m@2
            assert key == "m@3"
            np.testing.assert_array_equal(
                sess.predict("m@2", X[:10]),
                bst_a.predict(X[:10], device="tpu"))
        finally:
            sess.close()

    def test_unload_releases_every_version(self):
        X, y, cats = _make_data(n=700, seed=8)
        boosters = [_train(X, y, cats, rounds=2) for _ in range(2)]
        sess = ServingSession(params={"serving_warmup": False})
        try:
            sess.load("m", booster=boosters[0])
            sess.load("m", booster=boosters[1])
            sess.unload("m")
            assert sess.models() == []
            with pytest.raises(KeyError):
                sess.predict("m@1", X[:2])
        finally:
            sess.close()

    def test_request_beyond_queue_capacity_is_caller_error(self, served):
        sess, _, X = served
        big = np.zeros((int(sess.config.serving_queue_rows) + 1, X.shape[1]))
        with pytest.raises(ValueError, match="serving_queue_rows"):
            sess.predict("m", big)

    def test_unknown_model_and_bad_name(self, served):
        sess, _, X = served
        with pytest.raises(KeyError):
            sess.predict("nope", X[:2])
        with pytest.raises(ValueError, match="@"):
            sess.load("bad@name", model_str="x")

    def test_model_without_mapper_snapshot_serves_native(self):
        """A reference-style model string (no tpu_bin_mappers trailer)
        still serves — through the native walker, with no launch-shape
        accounting."""
        X, y, cats = _make_data(n=700, seed=3)
        bst = _train(X, y, cats, rounds=2)
        text = bst.model_to_string()
        stripped = text[:text.rfind("tpu_bin_mappers:")]
        assert "tpu_bin_mappers:" not in stripped
        sess = ServingSession()
        try:
            sess.load("legacy", model_str=stripped)
            entry = sess.registry.resolve("legacy")
            assert not entry.device_on
            got = sess.predict("legacy", X[:40])
            np.testing.assert_allclose(got,
                                       bst.predict(X[:40], device="cpu"),
                                       rtol=0, atol=1e-12)
            assert sess.stats()["compiles_warmup"] == 0
        finally:
            sess.close()

    def test_device_failure_falls_back_to_host_walker(self, monkeypatch):
        X, y, cats = _make_data(n=700, seed=4)
        bst = _train(X, y, cats, rounds=3)
        sess = ServingSession(params={"serving_warmup": False})
        try:
            sess.load("m", booster=bst)
            monkeypatch.setattr(
                bst._driver, "predict_raw_device",
                lambda *a, **k: (_ for _ in ()).throw(
                    RuntimeError("device lost")))
            got = sess.predict("m", X[:25])
            np.testing.assert_allclose(
                got, bst.predict(X[:25], device="cpu"), rtol=0, atol=1e-12)
            assert sess.stats()["device_fallbacks"] >= 1
        finally:
            sess.close()


class TestServeCLI:
    def test_serve_task_requires_input_model(self):
        from lightgbm_tpu.application import Application

        with pytest.raises(ValueError, match="input_model"):
            Application(["task=serve"]).run()

    def test_bare_serve_argv_maps_to_task(self, monkeypatch):
        from lightgbm_tpu import application

        seen = {}

        class FakeApp:
            def __init__(self, argv):
                seen["params"] = application.parse_argv(argv)

            def run(self):
                pass

        monkeypatch.setattr(application, "Application", FakeApp)
        assert application.main(["serve", "serving_port=0"]) == 0
        assert seen["params"]["task"] == "serve"
        assert seen["params"]["serving_port"] == "0"


class TestHTTPEndpoint:
    @pytest.fixture()
    def http_served(self, served):
        sess, bst, X = served
        server = serve_http(sess, "127.0.0.1", 0)
        port = server.server_address[1]
        yield f"http://127.0.0.1:{port}", bst, X
        server.shutdown()

    @staticmethod
    def _post(url, payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())

    def test_predict_roundtrip(self, http_served):
        base, bst, X = http_served
        rows = np.nan_to_num(X[:9], nan=0.0)  # JSON carries no NaN
        status, out = self._post(base + "/predict",
                                 {"model": "m", "rows": rows.tolist()})
        assert status == 200
        np.testing.assert_array_equal(np.asarray(out["predictions"]),
                                      bst.predict(rows, device="tpu"))

    def test_stats_and_models_routes(self, http_served):
        base, _, _ = http_served
        with urllib.request.urlopen(base + "/stats") as resp:
            st = json.loads(resp.read())
        for key in ("latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
                    "queue_depth_rows", "batch_fill_ratio",
                    "compile_cache_misses", "requests_shed"):
            assert key in st
        with urllib.request.urlopen(base + "/models") as resp:
            models = json.loads(resp.read())["models"]
        assert any(m["key"] == "m@1" and m["current"] for m in models)

    def test_unknown_model_404_and_bad_body_400(self, http_served):
        base, _, _ = http_served
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(base + "/predict", {"model": "nope", "rows": [[0.0]]})
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(base + "/predict", {"rows": [[0.0]]})
        assert ei.value.code == 400
        # wrong feature count is a CALLER error (LightGBMError -> 400),
        # not a 500 server fault
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(base + "/predict",
                       {"model": "m", "rows": [[0.0, 1.0]]})
        assert ei.value.code == 400
