"""Distributed (feature-sharded) bin finding: assignment, payload
round-trip, merge, and single-process degeneration.

Mirrors reference src/io/dataset_loader.cpp:959-1042: each machine finds
mappers for its feature range on its LOCAL rows, then allgathers the
serialized mappers.
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.bin_mapper import BinMapper
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.io.distributed_binning import (assign_features,
                                                 find_mappers_multihost,
                                                 local_payload,
                                                 merge_mapper_payloads)


class TestAssignment:
    def test_covers_all_features_once(self):
        for nf, nm in ((28, 4), (7, 3), (5, 8), (1, 1)):
            parts = assign_features(nf, nm)
            flat = [f for p in parts for f in p]
            assert sorted(flat) == list(range(nf))
            assert len(parts) == nm


class TestMerge:
    def test_simulated_four_machine_gather(self):
        """Four machines, disjoint row shards, feature-sharded finds: the
        merged mapper set must equal each owner's local find, and binning
        the full data with it must work."""
        rng = np.random.default_rng(0)
        n, nf, nm = 4000, 9, 4
        X = rng.normal(size=(n, nf))
        cfg = Config({"max_bin": 32})
        shards = np.array_split(X, nm)
        assignment = assign_features(nf, nm)
        payloads = [local_payload(shards[m], assignment[m], cfg,
                                  total_rows=n)
                    for m in range(nm)]
        mappers = merge_mapper_payloads(payloads, nf)
        assert len(mappers) == nf
        for m in mappers:
            assert isinstance(m, BinMapper)
            assert not m.is_trivial
        # owner's shard determined feature f's bins: spot-check feature 0
        td = TrainingData()
        td.feature_names = [f"Column_{i}" for i in range(nf)]
        td._find_mappers(shards[0][:, assignment[0]], cfg, [], {},
                         total_rows=n)
        assert mappers[assignment[0][0]].to_dict() == td.mappers[0].to_dict()
        # mappers bin the FULL matrix without error
        for f in range(nf):
            b = mappers[f].values_to_bins(X[:, f])
            assert b.min() >= 0 and b.max() < mappers[f].num_bin

    def test_global_feature_config_on_nonfirst_shard(self):
        """ignore_column / max_bin_by_feature / categorical are keyed by
        GLOBAL feature id even on machines owning later feature ranges."""
        rng = np.random.default_rng(7)
        n, nf, nm = 2000, 8, 2
        X = rng.normal(size=(n, nf))
        X[:, 6] = rng.integers(0, 5, size=n)  # categorical, owned by m1
        cfg = Config({"max_bin": 32, "ignore_column": "5",
                      "max_bin_by_feature": ",".join(
                          ["32"] * 7 + ["8"])})
        assignment = assign_features(nf, nm)  # m1 owns features 4..7
        payloads = [local_payload(np.array_split(X, nm)[m], assignment[m],
                                  cfg, categorical=[6], total_rows=n)
                    for m in range(nm)]
        mappers = merge_mapper_payloads(payloads, nf)
        assert mappers[5].is_trivial            # ignored globally
        assert not mappers[4].is_trivial        # NOT ignored (local idx 0
        #                                         of shard 1 != global 5)
        from lightgbm_tpu.io.bin_mapper import BinType
        assert mappers[6].bin_type == BinType.CATEGORICAL
        assert mappers[7].num_bin <= 8          # per-feature max_bin cap

    def test_double_assignment_rejected(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(500, 2))
        cfg = Config({"max_bin": 16})
        p = local_payload(X, [0, 1], cfg)
        with pytest.raises(ValueError, match="two machines"):
            merge_mapper_payloads([p, p], 2)

    def test_missing_feature_rejected(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(500, 3))
        cfg = Config({"max_bin": 16})
        p = local_payload(X, [0, 1], cfg)
        with pytest.raises(ValueError, match="missing"):
            merge_mapper_payloads([p], 3)


class TestSingleProcess:
    def test_degenerates_to_local_find(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(2000, 5))
        cfg = Config({"max_bin": 32})
        mappers = find_mappers_multihost(X, cfg)
        td = TrainingData()
        td.feature_names = [f"Column_{i}" for i in range(5)]
        td._find_mappers(X, cfg, [], {})
        assert [m.to_dict() for m in mappers] == \
            [m.to_dict() for m in td.mappers]
