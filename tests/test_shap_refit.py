"""SHAP contributions (pred_contrib) + refit.

SHAP mirrors reference Tree::PredictContrib (include/LightGBM/tree.h:133);
refit mirrors GBDT::RefitTree (src/boosting/gbdt.cpp:298) +
FitByExistingTree (src/treelearner/serial_tree_learner.cpp:239-270).
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb

from .conftest import ORACLE_LIB, has_oracle

pytestmark = pytest.mark.slow  # e2e trainings


@pytest.fixture(scope="module")
def model_and_data():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(1500, 6))
    X[rng.random(X.shape) < 0.03] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.6 * np.nan_to_num(X[:, 1]) ** 2
         - 0.4 * np.nan_to_num(X[:, 2]) > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "min_data_in_leaf": 10, "use_missing": True},
                    ds, num_boost_round=12, verbose_eval=False)
    return bst, X, y


class TestSHAP:
    def test_additivity(self, model_and_data):
        bst, X, _ = model_and_data
        Xs = X[:80]
        contrib = bst.predict(Xs, pred_contrib=True)
        raw = bst.predict(Xs, raw_score=True)
        assert contrib.shape == (80, X.shape[1] + 1)
        np.testing.assert_allclose(contrib.sum(axis=1), raw,
                                   rtol=1e-9, atol=1e-9)

    @pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
    def test_matches_reference_treeshap(self, model_and_data, tmp_path):
        bst, X, _ = model_and_data
        Xs = np.ascontiguousarray(X[:60], np.float64)
        contrib = bst.predict(Xs, pred_contrib=True)
        bst.save_model(str(tmp_path / "m.txt"))
        ref = ctypes.CDLL(ORACLE_LIB)
        ref.LGBM_GetLastError.restype = ctypes.c_char_p
        bh = ctypes.c_void_p()
        it = ctypes.c_int()
        assert ref.LGBM_BoosterCreateFromModelfile(
            str(tmp_path / "m.txt").encode(), ctypes.byref(it),
            ctypes.byref(bh)) == 0
        n, F = Xs.shape
        out = (ctypes.c_double * (n * (F + 1)))()
        olen = ctypes.c_int64()
        assert ref.LGBM_BoosterPredictForMat(
            bh, Xs.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
            ctypes.c_int32(F), 1, 3, 0, b"", ctypes.byref(olen), out) == 0
        ref_contrib = np.ctypeslib.as_array(out).reshape(n, F + 1)
        np.testing.assert_allclose(contrib, ref_contrib,
                                   rtol=1e-7, atol=1e-9)

    def test_additivity_categorical_nan(self, tmp_path):
        """Contribs must sum to raw predictions when NaN / fractional
        negatives hit a categorical split at predict time (both fold to
        category 0 for non-NaN missing types)."""
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(5)
        n = 1000
        Xc = rng.integers(0, 6, size=n).astype(np.float64)
        X = np.column_stack([Xc, rng.normal(size=n)])
        y = (Xc < 2) * 2.0 + X[:, 1]
        ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "min_data_in_leaf": 5,
                         "categorical_feature": [0]},
                        ds, num_boost_round=5, verbose_eval=False)
        vals = np.concatenate([np.full(20, np.nan), np.full(20, -0.5)])
        Xq = np.column_stack([vals, rng.normal(size=40)])
        contrib = bst.predict(Xq, pred_contrib=True)
        raw = bst.predict(Xq, raw_score=True)
        np.testing.assert_allclose(contrib.sum(axis=1), raw,
                                   rtol=1e-6, atol=1e-6)

    def test_multiclass_shape(self, multiclass_example):
        X, y = multiclass_example["X_train"], multiclass_example["y_train"]
        ds = lgb.Dataset(X, label=y)
        bst = lgb.train({"objective": "multiclass", "num_class": 5,
                         "num_leaves": 7}, ds, num_boost_round=3,
                        verbose_eval=False)
        Xs = X[:20]
        contrib = bst.predict(Xs, pred_contrib=True)
        assert contrib.shape == (20, 5 * (X.shape[1] + 1))
        raw = bst.predict(Xs, raw_score=True)
        per_class = contrib.reshape(20, 5, X.shape[1] + 1).sum(axis=2)
        np.testing.assert_allclose(per_class, raw, rtol=1e-9, atol=1e-9)


class TestRefit:
    def test_refit_moves_leaves_toward_new_data(self, model_and_data):
        bst, X, y = model_and_data
        rng = np.random.default_rng(11)
        # new data with flipped relationship on feature 2
        X2 = rng.normal(size=(1500, 6))
        y2 = (np.nan_to_num(X2[:, 0]) > 0.2).astype(np.float64)
        refitted = bst.refit(X2, y2, decay_rate=0.5)
        assert refitted.num_trees() == bst.num_trees()
        # structure unchanged: same leaf assignment on any input
        np.testing.assert_array_equal(
            bst.predict(X2[:100], pred_leaf=True),
            refitted.predict(X2[:100], pred_leaf=True))
        # quality on the NEW task must improve
        from sklearn.metrics import log_loss
        p_old = bst.predict(X2)
        p_new = refitted.predict(X2)
        assert log_loss(y2, p_new) < log_loss(y2, p_old)

    def test_decay_one_is_identity(self, model_and_data):
        bst, X, y = model_and_data
        same = bst.refit(X, y, decay_rate=1.0)
        np.testing.assert_allclose(same.predict(X[:50]), bst.predict(X[:50]),
                                   rtol=1e-12)

    @pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
    def test_refit_matches_reference(self, model_and_data, tmp_path):
        """Same model + same new data through the reference's refit must
        give the same refitted leaf values."""
        bst, X, y = model_and_data
        rng = np.random.default_rng(3)
        X2 = np.nan_to_num(X) + rng.normal(scale=0.1, size=X.shape)
        y2 = (X2[:, 0] > 0.1).astype(np.float64)
        mine = bst.refit(X2, y2, decay_rate=0.7)

        model_file = str(tmp_path / "m.txt")
        bst.save_model(model_file)
        ref = ctypes.CDLL(ORACLE_LIB)
        ref.LGBM_GetLastError.restype = ctypes.c_char_p
        bh = ctypes.c_void_p()
        it = ctypes.c_int()
        assert ref.LGBM_BoosterCreateFromModelfile(
            model_file.encode(), ctypes.byref(it), ctypes.byref(bh)) == 0

        # reference refit needs a Dataset + leaf predictions
        n, F = X2.shape
        Xc = np.ascontiguousarray(X2, np.float64)
        dh = ctypes.c_void_p()
        params = b"max_bin=63 objective=binary refit_decay_rate=0.7 verbosity=-1"
        assert ref.LGBM_DatasetCreateFromMat(
            Xc.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
            ctypes.c_int32(F), 1, params, None, ctypes.byref(dh)) == 0
        lab = y2.astype(np.float32)
        assert ref.LGBM_DatasetSetField(
            dh, b"label", lab.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int(n), 0) == 0
        bh2 = ctypes.c_void_p()
        assert ref.LGBM_BoosterCreate(dh, params, ctypes.byref(bh2)) == 0, \
            ref.LGBM_GetLastError()
        assert ref.LGBM_BoosterMerge(bh2, bh) == 0
        T = bst.num_trees()
        leaf_preds = bst.predict(X2, pred_leaf=True).astype(np.int32)
        leaf_flat = np.ascontiguousarray(leaf_preds.reshape(-1))
        assert ref.LGBM_BoosterRefit(
            bh2, leaf_flat.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int32(n), ctypes.c_int32(T)) == 0, \
            ref.LGBM_GetLastError()

        pred_ref = (ctypes.c_double * n)()
        olen = ctypes.c_int64()
        assert ref.LGBM_BoosterPredictForMat(
            bh2, Xc.ctypes.data_as(ctypes.c_void_p), 1, ctypes.c_int32(n),
            ctypes.c_int32(F), 1, 1, 0, b"", ctypes.byref(olen),
            pred_ref) == 0
        p_ref = np.ctypeslib.as_array(pred_ref)
        p_mine = mine.predict(X2, raw_score=True)
        np.testing.assert_allclose(p_mine, p_ref, rtol=1e-5, atol=1e-5)
