"""Shape bucketing: compile-cache policy for the padded (rows, features)
axes (SURVEY §7 "dispatch overhead is the #1 wall-clock risk").

With tpu_shape_buckets=k, at most k distinct padded shapes exist per
power-of-2 octave, so a NEW dataset of similar size maps to the SAME XLA
program and deserializes from the persistent compilation cache in seconds
instead of paying the cold compile.  tpu_shape_buckets=0 restores exact
block-multiple padding (the hardware-validated bench path).
"""

import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner


def _learner(n, f=10, **cfg):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] > 0).astype(np.float64)
    params = {"objective": "binary", "max_bin": 32, "num_leaves": 15,
              "tpu_block_rows": 256}
    params.update(cfg)
    config = Config(params)
    return TPUTreeLearner(config, TrainingData.from_matrix(X, y, config))


class TestBucketShapes:
    def test_similar_sizes_share_one_shape(self):
        a = _learner(5000, tpu_shape_buckets=4)
        b = _learner(5150, tpu_shape_buckets=4)
        assert (a.n_pad, a.f_pad, a.g_pad) == (b.n_pad, b.f_pad, b.g_pad)
        # exact mode keeps distinct block-multiple shapes
        a0 = _learner(5000, tpu_shape_buckets=0)
        b0 = _learner(5150, tpu_shape_buckets=0)
        assert a0.n_pad != b0.n_pad
        assert a0.n_pad == 5120 and b0.n_pad == 5376

    def test_waste_is_bounded(self):
        # worst-case pad waste is 2/buckets above the block quantum
        for n in (4097, 9000, 33333, 100001):
            lr = _learner(n, tpu_shape_buckets=16)
            assert lr.n_pad >= n
            assert lr.n_pad <= int(n * (1 + 2.0 / 16)) + 256, \
                (n, lr.n_pad)

    def test_sub_block_rows_bucket_too(self):
        # the common TPU regime: n below the resolved block (8-16k).
        # Rows quantize from the 128-lane tile upward instead of every n
        # being its own program
        a = _learner(5000, tpu_shape_buckets=32, tpu_block_rows=8192)
        b = _learner(5050, tpu_shape_buckets=32, tpu_block_rows=8192)
        assert a.n_pad == b.n_pad == 5120
        # exact mode keeps n as-is in the sub-block regime
        a0 = _learner(5000, tpu_shape_buckets=0, tpu_block_rows=8192)
        assert a0.n_pad == 5000

    def test_feature_axis_buckets(self):
        a = _learner(3000, f=70, tpu_shape_buckets=4)
        b = _learner(3000, f=75, tpu_shape_buckets=4)
        assert a.f_pad == b.f_pad and a.g_pad == b.g_pad

    def test_data_parallel_shards_stay_equal(self):
        lr = _learner(5000, tree_learner="data", num_machines=8,
                      tpu_shape_buckets=4)
        assert lr.n_pad % 8 == 0

    def test_bucketed_training_matches_exact(self):
        """Bucketing only adds masked padding rows/trivial features —
        the grown model must be identical."""
        import lightgbm_tpu as lgb

        rng = np.random.default_rng(3)
        X = rng.normal(size=(5000, 10))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
        out = []
        for buckets in (0, 4):
            p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                 "tpu_block_rows": 256, "tpu_shape_buckets": buckets}
            ds = lgb.Dataset(X, label=y, params=p)
            s = lgb.train(p, ds, num_boost_round=5).model_to_string()
            out.append(s.split("\nparameters:")[0])  # trees + headers only
        assert out[0] == out[1]


_CACHE_WORKER = """
import os, sys, time, importlib.util
root = {root!r}
sys.path.insert(0, root)
spec = importlib.util.spec_from_file_location(
    "_boot", os.path.join(root, "lightgbm_tpu", "utils", "backend.py"))
_b = importlib.util.module_from_spec(spec)
spec.loader.exec_module(_b)
_b.pin_cpu_backend()
import numpy as np
import lightgbm_tpu as lgb

n = int(sys.argv[1])
rng = np.random.default_rng(0)
X = rng.normal(size=(n, 10))
y = (X[:, 0] > 0).astype(np.float64)
p = {{"objective": "binary", "num_leaves": 31, "verbosity": -1,
     "tpu_block_rows": 256, "tpu_shape_buckets": 4}}
ds = lgb.Dataset(X, label=y, params=p)
from lightgbm_tpu.booster import Booster
bst = Booster(params=p, train_set=ds)
t0 = time.time()
bst.update()
np.asarray(bst._driver.train_scores.scores)  # sync
print(f"FIRST_ITER_S={{time.time() - t0:.2f}}", flush=True)
"""


@pytest.mark.slow
class TestPersistentCacheReuse:
    def test_second_similar_dataset_hits_cache(self, tmp_path):
        """A fresh process training a DIFFERENT dataset of similar size
        must reuse the cached grower program: no new cache entries, and
        the first iteration (compile included) runs in a fraction of the
        cold time."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cache = tmp_path / "fake_jax_cache"
        env = dict(os.environ, LIGHTGBM_TPU_CACHE_DIR=str(cache))
        env.pop("XLA_FLAGS", None)

        def run(n):
            t = time.time()
            r = subprocess.run([sys.executable, "-c",
                                _CACHE_WORKER.format(root=root), str(n)],
                               env=env, capture_output=True, text=True,
                               timeout=900)
            assert r.returncode == 0, r.stdout + r.stderr
            first = [ln for ln in r.stdout.splitlines()
                     if ln.startswith("FIRST_ITER_S=")][0]
            return float(first.split("=")[1]), time.time() - t

        cold_first, _ = run(5000)
        entries_after_a = sorted(os.listdir(cache))
        assert entries_after_a, "cold run persisted no cache entries"
        warm_first, _ = run(5150)   # different n, same bucket
        entries_after_b = sorted(os.listdir(cache))
        assert entries_after_b == entries_after_a, \
            "similar-size dataset compiled NEW programs"
        assert warm_first < max(0.6 * cold_first, 2.0), \
            f"warm {warm_first:.1f}s vs cold {cold_first:.1f}s"
