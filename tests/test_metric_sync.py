"""Distributed metric reduction (parallel/metric_sync.py; reference
Network::GlobalSyncUp* helpers, include/LightGBM/network.h:168-275).

Single-process unit coverage of the cross-rank metric merge.  The fake
2-rank world works by capture/replay on EQUAL halves: "rank 1" runs its
eval with an allgather stub that records every payload it sends (with
equal local lengths the padded payloads are identical to the real
multi-process ones), then "rank 0" re-runs with allgather returning
[local, recorded_peer] stacks.  The merged value must equal the plain
single-process metric on the concatenated data — the exact property that
keeps early stopping synchronized across ranks.  The REAL 2-process
rendezvous version of the same assertion lives in test_multihost.py.
"""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.models.metrics import create_metric
from lightgbm_tpu.parallel import metric_sync


class _Meta:
    def __init__(self, label, weight=None, query_boundaries=None,
                 qweights=None):
        self.label = np.asarray(label, np.float64)
        self.weight = weight
        self.query_boundaries = query_boundaries
        self.init_score = None
        self._qw = qweights

    def query_weights(self):
        return self._qw


class _FakeWorld:
    """Replays rank-1's recorded allgather payloads into rank-0's calls."""

    def __init__(self, monkeypatch):
        self.monkeypatch = monkeypatch
        self.recorded = []
        self.call_idx = 0

    def record(self, fn):
        """Run `fn` as rank 1: every allgather payload is captured and the
        stub returns [payload, payload] (self-peering — correct shapes
        because both ranks hold equal-length halves)."""
        self.monkeypatch.setattr(metric_sync, "process_count", lambda: 2)
        self.monkeypatch.setattr(
            metric_sync, "_allgather",
            lambda a: (self.recorded.append(np.array(a, copy=True)),
                       np.stack([a, a]))[1])
        try:
            return fn()
        finally:
            self.monkeypatch.setattr(metric_sync, "_allgather",
                                     _no_allgather)

    def replay(self, fn):
        """Run `fn` as rank 0: call i returns [local_i, recorded_i]."""
        self.call_idx = 0

        def gather(a):
            peer = self.recorded[self.call_idx]
            self.call_idx += 1
            assert peer.shape == np.shape(a), "rank call sequences diverged"
            return np.stack([np.asarray(a, peer.dtype), peer])

        self.monkeypatch.setattr(metric_sync, "process_count", lambda: 2)
        self.monkeypatch.setattr(metric_sync, "_allgather", gather)
        try:
            return fn()
        finally:
            self.monkeypatch.setattr(metric_sync, "_allgather",
                                     _no_allgather)
            self.monkeypatch.setattr(metric_sync, "process_count",
                                     lambda: 1)


def _no_allgather(a):  # pragma: no cover - guard
    raise AssertionError("allgather outside an armed fake world")


def _eval_metric(name, cfg, label, score, weight=None, qb=None):
    m = create_metric(name, cfg)
    meta = _Meta(label, weight, qb)
    m.init(meta, len(np.atleast_1d(label)))
    s = np.asarray(score, np.float64)
    if s.ndim == 1:
        s = s[None, :]
    return m.eval_all(s, None)


def _merged_vs_full(monkeypatch, name, cfg, label, score, weight=None,
                    qb=None, qb_split=None):
    """Core property: rank-merged metric == single-process full metric."""
    label = np.asarray(label, np.float64)
    score = np.asarray(score, np.float64)
    n = label.shape[0]
    assert n % 2 == 0
    h = n // 2
    full = _eval_metric(name, cfg, label, score, weight, qb)

    world = _FakeWorld(monkeypatch)
    cols = (slice(None), slice(h, None))
    qb1 = None if qb is None else \
        [q - h for q in qb if q >= h]
    world.record(lambda: _eval_metric(
        name, cfg, label[h:], score[..., h:],
        None if weight is None else weight[h:], qb1))
    qb0 = None if qb is None else [q for q in qb if q <= h]
    merged = world.replay(lambda: _eval_metric(
        name, cfg, label[:h], score[..., :h],
        None if weight is None else weight[:h], qb0))
    del cols
    for (n_full, v_full), (n_m, v_m) in zip(full, merged):
        assert n_full == n_m
        assert v_m == pytest.approx(v_full, rel=1e-12, abs=1e-12), name
    return full, merged


class TestSyncHelpers:
    def test_identity_single_process(self):
        assert metric_sync.process_count() == 1
        np.testing.assert_array_equal(metric_sync.sync_sums([1.5, 2.0]),
                                      [1.5, 2.0])
        (a,) = metric_sync.sync_concat(np.array([3.0, 1.0]))
        np.testing.assert_array_equal(a, [3.0, 1.0])

    def test_sync_sums_reduces(self, monkeypatch):
        monkeypatch.setattr(metric_sync, "process_count", lambda: 2)
        monkeypatch.setattr(
            metric_sync, "_allgather",
            lambda a: np.stack([a, 10.0 * np.asarray(a, np.float64)]))
        np.testing.assert_allclose(metric_sync.sync_sums([1.0, 2.0]),
                                   [11.0, 22.0])

    def test_sync_concat_ragged(self, monkeypatch):
        """Ranks with DIFFERENT local lengths merge correctly: simulate
        rank 0 (len 3) whose peer holds len 5 by scripted returns."""
        monkeypatch.setattr(metric_sync, "process_count", lambda: 2)
        calls = []

        def gather(a):
            calls.append(np.array(a, copy=True))
            if len(calls) == 1:  # the length exchange
                return np.array([[3], [5]], np.int64)
            # padded payload exchange: peer's 5 values in a len-5 buffer
            peer = np.array([10.0, 11.0, 12.0, 13.0, 14.0])
            return np.stack([np.asarray(a, np.float64), peer])

        monkeypatch.setattr(metric_sync, "_allgather", gather)
        (merged,) = metric_sync.sync_concat(np.array([1.0, 2.0, 3.0]))
        np.testing.assert_array_equal(
            merged, [1.0, 2.0, 3.0, 10.0, 11.0, 12.0, 13.0, 14.0])
        # the local payload was padded to the global max length
        assert calls[1].shape == (5,)

    def test_sync_concat_length_mismatch_raises(self, monkeypatch):
        monkeypatch.setattr(metric_sync, "process_count", lambda: 2)
        with pytest.raises(ValueError, match="local length"):
            metric_sync.sync_concat(np.zeros(3), np.zeros(4))


class TestMergedMetricsEqualFull:
    """Partition → reduce == full-data metric, per metric family."""

    def setup_method(self, _):
        rng = np.random.default_rng(11)
        self.n = 400
        self.score = rng.normal(size=self.n)
        self.label01 = (rng.random(self.n) < 0.4).astype(np.float64)
        self.label_reg = rng.normal(size=self.n) + 1.5
        self.weight = rng.random(self.n) + 0.25

    def test_avg_family(self, monkeypatch):
        cfg = Config()
        for name, label in (("l2", self.label_reg), ("l1", self.label_reg),
                            ("rmse", self.label_reg),
                            ("binary_logloss", self.label01),
                            ("binary_error", self.label01),
                            ("quantile", self.label_reg),
                            ("huber", self.label_reg)):
            _merged_vs_full(monkeypatch, name, cfg, label, self.score,
                            self.weight)

    def test_gamma_deviance_global_sum(self, monkeypatch):
        # sum-type metric: reduces across ranks ONLY when each rank holds
        # a distinct row shard.  The gate is the topology layer's derived
        # row-ownership predicate (not the pre_partition config flag) —
        # arm it the way a live partitioned learner would
        from lightgbm_tpu.parallel import topology

        monkeypatch.setattr(topology, "rows_partitioned", lambda: True)
        label = np.abs(self.label_reg) + 0.5
        score = np.abs(self.score) + 0.5
        _merged_vs_full(monkeypatch, "gamma_deviance",
                        Config({"pre_partition": True}), label,
                        score, self.weight)

    def test_gamma_deviance_replicated_not_scaled(self, monkeypatch):
        """Replicated multiprocess mode (every rank holds ALL rows): the
        sum must NOT be multiplied by the process count."""
        label = np.abs(self.label_reg) + 0.5
        score = np.abs(self.score) + 0.5
        full = _eval_metric("gamma_deviance", Config(), label, score,
                            self.weight)
        monkeypatch.setattr(metric_sync, "process_count", lambda: 2)
        # replicated ranks skip the collective entirely, so an armed
        # allgather would raise (_no_allgather is already installed)
        replicated = _eval_metric("gamma_deviance", Config(), label,
                                  score, self.weight)
        for (n_f, v_f), (n_r, v_r) in zip(full, replicated):
            assert n_f == n_r
            assert v_r == pytest.approx(v_f, rel=1e-12)

    def test_kldiv(self, monkeypatch):
        rng = np.random.default_rng(3)
        label = rng.random(self.n)
        prob = rng.random(self.n)
        _merged_vs_full(monkeypatch, "kldiv", Config(), label, prob,
                        self.weight)

    def test_auc_exact_merge(self, monkeypatch):
        # ties across the partition boundary exercise the global ranking
        score = np.round(self.score, 1)
        _merged_vs_full(monkeypatch, "auc", Config(), self.label01, score,
                        self.weight)
        _merged_vs_full(monkeypatch, "auc", Config(), self.label01, score)

    def test_auc_mu_exact_merge(self, monkeypatch):
        rng = np.random.default_rng(5)
        nc = 3
        label = rng.integers(0, nc, size=self.n).astype(np.float64)
        score = rng.normal(size=(nc, self.n))
        _merged_vs_full(monkeypatch, "auc_mu", Config({"num_class": nc,
                                "objective": "multiclass"}), label, score)

    def test_rank_metrics_weighted_queries(self, monkeypatch):
        """Per-query WEIGHTS make the reduction a genuine weighted sum
        (results and sum_query_weights both reduce)."""
        rng = np.random.default_rng(17)
        n = self.n
        qsz = 10
        qb = list(range(0, n + 1, qsz))
        nq = len(qb) - 1
        label = rng.integers(0, 4, size=n).astype(np.float64)
        score = rng.normal(size=n)
        qw = rng.random(nq) + 0.5
        cfg = Config()

        def _eval(lbl, sc, qbound, qws):
            m = create_metric("ndcg", cfg)
            m.init(_Meta(lbl, None, qbound, qws), len(lbl))
            return m.eval_all(np.asarray(sc)[None, :], None)

        full = _eval(label, score, qb, qw)
        h = n // 2
        hq = nq // 2
        world = _FakeWorld(monkeypatch)
        world.record(lambda: _eval(label[h:], score[h:],
                                   [q - h for q in qb if q >= h],
                                   qw[hq:]))
        merged = world.replay(lambda: _eval(label[:h], score[:h],
                                            [q for q in qb if q <= h],
                                            qw[:hq]))
        for (nf, vf), (nm, vm) in zip(full, merged):
            assert nf == nm
            assert vm == pytest.approx(vf, rel=1e-12)

    def test_rank_metrics(self, monkeypatch):
        # 40 queries of 10 docs: the halfway split lands on a query
        # boundary (queries live whole on one rank)
        rng = np.random.default_rng(7)
        n = self.n
        qb = list(range(0, n + 1, 10))
        label = rng.integers(0, 4, size=n).astype(np.float64)
        score = rng.normal(size=n)
        for name in ("ndcg", "map"):
            _merged_vs_full(monkeypatch, name, Config(), label, score,
                            qb=qb, qb_split=True)

    def test_multiclass(self, monkeypatch):
        rng = np.random.default_rng(9)
        nc = 3
        label = rng.integers(0, nc, size=self.n).astype(np.float64)
        prob = rng.random((nc, self.n)) + 1e-3
        prob /= prob.sum(axis=0, keepdims=True)
        mc = Config({"num_class": nc, "objective": "multiclass"})
        _merged_vs_full(monkeypatch, "multi_logloss", mc, label, prob,
                        self.weight)
        _merged_vs_full(monkeypatch, "multi_error", mc, label, prob,
                        self.weight)

    def test_replicated_mode_invariant(self, monkeypatch):
        """All-data-on-all-machines: both ranks hold the FULL sample; the
        reduction must leave averages and AUC unchanged (sums cancel /
        pairwise statistics are duplication-invariant)."""
        cfg = Config()
        for name, label in (("l2", self.label_reg),
                            ("binary_logloss", self.label01),
                            ("auc", self.label01)):
            full = _eval_metric(name, cfg, label, self.score, self.weight)
            world = _FakeWorld(monkeypatch)
            world.record(lambda: _eval_metric(name, cfg, label, self.score,
                                              self.weight))
            rep = world.replay(lambda: _eval_metric(name, cfg, label,
                                                    self.score, self.weight))
            for (_, v_full), (_, v_rep) in zip(full, rep):
                assert v_rep == pytest.approx(v_full, rel=1e-12), name
