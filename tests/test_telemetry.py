"""Unified telemetry (ISSUE 10): registry thread-safety, span
nesting/export schema, Prometheus endpoint agreement with /stats, the
telemetry-off overhead bound, bitwise-invisibility of tracing, log
attribution, and the multihost trace merge."""

import json
import os
import re
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs.metrics import MetricsRegistry, histogram_quantile

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Every test leaves the process-global telemetry policy off and the
    span buffer empty — other test modules must keep seeing the default
    near-zero-cost path."""
    yield
    obs.configure(mode="off", trace_dir="")
    obs.flush()
    obs.reset_events()


def _problem(n=400, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


_P = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
      "min_data_in_leaf": 5, "verbosity": -1}


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        r = MetricsRegistry()
        r.inc("a_total", 2, phase="x")
        r.inc("a_total", 3, phase="x")
        r.inc("a_total", 1, phase="y")
        assert r.value("a_total", phase="x") == 5
        assert r.value("a_total", phase="y") == 1
        assert r.value("a_total", phase="missing") == 0
        r.set_gauge("g", 7.5)
        r.set_gauge("g", 2.5)
        assert r.value("g") == 2.5
        r.observe("h_seconds", 0.3, buckets=(0.1, 0.5, 1.0))
        r.observe("h_seconds", 0.7, buckets=(0.1, 0.5, 1.0))
        n, s = r.histogram_stats("h_seconds")
        assert n == 2 and abs(s - 1.0) < 1e-12
        assert r.histogram_samples("h_seconds") == [0.3, 0.7]

    def test_kind_conflict_raises(self):
        r = MetricsRegistry()
        r.inc("m")
        with pytest.raises(ValueError, match="already registered"):
            r.observe("m", 1.0)

    def test_label_named_name_allowed(self):
        # the collective metrics label by collective name — the API must
        # accept a label literally called `name`
        r = MetricsRegistry()
        r.inc("c_total", 1, name="sync_sums")
        r.observe("w_seconds", 0.01, name="sync_sums")
        assert r.value("c_total", name="sync_sums") == 1

    def test_thread_safety_hammer(self):
        r = MetricsRegistry()
        threads, per = 16, 5000

        def work(k):
            for i in range(per):
                r.inc("hammer_total")
                r.inc("hammer_total", 1, worker=str(k % 4))
                r.observe("hammer_seconds", (i % 10) / 10.0,
                          buckets=(0.2, 0.5, 0.8))

        ts = [threading.Thread(target=work, args=(k,))
              for k in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert r.value("hammer_total") == threads * per
        assert sum(r.value("hammer_total", worker=str(w))
                   for w in range(4)) == threads * per
        n, _ = r.histogram_stats("hammer_seconds")
        assert n == threads * per

    def test_quantile_interpolation(self):
        r = MetricsRegistry()
        for v in (0.05, 0.15, 0.15, 0.25):  # buckets 0.1 / 0.2 / 0.3
            r.observe("q_seconds", v, buckets=(0.1, 0.2, 0.3))
        # rank(0.5) = 2 -> second bucket (1 below it, 2 inside):
        # 0.1 + 0.1 * (2 - 1) / 2 = 0.15
        assert abs(r.histogram_quantile("q_seconds", 0.5) - 0.15) < 1e-12
        # empty histogram -> 0.0
        assert r.histogram_quantile("missing", 0.99) == 0.0

    def test_prometheus_text_parses_and_is_cumulative(self):
        r = MetricsRegistry()
        r.inc("x_total", 3, help="a counter", phase="a b\"c")
        r.set_gauge("y", 1.5)
        for v in (0.05, 0.3, 2.0):
            r.observe("z_seconds", v, buckets=(0.1, 1.0))
        text = r.to_prometheus_text()
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+$|'
            r'^# (HELP|TYPE) .*$')
        for line in text.strip().splitlines():
            assert sample.match(line), f"unparseable line: {line!r}"
        # histogram buckets cumulative and +Inf == count
        buckets = {}
        for line in text.splitlines():
            m = re.match(r'z_seconds_bucket\{le="([^"]+)"\} (\d+)', line)
            if m:
                buckets[m.group(1)] = int(m.group(2))
        assert buckets["+Inf"] == 3
        vals = [buckets[k] for k in sorted(buckets, key=lambda s: (
            float("inf") if s == "+Inf" else float(s)))]
        assert vals == sorted(vals)
        assert 'phase="a b\\"c"' in text  # label escaping survives


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
class TestSpans:
    def test_off_mode_is_shared_null_cm(self):
        assert obs.mode() == "off"
        cm1 = obs.span("anything", tag=1)
        cm2 = obs.span("else")
        assert cm1 is cm2  # the shared null context manager
        with cm1:
            pass
        assert obs.events() == []

    def test_nesting_depth_and_parent_tags(self):
        obs.configure(mode="trace")
        obs.reset_events()
        with obs.span("outer"):
            with obs.span("inner"):
                time.sleep(0.001)
        evs = {e["name"]: e for e in obs.events()}
        assert evs["inner"]["tags"]["parent"] == "outer"
        assert evs["inner"]["tags"]["depth"] == 1
        assert evs["outer"]["tags"]["depth"] == 0
        # child window nested inside the parent's
        o, i = evs["outer"], evs["inner"]
        assert o["ts"] <= i["ts"]
        assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-6

    def test_chrome_trace_schema_roundtrip(self, tmp_path):
        obs.configure(mode="trace", trace_dir=str(tmp_path))
        obs.reset_events()
        with obs.span("a", iteration=3):
            with obs.span("b"):
                pass
        obs.event("watchdog_fired", name="sync")
        path = obs.write_chrome_trace()
        obs.flush()
        tr = json.loads(open(path).read())  # parses = loadable
        assert isinstance(tr["traceEvents"], list)
        phs = set()
        for ev in tr["traceEvents"]:
            assert isinstance(ev["name"], str)
            assert ev["ph"] in ("X", "M", "i")
            phs.add(ev["ph"])
            if ev["ph"] == "X":
                assert isinstance(ev["ts"], (int, float))
                assert isinstance(ev["dur"], (int, float))
                assert isinstance(ev["pid"], int)
                assert isinstance(ev["tid"], int)
        assert {"X", "M", "i"} <= phs
        # the JSONL stream carries the same records incrementally
        lines = [json.loads(ln) for ln in
                 open(tmp_path / "events-host0.jsonl")]
        kinds = {(ln["kind"], ln["name"]) for ln in lines}
        assert ("span", "a") in kinds and ("span", "b") in kinds
        assert ("event", "watchdog_fired") in kinds

    def test_timed_records_registry_samples(self):
        obs.configure(mode="metrics")
        with obs.timed("unit/seg"):
            time.sleep(0.002)
        samples = obs.REGISTRY.histogram_samples("lgbm_timed_seconds",
                                                 name="unit/seg")
        assert samples and samples[-1] >= 0.002


# ---------------------------------------------------------------------------
# end-to-end train trace
# ---------------------------------------------------------------------------
class TestTrainTrace:
    def test_trace_covers_train_wall_and_loads(self, tmp_path):
        X, y = _problem(n=800)
        p = dict(_P, tpu_telemetry="trace", tpu_trace_dir=str(tmp_path))
        obs.reset_events()
        ds = lgb.Dataset(X, label=y, params=p)
        vd = lgb.Dataset(X[:200], label=y[:200], reference=ds, params=p)
        lgb.train(p, ds, num_boost_round=10, valid_sets=[vd],
                  verbose_eval=False)
        trace = json.loads(open(tmp_path / "trace-host0.json").read())
        spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        rounds = [e for e in spans if e["name"] == "train/round"]
        assert len(rounds) == 10
        assert sorted(e["args"]["iteration"] for e in rounds) == list(
            range(10))
        # acceptance: per-iteration spans cover >= 95% of the train-loop
        # wall (first round start -> last round end)
        loop_wall = (max(e["ts"] + e["dur"] for e in rounds)
                     - min(e["ts"] for e in rounds))
        covered = sum(e["dur"] for e in rounds)
        assert covered >= 0.95 * loop_wall
        # the lifecycle vocabulary is present as child spans
        names = {e["name"] for e in spans}
        for want in ("train/iteration", "train_dispatch",
                     "tree_materialize", "metric_eval", "sketch",
                     "binning"):
            assert want in names, f"missing span {want!r} in {names}"

    def test_model_bit_identical_trace_on_vs_off(self, tmp_path):
        # telemetry must not touch PRNG streams or device math — bagged
        # int16 training is the sensitive configuration
        X, y = _problem(n=600)
        q = dict(_P, num_leaves=15, bagging_fraction=0.8, bagging_freq=1,
                 tpu_hist_precision="int16")

        def train_text():
            ds = lgb.Dataset(X, label=y, params=q)
            bst = lgb.train(q, ds, num_boost_round=4,
                            keep_training_booster=True)
            return bst.model_to_string().split("\nparameters:")[0]

        obs.configure(mode="off", trace_dir="")
        m_off = train_text()
        obs.configure(mode="trace", trace_dir=str(tmp_path))
        m_trace = train_text()
        assert m_off == m_trace


# ---------------------------------------------------------------------------
# serving /metrics <-> /stats agreement
# ---------------------------------------------------------------------------
class TestServingMetrics:
    @pytest.fixture()
    def served(self):
        from lightgbm_tpu.serving import ServingSession
        from lightgbm_tpu.serving.server import serve_http

        X, y = _problem(n=500)
        ds = lgb.Dataset(X, label=y, params=_P)
        bst = lgb.train(_P, ds, num_boost_round=3)
        sess = ServingSession(params={"serving_max_batch_rows": 256,
                                      "verbosity": -1})
        sess.load("m", booster=bst)
        server = serve_http(sess, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            yield sess, base, X
        finally:
            server.shutdown()
            sess.close()

    @staticmethod
    def _get(url):
        with urllib.request.urlopen(url) as resp:
            return resp.headers.get("Content-Type", ""), resp.read().decode()

    def test_metrics_endpoint_agrees_with_stats(self, served):
        sess, base, X = served
        for sz in (1, 9, 33, 120):
            sess.predict("m", X[:sz])
        ctype, text = self._get(base + "/metrics")
        assert ctype.startswith("text/plain")
        # every sample line parses
        sample = re.compile(
            r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+$|'
            r'^# (HELP|TYPE) .*$')
        for line in text.strip().splitlines():
            assert sample.match(line), f"unparseable line: {line!r}"
        # rebuild the latency estimate FROM THE SCRAPE and compare to
        # /stats — one estimator, two surfaces, zero disagreement
        buckets = {}
        for line in text.splitlines():
            m = re.match(
                r'lgbm_serving_latency_seconds_bucket\{le="([^"]+)"\} (\d+)',
                line)
            if m:
                buckets[m.group(1)] = int(m.group(2))
        assert buckets, "latency histogram missing from /metrics"
        bounds = sorted(float(k) for k in buckets if k != "+Inf")
        cum = [buckets[repr(b)] for b in bounds] + [buckets["+Inf"]]
        counts = [cum[0]] + [cum[i] - cum[i - 1]
                             for i in range(1, len(cum))]
        st = json.loads(self._get(base + "/stats")[1])
        assert st["latency_window"] >= 4
        for tag, q in (("latency_p50_ms", 0.50), ("latency_p95_ms", 0.95),
                       ("latency_p99_ms", 0.99)):
            scraped = round(histogram_quantile(bounds, counts, q) * 1e3, 3)
            assert scraped == st[tag], (tag, scraped, st[tag])
        # request totals agree between the two surfaces
        m = re.search(r"lgbm_serving_requests_total(\{\})? (\d+)", text)
        assert m and int(m.group(2)) == st["requests_total"]

    def test_drift_gauges_agree_with_drift_payload(self, served):
        """ISSUE 14 extension of the scrape-equality contract: the
        `lgbm_drift_*` gauges on /metrics and the GET /drift JSON read
        the SAME accumulators — values must agree (modulo the %g gauge
        formatting), and every profiled feature appears on both."""
        sess, base, X = served
        sess.predict("m", X[:200] + 1.0)   # shifted: non-trivial PSI
        payload = json.loads(self._get(base + "/drift")[1])
        assert "m@1" in payload["models"]
        snap = payload["models"]["m@1"]
        assert snap["rows_sampled"] > 0
        text = self._get(base + "/metrics")[1]
        gauges = {}
        for line in text.splitlines():
            m = re.match(r'lgbm_drift_psi\{feature="([^"]+)",'
                         r'model="m@1"\} (-?[0-9.eE+-]+)', line)
            if m:
                gauges[m.group(1)] = float(m.group(2))
        assert set(gauges) == set(snap["features"])
        for name, f in snap["features"].items():
            assert gauges[name] == pytest.approx(f["psi"], rel=1e-5,
                                                 abs=1e-9)
        m = re.search(r'lgbm_drift_score_js\{model="m@1"\} '
                      r'(-?[0-9.eE+-]+)', text)
        assert m and float(m.group(1)) == pytest.approx(
            snap["score_js_max"], rel=1e-5, abs=1e-9)
        m = re.search(r'lgbm_drift_sampled_rows\{model="m@1"\} (\d+)',
                      text)
        assert m and int(m.group(1)) >= snap["rows_sampled"]

    def test_queue_wait_and_dispatch_distributions_populate(self, served):
        sess, base, X = served
        for _ in range(3):
            sess.predict("m", X[:16])
        st = sess.stats()
        assert st["dispatch_mean_ms"] > 0.0
        assert st["queue_wait_mean_ms"] >= 0.0
        text = self._get(base + "/metrics")[1]
        assert "lgbm_serving_dispatch_seconds_bucket" in text
        assert "lgbm_serving_queue_wait_seconds_bucket" in text


# ---------------------------------------------------------------------------
# overhead: telemetry off vs the registry absent
# ---------------------------------------------------------------------------
class TestOffOverhead:
    N_ITERS = 100

    def _train_wall(self):
        X, y = _problem(n=1500, f=6, seed=3)
        ds = lgb.Dataset(X, label=y, params=_P)
        bst = lgb.Booster(params=dict(_P), train_set=ds)
        from lightgbm_tpu.utils.backend import host_sync

        bst.update()  # compile + warm
        host_sync(bst._driver.train_scores.scores)
        t0 = time.perf_counter()
        for _ in range(self.N_ITERS):
            bst.update()
        host_sync(bst._driver.train_scores.scores)
        return time.perf_counter() - t0

    def test_off_mode_regression_under_1pct(self, monkeypatch):
        import contextlib

        import lightgbm_tpu.models.gbdt as gbdt_mod
        import lightgbm_tpu.utils.timer as timer_mod

        assert obs.mode() == "off"

        # (a) deterministic microbench: the exact per-iteration gated
        # work (the spans/PHASE checks the hot loop added) must cost
        # < 1% of a measured training iteration.  Min-of-5 windows so a
        # transient container stall (GC, noisy neighbor) cannot inflate
        # the measured per-call cost
        reps = 5000
        from lightgbm_tpu.obs import flightrecorder, resources
        from lightgbm_tpu.utils import lockcheck

        assert not lockcheck.enabled()
        _lk = lockcheck.make_lock("test.offgate")
        per_call = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(reps):
                with obs.span("train/iteration", iteration=i):
                    with timer_mod.PHASE("train_dispatch"):
                        # ISSUE 12 sites: the gated phase watermark and
                        # the ALWAYS-ON flight-recorder round note must
                        # fit inside the same 1% gate
                        with resources.phase_peak("hist_build"):
                            pass
                flightrecorder.note("round", "train/round", iteration=i)
                # ISSUE 13 site: serving/obs locks are now created via
                # lockcheck.make_lock — a DISABLED instrumented lock
                # cycle rides the same 1% budget
                with _lk:
                    pass
            per_call = min(per_call,
                           (time.perf_counter() - t0) / reps)
        wall = self._train_wall()
        per_iter = wall / self.N_ITERS
        assert per_call < 0.01 * per_iter, (
            f"gated telemetry sites cost {per_call * 1e6:.2f}us/iter vs "
            f"{per_iter * 1e3:.2f}ms training iterations")

        # (b) end-to-end A/B vs "the registry absent" (instrumentation
        # stubbed to bare no-ops), interleaved min-of-N with a retry:
        # both arms run identical device work, so a consistent >1% gap
        # is a real regression, not container noise
        class _Null:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

        _null = _Null()

        @contextlib.contextmanager
        def _null_phase(name):
            yield

        import statistics

        def inside_gate(off, absent):
            # min-vs-min washes UPWARD noise spikes (container stalls)
            # but one lucky downward outlier in the stubbed arm poisons
            # it irrecoverably, so the median is an alternate judge: a
            # REAL >1% gap shifts min AND median, pure noise rarely
            # shifts both
            return (min(off) <= min(absent) * 1.01
                    or statistics.median(off)
                    <= statistics.median(absent) * 1.01)

        off_walls, absent_walls = [], []
        # 6 attempts (was 4): the CPU container's wall noise spans tens
        # of percent between repeats, and an extra retry round only
        # runs on the bad-luck path
        for attempt in range(6):
            for _ in range(2):
                off_walls.append(self._train_wall())
                with pytest.MonkeyPatch.context() as mp:
                    mp.setattr(obs, "span", lambda *a, **k: _null)
                    mp.setattr(gbdt_mod.obs, "span", lambda *a, **k: _null)
                    mp.setattr(timer_mod, "PHASE", _null_phase)
                    absent_walls.append(self._train_wall())
            if inside_gate(off_walls, absent_walls):
                break
        assert inside_gate(off_walls, absent_walls), (
            f"telemetry-off train min {min(off_walls):.3f}s / median "
            f"{statistics.median(off_walls):.3f}s vs registry-absent "
            f"min {min(absent_walls):.3f}s / median "
            f"{statistics.median(absent_walls):.3f}s (> 1% regression)")


# ---------------------------------------------------------------------------
# log attribution
# ---------------------------------------------------------------------------
class TestLogTelemetry:
    def test_warning_counts_into_registry(self):
        from lightgbm_tpu.utils.log import Log

        before = obs.REGISTRY.value("lgbm_log_warnings_total")
        lines = []
        Log.reset_callback(lines.append)
        try:
            Log.warning("observable warning")
        finally:
            Log.reset_callback(None)
        assert obs.REGISTRY.value("lgbm_log_warnings_total") == before + 1
        assert any("observable warning" in ln for ln in lines)

    def test_host_prefix_on_multiprocess(self):
        from lightgbm_tpu.utils import log as log_mod

        lines = []
        log_mod.Log.reset_callback(lines.append)
        prev = log_mod._host_tag_cache
        try:
            log_mod._host_tag_cache = "[host 3] "
            log_mod.Log.warning("who said this")
        finally:
            log_mod._host_tag_cache = prev
            log_mod.Log.reset_callback(None)
        assert lines and lines[-1].startswith("[host 3] [LightGBM]")

    def test_single_process_has_no_prefix(self):
        from lightgbm_tpu.utils import log as log_mod

        # on the single-process test harness the resolver must yield ""
        assert log_mod._host_tag() == ""


# ---------------------------------------------------------------------------
# multihost merge tool
# ---------------------------------------------------------------------------
class TestTraceMerge:
    def test_merges_hosts_and_skips_torn_tails(self, tmp_path):
        sys.path.insert(0, TOOLS)
        try:
            import trace_merge
        finally:
            sys.path.remove(TOOLS)
        for host in (0, 1):
            with open(tmp_path / f"events-host{host}.jsonl", "w") as f:
                for i in range(3):
                    f.write(json.dumps({
                        "kind": "span", "name": f"iter{i}",
                        "ts_us": 100.0 * i, "dur_us": 50.0,
                        "host": host, "tid": 1,
                        "tags": {"iteration": i}}) + "\n")
                if host == 1:  # a dying host's torn final line
                    f.write('{"kind": "span", "name": "tor')
        trace, counts, skipped = trace_merge.merge(str(tmp_path))
        assert counts == {0: 3, 1: 3}
        assert skipped == 1
        pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] == "X"}
        assert pids == {0, 1}
        names = {e["args"]["name"] for e in trace["traceEvents"]
                 if e["ph"] == "M"}
        assert names == {"lightgbm_tpu host 0", "lightgbm_tpu host 1"}
        out = trace_merge.main([str(tmp_path)])
        assert json.loads(open(out).read())["traceEvents"]

    def test_missing_dir_raises(self, tmp_path):
        sys.path.insert(0, TOOLS)
        try:
            import trace_merge
        finally:
            sys.path.remove(TOOLS)
        with pytest.raises(FileNotFoundError):
            trace_merge.merge(str(tmp_path))


# ---------------------------------------------------------------------------
# collective / checkpoint / guard counters
# ---------------------------------------------------------------------------
class TestLifecycleCounters:
    def test_collective_timeout_counts_and_events(self, tmp_path):
        from lightgbm_tpu.parallel.collective import (CollectiveTimeout,
                                                      guarded_collective)
        from lightgbm_tpu.utils import faultline

        obs.configure(mode="trace", trace_dir=str(tmp_path))
        obs.reset_events()
        before = obs.REGISTRY.value("lgbm_collective_timeouts_total",
                                    name="unit_sync")
        faultline.reset()
        faultline.arm("collective_sync", action="hang")
        try:
            with pytest.raises(CollectiveTimeout):
                guarded_collective(lambda: 1, name="unit_sync", local=True)
        finally:
            faultline.reset()
        assert obs.REGISTRY.value("lgbm_collective_timeouts_total",
                                  name="unit_sync") == before + 1
        assert any(e["name"] == "collective_timeout"
                   for e in obs.events() if e["kind"] == "event")
        # the successful path records wait time under metrics mode
        assert guarded_collective(lambda: 41, name="unit_sync",
                                  local=True) == 41
        n, _ = obs.REGISTRY.histogram_stats("lgbm_collective_wait_seconds",
                                            name="unit_sync")
        assert n >= 1

    def test_checkpoint_write_and_restore_count(self, tmp_path):
        from lightgbm_tpu.utils.checkpoint import (CheckpointManager,
                                                   restore_checkpoint,
                                                   save_checkpoint)

        X, y = _problem()
        ds = lgb.Dataset(X, label=y, params=_P)
        bst = lgb.Booster(params=dict(_P), train_set=ds)
        bst.update()
        w0 = obs.REGISTRY.value("lgbm_checkpoint_writes_total")
        r0 = obs.REGISTRY.value("lgbm_checkpoint_restores_total")
        manager = CheckpointManager(str(tmp_path), keep=2)
        save_checkpoint(bst, manager)
        assert obs.REGISTRY.value("lgbm_checkpoint_writes_total") == w0 + 1
        bst2 = lgb.Booster(params=dict(_P), train_set=ds)
        restore_checkpoint(bst2, manager)
        assert obs.REGISTRY.value("lgbm_checkpoint_restores_total") == r0 + 1

    def test_guard_poison_counts(self):
        from lightgbm_tpu.utils import faultline

        X, y = _problem()
        p = dict(_P, tpu_guard_numerics="warn")
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.Booster(params=p, train_set=ds)
        before = obs.REGISTRY.value("lgbm_guard_poisoned_total",
                                    mode="warn")
        faultline.reset()
        faultline.arm("grow_step", action="poison", at=2)
        try:
            for _ in range(3):
                bst.update()
        finally:
            faultline.reset()
        # warn mode CONTINUES with the poisoned scores, so every later
        # iteration re-detects them: at least one firing, maybe more
        assert obs.REGISTRY.value("lgbm_guard_poisoned_total",
                                  mode="warn") >= before + 1

    def test_fault_firing_counts(self):
        from lightgbm_tpu.utils import faultline

        before = obs.REGISTRY.value("lgbm_fault_injections_total",
                                    point="h2d_copy", action="raise")
        faultline.reset()
        faultline.arm("h2d_copy", action="raise")
        with pytest.raises(faultline.FaultInjected):
            faultline.fire("h2d_copy")
        faultline.reset()
        assert obs.REGISTRY.value("lgbm_fault_injections_total",
                                  point="h2d_copy",
                                  action="raise") == before + 1

    def test_phase_seconds_absorbed_into_registry(self):
        obs.configure(mode="metrics")
        from lightgbm_tpu.utils import timer

        s0 = obs.REGISTRY.value("lgbm_phase_seconds_total", phase="sketch")
        X, y = _problem()
        ds = lgb.Dataset(X, label=y, params=_P)
        ds.construct()
        s1 = obs.REGISTRY.value("lgbm_phase_seconds_total", phase="sketch")
        assert s1 > s0
        assert timer.summary().get("sketch", 0.0) == s1
