"""convert_model C++ codegen: generated source must compile (g++) and
reproduce Booster.predict bit-for-nearly-bit.

Mirrors the reference CLI task=convert_model (application.cpp:222-229,
gbdt_model_text.cpp:87 ModelToIfElse).
"""

import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # g++ in the loop


def _compile_and_predict(cpp_path, tmp, X, num_out):
    so = os.path.join(tmp, "model.so")
    subprocess.run(["g++", "-O1", "-shared", "-fPIC", "-o", so, cpp_path],
                   check=True, capture_output=True)
    lib = ctypes.CDLL(so)
    # C++ name mangling: ask the symbol table
    syms = subprocess.run(["nm", "-D", so], capture_output=True,
                          text=True).stdout
    raw_sym = next(s.split()[-1] for s in syms.splitlines()
                   if "PredictRaw" in s)
    pred_sym = next(s.split()[-1] for s in syms.splitlines()
                    if "Predict" in s and "PredictRaw" not in s)
    out = np.zeros((len(X), num_out))
    raw = np.zeros((len(X), num_out))
    for fname, buf in ((raw_sym, raw), (pred_sym, out)):
        fn = getattr(lib, fname)
        fn.argtypes = [ctypes.POINTER(ctypes.c_double),
                       ctypes.POINTER(ctypes.c_double)]
        for i, row in enumerate(np.ascontiguousarray(X, np.float64)):
            fn(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
               buf[i].ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return raw, out


def _roundtrip(tmp_path, params, X, y, num_out, categorical=None):
    import lightgbm_tpu as lgb

    if categorical is not None:
        params = dict(params, categorical_feature=categorical)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
    bst = lgb.train(params, ds, num_boost_round=5, verbose_eval=False)
    model_file = str(tmp_path / "m.txt")
    bst.save_model(model_file)

    from lightgbm_tpu.application import Application
    cpp = str(tmp_path / "model.cpp")
    Application(["task=convert_model", f"input_model={model_file}",
                 f"convert_model={cpp}"]).run()
    raw_c, pred_c = _compile_and_predict(cpp, str(tmp_path), X, num_out)
    raw_py = bst.predict(X, raw_score=True)
    pred_py = bst.predict(X)
    if num_out == 1:
        raw_py = raw_py.reshape(-1, 1)
        pred_py = pred_py.reshape(-1, 1)
    np.testing.assert_allclose(raw_c, raw_py, rtol=1e-10, atol=1e-10)
    np.testing.assert_allclose(pred_c, pred_py, rtol=1e-6, atol=1e-9)


class TestConvertModel:
    def test_binary_with_missing(self, tmp_path):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(800, 5))
        X[rng.random(X.shape) < 0.15] = np.nan
        y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) > 0).astype(
            np.float64)
        _roundtrip(tmp_path, {"objective": "binary", "num_leaves": 15,
                              "min_data_in_leaf": 5}, X, y, 1)

    def test_multiclass_softmax(self, tmp_path):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(900, 4))
        y = (np.digitize(X[:, 0], [-0.5, 0.5])).astype(np.float64)
        _roundtrip(tmp_path, {"objective": "multiclass", "num_class": 3,
                              "num_leaves": 7, "min_data_in_leaf": 5},
                   X, y, 3)

    def test_regression_categorical(self, tmp_path):
        rng = np.random.default_rng(2)
        n = 1000
        Xc = rng.integers(0, 9, size=n).astype(np.float64)
        Xn = rng.normal(size=n)
        X = np.column_stack([Xc, Xn])
        y = (Xc % 3) * 1.5 + Xn
        _roundtrip(tmp_path, {"objective": "regression", "num_leaves": 15,
                              "min_data_in_leaf": 5}, X, y, 1,
                   categorical=[0])

    def test_categorical_nan_routing(self, tmp_path):
        """NaN in a categorical feature at PREDICT time: for non-NaN
        missing types the tree folds it to category 0, so the generated
        C++ must too (the train data has no NaNs, making missing_type
        None/Zero)."""
        rng = np.random.default_rng(3)
        n = 1200
        Xc = rng.integers(0, 6, size=n).astype(np.float64)
        Xn = rng.normal(size=n)
        X = np.column_stack([Xc, Xn])
        y = (Xc < 2) * 2.0 + Xn  # category 0 lands left of the root split
        import lightgbm_tpu as lgb
        ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
        bst = lgb.train({"objective": "regression", "num_leaves": 15,
                         "min_data_in_leaf": 5,
                         "categorical_feature": [0]},
                        ds, num_boost_round=5, verbose_eval=False)
        model_file = str(tmp_path / "m.txt")
        bst.save_model(model_file)
        from lightgbm_tpu.application import Application
        cpp = str(tmp_path / "model.cpp")
        Application(["task=convert_model", f"input_model={model_file}",
                     f"convert_model={cpp}"]).run()
        Xq = np.column_stack([np.full(50, np.nan), rng.normal(size=50)])
        raw_c, _ = _compile_and_predict(cpp, str(tmp_path), Xq, 1)
        raw_py = bst.predict(Xq, raw_score=True).reshape(-1, 1)
        np.testing.assert_allclose(raw_c, raw_py, rtol=1e-10, atol=1e-10)
