"""Flight recorder (ISSUE 12): the always-on bounded blackbox ring.

Covers ring bounds under a 16-thread hammer, blackbox dumps on an
injected collective hang (the dump's newest entries must NAME the hung
collective site), dump-on-SIGTERM ordering against the PR-7 checkpoint
flush (the dump's metric snapshot proves the checkpoint landed first),
the guard-raise dump, breaker/fault transitions ringing, the
tpu_obs_* configuration wiring, and the off-mode overhead of a ring
note staying negligible beside a training iteration.
"""

import glob
import json
import os
import subprocess
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import flightrecorder as fr
from lightgbm_tpu.parallel.collective import CollectiveTimeout
from lightgbm_tpu.parallel.metric_sync import sync_sums
from lightgbm_tpu.utils import faultline

_P = {"objective": "binary", "num_leaves": 7, "max_bin": 31,
      "learning_rate": 0.1, "min_data_in_leaf": 5, "verbosity": -1}


def _problem(n=800, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


@pytest.fixture(autouse=True)
def _clean_recorder(tmp_path, monkeypatch):
    """Every test gets a fresh ring and a sandboxed dump dir."""
    monkeypatch.setenv("LIGHTGBM_TPU_BLACKBOX_DIR", str(tmp_path))
    fr.reset()
    faultline.reset()
    yield
    faultline.reset()
    fr.reset()
    fr.configure(events=fr.DEFAULT_EVENTS, dump_dir="")


def _read_dump(path):
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------
class TestRing:
    def test_ring_is_bounded_and_keeps_newest(self):
        fr.configure(events=64)
        for i in range(500):
            fr.note("k", f"e{i}", i=i)
        ents = fr.entries()
        assert len(ents) == 64
        assert ents[-1]["name"] == "e499"
        assert ents[0]["name"] == "e436"

    def test_sixteen_thread_hammer_never_exceeds_bound(self):
        """16 threads x 2000 notes: the ring stays exactly bounded,
        every surviving entry is well-formed, and no note is lost from
        the newest window (GIL-atomic deque appends, no lock)."""
        fr.configure(events=256)
        threads, per = 16, 2000
        barrier = threading.Barrier(threads)

        def hammer(t):
            barrier.wait()
            for i in range(per):
                fr.note("hammer", f"t{t}", i=i)

        ws = [threading.Thread(target=hammer, args=(t,))
              for t in range(threads)]
        for w in ws:
            w.start()
        for w in ws:
            w.join()
        ents = fr.entries()
        assert len(ents) == 256
        for e in ents:
            assert e["kind"] == "hammer"
            assert e["name"].startswith("t")
            assert isinstance(e["fields"]["i"], int)
        # the newest entry overall must be some thread's LAST note
        assert ents[-1]["fields"]["i"] == per - 1

    def test_resize_keeps_newest_entries(self):
        fr.configure(events=128)
        for i in range(128):
            fr.note("k", f"e{i}")
        fr.configure(events=32)
        ents = fr.entries()
        assert len(ents) == 32 and ents[-1]["name"] == "e127"

    def test_config_wiring_from_params(self):
        """tpu_obs_blackbox_events / tpu_obs_blackbox_dir ride
        obs.configure_from_config; 0/"" leave the policy untouched."""
        from lightgbm_tpu.config import Config

        fr.configure(events=100, dump_dir="")
        obs.configure_from_config(Config({}))  # defaults: no clobber
        assert fr.depth() == 100
        obs.configure_from_config(Config({
            "tpu_obs_blackbox_events": 48,
            "tpu_obs_blackbox_dir": "/tmp/some-bb"}))
        assert fr.depth() == 48
        assert fr.blackbox_dir() == "/tmp/some-bb"
        fr.configure(dump_dir="")


# ---------------------------------------------------------------------------
# blackbox dumps
# ---------------------------------------------------------------------------
class TestDump:
    def test_dump_is_atomic_json_with_metrics_snapshot(self, tmp_path):
        fr.note("k", "breadcrumb", detail="x")
        obs.REGISTRY.inc("lgbm_test_dump_counter_total", 3)
        path = fr.dump("unit_test")
        assert path == str(tmp_path / "blackbox-host0.json")
        rec = _read_dump(path)
        assert rec["reason"] == "unit_test"
        assert rec["entries"][-1]["name"] == "breadcrumb"
        assert rec["metrics"]["lgbm_test_dump_counter_total"] == 3
        assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]

    def test_explicit_dir_path_keeps_canonical_gitignored_name(
            self, tmp_path):
        """dump(path=<directory>) joins the canonical
        blackbox-host<k>.json name — the exact .gitignore pattern — so
        no caller can strand a differently-named (trackable) dump in a
        source checkout (ISSUE 13: a stale dump was sitting at the
        repo root)."""
        d = tmp_path / "dumps"
        d.mkdir()
        fr.note("k", "crumb")
        path = fr.dump("unit_test", path=str(d))
        assert path == str(d / "blackbox-host0.json")
        assert _read_dump(path)["reason"] == "unit_test"

    def test_no_blackbox_dump_is_tracked_or_stranded(self):
        """Regression for the stale `blackbox-host0.json` that sat at
        the repo root (removed in ISSUE 16, then REGREW by ISSUE 18 —
        the gitignore hid it from `git status` so nothing noticed): no
        dump may be committed, the .gitignore pattern must cover every
        canonical dump name, AND the repo root itself must hold no
        on-disk dump — ignored-but-present is exactly the failure mode
        this test exists to catch."""
        root = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        if not os.path.isdir(os.path.join(root, ".git")):
            pytest.skip("not a git checkout")
        tracked = subprocess.run(
            ["git", "ls-files", "--cached", "*blackbox*"],
            cwd=root, capture_output=True, text=True).stdout.split()
        assert tracked == [], f"blackbox dumps are tracked: {tracked}"
        gitignore = open(os.path.join(root, ".gitignore")).read()
        assert "blackbox-host*.json" in gitignore.split()
        stranded = glob.glob(os.path.join(root, "blackbox-host*.json"))
        assert stranded == [], (
            f"stranded blackbox dumps at the repo root: {stranded} — "
            "crash-path tests must dump into tmp_path (fr.dump(path=...))"
            " and ad-hoc debugging runs must clean up after themselves")

    def test_dump_on_injected_collective_hang_names_the_site(self,
                                                             tmp_path):
        """The acceptance scenario: a faultline collective_sync hang
        kills the collective; the blackbox left behind must show the
        IN-FLIGHT collective span (a span_begin with no span_end) in
        its newest entries."""
        faultline.arm("collective_sync", action="hang")
        with pytest.raises(CollectiveTimeout):
            sync_sums([1.0])
        path = str(tmp_path / "blackbox-host0.json")
        assert os.path.exists(path)
        rec = _read_dump(path)
        assert rec["reason"] == "collective_timeout"
        tail = rec["entries"][-4:]
        begins = [e for e in tail if e["kind"] == "span_begin"
                  and e["name"].startswith("collective/")]
        assert begins, f"no in-flight collective in dump tail: {tail}"
        hung = begins[-1]["name"]
        ends = [e for e in tail if e["kind"] == "span_end"
                and e["name"] == hung]
        assert not ends, "the hung collective must have no span_end"
        # and the structured transition rode the ring too
        assert any(e["name"] == "collective_timeout" for e in tail)

    def test_dump_on_hang_mid_train_via_engine(self, tmp_path):
        """The full path: an armed hang inside a training run's metric
        sync surfaces CollectiveTimeout through lgb.train, and the
        blackbox names the collective plus the round it died in."""
        X, y = _problem()
        ds = lgb.Dataset(X, label=y, params=_P)
        dv = lgb.Dataset(X[:200], label=y[:200], reference=ds, params=_P)
        faultline.arm("collective_sync", action="hang", at=3,
                      absolute=True)
        with pytest.raises(CollectiveTimeout):
            lgb.train(dict(_P), ds, num_boost_round=6, valid_sets=[dv],
                      verbose_eval=False, keep_training_booster=True)
        rec = _read_dump(str(tmp_path / "blackbox-host0.json"))
        names = [e["name"] for e in rec["entries"]]
        assert any(n.startswith("collective/") for n in names)
        assert "train/round" in names  # the always-on per-round entry
        assert any(e["kind"] == "fault" for e in rec["entries"])

    def test_dump_on_sigterm_orders_after_checkpoint_flush(self,
                                                           tmp_path):
        """SIGTERM mid-train (the engine maps it to KeyboardInterrupt)
        must flush the PR-7 checkpoint FIRST, then dump the blackbox —
        proven by the dump's own metric snapshot carrying the flush's
        write counter."""
        import signal

        from lightgbm_tpu.utils.checkpoint import CheckpointManager

        ck = tmp_path / "ck"
        writes_before = obs.REGISTRY.value("lgbm_checkpoint_writes_total")

        def bomb(env):
            if env.iteration == 2:
                os.kill(os.getpid(), signal.SIGTERM)

        X, y = _problem()
        p = dict(_P, tpu_checkpoint_dir=str(ck))
        ds = lgb.Dataset(X, label=y, params=p)
        with pytest.raises(KeyboardInterrupt):
            lgb.train(p, ds, num_boost_round=8, callbacks=[bomb],
                      verbose_eval=False, keep_training_booster=True)
        found = CheckpointManager(str(ck)).load_latest()
        assert found is not None and found[0] >= 1
        rec = _read_dump(str(tmp_path / "blackbox-host0.json"))
        assert rec["reason"].startswith("train_interrupt")
        assert rec["exception"]["type"] == "KeyboardInterrupt"
        writes_in_dump = rec["metrics"].get(
            "lgbm_checkpoint_writes_total", 0)
        assert writes_in_dump > writes_before, (
            "the blackbox snapshot must include the final checkpoint "
            "flush — dump ran before the flush?")

    def test_dump_on_guard_raise(self, tmp_path):
        from lightgbm_tpu.booster import Booster
        from lightgbm_tpu.utils.log import LightGBMError

        X, y = _problem()
        p = dict(_P, tpu_guard_numerics="raise")
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        faultline.arm("grow_step", action="poison", at=2)
        with pytest.raises(LightGBMError):
            for _ in range(4):
                bst.update()
        rec = _read_dump(str(tmp_path / "blackbox-host0.json"))
        assert rec["reason"] == "guard_raise"
        assert any(e["name"] == "guard_poisoned"
                   for e in rec["entries"])

    def test_dump_on_unhandled_thread_exception(self, tmp_path):
        """sys.excepthook never fires for worker threads; the chained
        threading.excepthook must dump for the multithreaded serving
        runtime's deaths too."""
        def die():
            raise RuntimeError("worker died")

        t = threading.Thread(target=die, name="doomed")
        t.start()
        t.join()
        path = str(tmp_path / "blackbox-host0.json")
        assert os.path.exists(path)
        rec = _read_dump(path)
        assert rec["reason"] == "unhandled_thread_exception"
        crash = rec["entries"][-1]
        assert crash["fields"]["thread"] == "doomed"
        assert "worker died" in crash["fields"]["message"]

    def test_repeated_dumps_overwrite_in_place(self, tmp_path):
        fr.note("k", "first")
        fr.dump("one")
        fr.note("k", "second")
        path = fr.dump("two")
        rec = _read_dump(path)
        assert rec["reason"] == "two"
        assert [p for p in os.listdir(tmp_path)
                if p.startswith("blackbox-")] == ["blackbox-host0.json"]


# ---------------------------------------------------------------------------
# transition sources
# ---------------------------------------------------------------------------
class TestTransitions:
    def test_breaker_transitions_ring(self):
        from lightgbm_tpu.serving.stats import CircuitBreaker, ServingStats

        br = CircuitBreaker(threshold=2, cooldown_s=0.01,
                            stats=ServingStats())
        br.record_failure()
        br.record_failure()          # -> open
        time.sleep(0.02)
        assert br.allow()            # -> half_open
        br.record_success(br.generation)  # -> closed
        names = [e["name"] for e in fr.entries()
                 if e["kind"] == "breaker"]
        assert names == ["open", "half_open", "closed"]

    def test_trace_mode_mirrors_spans_into_ring(self):
        prev = obs.mode()
        obs.configure(mode="trace")
        try:
            with obs.span("train/iteration", iteration=7):
                pass
        finally:
            obs.configure(mode=prev or "off")
        spans = [e for e in fr.entries() if e["kind"] == "span"]
        assert any(e["name"] == "train/iteration" for e in spans)


# ---------------------------------------------------------------------------
# overhead: a ring note beside the existing <1% telemetry gate
# ---------------------------------------------------------------------------
class TestNoteOverhead:
    def test_note_cost_is_microseconds(self):
        """The always-on note must stay ring-cheap: recorded once per
        ROUND / collective / transition, so even a conservative 10us
        bound keeps it orders of magnitude under the 1% off-mode gate
        (training rounds are milliseconds at minimum)."""
        reps = 20000
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for i in range(reps):
                fr.note("bench", "train/round", iteration=i)
            best = min(best, (time.perf_counter() - t0) / reps)
        assert best < 10e-6, f"flight-recorder note costs {best * 1e6:.2f}us"
