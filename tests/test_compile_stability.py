"""Shape-stable programs (ROADMAP item 3 / ISSUE 6): the compile ledger,
grower memoization, buffer donation, and the launch-shape bucket policy.

The contract under test:

* a canonical binary train + predict + serve lifecycle on the default
  configuration compiles an EXACT, small set of ledgered programs;
* re-running an identical training in-process compiles nothing new (the
  grower/strategy memoization reuses the jitted executables);
* buffer donation (tpu_donate_buffers) is bit-invisible: model files are
  identical with donation on or off, serial and sharded, and the int8
  cross-shard-count bitwise guarantee survives with donation enabled
  (the existing slow shard sweeps in test_sharded_agg/test_quantized now
  run WITH donation by default — this file keeps a fast 1/2-shard gate);
* the serving registry dedupes warmup across same-shaped models: loading
  a second model with an equal warm signature adds ZERO compiled
  programs (asserted on the predict kernel's own jit cache);
* the `wide` bucket policy produces strictly fewer launch shapes than
  `fine`, through the ONE shared ladder in ops/predict.py;
* `tools/perf_probe.py retrace` (the tier-1 retrace smoke at the bottom)
  keeps the lifecycle's n_programs under a hard bound, so a PR that
  doubles the program zoo fails loudly instead of silently inflating
  compile_s.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner
from lightgbm_tpu.ops.grower import (GrowerParams, canonical_params,
                                     make_grower, mode_flags_np)
from lightgbm_tpu.ops.predict import (_depth_bucket, predict_row_buckets,
                                      row_bucket)
from lightgbm_tpu.utils.compile_ledger import LEDGER, ledger_jit


def _data(n=3100, f=9, seed=11):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.4 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


# deliberately off-beat shapes (47 bins, 13 leaves) so no other test
# module warms these jit caches first — the exact-count assertions
# depend on this file doing the first compile of its own configuration
P_LIFE = {"objective": "binary", "num_leaves": 13, "max_bin": 47,
          "min_data_in_leaf": 5, "tpu_block_rows": 512, "verbosity": -1}


@pytest.fixture
def ledger():
    LEDGER.enable()
    LEDGER.reset()
    try:
        yield LEDGER
    finally:
        LEDGER.enable(False)


class TestLedgerUnit:
    def test_counts_programs_not_calls(self, ledger):
        calls = []

        @ledger_jit(site="unit.f", static_argnames=("k",))
        def f(x, k: int):
            calls.append(1)
            return x * k

        f(jnp.ones(8), k=2)
        f(jnp.ones(8), k=2)          # cache hit: not a new program
        f(jnp.ones(8), k=3)          # new static value: new program
        f(jnp.ones(16), k=3)         # new aval: new program
        assert ledger.n_programs("unit.f") == 3
        rep = {a["site"]: a["programs"] for a in ledger.report()}
        assert rep["unit.f"] == 3

    def test_disabled_ledger_records_nothing(self):
        LEDGER.enable(False)
        LEDGER.reset()

        @ledger_jit(site="unit.g")
        def g(x):
            return x + 1

        g(jnp.ones(4))  # compiles, but the disabled ledger records nothing
        assert LEDGER.n_programs() == 0

    def test_wrapper_delegates_jit_internals(self):
        f = ledger_jit(lambda x: x * 2, site="unit.h")
        f(jnp.ones(4))
        # transparent delegation: the serving tests poke _cache_size()
        assert f._cache_size() >= 1


class TestBucketPolicy:
    def test_wide_ladder_is_strictly_smaller(self):
        chunk = 65536
        wide = predict_row_buckets(chunk, chunk, policy="wide")
        fine = predict_row_buckets(chunk, chunk, policy="fine")
        assert wide == [4096, 16384, 65536]
        assert fine == [1024, 2048, 4096, 8192, 16384, 32768, 65536]
        assert len(wide) < len(fine)
        # row_bucket lands every n on its policy's ladder
        for n in (1, 100, 4096, 4097, 20000, 65536, 70000):
            assert row_bucket(n, chunk, policy="wide") in wide
            assert row_bucket(n, chunk, policy="fine") in fine
            assert row_bucket(n, chunk, policy="wide") >= min(n, chunk)

    def test_depth_bucket_floors(self):
        assert [_depth_bucket(d, "wide") for d in (1, 3, 8, 9, 17)] == \
            [8, 8, 8, 16, 32]
        assert [_depth_bucket(d, "fine") for d in (1, 3, 8, 9, 17)] == \
            [1, 4, 8, 16, 32]

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError, match="tpu_bucket_policy"):
            row_bucket(10, 1024, policy="chunky")
        X, y = _data(600, 4)
        config = Config({"objective": "binary",
                         "tpu_bucket_policy": "chunky"})
        td = TrainingData.from_matrix(X, y, config)
        with pytest.raises(ValueError, match="tpu_bucket_policy"):
            TPUTreeLearner(config, td)

    def test_wide_ramp_step_halves_preround_count(self):
        X, y = _data(1200, 6, seed=3)
        cfg = dict(P_LIFE, tpu_split_batch=8)
        config_w = Config(dict(cfg, tpu_bucket_policy="wide"))
        lw = TPUTreeLearner(config_w,
                            TrainingData.from_matrix(X, y, config_w))
        config_f = Config(dict(cfg, tpu_bucket_policy="fine"))
        lf = TPUTreeLearner(config_f,
                            TrainingData.from_matrix(X, y, config_f))
        assert lw.params.ramp_step == 4 and lf.params.ramp_step == 2


class TestCanonicalParams:
    def test_folded_fields_share_one_grower(self):
        base = dict(num_leaves=7, num_bins=16, block_rows=256,
                    precision="hilo", l1=0.0, l2=1.0, max_delta_step=0.0,
                    min_data_in_leaf=1.0, min_sum_hessian=1e-3,
                    min_gain_to_split=0.0, max_depth=0)
        a = GrowerParams(**base, quant_round="stochastic",
                         cegb_tradeoff=1.0)
        b = GrowerParams(**base, quant_round="nearest", cegb_tradeoff=3.0)
        assert canonical_params(a) == canonical_params(b)
        # memoized: the SAME jitted callable comes back
        ga = make_grower(canonical_params(a), 4)
        gb = make_grower(canonical_params(b), 4)
        assert ga is gb

    def test_mode_flags_vector(self):
        mf = mode_flags_np(quant_round="nearest", quant_refit=True,
                           cegb_tradeoff=2.0, cegb_penalty_split=0.5)
        np.testing.assert_array_equal(mf, [0.0, 1.0, 2.0, 0.5])


class TestLifecycleProgramCounts:
    def test_exact_counts_and_train_twice_compiles_nothing(self, ledger):
        """The canonical binary train + predict + serve lifecycle on the
        default (serial, bucketed) configuration: EXACT ledgered program
        counts, and an identical re-train reuses every executable."""
        from lightgbm_tpu.serving import ServingSession

        X, y = _data()
        ds = lgb.Dataset(X, label=y, params=P_LIFE)
        bst = lgb.train(P_LIFE, ds, num_boost_round=3,
                        keep_training_booster=True)
        # ONE grow program for the whole training run
        assert ledger.n_programs("grower.grow") == 1
        after_train = ledger.n_programs()

        # identical second training: the memoized grower (and every
        # other ledgered site) reuses its compiled executables
        ds2 = lgb.Dataset(X, label=y, params=P_LIFE)
        lgb.train(P_LIFE, ds2, num_boost_round=3,
                  keep_training_booster=True)
        assert ledger.n_programs() == after_train, (
            "a second identical train() compiled new programs:\n"
            + ledger.format_report())

        # serve: warmup compiles exactly the wide policy's bucket ladder
        # (one 4096-row bucket) for the class-scores kernel
        sess = ServingSession(params={"serving_max_batch_rows": 4096,
                                      "verbosity": -1})
        sess.load("m", booster=bst)
        got = sess.predict("m", X[:37], raw_score=True)
        # tpu_predict_device pinned per call: an unqualified device="tpu"
        # on a CPU host would auto-veto to the native walker and the
        # comparison would be device-kernel vs f64 walker ulps
        np.testing.assert_array_equal(
            got, bst.predict(X[:37], raw_score=True, device="tpu",
                             tpu_predict_device="true"))
        serve_programs = ledger.n_programs()

        # ISSUE 11 gate: the whole overload/robustness layer is host-
        # side control flow — admission sheds, a priority predict, a
        # deadline-capped predict, a device failover onto the native
        # walker, and the drain lifecycle must compile ZERO new
        # programs on top of the warmed serve lifecycle
        from lightgbm_tpu.serving import ServingOverloaded
        from lightgbm_tpu.utils import faultline

        sess.predict("m", X[:23], priority="high", deadline_ms=30000)
        import time as _time

        sess.admission._level = 1.0  # force an admission shed
        sess.admission.min_level = 1  # bypass the one-batch floor
        # pin the lazy AIMD update past the test so it cannot re-open
        # the level before the shed lands
        sess.admission._next_update = _time.monotonic() + 60.0
        try:
            with pytest.raises(ServingOverloaded):
                sess.predict("m", X[:23], priority="low")
        finally:
            sess.admission._level = float(sess.admission.queue_rows)
            sess.admission.min_level = 4096
        faultline.reset()
        faultline.arm("serve_dispatch", action="raise", times=1)
        try:
            sess.predict("m", X[:23])  # served via walker failover
        finally:
            faultline.reset()
        assert sess.drain()["drained"] is True
        sess.close()
        assert ledger.n_programs() == serve_programs, (
            "admission/drain/failover compiled new programs:\n"
            + ledger.format_report())

        sites = {a["site"]: a["programs"] for a in ledger.report()}
        assert sites == {"grower.grow": 1, "predict.class_scores": 1}, \
            ledger.format_report()
        # the regression gate the tier-1 smoke enforces: the whole
        # lifecycle stays a countable handful of programs
        assert ledger.n_programs() <= 4


class TestDonationBitwise:
    def _model_text(self, X, y, **cfg):
        params = dict(P_LIFE, tpu_shape_buckets=0)
        params.update(cfg)
        ds = lgb.Dataset(X, label=y, params=params)
        bst = lgb.train(params, ds, num_boost_round=3,
                        keep_training_booster=True)
        return bst.model_to_string().split("\nparameters:")[0]

    def test_donation_is_bit_invisible_serial(self):
        X, y = _data(2048, 8, seed=5)
        on = self._model_text(X, y, tpu_donate_buffers=True)
        off = self._model_text(X, y, tpu_donate_buffers=False)
        assert on == off

    def test_int8_shard_bitwise_with_donation(self):
        """The PR-4/PR-5 guarantee with donation enabled: int8 model
        files bit-identical serial vs 2/4-shard scatter (the full
        1/2/4/8 sweep stays in test_sharded_agg's slow tier, which now
        also runs with donation by default)."""
        X, y = _data(2048, 8, seed=9)
        # refit off like the slow shard sweeps: the refit leaf psum is
        # the one f32 reduction whose shard-order ulps may reach values
        q = dict(tpu_hist_precision="int8", tpu_donate_buffers=True,
                 tpu_quant_refit_leaves=False)
        serial = self._model_text(X, y, **q)
        for shards in (2,):
            sharded = self._model_text(X, y, tree_learner="data",
                                       num_machines=shards, **q)
            assert serial == sharded, f"int8 mismatch at {shards} shards"
        # and donation itself changed nothing
        off = self._model_text(X, y, **{**q, "tpu_donate_buffers": False})
        assert serial == off

    def test_quant_round_mode_rides_one_program(self, ledger):
        """The traced rounding-mode flag: nearest vs stochastic share
        ONE grow program (previously distinct static closures) and still
        produce different (mode-correct) models."""
        X, y = _data(1600, 7, seed=13)
        # refit off: refit recomputes leaf values from TRUE f32 sums, so
        # with identical structures the two modes' models could coincide
        params = dict(P_LIFE, tpu_hist_precision="int16",
                      tpu_quant_refit_leaves=False)

        def run(round_mode):
            p = dict(params, tpu_quant_round=round_mode)
            ds = lgb.Dataset(X, label=y, params=p)
            bst = lgb.train(p, ds, num_boost_round=2,
                            keep_training_booster=True)
            return bst.model_to_string().split("\nparameters:")[0]

        a = run("stochastic")
        grower_programs = ledger.n_programs("grower.grow")
        b = run("nearest")
        assert ledger.n_programs("grower.grow") == grower_programs, \
            "flipping tpu_quant_round compiled a NEW grow program"
        assert a != b  # the traced flag actually changes the rounding


class TestCheckpointRetrace:
    def test_checkpointed_train_and_resume_add_zero_programs(
            self, ledger, tmp_path):
        """Bench hygiene (ISSUE 7): interval checkpointing is pure host
        IO + device_get — a checkpointed train (and a resumed one) must
        add ZERO programs to the CompileLedger beyond what the identical
        un-checkpointed train compiles."""
        X, y = _data(1400, 6, seed=17)
        ds = lgb.Dataset(X, label=y, params=P_LIFE)
        lgb.train(P_LIFE, ds, num_boost_round=3,
                  keep_training_booster=True)
        base = ledger.n_programs()

        p = dict(P_LIFE, tpu_checkpoint_dir=str(tmp_path),
                 tpu_checkpoint_interval=1)
        ds2 = lgb.Dataset(X, label=y, params=p)
        lgb.train(p, ds2, num_boost_round=3, keep_training_booster=True)
        assert ledger.n_programs() == base, (
            "checkpointing compiled new programs:\n"
            + ledger.format_report())

        ds3 = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds3, num_boost_round=5,
                        keep_training_booster=True, resume=True)
        assert bst.num_trees() == 5
        assert ledger.n_programs() == base, (
            "checkpoint resume compiled new programs:\n"
            + ledger.format_report())


class TestServingWarmupDedupe:
    def test_second_same_shaped_model_adds_zero_programs(self):
        from lightgbm_tpu.ops.predict import _class_scores_kernel
        from lightgbm_tpu.serving import ServingSession

        X, y = _data(1500, 6, seed=21)

        def train_one():
            p = dict(P_LIFE)
            ds = lgb.Dataset(X, label=y, params=p)
            return lgb.train(p, ds, num_boost_round=3,
                             keep_training_booster=True)

        b1, b2 = train_one(), train_one()
        sess = ServingSession(params={"serving_max_batch_rows": 2048,
                                      "verbosity": -1})
        sess.load("m1", booster=b1)
        before = _class_scores_kernel._cache_size()
        st1 = sess.stats()
        sess.load("m2", booster=b2)  # equal warm signature
        assert _class_scores_kernel._cache_size() == before, \
            "a same-shaped second model compiled new predict programs"
        # the dedupe also skipped the warmup device launches, but the
        # shape accounting still covers m2: its first real predict is a
        # cache HIT, not a miss
        assert sess.stats()["compiles_warmup"] > st1["compiles_warmup"]
        got = sess.predict("m2", X[:33], raw_score=True)
        np.testing.assert_array_equal(
            got, b2.predict(X[:33], raw_score=True, device="tpu",
                            tpu_predict_device="true"))
        assert sess.stats()["compile_cache_misses"] == 0
        assert _class_scores_kernel._cache_size() == before
        sess.close()


class TestRetraceSmoke:
    """The tier-1 wiring for `tools/perf_probe.py retrace`: the canonical
    lifecycle audit runs as a fast smoke, so a future PR that doubles
    n_programs fails HERE instead of silently inflating compile_s in the
    next bench round."""

    def test_retrace_lifecycle_bounds(self):
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "_perf_probe", os.path.join(root, "tools", "perf_probe.py"))
        probe = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(probe)
        try:
            phases, total = probe.run_retrace(n=2000, f=6, leaves=7,
                                              bins=31, iters=2)
        finally:
            LEDGER.enable(False)
        # an identical second train compiles NOTHING
        labels = list(phases)
        deltas = {}
        prev = 0
        for label in labels:
            deltas[label] = phases[label] - prev
            prev = phases[label]
        assert deltas["second identical train"] == 0, phases
        # a same-shaped second serving model adds at most the batcher's
        # own bucket (it must not re-compile the first model's shapes)
        assert deltas["serve (2 same-shaped models)"] <= 1, phases
        # the hard regression gate: the whole lifecycle is a handful of
        # programs — double the zoo and this fails loudly
        assert total <= 6, (phases, total)
