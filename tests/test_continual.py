"""Continual learning (ISSUE 17): the drift-triggered train-behind-serve
loop with shadow-gated zero-downtime promotion.

The contract under test:

* `RowBuffer` bins streaming rows through the model's FROZEN training
  mappers bit-for-bit (vs the per-column `values_to_bins` oracle), into
  the PR-16 `[G, rows]` C-contiguous block layout, under a bounded
  retention window with freshness-decayed raw reads;
* `ContinualTrainer` fires triggers in priority order (drift > rows >
  interval) only past `tpu_continual_min_rows`, and policy `auto` maps
  drift -> boost (escalating to resketch on tail-bin saturation) and
  everything else -> refit;
* `Booster.refit` carries the model-health profile trailer forward and
  RECAPTURES the score histogram on the refit window (satellite 1);
* `lgbm_drift_warn_active{model}` is a pollable gauge twin of the PSI
  warning: 1 while warned, 0 once re-armed, gone after unload
  (satellite 2);
* the shadow gate defers on HBM headroom (nothing touched the device),
  refuses + unloads worse candidates (alias untouched), and promotes
  via an atomic alias flip that a concurrent 16-thread hammer never
  observes as an error, with a post-promote regression auto-rolling
  back — the E2E acceptance flow;
* an int8/int16 warm continue (`init_model`) stays BITWISE identical
  across 1/2/4 data-parallel shards (satellite 3, slow);
* a steady-state refit cycle (same-shaped candidate) compiles ZERO new
  XLA programs: retrain, shadow load (warm-signature dedupe), verdict
  scoring, promotion, and post-promote predicts all reuse the warmed
  caches — the compile-ledger acceptance gate.
"""

import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.continual import (ContinualController, ContinualTrainer,
                                    RowBuffer, shadow_verdict)
from lightgbm_tpu.continual.promote import promote_candidate, rollback
from lightgbm_tpu.serving import ServingSession
from lightgbm_tpu.utils import faultline, membudget
from lightgbm_tpu.utils.compile_ledger import LEDGER

_P = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
      "min_data_in_leaf": 5, "tpu_block_rows": 512, "verbosity": -1}


def _problem(n=800, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _train(X, y, params=None, rounds=5, **kw):
    p = dict(_P, **(params or {}))
    ds = lgb.Dataset(X, label=y, params=p)
    return lgb.train(p, ds, num_boost_round=rounds, verbose_eval=False,
                     **kw)


def _ccfg(**over):
    return Config({"verbosity": -1, **over})


@pytest.fixture(autouse=True)
def _faultline_isolation():
    faultline.reset()
    yield
    faultline.reset()


@pytest.fixture(scope="module")
def base_model():
    X, y = _problem(n=800, seed=1)
    return _train(X, y), X, y


# ---------------------------------------------------------------------------
# RowBuffer: frozen-mapper binning, block layout, retention
# ---------------------------------------------------------------------------
class TestRowBuffer:
    def test_bins_match_mapper_oracle_in_block_layout(self, base_model):
        bst, X, _ = base_model
        buf = RowBuffer(bst, _ccfg())
        rng = np.random.default_rng(2)
        Xq = rng.normal(size=(257, X.shape[1])) * 2.0
        buf.ingest(Xq)
        blocks = buf.host_blocks()
        assert len(blocks) == 1
        blk = blocks[0]
        assert blk.flags["C_CONTIGUOUS"]
        ctx = bst._driver._pred_context()
        used = [int(c) for c in ctx.used_feature_idx]
        assert blk.shape == (len(used), 257)
        for j, c in enumerate(used):
            oracle = ctx.mappers[c].values_to_bins(
                np.ascontiguousarray(Xq[:, c]))
            np.testing.assert_array_equal(blk[j], oracle)

    def test_retention_window_evicts_oldest(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg(tpu_continual_buffer_rows=100))
        for lo in (0, 60, 120):
            buf.ingest(X[lo:lo + 60], y[lo:lo + 60])
        # 180 ingested, window 100: two oldest blocks evicted
        assert buf.rows == 60
        assert buf.ingested_total == 180
        Xw, yw, _ = buf.raw()
        np.testing.assert_array_equal(Xw, X[120:180])
        np.testing.assert_array_equal(yw, y[120:180])

    def test_raw_freshness_decay_newest_block_weighs_one(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        for lo in (0, 10, 20):
            buf.ingest(X[lo:lo + 10], y[lo:lo + 10])
        _, _, w = buf.raw(fresh_decay=0.5)
        np.testing.assert_allclose(w[:10], 0.25)
        np.testing.assert_allclose(w[10:20], 0.5)
        np.testing.assert_allclose(w[20:], 1.0)

    def test_any_unlabeled_block_means_no_labels(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        buf.ingest(X[:20], y[:20])
        buf.ingest(X[20:40])                     # unlabeled
        _, yw, _ = buf.raw()
        assert yw is None

    def test_tail_fraction_saturates_on_off_range_values(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        buf.ingest(X[:100], y[:100])
        assert buf.tail_fraction() < 0.5
        buf.drain()
        buf.ingest(np.full((50, X.shape[1]), 1e6))
        assert buf.tail_fraction() == 1.0

    def test_host_blocks_repartition(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        for lo in (0, 100, 200):
            buf.ingest(X[lo:lo + 100], y[lo:lo + 100])
        whole = np.concatenate(buf.host_blocks(), axis=1)
        parts = buf.host_blocks(stream_rows=128)
        assert all(b.shape[1] <= 128 for b in parts)
        assert all(b.flags["C_CONTIGUOUS"] for b in parts)
        np.testing.assert_array_equal(
            np.concatenate(parts, axis=1), whole)

    def test_drain_and_width_validation(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        buf.ingest(X[:30], y[:30])
        assert buf.drain() == 30
        assert buf.rows == 0
        with pytest.raises(ValueError, match="width"):
            buf.ingest(X[:5, :3])

    def test_reference_shim_carries_frozen_mappers(self, base_model):
        bst, X, _ = base_model
        buf = RowBuffer(bst, _ccfg())
        ref = buf.reference_data()
        ctx = bst._driver._pred_context()
        assert ref.used_feature_idx == [int(c) for c in
                                        ctx.used_feature_idx]
        assert ref.num_total_features == bst.num_feature()
        assert ref.mappers is ctx.mappers


# ---------------------------------------------------------------------------
# trainer: triggers and policies
# ---------------------------------------------------------------------------
class _StubBuffer:
    def __init__(self, rows=0, ingested=0, retain=1000, tail=0.0):
        self.rows = rows
        self.ingested_total = ingested
        self.retain_rows = retain
        self._tail = tail

    def tail_fraction(self):
        return self._tail


class TestTrainerPolicy:
    def test_min_rows_gates_every_trigger(self):
        t = ContinualTrainer(_StubBuffer(rows=10),
                             _ccfg(tpu_continual_min_rows=100))
        assert t.pending_trigger(drift_warn=True) is None

    def test_trigger_priority_drift_over_rows(self):
        buf = _StubBuffer(rows=500, ingested=2000, retain=500)
        t = ContinualTrainer(buf, _ccfg(tpu_continual_min_rows=100))
        assert t.pending_trigger(drift_warn=True) == "drift"
        assert t.pending_trigger(drift_warn=False) == "rows"

    def test_interval_trigger(self):
        buf = _StubBuffer(rows=500, ingested=500, retain=10_000)
        t = ContinualTrainer(buf, _ccfg(tpu_continual_min_rows=100,
                                        tpu_continual_interval_s=0.01))
        assert t.pending_trigger(drift_warn=False) is None
        time.sleep(0.02)
        assert t.pending_trigger(drift_warn=False) == "interval"

    def test_auto_policy_mapping(self):
        cfg = _ccfg(tpu_continual_resketch_tail_frac=0.25)
        t = ContinualTrainer(_StubBuffer(tail=0.1), cfg)
        assert t.choose_policy("drift") == "boost"
        assert t.choose_policy("rows") == "refit"
        assert t.choose_policy("interval") == "refit"
        t2 = ContinualTrainer(_StubBuffer(tail=0.3), cfg)
        assert t2.choose_policy("drift") == "resketch"

    def test_pinned_policy_wins(self):
        t = ContinualTrainer(_StubBuffer(tail=0.9),
                             _ccfg(tpu_continual_policy="refit"))
        assert t.choose_policy("drift") == "refit"
        with pytest.raises(ValueError, match="tpu_continual_policy"):
            ContinualTrainer(_StubBuffer(),
                             _ccfg(tpu_continual_policy="bogus"))

    def test_unlabeled_window_raises(self, base_model):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        buf.ingest(X[:300])                      # no labels
        t = ContinualTrainer(buf, _ccfg(tpu_continual_min_rows=100))
        with pytest.raises(ValueError, match="no labels"):
            t.retrain(bst, "rows")

    def test_all_three_retrain_paths_produce_usable_models(
            self, base_model, tmp_path):
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        buf.ingest(X[:400], y[:400])
        for policy, check in (
                ("refit", lambda c: c.num_trees() == bst.num_trees()),
                ("boost", lambda c: c.num_trees() == bst.num_trees() + 2),
                ("resketch", lambda c: c.num_trees() ==
                 bst.num_trees() + 2)):
            t = ContinualTrainer(buf, _ccfg(
                tpu_continual_policy=policy,
                tpu_continual_boost_rounds=2,
                tpu_continual_dir=str(tmp_path)),
                params={"verbosity": -1})
            cand, used = t.retrain(bst, "rows")
            assert used == policy
            assert check(cand)
            pred = np.asarray(cand.predict(X[:50]))
            assert np.isfinite(pred).all()
        # a COMPLETED boost retrain leaves no checkpoints behind for a
        # later run to masquerade-resume from
        assert not (tmp_path / "retrain").exists()

    def test_boost_keeps_frozen_bins(self, base_model):
        """The boost continue's new trees split on the SAME bin edges
        the buffer ingests through: thresholds of continued trees stay
        inside the frozen mappers' upper bounds."""
        bst, X, y = base_model
        buf = RowBuffer(bst, _ccfg())
        buf.ingest(X[:400], y[:400])
        t = ContinualTrainer(buf, _ccfg(tpu_continual_policy="boost",
                                        tpu_continual_boost_rounds=2),
                             params={"verbosity": -1})
        cand, _ = t.retrain(bst, "rows")
        ctx = bst._driver._pred_context()
        for tree in cand._driver.models[bst.num_trees():]:
            ni = tree.num_leaves - 1
            for f, thr in zip(tree.split_feature[:ni],
                              tree.threshold_in_bin[:ni]):
                assert 0 <= int(thr) < ctx.mappers[int(f)].num_bin


# ---------------------------------------------------------------------------
# satellite 1: refit profile carry-forward + score recapture
# ---------------------------------------------------------------------------
class TestRefitProfileCarryForward:
    def _trailer(self, model_str):
        lines = [ln for ln in model_str.splitlines()
                 if ln.startswith("tpu_feature_profile:")]
        return lines[0] if lines else None

    def test_loaded_booster_refit_no_crash(self, base_model, tmp_path):
        bst, X, y = base_model
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        loaded = lgb.Booster(model_file=path)
        out = loaded.refit(X[:300], y[:300], decay_rate=0.9)
        pred = np.asarray(out.predict(X[:50]))
        assert np.isfinite(pred).all()
        assert out.num_trees() == bst.num_trees()

    def test_loaded_booster_boost_continue(self, base_model, tmp_path):
        # a file-loaded booster round-trips its objective in
        # model-string form ('binary sigmoid:1') and carries metadata
        # keys; the boost path must normalize both instead of handing
        # them straight back to engine.train
        bst, X, y = base_model
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        loaded = lgb.Booster(model_file=path)
        cfg = _ccfg(tpu_continual_policy="boost",
                    tpu_continual_min_rows=64,
                    tpu_continual_boost_rounds=2)
        buf = RowBuffer(loaded, cfg)
        buf.ingest(X[:400], y[:400])
        tr = ContinualTrainer(buf, config=cfg)
        cand, policy = tr.retrain(loaded, "drift")
        assert policy == "boost"
        assert cand.num_trees() == bst.num_trees() + 2
        assert np.isfinite(np.asarray(cand.predict(X[:50]))).all()

    def test_refit_keeps_trailer_and_recaptures_scores(self, base_model):
        bst, X, y = base_model
        base_prof = bst._driver.health_profile()
        assert base_prof is not None
        # refit on a SHIFTED window: leaf values move, so the score
        # histogram must be recaptured (a stale baseline would flag the
        # refit model's own outputs as drift)
        out = bst.refit(X[:300] + 1.0, y[:300], decay_rate=0.5)
        trailer = self._trailer(out.model_to_string())
        assert trailer is not None, "refit dropped the profile trailer"
        prof = out._driver.health_profile()
        assert prof is not None
        assert prof.score_counts != base_prof.score_counts
        # each class row of the recaptured histogram covers the refit
        # window exactly
        for row in prof.score_counts:
            assert sum(row) == 300
        # feature occupancy (training-data facts) carries forward
        assert set(prof.features) == set(base_prof.features)
        for c in prof.features:
            assert prof.features[c]["cnt"] == base_prof.features[c]["cnt"]

    def test_refit_model_serves_with_drift_monitor(self, base_model):
        bst, X, y = base_model
        out = bst.refit(X[:300], y[:300])
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("r", booster=out)
            assert sess.registry.resolve("r").drift is not None
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# satellite 2: the lgbm_drift_warn_active gauge
# ---------------------------------------------------------------------------
class TestDriftWarnGauge:
    def test_gauge_sets_rearms_and_clears(self, base_model):
        bst, X, y = base_model
        sess = ServingSession(params={
            "serving_drift_sample_rows": 256,
            "serving_drift_psi_warn": 0.25, "verbosity": -1},
            start=False)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            entry.predict(X[:200] + 2.5)         # shifted traffic
            entry.drift.snapshot()
            text = sess._stats.to_prometheus_text()
            assert 'lgbm_drift_warn_active{model="m@1"} 1' in text
            assert entry.drift.warn_active()
            # a clean flood dilutes cumulative PSI below the warn
            # line: the gauge re-arms.  Fresh draws, not replays — a
            # repeated fixed subset keeps its finite-sample divergence
            # vs the training baseline forever
            rng = np.random.default_rng(42)
            for _ in range(24):
                entry.predict(rng.normal(size=(256, X.shape[1])))
            entry.drift.snapshot()
            text = sess._stats.to_prometheus_text()
            assert 'lgbm_drift_warn_active{model="m@1"} 0' in text
            assert not entry.drift.warn_active()
            sess.unload("m")
            assert "lgbm_drift_warn_active{" not in \
                sess._stats.to_prometheus_text()
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# the shadow gate: defer / refuse / promote / rollback
# ---------------------------------------------------------------------------
class TestPromotionGate:
    def test_verdict_promotes_better_refuses_worse(self, base_model):
        bst, X, y = base_model
        rng = np.random.default_rng(5)
        yb = y.copy()
        rng.shuffle(yb)
        worse = _train(X, yb)
        v = shadow_verdict(bst, worse, X[:300], y[:300])
        assert v["verdict"] == "refuse"
        assert v["candidate_loss"] > v["live_loss"]
        v2 = shadow_verdict(bst, bst, X[:300], y[:300])
        assert v2["verdict"] == "promote"
        v3 = shadow_verdict(bst, worse, X[:300])
        assert v3["verdict"] == "no-labels"

    def test_refused_candidate_is_unloaded_alias_untouched(
            self, base_model):
        bst, X, y = base_model
        rng = np.random.default_rng(6)
        yb = y.copy()
        rng.shuffle(yb)
        worse = _train(X, yb)
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            res = promote_candidate(sess.registry, "m", worse,
                                    X[:300], y[:300])
            assert res["status"] == "refused"
            assert sess.registry.resolve("m").key == "m@1"
            with pytest.raises(KeyError):
                sess.registry.resolve("m.shadow")
        finally:
            sess.close()

    def test_promote_flips_alias_and_rollback_restores(self, base_model):
        bst, X, y = base_model
        better = _train(X, y, rounds=10)
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            res = promote_candidate(sess.registry, "m", better,
                                    X[:300], y[:300], tolerance=0.5)
            assert res["status"] == "promoted"
            assert res["prev_key"] == "m@1"
            assert sess.registry.resolve("m").key == res["shadow_key"]
            assert res["shadow_key"].startswith("m.shadow@")
            rollback(sess.registry, "m", res["prev_key"],
                     res["shadow_key"], "test")
            assert sess.registry.resolve("m").key == "m@1"
            with pytest.raises(KeyError):
                sess.registry.resolve(res["shadow_key"])
        finally:
            sess.close()

    def test_no_headroom_defers_without_touching_the_alias(
            self, base_model):
        bst, X, y = base_model
        plan = membudget.plan_model_load(bst, Config({"verbosity": -1}))
        assert plan is not None
        tables = plan.components.get("packed_tables", 0)
        assert tables > 0
        # budget admits the live model but NOT joint live+candidate
        # residency (launch scratch reserves once — dispatches
        # serialize — so the squeeze must come from TABLE bytes); the
        # live alias is never an eviction victim, so the gate must
        # defer before anything touches the device
        sess = ServingSession(params={
            "serving_hbm_budget_bytes": int(plan.total + tables // 2),
            "verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            res = promote_candidate(sess.registry, "m", bst,
                                    X[:300], y[:300])
            assert res["status"] == "deferred"
            assert "short" in res["reason"]
            assert sess.registry.resolve("m").key == "m@1"
            with pytest.raises(KeyError):
                sess.registry.resolve("m.shadow")
        finally:
            sess.close()

    def test_injected_fault_at_shadow_load_is_contained(self, base_model):
        """An armed continual_shadow_load fault surfaces as a counted
        deferral through the controller, never an exception."""
        bst, X, y = base_model
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            ctl = ContinualController(
                sess, "m",
                config=_ccfg(tpu_continual_min_rows=64,
                             tpu_continual_interval_s=0.001,
                             tpu_continual_policy="refit"),
                params={"verbosity": -1})
            ctl.observe(X[:256], y[:256])
            time.sleep(0.01)
            faultline.arm("continual_shadow_load", action="oom")
            res = ctl.step()
            assert res["status"] == "deferred"
            assert sess.registry.resolve("m").key == "m@1"
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# the E2E acceptance flow
# ---------------------------------------------------------------------------
class TestContinualAcceptance:
    def test_drift_to_promotion_to_rollback_under_hammer(self):
        """Shifted traffic crosses psi_warn; the rows trigger exercises
        the refit path and the drift trigger the boost path; the gate
        refuses a worse candidate and promotes a better one; a 16-thread
        hammer sees every request answered exactly once with zero errors
        across both alias flips; an injected post-promote regression
        auto-rolls back."""
        X, y = _problem(n=1200, seed=11)
        live = _train(X, y, rounds=6)
        rng = np.random.default_rng(5)
        yb = y.copy()
        rng.shuffle(yb)
        worse = _train(X, yb, rounds=6)          # the gate's punchbag
        sess = ServingSession(params={
            "serving_max_batch_rows": 512,
            "serving_drift_sample_rows": 256,
            "serving_drift_psi_warn": 0.25, "verbosity": -1})
        cfg = _ccfg(tpu_continual_buffer_rows=600,
                    tpu_continual_min_rows=256,
                    tpu_continual_policy="auto",
                    tpu_continual_boost_rounds=3,
                    tpu_continual_shadow_rows=256,
                    tpu_continual_tolerance=0.25,
                    tpu_continual_resketch_tail_frac=0.9)
        ok = [0] * 16
        err = [0] * 16
        stop = threading.Event()
        # the hammer serves whatever "live traffic" currently looks
        # like; phase transitions swap this pool in place, the way a
        # real distribution shift hits every request, not a side
        # channel.  Each request slides a fresh window over the pool —
        # replaying one fixed batch would pin the drift monitor's
        # cumulative occupancy to that subset's finite-sample noise
        traffic = [X]

        def _hammer(w):
            k = 0
            while not stop.is_set():
                pool = traffic[0]
                lo = (37 * w + 32 * k) % (len(pool) - 32)
                k += 1
                try:
                    out = sess.predict("m", pool[lo:lo + 32],
                                       raw_score=True)
                    # answered exactly once: one result per request,
                    # row-complete and finite
                    if len(np.asarray(out)) == 32 and \
                            np.isfinite(np.asarray(out)).all():
                        ok[w] += 1
                    else:
                        err[w] += 1
                except Exception:
                    err[w] += 1

        def _pump_until_warn(Xp):
            """Predict `Xp` until the cumulative sampled occupancy
            crosses psi_warn on the CURRENT live entry (bounded: the
            hammer is pushing the same distribution concurrently)."""
            for _ in range(300):
                sess.predict("m", Xp)
                models = sess.drift().get("models", {})
                if any(m["warn"] for m in models.values()):
                    return
            pytest.fail("psi_warn never crossed on shifted traffic")

        threads = [threading.Thread(target=_hammer, args=(w,))
                   for w in range(16)]
        try:
            sess.load("m", booster=live)
            for t in threads:
                t.start()
            ctl = ContinualController(sess, "m", config=cfg,
                                      params={"verbosity": -1})
            # -- phase A: a full window of clean rows -> rows trigger,
            # auto policy -> refit -> promote
            ctl.observe(X[:600], y[:600])
            ra = ctl.step()
            assert ra["status"] == "promoted", f"refit cycle failed: {ra}"
            assert ra["trigger"] == "rows" and ra["policy"] == "refit"
            key_a = sess.registry.resolve("m").key
            assert key_a.startswith("m.shadow@")
            # drain the post-promote watch with clean idle cycles
            for _ in range(3):
                assert ctl.step()["status"] == "idle"
            # -- gate check: a label-permuted candidate is refused and
            # the alias does not move
            assert promote_candidate(sess.registry, "m", worse, X[:256],
                                     y[:256])["status"] == "refused"
            assert sess.registry.resolve("m").key == key_a
            # -- phase B: covariate-shifted traffic crosses psi_warn ->
            # drift trigger, auto policy -> boost -> promote (the
            # candidate trained on the shifted window beats the clean
            # live model on shifted shadow rows)
            Xsh = X[:600] + 2.0
            ysh = (Xsh[:, 0] + 0.5 * Xsh[:, 1] > 0).astype(np.float64)
            traffic[0] = Xsh
            _pump_until_warn(Xsh[:512])
            ctl.observe(Xsh, ysh)
            rb = ctl.step()
            assert rb["status"] == "promoted", f"boost cycle failed: {rb}"
            assert rb["trigger"] == "drift" and rb["policy"] == "boost"
            key_b = sess.registry.resolve("m").key
            assert key_b != key_a
            # -- phase C: a post-promote regression inside the watch
            # window (traffic walks far off the candidate's own
            # training distribution): the controller rolls the alias
            # back to the displaced version on its own
            traffic[0] = X + 8.0
            sess.predict("m", X[:512] + 8.0)
            rc = ctl.step()
            assert rc["status"] == "rolled_back", f"no rollback: {rc}"
            assert rc["reason"] == "drift_regression"
            assert sess.registry.resolve("m").key == key_a
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
            sess.close()
        assert sum(err) == 0, f"hammer saw {sum(err)} failed requests"
        assert sum(ok) > 0


# ---------------------------------------------------------------------------
# satellite 3: warm continue stays bitwise across shard counts
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestShardedWarmContinue:
    @pytest.mark.parametrize("prec", ["int8", "int16"])
    def test_init_model_continue_bitwise_across_shards(self, prec):
        """+K rounds continued from the same base model emit BITWISE
        identical model files at 1, 2, and 4 data-parallel shards for
        the quantized precisions (int32 histogram sums are associative;
        refit-leaves off keeps f32 psum order out of the model)."""
        rng = np.random.default_rng(7)
        X = rng.normal(size=(4096, 8))
        y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
        base_p = dict(_P, tpu_hist_precision=prec,
                      tpu_quant_refit_leaves=False,
                      tpu_shape_buckets=0)
        base = lgb.train(base_p, lgb.Dataset(X, label=y, params=base_p),
                         num_boost_round=3, verbose_eval=False)
        texts = []
        for shards in (1, 2, 4):
            p = dict(base_p)
            if shards > 1:
                p.update(tree_learner="data", num_machines=shards)
            ds = lgb.Dataset(X, label=y, params=p)
            cont = lgb.train(p, ds, num_boost_round=3, init_model=base,
                             verbose_eval=False)
            assert cont.num_trees() == 6
            texts.append(cont.model_to_string().split(
                "\nparameters:")[0])
        assert texts[0] == texts[1] == texts[2]


# ---------------------------------------------------------------------------
# the compile-ledger acceptance gate
# ---------------------------------------------------------------------------
# off-beat shapes (17 leaves / 53 bins): no other suite warms these jit
# caches, so the steady-state zero-new-programs assertion is about THIS
# lifecycle's reuse, not another test's leftovers
P_LEDGER = {"objective": "binary", "num_leaves": 17, "max_bin": 53,
            "min_data_in_leaf": 5, "tpu_block_rows": 512,
            "verbosity": -1}


class TestContinualCompileStability:
    def test_steady_state_refit_cycle_compiles_nothing(self):
        """Cycle 1 warms every stage (retrain, shadow load + warmup,
        verdict scoring, promotion, serving predicts).  Cycle 2 — a
        same-shaped refit candidate through the same gate — must compile
        ZERO new programs: refit preserves tree shapes, the registry's
        warm-signature dedupe skips the shadow warmup, and every predict
        rides the warmed launch buckets."""
        rng = np.random.default_rng(23)
        X = rng.normal(size=(1024, 6))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
        live = lgb.train(P_LEDGER,
                         lgb.Dataset(X, label=y, params=P_LEDGER),
                         num_boost_round=4, verbose_eval=False)
        cfg = _ccfg(tpu_continual_buffer_rows=512,
                    tpu_continual_min_rows=256,
                    tpu_continual_policy="refit",
                    tpu_continual_shadow_rows=256,
                    tpu_continual_tolerance=10.0)
        # Drift monitors off: the test replays the same fixed 64-row
        # batch, whose cumulative finite-sample PSI would otherwise pin
        # above the warn bar and roll the cycle-2 promotion back.  The
        # cycle trigger here is rows-based; drift plays no part.
        sess = ServingSession(params={"serving_drift_sample_rows": 0,
                                      "verbosity": -1})
        LEDGER.enable()
        LEDGER.reset()
        try:
            sess.load("m", booster=live)
            sess.predict("m", X[:64])
            ctl = ContinualController(sess, "m", config=cfg,
                                      params={"verbosity": -1})
            # cycle 1: warm the full lifecycle
            ctl.observe(X[:512], y[:512])
            r1 = ctl.step()
            assert r1["status"] == "promoted", f"warm cycle failed: {r1}"
            sess.predict("m", X[:64])
            warmed = LEDGER.n_programs()
            # cycle 2: the steady state — same shapes end to end
            ctl.observe(X[512:1024], y[512:1024])
            r2 = ctl.step()
            assert r2["status"] == "promoted", \
                f"steady-state cycle failed: {r2}"
            assert r2["policy"] == "refit"
            sess.predict("m", X[:64])
            assert LEDGER.n_programs() == warmed, (
                "a steady-state refit promotion compiled "
                f"{LEDGER.n_programs() - warmed} new program(s); "
                "same-shaped candidates must ride the warmed caches")
        finally:
            LEDGER.enable(False)
            sess.close()
