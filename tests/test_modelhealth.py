"""Model & data health (ISSUE 14): PSI/JS float64 oracle equality, the
tpu_feature_profile: trailer byte-identity round trip (save -> load ->
registry load -> checkpoint resume), the drift-injected warn -> shadow
-> refuse promotion flow, and the training-telemetry <->
feature_importance cross-check."""

import json
import os
import sys
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import modelhealth

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

# max_bin 63 (not the 31 most suites share): padded launch shapes stay
# distinct from tests that assert on NEWLY-compiled programs later in
# the alphabet (test_resources' ledger-capture smoke trains the shared
# shape and must still see a fresh compile)
_P = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
      "min_data_in_leaf": 5, "verbosity": -1}


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    yield
    obs.configure(mode="off", trace_dir="")
    obs.flush()
    obs.reset_events()


def _problem(n=600, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _train(X, y, params=None, rounds=5, **kw):
    p = dict(_P, **(params or {}))
    ds = lgb.Dataset(X, label=y, params=p)
    return lgb.train(p, ds, num_boost_round=rounds, verbose_eval=False,
                     **kw)


def _trailer(model_str):
    lines = [ln for ln in model_str.splitlines()
             if ln.startswith("tpu_feature_profile:")]
    return lines[0] if lines else None


# ---------------------------------------------------------------------------
# divergences: independent float64 oracles
# ---------------------------------------------------------------------------
def _oracle_psi(e, o):
    e = np.asarray(e, np.float64) + 0.5
    o = np.asarray(o, np.float64) + 0.5
    ep = e / e.sum()
    op = o / o.sum()
    return float(np.sum((op - ep) * np.log(op / ep)))


def _oracle_js(e, o):
    p = np.asarray(e, np.float64)
    q = np.asarray(o, np.float64)
    p = p / p.sum()
    q = q / q.sum()
    m = 0.5 * (p + q)
    acc = 0.0
    for pi, qi, mi in zip(p, q, m):
        if pi > 0:
            acc += 0.5 * pi * np.log(pi / mi)
        if qi > 0:
            acc += 0.5 * qi * np.log(qi / mi)
    return float(acc)


class TestDivergences:
    def test_psi_js_match_oracle(self):
        rng = np.random.default_rng(1)
        for _ in range(20):
            b = rng.integers(2, 40)
            e = rng.integers(0, 1000, size=b)
            o = rng.integers(0, 1000, size=b)
            if e.sum() == 0 or o.sum() == 0:
                continue
            assert abs(modelhealth.psi(e, o) - _oracle_psi(e, o)) < 1e-12
            assert abs(modelhealth.js_divergence(e, o)
                       - _oracle_js(e, o)) < 1e-12

    def test_identity_and_bounds(self):
        c = np.array([5, 10, 0, 85])
        assert modelhealth.psi(c, c) == 0.0
        assert modelhealth.js_divergence(c, c) == 0.0
        # disjoint distributions approach the JS bound ln 2
        a, b = np.array([100, 0]), np.array([0, 100])
        assert abs(modelhealth.js_divergence(a, b) - np.log(2)) < 1e-12
        assert modelhealth.psi(a, b) > 1.0
        # no evidence is not drift
        assert modelhealth.psi([], []) == 0.0
        assert modelhealth.js_divergence([1, 2], [0, 0]) == 0.0


# ---------------------------------------------------------------------------
# profile trailer round trips
# ---------------------------------------------------------------------------
class TestProfileTrailer:
    def test_save_load_save_byte_identical(self):
        X, y = _problem()
        bst = _train(X, y)
        s1 = bst.model_to_string()
        t1 = _trailer(s1)
        assert t1 is not None, "trained model carries no profile trailer"
        b2 = lgb.Booster(model_str=s1)
        t2 = _trailer(b2.model_to_string())
        assert t1 == t2

    def test_registry_load_keeps_trailer(self, tmp_path):
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem()
        bst = _train(X, y)
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        t1 = _trailer(open(path).read())
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", model_file=path)
            entry = sess.registry.resolve("m")
            assert entry.drift is not None
            t2 = _trailer(entry.booster.model_to_string())
            assert t1 == t2
        finally:
            sess.close()

    def test_checkpoint_resume_keeps_trailer(self, tmp_path):
        X, y = _problem()
        p = dict(_P)
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.Booster(params=p, train_set=ds)
        for _ in range(4):
            bst.update()
        t1 = _trailer(bst.model_to_string())
        bst.save_checkpoint(str(tmp_path))
        ds2 = lgb.Dataset(X, label=y, params=p)
        b2 = lgb.Booster(params=p, train_set=ds2)
        assert b2.resume_from_checkpoint(str(tmp_path)) == 4
        t2 = _trailer(b2.model_to_string())
        assert t1 == t2

    def test_binary_cache_keeps_profile(self, tmp_path):
        """cnt_in_bin rides the mapper snapshot: a model trained from a
        binary dataset cache (mappers rebuilt via from_dict) must still
        write a full profile trailer."""
        from lightgbm_tpu.io.dataset import TrainingData

        X, y = _problem(n=500)
        ds = lgb.Dataset(X, label=y, params=_P)
        ds.construct()
        ref = {c: ds._inner.mappers[c].cnt_in_bin
               for c in ds._inner.used_feature_idx}
        path = str(tmp_path / "cache.bin")
        ds.save_binary(path)
        td = TrainingData.from_binary(path)
        for c, cnt in ref.items():
            assert td.mappers[c].cnt_in_bin == cnt
        prof = modelhealth.FeatureProfile.from_training(
            td, [], np.zeros((1, td.num_data)), 8)
        assert prof is not None
        assert set(prof.features) == {c for c, cnt in ref.items() if cnt}

    def test_capture_off_suppresses_trailer(self):
        X, y = _problem()
        bst = _train(X, y, params={"tpu_profile_capture": False})
        assert _trailer(bst.model_to_string()) is None

    def test_payload_contents(self):
        X, y = _problem(n=500)
        bst = _train(X, y)
        prof = bst._driver.health_profile()
        assert prof is not None
        pay = prof.to_payload()
        assert pay["label"]["n"] == 500
        assert abs(pay["label"]["mean"] - float(y.mean())) < 1e-12
        # occupancy sums to the sample count per feature
        for f in pay["features"].values():
            assert sum(f["cnt"]) == 500
            assert len(f["cnt"]) == f["num_bin"]
        # score histogram covers every training row per class
        for row in pay["score"]["counts"]:
            assert sum(row) == 500


# ---------------------------------------------------------------------------
# drift monitor vs the float64 oracle
# ---------------------------------------------------------------------------
class TestDriftOracle:
    def test_monitor_matches_numpy_oracle(self):
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem(n=800)
        bst = _train(X, y)
        sample_rows = 100
        sess = ServingSession(params={
            "serving_drift_sample_rows": sample_rows,
            "serving_max_batch_rows": 4096, "verbosity": -1},
            start=False)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            batches = [X[:300] + 1.5, X[300:550], X[550:]]
            for Xb in batches:
                entry.predict(Xb)
            snap = entry.drift.snapshot()
        finally:
            sess.close()

        # oracle: replicate the stride sampling, bin through the SAME
        # mappers, accumulate int64, and apply the independent PSI/JS
        # oracles — equality to 1e-12 is the acceptance bar
        prof = json.loads(_trailer(bst.model_to_string())
                          .split(":", 1)[1])
        ctx = bst._driver._pred_context()
        sampled = []
        for Xb in batches:
            n = Xb.shape[0]
            if n > sample_rows:
                step = -(-n // sample_rows)
                Xb = Xb[::step][:sample_rows]
            sampled.append(np.asarray(Xb, np.float64))
        Xs = np.concatenate(sampled, axis=0)
        assert snap["rows_sampled"] == Xs.shape[0]
        for key, ref in prof["features"].items():
            c = int(key)
            mapper = ctx.mappers[c]
            bins = mapper.values_to_bins(Xs[:, c])
            ocnt = np.bincount(bins, minlength=ref["num_bin"])
            got = snap["features"][ref["name"]]
            assert abs(got["psi"] - _oracle_psi(ref["cnt"], ocnt)) < 1e-12
            assert abs(got["js"] - _oracle_js(ref["cnt"], ocnt)) < 1e-12
            assert got["rows"] == Xs.shape[0]
        # raw-score histogram divergence, same bar
        raw = np.asarray(bst.predict(Xs, raw_score=True), np.float64)
        edges = np.asarray(prof["score"]["edges"], np.float64)
        idx = np.clip(np.searchsorted(edges[1:-1], raw, side="right"),
                      0, len(edges) - 2)
        ocnt = np.bincount(idx, minlength=len(edges) - 1)
        assert abs(snap["score_js"][0]
                   - _oracle_js(prof["score"]["counts"][0], ocnt)) < 1e-12

    def test_nan_and_unseen_rates(self):
        from lightgbm_tpu.serving import ServingSession

        rng = np.random.default_rng(3)
        X = rng.normal(size=(500, 3))
        X[:100, 1] = np.nan                      # train-time NaNs too
        y = (X[:, 0] > 0).astype(np.float64)
        bst = _train(X, y)
        sess = ServingSession(params={
            "serving_drift_sample_rows": 4096, "verbosity": -1},
            start=False)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            Xq = X[:200].copy()
            Xq[:100, 1] = np.nan                 # 50% NaN vs 20% trained
            entry.predict(Xq)
            snap = entry.drift.snapshot()
        finally:
            sess.close()
        names = bst.feature_name()
        f = snap["features"][names[1]]
        assert abs(f["nan_rate"] - 0.5) < 1e-12
        assert abs(f["nan_delta"] - (0.5 - 0.2)) < 1e-12

    def test_sampling_disabled_means_no_monitor(self):
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem()
        bst = _train(X, y)
        sess = ServingSession(params={
            "serving_drift_sample_rows": 0, "verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            assert sess.registry.resolve("m").drift is None
            assert sess.drift()["models"] == {}
        finally:
            sess.close()

    def test_no_profile_means_no_monitor(self):
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem()
        bst = _train(X, y, params={"tpu_profile_capture": False})
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            assert sess.registry.resolve("m").drift is None
        finally:
            sess.close()

    def test_wrong_width_request_does_not_poison_monitor(self):
        """A 400-class request (wrong feature count) fails alone — it
        must not land in the drift accumulator, where a mixed-width
        concatenate would break every later scrape."""
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem(n=500)
        bst = _train(X, y)
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            entry.predict(X[:40])
            with pytest.raises(Exception):
                entry.predict(X[:10, :3])       # wrong width: 3 vs 5
            snap = entry.drift.snapshot()       # scrape must survive
            assert snap["rows_sampled"] == 40   # bad batch not counted
            entry.predict(X[40:80])
            assert entry.drift.snapshot()["rows_sampled"] == 80
        finally:
            sess.close()

    def test_truncated_categorical_has_no_phantom_nan_frac(self):
        """A truncated high-cardinality categorical sets
        missing_type=NAN without a dedicated NaN bin; its rare-tail
        mass must not be recorded as NaN fraction."""
        rng = np.random.default_rng(8)
        X = rng.normal(size=(600, 2))
        # 40 categories over a max_bin=31 budget: guaranteed truncation
        X[:, 1] = rng.integers(0, 40, size=600)
        y = (X[:, 0] > 0).astype(np.float64)
        p = dict(_P, max_bin=31)
        ds = lgb.Dataset(X, label=y, params=p, categorical_feature=[1])
        bst = lgb.train(p, ds, num_boost_round=3, verbose_eval=False)
        prof = bst._driver.health_profile()
        f = prof.features.get(1)
        if f is not None:                        # categorical profiled
            assert f["bin_type"] == 1
            assert f["nan_frac"] == 0.0

    def test_unload_during_scrape_cannot_resurrect_gauges(self):
        """The clear_drift tombstone: a publish that snapshotted the
        entry before its unload must not re-create the per-model
        series (the phantom-series race)."""
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem(n=400)
        bst = _train(X, y)
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            entry.predict(X[:50])
            monitor = entry.drift
            sess.unload("m")                     # clears + tombstones
            monitor.snapshot()                   # in-flight publish
            assert "lgbm_drift_" not in sess._stats.to_prometheus_text()
            # reloading the same key re-arms publishing
            sess.load("m", booster=bst, version="1")
            e2 = sess.registry.resolve("m")
            e2.predict(X[:50])
            e2.drift.snapshot()
            assert "lgbm_drift_psi{" in sess._stats.to_prometheus_text()
        finally:
            sess.close()

    def test_unload_clears_drift_gauges(self):
        from lightgbm_tpu.serving import ServingSession

        X, y = _problem()
        bst = _train(X, y)
        sess = ServingSession(params={"verbosity": -1}, start=False)
        try:
            sess.load("m", booster=bst)
            entry = sess.registry.resolve("m")
            entry.predict(X[:50])
            entry.drift.snapshot()
            assert "lgbm_drift_psi{" in sess._stats.to_prometheus_text()
            sess.unload("m")
            assert "lgbm_drift_psi{" not in sess._stats.to_prometheus_text()
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# the acceptance flow: drift warn -> shadow compare -> refuse
# ---------------------------------------------------------------------------
class TestPromotionFlow:
    def test_drift_warn_and_shadow_refuse_end_to_end(self, tmp_path):
        from lightgbm_tpu.obs import flightrecorder
        from lightgbm_tpu.serving import ServingSession
        from lightgbm_tpu.serving.server import serve_http

        sys.path.insert(0, TOOLS)
        try:
            import model_report
        finally:
            sys.path.remove(TOOLS)

        X, y = _problem(n=800, seed=11)
        live = _train(X, y, rounds=8)
        live_path = str(tmp_path / "live.txt")
        live.save_model(live_path)
        # worse candidate: trained on permuted labels
        rng = np.random.default_rng(5)
        yb = y.copy()
        rng.shuffle(yb)
        cand = _train(X, yb, rounds=8)
        cand_path = str(tmp_path / "cand.txt")
        cand.save_model(cand_path)

        flightrecorder.reset()
        sess = ServingSession(params={
            "serving_max_batch_rows": 512,
            "serving_drift_sample_rows": 256,
            "serving_drift_psi_warn": 0.25, "verbosity": -1})
        server = serve_http(sess, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            sess.load("live", model_file=live_path)
            for lo in range(0, 600, 200):          # shifted traffic
                sess.predict("live", X[lo:lo + 200] + 2.5)
            with urllib.request.urlopen(base + "/drift") as resp:
                payload = json.loads(resp.read().decode())
            snap = payload["models"]["live@1"]
            assert snap["warn"] is True
            assert snap["psi_max"] >= 0.25
            # gauges on /metrics agree with the payload
            with urllib.request.urlopen(base + "/metrics") as resp:
                text = resp.read().decode()
            assert "lgbm_drift_psi{" in text
            # flight recorder carries the psi_warn transition
            kinds = [(e["kind"], e["name"])
                     for e in flightrecorder.entries()]
            assert ("drift", "psi_warn") in kinds
            assert sess.stats()["drift_warnings"] >= 1
        finally:
            server.shutdown()
            sess.close()

        # the promotion gate refuses the worse candidate on the same
        # (labeled) sample, and promotes the live model vs itself
        np.savez(tmp_path / "sample.npz", X=X[:400], y=y[:400])
        rc = model_report.main([
            "--shadow", "--live", live_path, "--candidate", cand_path,
            "--data", str(tmp_path / "sample.npz")])
        assert rc == model_report.EXIT_REFUSED
        rc = model_report.main([
            "--shadow", "--live", live_path, "--candidate", live_path,
            "--data", str(tmp_path / "sample.npz")])
        assert rc == model_report.EXIT_OK


# ---------------------------------------------------------------------------
# training telemetry <-> feature_importance cross-check
# ---------------------------------------------------------------------------
class TestTrainingTelemetry:
    def test_importance_counters_cross_check(self):
        obs.configure(mode="metrics")
        for fam in ("lgbm_train_splits_total",
                    "lgbm_train_split_gain_total"):
            obs.REGISTRY.clear_family(fam)
        X, y = _problem(n=700, seed=2)
        bst = _train(X, y, rounds=6, keep_training_booster=True)
        names = bst.feature_name()
        split = bst.feature_importance("split")
        gain = bst.feature_importance("gain")
        for i, nm in enumerate(names):
            assert obs.REGISTRY.value("lgbm_train_splits_total",
                                      feature=nm) == split[i]
            # the per-split f64 inc order matches feature_importance's
            # flat walk, so equality is EXACT, not approximate
            assert obs.REGISTRY.value("lgbm_train_split_gain_total",
                                      feature=nm) == gain[i]
        # ... and a model reloaded from string reports the SAME
        # importances the live counters recorded
        b2 = lgb.Booster(model_str=bst.model_to_string())
        s2 = b2.feature_importance("split")
        g2 = b2.feature_importance("gain")
        for i, nm in enumerate(b2.feature_name()):
            assert obs.REGISTRY.value("lgbm_train_splits_total",
                                      feature=nm) == s2[i]
            assert obs.REGISTRY.value("lgbm_train_split_gain_total",
                                      feature=nm) == pytest.approx(
                                          g2[i], rel=1e-6, abs=1e-12)

    def test_leaf_depth_distributions_and_metric_series(self):
        obs.configure(mode="metrics")
        for fam in ("lgbm_train_leaf_count", "lgbm_train_tree_depth",
                    "lgbm_train_metric"):
            obs.REGISTRY.clear_family(fam)
        X, y = _problem(n=700, seed=4)
        p = dict(_P, metric=["binary_logloss"])
        ds = lgb.Dataset(X, label=y, params=p)
        vd = lgb.Dataset(X[:150], label=y[:150], reference=ds, params=p)
        bst = lgb.train(p, ds, num_boost_round=6, valid_sets=[vd],
                        verbose_eval=False, keep_training_booster=True)
        n_leaf, _ = obs.REGISTRY.histogram_stats("lgbm_train_leaf_count")
        assert n_leaf == 6
        samples = obs.REGISTRY.histogram_samples(
            "lgbm_train_leaf_count")
        drv = bst._driver
        assert samples == [float(t.num_leaves) for t in drv.models]
        # metric time series: one sample per iteration, in order
        series = obs.REGISTRY.histogram_samples(
            "lgbm_train_metric", dataset="valid_0",
            metric="binary_logloss")
        assert len(series) == 6
        assert all(isinstance(v, float) for v in series)

    def test_guard_skip_rollback_not_counted_on_sync_path(self):
        """A tpu_guard_numerics=skip iteration's trees are rolled back
        — the sync path must not have counted them (telemetry defers
        until the guard accepts the iteration), keeping the counter <->
        feature_importance bit-equality."""
        from lightgbm_tpu.utils import faultline

        obs.configure(mode="metrics")
        for fam in ("lgbm_train_splits_total",
                    "lgbm_train_split_gain_total"):
            obs.REGISTRY.clear_family(fam)
        X, y = _problem(n=500, seed=12)

        def fobj(preds, ds):
            p = 1.0 / (1.0 + np.exp(-np.asarray(preds)))
            return (p - y).astype(np.float32), \
                (p * (1 - p)).astype(np.float32)

        # bagging gives skip-mode the stochastic lever its re-bag needs
        p = dict(_P, objective="none", tpu_guard_numerics="skip",
                 bagging_fraction=0.8, bagging_freq=1)
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.Booster(params=p, train_set=ds)
        faultline.reset()
        faultline.arm("grow_step", action="poison", at=1)
        try:
            for _ in range(4):
                bst.update(fobj=fobj)   # custom fobj = the SYNC path
        finally:
            faultline.reset()
        split = bst.feature_importance("split")
        gain = bst.feature_importance("gain")
        for i, nm in enumerate(bst.feature_name()):
            assert obs.REGISTRY.value("lgbm_train_splits_total",
                                      feature=nm) == split[i]
            assert obs.REGISTRY.value("lgbm_train_split_gain_total",
                                      feature=nm) == gain[i]

    def test_off_mode_records_nothing(self):
        assert obs.mode() == "off"
        for fam in ("lgbm_train_splits_total", "lgbm_train_leaf_count"):
            obs.REGISTRY.clear_family(fam)
        X, y = _problem(n=400, seed=6)
        _train(X, y, rounds=3)
        assert obs.REGISTRY.value("lgbm_train_splits_total",
                                  feature="Column_0") == 0.0
        n, _ = obs.REGISTRY.histogram_stats("lgbm_train_leaf_count")
        assert n == 0
