"""Categorical split training (FindBestThresholdCategorical,
reference feature_histogram.hpp:118-279; fixture = the reference cpp_test
config: tests/cpp_test/train.conf on tests/data/categorical.data)."""

import os
import subprocess

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.parser import load_text_file

from .conftest import ORACLE_BIN, REFERENCE_DIR, has_oracle

CAT_DATA = os.path.join(REFERENCE_DIR, "tests", "data", "categorical.data")
CAT_COLS = [0, 1, 4, 5, 6]


@pytest.fixture(scope="module")
def cat_example():
    X, y, _, _, _, _ = load_text_file(CAT_DATA)
    return X, y


def _train(X, y, extra=None, rounds=10):
    params = {"objective": "binary", "verbosity": -1,
              "metric": "binary_logloss"}
    params.update(extra or {})
    ds = lgb.Dataset(X, label=y, categorical_feature=CAT_COLS)
    evals = {}
    bst = lgb.train(params, ds, num_boost_round=rounds,
                    valid_sets=[ds],
                    evals_result=evals, keep_training_booster=True)
    return bst, evals


class TestCategoricalTraining:
    def test_learns_and_uses_cat_splits(self, cat_example):
        X, y = cat_example
        bst, evals = _train(X, y)
        ll = next(iter(evals.values()))["binary_logloss"]
        assert ll[-1] < ll[0] * 0.9
        dumped = bst.dump_model()
        found_cat = []

        def walk(node):
            if "decision_type" in node:
                found_cat.append(node["decision_type"] == "==")
                walk(node["left_child"])
                walk(node["right_child"])
        for t in dumped["tree_info"]:
            if "split_feature" in t["tree_structure"]:
                walk(t["tree_structure"])
        assert any(found_cat), "no categorical split in 10 trees"

    def test_onehot_mode(self, cat_example):
        X, y = cat_example
        # force one-hot search for low-cardinality features
        bst, evals = _train(X, y, {"max_cat_to_onehot": 64})
        assert next(iter(evals.values()))["binary_logloss"][-1] < 0.6

    def test_predict_consistency_raw_vs_binned(self, cat_example):
        """Raw-value predict (bitset on category values) must agree with the
        training-time binned routing (bitset on bins)."""
        X, y = cat_example
        bst, _ = _train(X, y, rounds=5)
        pred = bst.predict(X, raw_score=True)
        driver = bst._driver
        import jax
        train_scores = np.asarray(
            jax.device_get(driver.train_scores.scores))[0]
        np.testing.assert_allclose(pred, train_scores, rtol=1e-4, atol=1e-4)

    def test_model_roundtrip(self, cat_example, tmp_path):
        X, y = cat_example
        bst, _ = _train(X, y, rounds=5)
        p = tmp_path / "cat_model.txt"
        bst.save_model(str(p))
        bst2 = lgb.Booster(model_file=str(p))
        np.testing.assert_allclose(bst.predict(X), bst2.predict(X),
                                   rtol=1e-6)

    @pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
    def test_oracle_logloss_parity(self, cat_example, tmp_path):
        """Final train logloss within tolerance of the reference CLI run on
        the identical config (the cpp_test smoke config)."""
        X, y = cat_example
        rounds = 10
        conf = tmp_path / "train.conf"
        conf.write_text(
            f"data={CAT_DATA}\nvalid_data={CAT_DATA}\napp=binary\n"
            f"num_trees={rounds}\n"
            f"categorical_column={','.join(map(str, CAT_COLS))}\n"
            f"metric=binary_logloss\nmetric_freq=1\n"
            f"output_model={tmp_path}/m.txt\n")
        out = subprocess.run([ORACLE_BIN, f"config={conf}"],
                             capture_output=True, text=True, timeout=120,
                             cwd=str(tmp_path))
        lls = [float(line.rsplit(":", 1)[1])
               for line in out.stdout.splitlines()
               if "binary_logloss" in line]
        assert lls, out.stdout + out.stderr
        # strict best-first split order for oracle parity
        bst, evals = _train(X, y, {"tpu_split_batch": 1}, rounds=rounds)
        mine = next(iter(evals.values()))["binary_logloss"][-1]
        ref = lls[-1]
        assert mine < ref + 0.02, f"logloss {mine} vs oracle {ref}"

    def test_init_model_continuation(self, cat_example, tmp_path):
        """Categorical init models rebind value-bitsets to the new dataset's
        bins (GBDT._rebind_tree) and continue training."""
        X, y = cat_example
        bst, _ = _train(X, y, rounds=5)
        p = tmp_path / "cat_init.txt"
        bst.save_model(str(p))
        ds = lgb.Dataset(X, label=y, categorical_feature=CAT_COLS)
        evals = {}
        bst2 = lgb.train({"objective": "binary", "verbosity": -1,
                          "metric": "binary_logloss"}, ds,
                         num_boost_round=3, init_model=str(p),
                         valid_sets=[ds], evals_result=evals)
        ll = next(iter(evals.values()))["binary_logloss"]
        assert ll[-1] < 0.45
        assert bst2.num_trees() >= 8
