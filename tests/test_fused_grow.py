"""Fused frontier growth (ISSUE 18): the grow megakernel, device-resident
split search, the row-partition kernel, and the persisted autotuner.

1. **bitwise sweep** — int8/int16 models from tpu_hist_impl=fused (the
   megakernel's in-kernel split scan + device split records) are
   BYTE-IDENTICAL to the unfused xla composition: serial, 2/4 data
   shards, the resident AND the streamed layout, and with the pallas
   row-partition kernel (tpu_partition_impl=kernel).  int32 histogram
   accumulation is associative and the in-kernel scan runs the same
   elementwise f32 gain math as select(), so equality is exact, not
   approximate.
2. **device records vs host select()** — the [2K, F, 8] per-feature
   best records the kernel emits equal pack_pf_records of the host
   per_feature_best_split run on the same histograms, field for field.
3. **compile-ledger gate** — fusion SHRINKS (never grows) the training
   program zoo: n_programs with fused on <= the unfused count.
4. **autotune profile** — tune-mode measures + persists, load-mode
   resolves the same winners into _resolve_hist_impl, a missing bucket
   falls back to heuristics, and a profile from another topology raises
   AutotuneStaleProfile instead of quietly applying wrong winners.
5. **memory-pressure interaction** — the degradation ladder owns a
   fused_unfuse rung (fused -> pallas2 + host select) ordered between
   the scatter switch and the fine bucket policy; an injected OOM during
   a fused training descends it and completes byte-identical to an
   undisturbed run, and plan_training itemizes the fused record/parent
   buffers plus the autotune probe scratch.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.booster import Booster
from lightgbm_tpu.config import Config
from lightgbm_tpu.models.learner import TPUTreeLearner
from lightgbm_tpu.ops import split as SP
from lightgbm_tpu.ops.fused import (fused_hist_scan, fused_scan_ok,
                                    fused_supported, mosaic_int16_ok)
from lightgbm_tpu.ops.histogram import (bench_hist_operands,
                                        build_histogram_batched_t)
from lightgbm_tpu.utils import autotune, faultline, membudget
from lightgbm_tpu.utils.compile_ledger import LEDGER

PRECS = ("int8", "int16")

SPLIT_KW = dict(l1=0.0, l2=1.0, max_delta_step=0.0, min_data_in_leaf=1.0,
                min_sum_hessian=1e-3, min_gain_to_split=0.0)


def _problem(n=4096, f=10, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _train_text(X, y, prec, impl, rounds=5, **extra):
    p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
         "min_data_in_leaf": 5, "verbosity": -1, "tpu_block_rows": 512,
         "tpu_hist_precision": prec, "tpu_hist_impl": impl,
         "tpu_quant_refit_leaves": False, **extra}
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train(p, ds, num_boost_round=rounds)
    return bst.model_to_string().split("\nparameters:")[0]


@pytest.fixture(scope="module")
def xy():
    return _problem()


@pytest.fixture(scope="module")
def xla_ref(xy):
    X, y = xy
    return {prec: _train_text(X, y, prec, "xla") for prec in PRECS}


# ---------------------------------------------------------------------------
# 1. fused-vs-unfused bitwise model sweep
# ---------------------------------------------------------------------------
class TestFusedBitwise:
    @pytest.mark.parametrize("prec", PRECS)
    @pytest.mark.parametrize("shards", [1, 2, 4])
    def test_resident_bitwise(self, xy, xla_ref, prec, shards):
        # fused == unfused AT EACH shard count.  (Serial-vs-sharded
        # equality is a separate, int8-only property — int16 quantized
        # rows are not sharding-invariant — pinned in test_quantized.)
        X, y = xy
        extra = ({} if shards == 1
                 else {"tree_learner": "data", "num_machines": shards})
        ref = (xla_ref[prec] if shards == 1
               else _train_text(X, y, prec, "xla", **extra))
        assert _train_text(X, y, prec, "fused", **extra) == ref

    @pytest.mark.parametrize("prec", PRECS)
    def test_streamed_bitwise(self, xy, prec):
        # streamed-vs-streamed: the streamed layout's quantization walks
        # rows in host-block order, so its models legitimately differ
        # from resident ones — the fusion claim is fused == unfused
        # WITHIN each layout
        X, y = xy
        ref = _train_text(X, y, prec, "xla", tpu_stream_mode="streamed")
        assert _train_text(X, y, prec, "fused",
                           tpu_stream_mode="streamed") == ref

    @pytest.mark.parametrize("prec", PRECS)
    def test_kernel_partition_bitwise(self, xy, xla_ref, prec):
        assert _train_text(X=xy[0], y=xy[1], prec=prec, impl="fused",
                           tpu_partition_impl="kernel") == xla_ref[prec]

    def test_kernel_partition_rejects_uncovered_modes(self, xy):
        # categorical splits keep the select-family lowerings; the
        # row-partition kernel must refuse loudly, not mis-route rows
        X, y = xy
        Xc = np.column_stack([np.abs(X[:, 0] * 3).astype(np.int32) % 4,
                              X[:, 1:]])
        p = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
             "min_data_in_leaf": 5, "verbosity": -1,
             "tpu_hist_precision": "int8", "tpu_hist_impl": "fused",
             "tpu_partition_impl": "kernel"}
        ds = lgb.Dataset(Xc, label=y, params={"max_bin": 63},
                         categorical_feature=[0])
        with pytest.raises(Exception, match="tpu_partition_impl=kernel"):
            lgb.train(p, ds, num_boost_round=2)

    def test_fused_degrades_outside_its_envelope(self, xy):
        # an unsupported mode (float precision: no in-kernel int scan)
        # degrades to the perfeature hist + host select INSIDE the same
        # grow program — same model as pallas2, no error
        X, y = xy
        assert (_train_text(X, y, "hilo", "fused")
                == _train_text(X, y, "hilo", "pallas2"))
        assert fused_supported("hilo") is not None
        assert fused_supported("int8") is None
        assert fused_supported("int8", has_cat=True) is not None


# ---------------------------------------------------------------------------
# 2. device split records vs the host select() oracle
# ---------------------------------------------------------------------------
class TestDeviceRecordsOracle:
    @pytest.mark.parametrize("precision", PRECS)
    def test_records_match_host_scan(self, precision):
        rng = np.random.default_rng(11)
        n, F, B, block, K = 1024, 6, 16, 128, 3
        bins_np = rng.integers(0, B, size=(n, F)).astype(np.uint8)
        bins_tb, stats, n_use = bench_hist_operands(bins_np, precision,
                                                    block)
        nb = n_use // block
        leaf = jnp.asarray(rng.integers(0, K, size=n_use)
                           .astype(np.int32).reshape(nb, block))
        slots = jnp.arange(K, dtype=jnp.int32)
        small = build_histogram_batched_t(bins_tb, stats, leaf, slots, B,
                                          precision, impl="xla")
        parent = small * 2 + jnp.flip(small, axis=0)
        C = 2 * K
        ctx_np = np.zeros((C + 1, 8), np.float32)
        ctx_np[:C, 0] = 3.0 + np.arange(C)          # sum_g
        ctx_np[:C, 1] = 7.0 + np.arange(C)          # sum_h
        ctx_np[:C, 2] = 64.0                        # count
        ctx_np[:C, 3] = -1e30
        ctx_np[:C, 4] = 1e30
        ctx_np[:C, 5] = (np.arange(C) % 2).astype(np.float32)
        ctx_np[C, :3] = (0.5, 0.25, 1.0)            # qscale
        meta_i = jnp.zeros((F, 8), jnp.int32).at[:, 0].set(B)
        meta_f = jnp.ones((F, 8), jnp.float32)

        hist, recs = fused_hist_scan(
            bins_tb, stats, leaf, slots, parent, jnp.asarray(ctx_np),
            meta_i, meta_f, B, precision, split_kw=SPLIT_KW)
        np.testing.assert_array_equal(np.asarray(hist), np.asarray(small))

        qs = jnp.asarray(ctx_np[C, :3])
        for j in range(C):
            k = j % K
            hs = small[k] if ctx_np[j, 5] > 0 else parent[k] - small[k]
            pf = SP.per_feature_best_split(
                hs, ctx_np[j, 0], ctx_np[j, 1], ctx_np[j, 2],
                meta_i[:, 0], meta_i[:, 1], meta_i[:, 2], meta_i[:, 3],
                meta_f[:, 0], meta_f[:, 1],
                min_constraint=ctx_np[j, 3], max_constraint=ctx_np[j, 4],
                acc_scale=qs, **SPLIT_KW)
            expect = SP.pack_pf_records(pf)
            np.testing.assert_array_equal(np.asarray(recs[j]),
                                          np.asarray(expect),
                                          err_msg=f"child {j}")
            # unpack round-trips the exact fields select() consumes
            back = SP.unpack_pf_records(recs[j])
            np.testing.assert_array_equal(np.asarray(back.gain),
                                          np.asarray(pf.gain))
            np.testing.assert_array_equal(np.asarray(back.threshold),
                                          np.asarray(pf.threshold))

    def test_validation_probes_pass_here(self):
        # trivially exact on CPU interpret; true Mosaic checks on TPU.
        # auto's loud-fallback contract rides on these two.
        assert mosaic_int16_ok() is True
        for prec in PRECS:
            assert fused_scan_ok(prec) is True


# ---------------------------------------------------------------------------
# 3. compile-ledger gate: fusion shrinks, never grows, the program zoo
# ---------------------------------------------------------------------------
class TestCompileLedgerGate:
    def test_fusion_does_not_grow_program_zoo(self):
        X, y = _problem(n=2048, f=8, seed=3)
        counts = {}
        for impl in ("xla", "fused"):
            LEDGER.enable()
            LEDGER.reset()
            try:
                _train_text(X, y, "int8", impl, rounds=3)
                counts[impl] = LEDGER.n_programs()
            finally:
                LEDGER.enable(False)
                LEDGER.reset()
        assert counts["fused"] <= counts["xla"], (
            "fused frontier grew the program zoo: "
            f"{counts['fused']} programs vs {counts['xla']} unfused — "
            "the megakernel must live INSIDE the existing grow sites")


# ---------------------------------------------------------------------------
# 4. autotune profile: round-trip, fallback, stale refusal
# ---------------------------------------------------------------------------
class TestAutotuneProfile:
    def test_tune_round_trip_resolves_into_auto(self, tmp_path):
        path = str(tmp_path / "prof.json")
        cfg = Config({"objective": "binary", "tpu_autotune": "tune",
                      "tpu_autotune_profile": path})
        entry = autotune.resolve_autotune(cfg, 8192, 8, 64, "int8")
        assert entry is not None and os.path.exists(path)
        assert entry["hist_impl"] in ("xla", "pallas2", "fused")
        cfg2 = Config({"objective": "binary", "tpu_autotune": "load",
                       "tpu_autotune_profile": path})
        entry2 = autotune.resolve_autotune(cfg2, 8192, 8, 64, "int8")
        assert entry2["hist_impl"] == entry["hist_impl"]
        assert entry2["block_rows"] == entry["block_rows"]
        impl, block = TPUTreeLearner._resolve_hist_impl(
            cfg2, 64, "int8", tuned=entry2)
        assert impl == entry2["hist_impl"]
        assert block == entry2["block_rows"]

    def test_missing_bucket_in_load_mode_falls_back(self, tmp_path):
        path = str(tmp_path / "empty.json")
        autotune.save_profile(path, {
            "version": autotune.PROFILE_VERSION,
            **autotune.backend_fingerprint(), "entries": {}})
        cfg = Config({"objective": "binary", "tpu_autotune": "load",
                      "tpu_autotune_profile": path})
        assert autotune.resolve_autotune(cfg, 8192, 8, 64, "int8") is None
        # heuristics still apply: CPU auto resolves xla
        impl, block = TPUTreeLearner._resolve_hist_impl(cfg, 64, "int8",
                                                        tuned=None)
        assert impl == "xla"

    @pytest.mark.parametrize("mutate", [
        {"platform": "tpu"},
        {"device_count": 1024},
        {"version": -5},
    ])
    def test_stale_profile_refused(self, tmp_path, mutate):
        path = str(tmp_path / "stale.json")
        prof = {"version": autotune.PROFILE_VERSION,
                **autotune.backend_fingerprint(),
                "entries": {"r8192_f8_b64": {"hist_impl": "fused",
                                             "block_rows": 8192,
                                             "precision": "int8"}}}
        prof.update(mutate)
        autotune.save_profile(path, prof)
        cfg = Config({"objective": "binary", "tpu_autotune": "load",
                      "tpu_autotune_profile": path})
        with pytest.raises(autotune.AutotuneStaleProfile):
            autotune.resolve_autotune(cfg, 8192, 8, 64, "int8")

    def test_small_dataset_tune_clamps_or_falls_back(self, tmp_path):
        # regression: every candidate block used to exceed a small
        # dataset's rows -> 'no viable candidate' RuntimeError killed
        # the training run.  Now blocks clamp to the largest pow2 the
        # rows fill (3000 rows -> measured winner), and a dataset too
        # tiny for even the floor degrades to heuristics with a logged
        # warning instead of raising
        cfg = Config({"objective": "binary", "tpu_autotune": "tune",
                      "tpu_autotune_profile": str(tmp_path / "s.json")})
        entry = autotune.resolve_autotune(cfg, 3000, 10, 64, "int8")
        assert entry is not None and entry["block_rows"] <= 2048
        cfg2 = Config({"objective": "binary", "tpu_autotune": "tune",
                       "tpu_autotune_profile": str(tmp_path / "t.json")})
        assert autotune.resolve_autotune(cfg2, 300, 10, 16,
                                         "int8") is None
        assert not os.path.exists(str(tmp_path / "t.json"))

    def test_tuned_never_overrides_explicit_config(self):
        cfg = Config({"objective": "binary", "tpu_hist_impl": "xla",
                      "tpu_block_rows": 2048})
        impl, block = TPUTreeLearner._resolve_hist_impl(
            cfg, 64, "int8",
            tuned={"hist_impl": "fused", "block_rows": 8192})
        assert (impl, block) == ("xla", 2048)

    def test_learner_training_with_profile_stays_bitwise(self, xy,
                                                         xla_ref,
                                                         tmp_path):
        # end to end: tune writes the profile during learner init, the
        # tuned winners change only SPEED knobs — model bytes match the
        # plain xla reference exactly
        X, y = xy
        path = str(tmp_path / "train_prof.json")
        text = _train_text(X, y, "int8", "auto", tpu_autotune="tune",
                           tpu_autotune_profile=path)
        assert os.path.exists(path)
        assert text == xla_ref["int8"]


# ---------------------------------------------------------------------------
# 5. memory-pressure interaction
# ---------------------------------------------------------------------------
class TestMemoryPressure:
    @pytest.fixture(autouse=True)
    def _clean_faults(self):
        faultline.reset()
        yield
        faultline.reset()

    def test_ladder_owns_fused_unfuse_rung(self):
        assert "fused_unfuse" in membudget.LADDER_STEPS
        cfg = Config({"objective": "binary", "tpu_hist_impl": "fused",
                      "tpu_ingest_chunk_rows": membudget.CHUNK_FLOOR,
                      "tpu_predict_chunk_rows": membudget.CHUNK_FLOOR})
        lad = membudget.DegradationLadder()
        step, over = lad.next_step(cfg)
        assert step == "fused_unfuse"
        assert over == {"tpu_hist_impl": "pallas2"}
        # an auto impl never unpins (it re-resolves per backend)
        cfg2 = Config({"objective": "binary",
                       "tpu_ingest_chunk_rows": membudget.CHUNK_FLOOR,
                       "tpu_predict_chunk_rows": membudget.CHUNK_FLOOR})
        step2, _ = membudget.DegradationLadder().next_step(cfg2)
        assert step2 == "bucket_policy_fine"

    def test_oom_during_fused_step_descends_bitwise(self):
        X, y = _problem(n=800, f=6, seed=0)
        base = {"objective": "binary", "num_leaves": 7, "max_bin": 15,
                "min_data_in_leaf": 5, "verbosity": -1,
                "tpu_hist_precision": "int8", "tpu_hist_impl": "fused",
                "tpu_quant_refit_leaves": False,
                "tpu_ingest_chunk_rows": membudget.CHUNK_FLOOR,
                "tpu_predict_chunk_rows": membudget.CHUNK_FLOOR}
        ds = lgb.Dataset(X, label=y, params=dict(base))
        ref = lgb.train(dict(base), ds, num_boost_round=4,
                        keep_training_booster=True)
        ref_text = ref.model_to_string().split("\nparameters:")[0]
        bst = Booster(params=dict(base),
                      train_set=lgb.Dataset(X, label=y, params=dict(base)))
        for it in range(4):
            if it == 2:
                faultline.arm("device_alloc", action="oom", at=1)
            bst.update()
        steps = bst._driver._mem_ladder.describe()
        assert steps == ["fused_unfuse"], steps
        assert str(bst._driver.config.tpu_hist_impl) == "pallas2"
        assert (bst.model_to_string().split("\nparameters:")[0]
                == ref_text)

    def test_plan_itemizes_fused_and_autotune_scratch(self, tmp_path):
        X, y = _problem(n=800, f=6, seed=0)
        p = {"objective": "binary", "num_leaves": 7, "max_bin": 15,
             "min_data_in_leaf": 5, "verbosity": -1,
             "tpu_hist_precision": "int8", "tpu_hist_impl": "fused",
             "tpu_autotune": "load",
             "tpu_autotune_profile": str(tmp_path / "none.json")}
        bst = Booster(params=p,
                      train_set=lgb.Dataset(X, label=y, params=p))
        bst.update()
        plan = membudget.plan_training(bst._driver.config,
                                       bst._driver.learner, 1)
        assert plan.components["fused_records"] > 0
        assert plan.components["fused_parent_hist"] > 0
        assert plan.components["autotune_scratch"] > 0
