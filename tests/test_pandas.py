"""Pandas DataFrame ingestion: category-dtype columns become categorical
features with stable code tables across train/valid/predict/model-IO
(the role of the reference package's pandas handling, reference
python-package/lightgbm/basic.py:313-367 — re-derived)."""

import numpy as np
import pandas as pd
import pytest

import lightgbm_tpu as lgb


def _frame(n=3000, seed=5, cats=("red", "green", "blue", "violet")):
    rng = np.random.default_rng(seed)
    color = pd.Categorical.from_codes(rng.integers(0, len(cats), size=n),
                                      categories=list(cats))
    df = pd.DataFrame({
        "color": color,
        "x0": rng.normal(size=n),
        "x1": rng.normal(size=n),
    })
    # the categorical drives the label: codes 0/2 -> positive-leaning
    y = ((np.isin(np.asarray(color.codes), (0, 2)))
         .astype(float) * 2.0 + df["x0"].to_numpy()
         + 0.3 * rng.normal(size=n))
    return df, (y > 1.0).astype(np.float64)


PARAMS = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 5,
          "verbosity": -1}


class TestPandasIngestion:
    def test_auto_names_and_categoricals(self):
        df, y = _frame()
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train(PARAMS, ds, num_boost_round=10)
        assert bst.feature_name() == ["color", "x0", "x1"]
        assert bst.pandas_categorical == [["red", "green", "blue",
                                           "violet"]]
        # the categorical must actually be used as one: some tree splits
        # on feature 0 categorically
        dump = bst.dump_model()
        cat_splits = [
            1 for t in dump["tree_info"]
            for node in _walk(t["tree_structure"])
            if node.get("split_feature") == 0
            and node.get("decision_type") == "=="]
        assert cat_splits

    def test_predict_remaps_reordered_categories(self):
        df, y = _frame()
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train(PARAMS, ds, num_boost_round=10)
        base = bst.predict(df)
        # same data, categories declared in a different order: codes
        # differ but values are identical -> predictions must match
        df2 = df.copy()
        df2["color"] = df2["color"].cat.reorder_categories(
            ["violet", "blue", "green", "red"])
        np.testing.assert_allclose(bst.predict(df2), base)
        # unseen category routes like missing, not like a trained code
        df3 = df.copy()
        df3["color"] = pd.Categorical(
            ["white"] * len(df3), categories=["white"])
        p3 = bst.predict(df3)
        assert p3.shape == base.shape

    def test_model_io_roundtrip_preserves_tables(self, tmp_path):
        df, y = _frame()
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train(PARAMS, ds, num_boost_round=5)
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        text = open(path).read()
        assert "pandas_categorical:" in text
        loaded = lgb.Booster(model_file=path)
        assert loaded.pandas_categorical == bst.pandas_categorical
        np.testing.assert_allclose(loaded.predict(df), bst.predict(df))
        # string round-trip too
        b2 = lgb.Booster(model_str=bst.model_to_string())
        assert b2.pandas_categorical == bst.pandas_categorical

    def test_valid_set_uses_train_tables(self):
        df, y = _frame()
        dv, yv = _frame(seed=9)
        dv["color"] = dv["color"].cat.reorder_categories(
            ["blue", "red", "violet", "green"])
        ds = lgb.Dataset(df, label=y)
        vs = lgb.Dataset(dv, label=yv, reference=ds)
        bst = lgb.train({**PARAMS, "metric": "auc"}, ds,
                        num_boost_round=10, valid_sets=[vs],
                        valid_names=["v"])
        rec = bst.best_score.get("v") or {}
        # the reordered valid frame must still evaluate sanely
        assert rec.get("auc", 0.0) > 0.7

    def test_object_dtype_rejected(self):
        df, y = _frame()
        df["color"] = df["color"].astype(str)
        with pytest.raises(ValueError, match="non-numeric"):
            lgb.Dataset(df, label=y).construct()


def _walk(node):
    yield node
    for k in ("left_child", "right_child"):
        if isinstance(node.get(k), dict):
            yield from _walk(node[k])


class TestPandasEdgeCases:
    def test_integer_categories_roundtrip(self, tmp_path):
        rng = np.random.default_rng(21)
        n = 2000
        code = pd.Categorical.from_codes(
            rng.integers(0, 3, size=n), categories=[10, 20, 30])
        df = pd.DataFrame({"c": code, "x": rng.normal(size=n)})
        y = (np.asarray(code.codes) == 1).astype(float) * 2 + \
            df["x"].to_numpy() * 0.1
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1}, ds, num_boost_round=5)
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        loaded = lgb.Booster(model_file=path)
        # int category values must survive JSON (not become strings)
        assert loaded.pandas_categorical == [[10, 20, 30]]
        np.testing.assert_allclose(loaded.predict(df), bst.predict(df))

    def test_predict_without_tables_raises(self):
        rng = np.random.default_rng(22)
        X = rng.integers(0, 3, size=(500, 2)).astype(np.float64)
        y = (X[:, 0] == 1).astype(np.float64)
        ds = lgb.Dataset(X, label=y, categorical_feature=[0])
        bst = lgb.train(PARAMS, ds, num_boost_round=3)
        df = pd.DataFrame({
            "a": pd.Categorical.from_codes([0, 1, 2], ["x", "y", "z"]),
            "b": [0.0, 1.0, 2.0]})
        with pytest.raises(ValueError, match="no stored pandas category"):
            bst.predict(df)

    def test_categorical_roundtrip_predictions_bitwise(self, tmp_path):
        """save_model -> Booster(model_file) with a pandas-categorical
        table: predictions must match pre-save EXACTLY (thresholds and
        leaf values round-trip through repr, the category table through
        the `pandas_categorical:` JSON line)."""
        df, y = _frame()
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train(PARAMS, ds, num_boost_round=6)
        pre = bst.predict(df)
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        loaded = lgb.Booster(model_file=path)
        np.testing.assert_array_equal(loaded.predict(df), pre)
        # string round trip too, and with reordered category declarations
        b2 = lgb.Booster(model_str=bst.model_to_string())
        df2 = df.copy()
        df2["color"] = df2["color"].cat.reorder_categories(
            ["violet", "blue", "green", "red"])
        np.testing.assert_array_equal(b2.predict(df2), pre)

    def test_numpy_scalar_categories_roundtrip(self, tmp_path):
        """np.integer / np.floating category values go through the
        _pandas_categorical_line np_default converter and come back as
        plain ints/floats."""
        rng = np.random.default_rng(31)
        n = 1500
        cats = np.array([5, 15, 25], dtype=np.int64)
        code = pd.Categorical.from_codes(rng.integers(0, 3, size=n),
                                         categories=cats)
        df = pd.DataFrame({"c": code, "x": rng.normal(size=n)})
        y = (np.asarray(code.codes) == 2).astype(float) * 2 \
            + df["x"].to_numpy() * 0.1
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1}, ds, num_boost_round=4)
        assert [int(c) for c in bst.pandas_categorical[0]] == [5, 15, 25]
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        loaded = lgb.Booster(model_file=path)
        assert loaded.pandas_categorical == [[5, 15, 25]]
        np.testing.assert_array_equal(loaded.predict(df), bst.predict(df))

    def test_unsupported_category_type_fails_at_save(self):
        """Non-str/int/float category values must fail AT SAVE TIME: a
        str() fallback would write a table whose values no longer match
        the frame's at predict time (everything -> missing)."""
        rng = np.random.default_rng(33)
        n = 600
        stamps = pd.to_datetime(["2020-01-01", "2021-06-01", "2022-12-31"])
        code = pd.Categorical.from_codes(rng.integers(0, 3, size=n),
                                         categories=stamps)
        df = pd.DataFrame({"c": code, "x": rng.normal(size=n)})
        y = df["x"].to_numpy() + (np.asarray(code.codes) == 1)
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1}, ds, num_boost_round=2)
        with pytest.raises(TypeError, match="cannot persist"):
            bst.model_to_string()
        with pytest.raises(TypeError, match="cannot persist"):
            bst.save_model("/dev/null")

    def test_corrupt_table_line_raises(self):
        df, y = _frame(n=500)
        ds = lgb.Dataset(df, label=y)
        bst = lgb.train(PARAMS, ds, num_boost_round=2)
        text = bst.model_to_string()
        broken = text.rsplit("pandas_categorical:", 1)[0] \
            + "pandas_categorical:[[\"re\n"
        with pytest.raises(ValueError, match="corrupt pandas_categorical"):
            lgb.Booster(model_str=broken)
