"""The unified (hosts, data, feature) topology (ISSUE 20).

Load-bearing guarantees under test:

* `make_topology` always builds the 3-axis mesh (hosts may be size 1)
  over process-major `jax.devices()`, so relabeling the flat data axis
  as (hosts, data) preserves device placement — and therefore bitwise
  model output — exactly.  `axis_index(ROW_AXES)` linearizes row-major
  back to the old flat shard index.
* the (hosts × devices) bitwise grid: int8/int16 model files are
  byte-identical across {1,2}-host × {1,2,4}-device points (hosts
  simulated on one process via `tpu_topology_hosts`), and an elastic
  resume may cross a host-count change.
* `tree_learner=feature` under hosts>1 remaps onto the data_feature
  grower (rows ride the hosts axis) instead of refusing — the carve-out
  ISSUE 20 deleted.
* `rows_partitioned()` is the single sum-type predicate (replaces
  config.pre_partition echoes); host transport helpers degrade to
  identities in a 1-process world but still honor fault points.

Runs on the 8-virtual-device CPU mesh (tests/conftest.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import topology
from lightgbm_tpu.parallel.topology import (DATA, FEATURE, HOSTS, ROW_AXES,
                                            axis_index, axis_psum,
                                            make_topology, ragged_all_gather,
                                            resolve_hosts, rows_partitioned)


def _problem(n=4096, f=10, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
    return X, y


def _train_model_text(X, y, rounds=3, **cfg):
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 63,
              "min_data_in_leaf": 5, "tpu_block_rows": 512,
              "verbosity": -1, "tpu_shape_buckets": 0}
    params.update(cfg)
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.train(params, ds, num_boost_round=rounds,
                    keep_training_booster=True)
    text = bst.model_to_string().split("\nparameters:")[0]
    return text, bst


@pytest.fixture(autouse=True)
def _restore_active_topology():
    """Training activates the learner's topology in the module registry;
    leave no cross-test residue."""
    yield
    topology.activate(None)


# ---------------------------------------------------------------------------
class TestMakeTopology:
    def test_three_axes_always(self):
        t = make_topology(num_data_shards=4)
        assert t.mesh.axis_names == (HOSTS, DATA, FEATURE)
        assert dict(t.mesh.shape) == {HOSTS: 1, DATA: 4, FEATURE: 1}
        assert (t.hosts, t.data_shards, t.feature_shards) == (1, 4, 1)
        assert t.local_data_shards == 4

    def test_hosts_axis_factorizes_the_row_shards(self):
        t = make_topology(num_data_shards=4, num_hosts=2)
        assert dict(t.mesh.shape) == {HOSTS: 2, DATA: 2, FEATURE: 1}
        assert t.data_shards == 4 and t.local_data_shards == 2

    def test_device_order_is_flat_reshape(self):
        """(hosts, data) relabeling must NOT permute devices — that is
        the whole bitwise-invariance argument."""
        flat = make_topology(num_data_shards=4).mesh.devices.ravel()
        split = make_topology(num_data_shards=4,
                              num_hosts=2).mesh.devices.ravel()
        assert list(flat) == list(split)

    def test_indivisible_hosts_rejected(self):
        with pytest.raises(ValueError, match="hosts"):
            make_topology(num_data_shards=3, num_hosts=2)

    def test_too_few_devices_rejected(self):
        with pytest.raises(ValueError, match="devices"):
            make_topology(num_data_shards=8, num_feature_shards=2)

    def test_resolve_hosts(self):
        assert resolve_hosts(0) == jax.process_count()
        assert resolve_hosts(3) == 3


class TestAxisVocabulary:
    def test_row_axes_index_linearizes_row_major(self):
        """axis_index(ROW_AXES) on the (2, 2) factorization equals the
        old flat data-axis index 0..3 in device order."""
        from jax.sharding import PartitionSpec as P

        from lightgbm_tpu.parallel.strategies import shard_map

        t = make_topology(num_data_shards=4, num_hosts=2)

        def body():
            return axis_index(ROW_AXES)[None]

        out = jax.jit(shard_map(body, mesh=t.mesh, in_specs=(),
                                out_specs=P(ROW_AXES),
                                check_vma=False))()
        np.testing.assert_array_equal(np.asarray(out), [0, 1, 2, 3])

    def test_axis_psum_over_row_axes_is_global(self):
        from jax.sharding import PartitionSpec as P

        from lightgbm_tpu.parallel.strategies import shard_map

        t = make_topology(num_data_shards=4, num_hosts=2)

        def body(x):
            return axis_psum(x, ROW_AXES)

        x = jnp.arange(4, dtype=jnp.int32)
        out = jax.jit(shard_map(body, mesh=t.mesh,
                                in_specs=P(ROW_AXES), out_specs=P(),
                                check_vma=False))(x)
        assert int(out[0]) == 6


class TestRowsPartitioned:
    def test_default_false(self):
        topology.activate(None)
        assert rows_partitioned() is False

    def test_single_process_world_is_never_partitioned(self):
        t = make_topology(num_data_shards=2, partitioned_rows=True)
        topology.activate(t)
        assert rows_partitioned() is False  # process_count() == 1

    def test_true_under_multiprocess_partitioned(self, monkeypatch):
        t = make_topology(num_data_shards=2, partitioned_rows=True)
        topology.activate(t)
        monkeypatch.setattr(topology.jax, "process_count", lambda: 2)
        assert rows_partitioned() is True
        topology.activate(t._replace(partitioned_rows=False))
        assert rows_partitioned() is False


class TestHostTransportLocal:
    """1-process world: every host collective is an identity that still
    rides the watchdog (fault points must fire even locally)."""

    def test_host_allgather_identity(self):
        a = np.arange(6, dtype=np.int64).reshape(2, 3)
        out = topology.host_allgather(a, name="t")
        assert out.shape == (1, 2, 3)
        np.testing.assert_array_equal(out[0], a)

    def test_ragged_all_gather_identity_and_split(self):
        a = np.arange(5, dtype=np.float64)
        np.testing.assert_array_equal(ragged_all_gather(a, name="t"), a)
        parts = ragged_all_gather(a, name="t", split=True)
        assert len(parts) == 1
        np.testing.assert_array_equal(parts[0], a)

    def test_local_collectives_fire_fault_points(self):
        from lightgbm_tpu.parallel.collective import CollectiveTimeout
        from lightgbm_tpu.utils import faultline

        faultline.reset()
        try:
            faultline.arm("collective_sync", action="hang")
            from lightgbm_tpu.parallel import collective
            collective.configure(timeout_s=0.2, retries=1, backoff_s=0.0)
            with pytest.raises(CollectiveTimeout):
                topology.host_allgather(np.zeros(2), name="t")
        finally:
            faultline.reset()
            from lightgbm_tpu.parallel import collective
            collective.configure(timeout_s=0.0, retries=1, backoff_s=0.25)


# ---------------------------------------------------------------------------
class TestHostsGridQuick:
    """Tier-1 hosts-axis coverage: one cheap bitwise point per claim;
    the full {1,2}-host × {1,2,4}-device × {int8,int16} grid is the slow
    sweep below + the multichip dryrun's topology section."""

    def test_data_hosts2_bitwise_vs_serial_int8(self):
        X, y = _problem(n=2048)
        # refit off, as in the shard-count sweep: the refit leaf psum is
        # the one f32 reduction whose shard-order ulps reach the model
        q = {"tpu_hist_precision": "int8",
             "tpu_quant_refit_leaves": False}
        ref, _ = _train_model_text(X, y, **q)
        got, bst = _train_model_text(X, y, tree_learner="data",
                                     num_machines=4, tpu_topology_hosts=2,
                                     **q)
        assert got == ref
        assert bst._driver.learner.hosts == 2

    def test_feature_under_hosts_remaps_to_data_feature(self):
        """The deleted carve-out: feature sharding under a multihost
        topology now rides the data_feature grower (rows on the hosts
        axis) and must match the explicit data_feature factorization
        bitwise."""
        X, y = _problem(n=2048)
        q = {"tpu_hist_precision": "int8"}
        got, bst = _train_model_text(X, y, tree_learner="feature",
                                     num_machines=4, tpu_topology_hosts=2,
                                     **q)
        lrn = bst._driver.learner
        assert lrn.strategy == "data_feature"
        assert (lrn.d_shards, lrn.f_shards) == (2, 2)
        ref, _ = _train_model_text(X, y, tree_learner="data_feature",
                                   num_machines=4, **q)
        assert got == ref

    def test_hosts_must_divide_shards(self):
        X, y = _problem(n=512)
        with pytest.raises(ValueError, match="hosts"):
            _train_model_text(X, y, tree_learner="data", num_machines=3,
                              tpu_topology_hosts=2)

    def test_snapshot_reports_hosts(self):
        X, y = _problem(n=1024)
        _, bst = _train_model_text(X, y, tree_learner="data",
                                   num_machines=2, tpu_topology_hosts=2)
        snap = bst._driver.topology_snapshot()
        assert snap["hosts"] == 2


@pytest.mark.slow
class TestHostsGridBitwise:
    """The acceptance grid: int8/int16 model files byte-identical across
    every (hosts, devices) point — {1,2} hosts × {1,2,4} device shards,
    hosts simulated on one process via tpu_topology_hosts."""

    @pytest.mark.parametrize("prec", ["int8", "int16"])
    def test_grid(self, prec):
        X, y = _problem()
        q = {"tpu_hist_precision": prec, "tpu_quant_refit_leaves": False}
        ref, _ = _train_model_text(X, y, **q)  # serial baseline
        for hosts in (1, 2):
            for shards in (1, 2, 4):
                if shards % hosts != 0 or shards < hosts:
                    continue
                if shards == 1:
                    continue  # serial IS the baseline
                got, bst = _train_model_text(
                    X, y, tree_learner="data", num_machines=shards,
                    tpu_topology_hosts=hosts, **q)
                assert got == ref, (hosts, shards, prec)
                assert bst._driver.learner.hosts == hosts


# ---------------------------------------------------------------------------
class TestElasticResumeHostCrossing:
    """A checkpoint taken on one host layout resumes on another: the
    hosts axis is an ELASTIC param (scores are global f32 buffers;
    quantized rounding keys on the GLOBAL row index)."""

    def test_int8_bitwise_across_host_counts(self, tmp_path):
        X, y = _problem(n=1500, f=6, seed=11)
        q = {"objective": "binary", "num_leaves": 13, "max_bin": 47,
             "min_data_in_leaf": 5, "verbosity": -1,
             "tpu_hist_precision": "int8", "tree_learner": "data",
             "tpu_quant_refit_leaves": False, "tpu_shape_buckets": 0}

        def train(params, rounds, resume=False):
            ds = lgb.Dataset(X, label=y, params=params)
            return lgb.train(params, ds, num_boost_round=rounds,
                             keep_training_booster=True, resume=resume)

        def model(bst):
            return bst.model_to_string(
                num_iteration=-1).split("\nparameters:")[0]

        base = model(train(dict(q, num_machines=1), 6))
        pc = dict(q, tpu_checkpoint_dir=str(tmp_path))
        train(dict(pc, num_machines=4, tpu_topology_hosts=1), 3)
        resumed = train(dict(pc, num_machines=4, tpu_topology_hosts=2), 6,
                        resume=True)
        assert model(resumed) == base
