"""Device-resident forest prediction (ops/predict.py).

Parity contract: the jitted bin-space traversal must match the host
walker `gbdt._predict_binned` LEAF-FOR-LEAF (f32-exact on leaf values)
across missing types (NaN/zero/none), categorical splits, multiclass,
and `num_iteration` subsets — plus the pipeline guarantee that valid-set
scoring performs zero per-tree host transfers.
"""

import numpy as np
import pytest

from .conftest import *  # noqa: F401,F403  (cpu backend pin)

import lightgbm_tpu as lgb
from lightgbm_tpu.models import gbdt as gbdt_mod
from lightgbm_tpu.models.gbdt import _predict_binned
from lightgbm_tpu.models.tree import Tree
from lightgbm_tpu.ops.predict import (PackedForest, feature_meta_dev,
                                      device_tables, forest_class_scores,
                                      forest_leaf_values, pack_trees)

DEVICE_ON = {"tpu_predict_device": "true", "verbose": -1}


def _make_data(n=1500, f=6, seed=0, with_nan=True, with_zero=True,
               with_cat=True):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    if with_nan:
        X[rng.random((n, f)) < 0.12] = np.nan
    if with_zero:
        X[:, 2] = np.where(rng.random(n) < 0.55, 0.0, X[:, 2])
    cat_cols = []
    if with_cat:
        X[:, f - 1] = rng.integers(0, 14, size=n).astype(float)
        cat_cols = [f - 1]
    y = (np.nansum(X[:, :3], axis=1)
         + (X[:, f - 1] % 3 == 0 if with_cat else 0) > 0).astype(float)
    return X, y, cat_cols


def _train(X, y, cat_cols, params=None, rounds=8):
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                     categorical_feature=cat_cols or "auto")
    p = {"objective": "binary", "num_leaves": 15, **DEVICE_ON,
         **(params or {})}
    return lgb.train(p, ds, num_boost_round=rounds, verbose_eval=False,
                     keep_training_booster=True)


class TestLeafForLeafParity:
    def _assert_forest_parity(self, drv, data):
        meta = drv.learner.meta_np
        tables, depth = pack_trees(drv.models)
        vals = np.asarray(forest_leaf_values(
            device_tables(tables), data.device_bins(),
            feature_meta_dev(meta), depth))
        assert vals.dtype == np.float32
        for i, tree in enumerate(drv.models):
            host = _predict_binned(tree, data.bins, meta).astype(np.float32)
            np.testing.assert_array_equal(
                host, vals[i], err_msg=f"tree {i} diverged from the host "
                "walker")

    @pytest.mark.parametrize("with_nan,with_zero",
                             [(True, True), (True, False), (False, True),
                              (False, False)])
    def test_missing_types(self, with_nan, with_zero):
        X, y, cats = _make_data(with_nan=with_nan, with_zero=with_zero)
        bst = _train(X, y, cats)
        drv = bst._driver
        drv._materialize()
        if with_nan or with_zero:
            assert any(t.num_cat > 0 for t in drv.models), \
                "fixture lost its categorical splits"
        self._assert_forest_parity(drv, drv.train_data)

    def test_randomized_trees(self):
        """Structural fuzz: random bin-space trees (every missing type,
        random default-left, random categorical bitsets) over random bin
        matrices — no training involved."""
        rng = np.random.default_rng(7)
        F, n = 5, 400
        num_bin = rng.integers(4, 33, size=F).astype(np.int32)
        meta = {"num_bin": num_bin,
                "default_bin": (num_bin // 3).astype(np.int32),
                "missing_type": rng.integers(0, 3, size=F).astype(np.int32)}
        bins = (rng.random((n, F)) * num_bin).astype(np.int64) % num_bin
        import jax.numpy as jnp

        bins_dev = jnp.asarray(bins.astype(np.int32))
        trees = []
        for _ in range(12):
            t = Tree(8)
            leaf = 0
            for _s in range(rng.integers(1, 8)):
                f = int(rng.integers(0, F))
                if rng.random() < 0.3:
                    width = int(num_bin[f])
                    members = rng.integers(0, 2, size=width)
                    words = np.zeros(width // 32 + 1, np.int64)
                    for b in np.nonzero(members)[0]:
                        words[b // 32] |= 1 << (b % 32)
                    t.split_categorical(
                        leaf, f, f, [int(w) for w in words],
                        [int(w) for w in words],
                        float(rng.normal()), float(rng.normal()), 10, 10,
                        1.0, 1.0, 1.0,
                        missing_type=int(meta["missing_type"][f]))
                else:
                    t.split(leaf, f, f,
                            int(rng.integers(0, num_bin[f])),
                            0.0, float(rng.normal()), float(rng.normal()),
                            10, 10, 1.0, 1.0, 1.0,
                            missing_type=int(meta["missing_type"][f]),
                            default_left=bool(rng.random() < 0.5))
                leaf = int(rng.integers(0, t.num_leaves))
            trees.append(t)
        trees.append(Tree(2))  # constant tree rides along
        trees[-1].as_constant_tree(0.625)
        tables, depth = pack_trees(trees)
        vals = np.asarray(forest_leaf_values(
            device_tables(tables), bins_dev, feature_meta_dev(meta), depth))
        for i, t in enumerate(trees):
            host = _predict_binned(t, bins, meta).astype(np.float32)
            np.testing.assert_array_equal(host, vals[i],
                                          err_msg=f"random tree {i}")

    def test_multiclass_class_scores(self):
        X, y, cats = _make_data(with_cat=False)
        y3 = (np.abs(y * 2 + (X[:, 0] > 0)) % 3).astype(float)
        bst = _train(X, y3, cats, params={"objective": "multiclass",
                                          "num_class": 3})
        drv = bst._driver
        drv._materialize()
        td = drv.train_data
        meta = drv.learner.meta_np
        k = drv.num_tree_per_iteration
        assert k == 3
        tables, depth = pack_trees(drv.models)
        dev = np.asarray(forest_class_scores(
            device_tables(tables), td.device_bins(),
            feature_meta_dev(meta), k, depth))
        host = np.zeros((k, td.num_data), np.float64)
        for i, t in enumerate(drv.models):
            host[i % k] += _predict_binned(t, td.bins, meta)
        np.testing.assert_allclose(dev, host, rtol=0, atol=1e-5)


class TestPredictPaths:
    def test_device_predict_matches_native(self):
        X, y, cats = _make_data()
        bst = _train(X, y, cats)
        p_native = bst.predict(X, raw_score=True)
        p_dev = bst.predict(X, raw_score=True, device="tpu")
        np.testing.assert_allclose(p_dev, p_native, rtol=0, atol=1e-5)
        # probabilities convert identically on both paths
        np.testing.assert_allclose(bst.predict(X, device="tpu"),
                                   bst.predict(X), rtol=0, atol=1e-5)

    def test_num_iteration_table_slice(self):
        X, y, cats = _make_data()
        bst = _train(X, y, cats, rounds=10)
        for ni in (1, 3, 10):
            np.testing.assert_allclose(
                bst.predict(X, raw_score=True, num_iteration=ni,
                            device="tpu"),
                bst.predict(X, raw_score=True, num_iteration=ni),
                rtol=0, atol=1e-5,
                err_msg=f"num_iteration={ni}")

    def test_prebinned_dataset_predict(self):
        X, y, cats = _make_data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         **DEVICE_ON}, ds, num_boost_round=6,
                        verbose_eval=False, keep_training_booster=True)
        Xv = X[:400]
        vd = ds.create_valid(Xv, label=y[:400])
        p_binned = bst.predict(vd, raw_score=True, device="tpu")
        p_raw = bst.predict(Xv, raw_score=True, device="tpu")
        np.testing.assert_allclose(p_binned, p_raw, rtol=0, atol=1e-5)

    def test_dataset_predict_needs_device_path(self):
        X, y, cats = _make_data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "tpu_predict_device": "false", "verbose": -1},
                        ds, num_boost_round=2, verbose_eval=False,
                        keep_training_booster=True)
        with pytest.raises(TypeError):
            bst.predict(ds)

    def test_shuffle_models_invalidates_packed_forest(self):
        X, y, cats = _make_data()
        bst = _train(X, y, cats, rounds=6)
        before = bst.predict(X, raw_score=True, device="tpu")
        bst._driver.shuffle_models()  # reorders trees in place
        after = bst.predict(X, raw_score=True, device="tpu")
        native = bst.predict(X, raw_score=True)
        # sums are order-invariant, so parity with the native walker
        # proves the device tables repacked in the NEW order (a stale
        # cache would only show up via num_iteration subsets)
        np.testing.assert_allclose(after, native, rtol=0, atol=1e-5)
        sub_dev = bst.predict(X, raw_score=True, num_iteration=2,
                              device="tpu")
        sub_nat = bst.predict(X, raw_score=True, num_iteration=2)
        np.testing.assert_allclose(sub_dev, sub_nat, rtol=0, atol=1e-5)
        del before

    def test_foreign_mappers_rejected(self):
        """A Dataset binned against a DIFFERENT reference must be refused
        — traversing foreign bin space would silently return garbage."""
        X, y, cats = _make_data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         **DEVICE_ON}, ds, num_boost_round=3,
                        verbose_eval=False, keep_training_booster=True)
        X2, y2, _ = _make_data(seed=99)
        ds2 = lgb.Dataset(X2, label=y2, params={"max_bin": 31})
        foreign = ds2.create_valid(X2[:200], label=y2[:200])
        foreign.construct()
        with pytest.raises(ValueError, match="reference"):
            bst.predict(foreign, device="tpu")

    def test_device_predict_survives_free_dataset(self):
        X, y, cats = _make_data()
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         **DEVICE_ON}, ds, num_boost_round=4,
                        verbose_eval=False)  # train() frees the dataset
        assert bst._driver.train_data is None
        p_dev = bst.predict(X, raw_score=True, device="tpu")
        p_native = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(p_dev, p_native, rtol=0, atol=1e-5)


class TestReloadedModelDevicePath:
    """save_model / model_to_string round trips carry the bin-mapper
    snapshot (`tpu_bin_mappers:` trailer), so a RELOADED booster keeps
    the packed-forest device path instead of silently degrading to the
    host walker."""

    def _assert_device_path_used(self, booster, X):
        """Predict with the native walker broken: only the packed path
        can produce the answer."""
        drv = booster._driver

        def boom(*a, **k):
            raise AssertionError("native walker used on a reloaded model")

        real = drv.predict_raw
        drv.predict_raw = boom
        try:
            return booster.predict(X, raw_score=True, device="tpu")
        finally:
            drv.predict_raw = real

    def test_model_file_reload_stays_on_device(self, tmp_path):
        X, y, cats = _make_data()
        bst = _train(X, y, cats)
        path = str(tmp_path / "m.txt")
        bst.save_model(path)
        assert "tpu_bin_mappers:" in open(path).read()
        loaded = lgb.Booster(params=dict(DEVICE_ON), model_file=path)
        assert loaded._driver._pred_context() is not None
        dev = self._assert_device_path_used(loaded, X)
        # bitwise vs the original booster's device predict, allclose vs
        # its native walker (the usual f32-traversal tolerance)
        np.testing.assert_array_equal(
            dev, bst.predict(X, raw_score=True, device="tpu"))
        np.testing.assert_allclose(dev, bst.predict(X, raw_score=True),
                                   rtol=0, atol=1e-5)

    def test_model_str_reload_num_iteration_subsets(self):
        X, y, cats = _make_data()
        bst = _train(X, y, cats, rounds=10)
        loaded = lgb.Booster(params=dict(DEVICE_ON),
                             model_str=bst.model_to_string())
        for ni in (1, 4, 10):
            np.testing.assert_array_equal(
                loaded.predict(X, raw_score=True, num_iteration=ni,
                               device="tpu"),
                bst.predict(X, raw_score=True, num_iteration=ni,
                            device="tpu"),
                err_msg=f"num_iteration={ni}")

    def test_pickle_roundtrip_keeps_device_path(self):
        import pickle

        X, y, cats = _make_data(n=800)
        bst = _train(X, y, cats, rounds=4)
        clone = pickle.loads(pickle.dumps(bst))
        assert clone._driver._pred_context() is not None
        np.testing.assert_array_equal(
            self._assert_device_path_used(clone, X),
            bst.predict(X, raw_score=True, device="tpu"))

    def test_double_reload_preserves_snapshot(self):
        """Re-saving a reloaded booster keeps the trailer (the snapshot
        survives save -> load -> save -> load)."""
        X, y, cats = _make_data(n=800)
        bst = _train(X, y, cats, rounds=3)
        text1 = bst.model_to_string()
        loaded1 = lgb.Booster(params=dict(DEVICE_ON), model_str=text1)
        text2 = loaded1.model_to_string()
        assert "tpu_bin_mappers:" in text2
        loaded2 = lgb.Booster(params=dict(DEVICE_ON), model_str=text2)
        np.testing.assert_array_equal(
            loaded2.predict(X, raw_score=True, device="tpu"),
            bst.predict(X, raw_score=True, device="tpu"))

    def test_stripped_snapshot_degrades_to_host_walker(self):
        """Reference-produced models (no trailer) keep working on the
        native walker — the device path is opt-in via the snapshot."""
        X, y, cats = _make_data(n=800)
        bst = _train(X, y, cats, rounds=3)
        text = bst.model_to_string()
        stripped = text[:text.rfind("tpu_bin_mappers:")]
        loaded = lgb.Booster(params=dict(DEVICE_ON), model_str=stripped)
        assert loaded._driver._pred_context() is None
        # native walker vs native walker: exact
        np.testing.assert_array_equal(
            loaded.predict(X, raw_score=True, device="cpu"),
            bst.predict(X, raw_score=True, device="cpu"))

    def test_corrupt_snapshot_line_raises(self):
        X, y, cats = _make_data(n=600)
        bst = _train(X, y, cats, rounds=2)
        text = bst.model_to_string()
        broken = text.rsplit("tpu_bin_mappers:", 1)[0] \
            + "tpu_bin_mappers:{\"num_total\n"
        with pytest.raises(ValueError, match="corrupt tpu_bin_mappers"):
            lgb.Booster(model_str=broken)


class TestValidScoringPipeline:
    def test_valid_scores_match_host_replay(self):
        X, y, cats = _make_data(n=1200)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        Xv, yv = X[:500].copy(), y[:500]
        vd = ds.create_valid(Xv, label=yv)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         "metric": "binary_logloss", **DEVICE_ON},
                        ds, num_boost_round=8, valid_sets=[vd],
                        verbose_eval=False, keep_training_booster=True)
        drv = bst._driver
        drv._materialize()
        meta = drv.learner.meta_np
        host = np.zeros(drv.valid_sets[0].num_data, np.float32)
        for t in drv.models:
            host += _predict_binned(t, drv.valid_sets[0].bins,
                                    meta).astype(np.float32)
        dev = drv.valid_scores[0].numpy()[0].astype(np.float32)
        np.testing.assert_allclose(dev, host, rtol=0, atol=1e-5)

    def test_materialize_does_no_per_tree_fetches(self, monkeypatch):
        """The async-pipeline contract: materializing N pending trees
        with valid sets attached performs exactly ONE device_get (the
        batched record fetch) and never touches the host walker."""
        X, y, cats = _make_data(n=800)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        vd = ds.create_valid(X[:300].copy(), label=y[:300])
        bst = lgb.Booster(params={"objective": "binary", "num_leaves": 15,
                                  **DEVICE_ON}, train_set=ds)
        bst.add_valid(vd, "valid")
        n_iters = 5
        for _ in range(n_iters):
            bst.update()
        drv = bst._driver
        assert drv._pending, "async fast path not engaged"

        import jax

        calls = {"device_get": 0}
        real_device_get = jax.device_get

        def counting_device_get(x):
            calls["device_get"] += 1
            return real_device_get(x)

        monkeypatch.setattr(gbdt_mod.jax, "device_get", counting_device_get)

        def no_host_walk(*a, **k):
            raise AssertionError("host binned walker used for valid "
                                 "scoring on the device path")

        monkeypatch.setattr(gbdt_mod, "_predict_binned", no_host_walk)
        monkeypatch.setattr(drv, "_score_trees_binned", no_host_walk)
        drv._materialize()
        assert calls["device_get"] == 1, \
            f"expected 1 batched fetch, saw {calls['device_get']}"
        assert len(drv.models) == n_iters

    def test_add_valid_replays_on_device(self, monkeypatch):
        X, y, cats = _make_data(n=900)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 31},
                         categorical_feature=cats)
        bst = lgb.train({"objective": "binary", "num_leaves": 15,
                         **DEVICE_ON}, ds, num_boost_round=5,
                        verbose_eval=False, keep_training_booster=True)
        drv = bst._driver
        drv._materialize()
        monkeypatch.setattr(
            gbdt_mod, "_predict_binned",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("host walker used in add_valid replay")))
        vd = ds.create_valid(X[:300].copy(), label=y[:300])
        vd.construct()
        drv.add_valid(vd._inner, "late_valid")
        meta = drv.learner.meta_np
        # parity of the replayed state against a fresh device pass
        tables, depth = pack_trees(drv.models)
        dev = np.asarray(forest_class_scores(
            device_tables(tables), vd._inner.device_bins(),
            feature_meta_dev(meta), 1, depth))
        np.testing.assert_allclose(drv.valid_scores[-1].numpy(), dev,
                                   rtol=0, atol=1e-5)


class TestPackedForestAppend:
    def test_incremental_append_matches_full_pack(self):
        X, y, cats = _make_data()
        bst = _train(X, y, cats, rounds=4)
        drv = bst._driver
        drv._materialize()
        pf = PackedForest()
        pf.sync(drv.models[:2])
        pf.sync(drv.models)  # appends trees 2..3 only
        full, depth = pack_trees(drv.models)
        dev = pf.device()
        for key in full:
            np.testing.assert_array_equal(
                np.asarray(dev[key]),
                full[key] if key == "cat_words"
                else full[key][:len(drv.models)],
                err_msg=f"table {key} diverged after incremental append")
        assert pf.depth >= depth

    def test_cat_word_rebase(self):
        """Bitset windows of appended categorical trees must land past
        the existing word pool."""
        X, y, cats = _make_data()
        bst = _train(X, y, cats, rounds=6)
        drv = bst._driver
        drv._materialize()
        cat_trees = [t for t in drv.models if t.num_cat > 0]
        if len(cat_trees) < 2:
            pytest.skip("fixture produced too few categorical trees")
        pf = PackedForest()
        pf.sync(cat_trees[:1])
        pf.sync(cat_trees)
        meta = drv.learner.meta_np
        td = drv.train_data
        vals = np.asarray(forest_leaf_values(
            pf.device(), td.device_bins(), feature_meta_dev(meta),
            pf.depth))
        for i, t in enumerate(cat_trees):
            host = _predict_binned(t, td.bins, meta).astype(np.float32)
            np.testing.assert_array_equal(host, vals[i])
