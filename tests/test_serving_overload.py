"""Serving under fire (ISSUE 11): adaptive admission, deadline-aware
shedding, drain, and device failover.

Contracts under test:

* the AIMD admission controller cuts its level multiplicatively when
  the SLO projection is violated, regrows additively when slack, and
  sheds priority classes asymmetrically (low before high) with 429 +
  Retry-After while the hard queue wall stays 503;
* `X-Deadline-Ms` propagates into the batcher and requests that expire
  IN QUEUE are cancelled before device time — counted
  `requests_expired`, separate from `requests_timeout` dispatch waits;
* the batch window adapts: slack latency widens it toward
  `serving_max_wait_ms`, SLO pressure narrows it toward
  `serving_min_wait_ms`;
* drain stops admission (503 + Retry-After), flushes in-flight batches
  and loses / double-answers ZERO requests; SIGTERM and `close()` ride
  the same path;
* a dispatch that dies (faultline `serve_dispatch` raise) or wedges
  (`hang` + dispatch watchdog) fails the batch over to the native
  walker — accepted requests never see the failure — and feeds the
  per-entry breaker WITHOUT inflating the shed counters;
* an overload ramp at ~5x saturation keeps accepted-request latency
  inside the SLO while sheds absorb the excess, and a mid-ramp device
  failure surfaces zero errors to accepted requests.

Everything runs under JAX_PLATFORMS=cpu (tier-1).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from .conftest import *  # noqa: F401,F403  (cpu backend pin)

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (AdmissionController, MicroBatcher,
                                  ServingDraining, ServingExpired,
                                  ServingOverloaded, ServingQueueFull,
                                  ServingSession, ServingStats,
                                  ServingTimeout, serve_http)
from lightgbm_tpu.utils import faultline

PARAMS = {"objective": "binary", "num_leaves": 15,
          "tpu_predict_device": "true", "verbose": -1}


def _train(n=1500, f=6, seed=0, rounds=4):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, :3].sum(axis=1) > 0).astype(float)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 31})
    return lgb.train(PARAMS, ds, num_boost_round=rounds,
                     verbose_eval=False), X


@pytest.fixture(autouse=True)
def _clean_faults():
    faultline.reset()
    yield
    faultline.reset()


# ---------------------------------------------------------------------------
# Admission controller unit
# ---------------------------------------------------------------------------
class TestAdmissionController:
    def _ctl(self, stats=None, **kw):
        stats = stats if stats is not None else ServingStats()
        args = dict(slo_ms=50.0, queue_rows=10000, max_batch_rows=512,
                    interval_ms=1.0, step_rows=1000, backoff=0.5,
                    min_wait_ms=0.0, max_wait_ms=4.0)
        args.update(kw)
        return AdmissionController(stats, **args), stats

    def _feed(self, stats, qwait_s, dispatch_s, n=16):
        for _ in range(n):
            stats.record_queue_wait(qwait_s)
            stats.record_dispatch(dispatch_s)

    def test_multiplicative_decrease_on_slo_violation(self):
        ctl, stats = self._ctl()
        self._feed(stats, qwait_s=0.2, dispatch_s=0.05)  # way past 50ms
        time.sleep(0.002)
        ctl._maybe_update()
        assert ctl._level == pytest.approx(10000 * 0.5)
        time.sleep(0.002)
        ctl._maybe_update()
        assert ctl._level == pytest.approx(10000 * 0.25)
        # the floor: one max batch always stays admissible
        for _ in range(64):
            time.sleep(0.0015)
            ctl._maybe_update()
        assert ctl._level == 512

    def test_additive_increase_on_slack(self):
        ctl, stats = self._ctl()
        self._feed(stats, qwait_s=0.2, dispatch_s=0.05)
        time.sleep(0.002)
        ctl._maybe_update()
        level_after_cut = ctl._level
        self._feed(stats, qwait_s=0.001, dispatch_s=0.001, n=300)
        time.sleep(0.002)
        ctl._maybe_update()
        assert ctl._level == pytest.approx(level_after_cut + 1000)

    def test_priority_classes_shed_asymmetrically(self):
        ctl, stats = self._ctl()
        self._feed(stats, qwait_s=0.2, dispatch_s=0.05)
        time.sleep(0.002)
        ctl._maybe_update()          # level = 5000
        depth = 4000
        with pytest.raises(ServingOverloaded):
            ctl.admit(600, "low", depth)       # 4600 > 5000*0.6
        with pytest.raises(ServingOverloaded):
            ctl.admit(600, "normal", depth)    # 4600 > 5000*0.85
        ctl.admit(600, "high", depth)          # 4600 <= 5000*1.0
        snap = stats.snapshot()
        assert snap["requests_overload"] == 2
        assert snap["requests_shed"] == 0, \
            "admission sheds must not count as queue-capacity sheds"

    def test_window_narrows_under_pressure_and_widens_when_slack(self):
        ctl, stats = self._ctl()
        assert ctl.batch_window_s() == pytest.approx(4e-3)  # starts wide
        self._feed(stats, qwait_s=0.2, dispatch_s=0.05)
        time.sleep(0.002)
        ctl._maybe_update()
        assert ctl.batch_window_s() == 0.0                  # pinned at SLO
        self._feed(stats, qwait_s=0.0001, dispatch_s=0.0001, n=300)
        time.sleep(0.002)
        ctl._maybe_update()
        assert ctl.batch_window_s() > 3e-3                  # re-widened

    def test_drain_gate_and_disabled_mode(self):
        ctl, stats = self._ctl(enabled=False)
        ctl.admit(1000, "low", 9000)  # disabled: only drain gates
        ctl.begin_drain()
        with pytest.raises(ServingDraining):
            ctl.admit(1, "high", 0)
        assert stats.snapshot()["requests_drain_rejected"] == 1

    def test_unknown_priority_rejected(self):
        from lightgbm_tpu.serving.admission import resolve_priority

        assert resolve_priority(None) == "normal"
        assert resolve_priority("HIGH") == "high"
        with pytest.raises(ValueError, match="priority"):
            resolve_priority("urgent")


# ---------------------------------------------------------------------------
# Deadline propagation / in-queue expiry
# ---------------------------------------------------------------------------
class TestDeadlines:
    def test_expired_in_queue_cancelled_before_device(self):
        """Expired slices never reach the runner and count as
        requests_expired — NOT requests_timeout (dispatch waits)."""
        ran = []
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=0.0, stats=stats)

        def runner(Xb):
            ran.append(Xb.shape[0])
            return Xb[:, 0]

        now = time.monotonic()
        r1 = b.submit("k", runner, np.zeros((3, 2)),
                      deadline=now - 0.001)   # already expired
        r2 = b.submit("k", runner, np.zeros((5, 2)),
                      deadline=now + 30.0)
        b.start()
        try:
            out = b.wait(r2, 5.0)
            assert out.shape == (5,)
            with pytest.raises(ServingExpired):
                b.wait(r1, 5.0)
            assert ran == [5], "expired slice burned device time"
            snap = stats.snapshot()
            assert snap["requests_expired"] == 1
            assert snap["requests_timeout"] == 0
            with b._cv:
                assert b._pending_rows == 0
        finally:
            b.close()

    def test_deadline_caps_session_wait(self):
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False},
                              start=False)  # no worker -> guaranteed stall
        sess.load("m", booster=bst)
        try:
            t0 = time.monotonic()
            with pytest.raises(ServingTimeout):
                sess.predict("m", X[:4], deadline_ms=60)
            assert time.monotonic() - t0 < 5.0, \
                "deadline did not cap the default 10s timeout"
        finally:
            sess.close()

    def test_http_deadline_header(self):
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False},
                              start=False)  # stalled: everything expires
        sess.load("m", booster=bst)
        server = serve_http(sess, "127.0.0.1", 0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"model": "m",
                                 "rows": [[0.0] * X.shape[1]]}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Deadline-Ms": "80"})
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 504
            assert time.monotonic() - t0 < 5.0
            body = json.loads(ei.value.read())
            assert body["code"] == "timeout"
        finally:
            server.shutdown()
            sess.close()


# ---------------------------------------------------------------------------
# Structured shed responses (429 vs 503 + Retry-After)
# ---------------------------------------------------------------------------
class TestShedResponses:
    @pytest.fixture()
    def overloaded_http(self):
        """A session whose admission level is crushed to the floor, so
        low-priority requests shed at the door."""
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False,
                                      "serving_slo_ms": 10.0,
                                      "serving_aimd_interval_ms": 1.0,
                                      "serving_max_batch_rows": 64,
                                      "serving_queue_rows": 4096})
        sess.load("m", booster=bst)
        # feed the controller an SLO-violating history and force updates
        for _ in range(64):
            sess._stats.record_queue_wait(0.5)
            sess._stats.record_dispatch(0.1)
        for _ in range(16):
            time.sleep(0.002)
            sess.admission._maybe_update()
        assert sess.admission._level == 64  # crushed to one batch
        server = serve_http(sess, "127.0.0.1", 0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        yield base, sess, bst, X
        server.shutdown()
        sess.close()

    @staticmethod
    def _post(url, payload, headers=None):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **(headers or {})})
        return urllib.request.urlopen(req)

    def test_low_priority_sheds_429_with_retry_after(self, overloaded_http):
        base, sess, bst, X = overloaded_http
        # 80 rows > 64-row level * 0.6 for low priority
        rows = np.nan_to_num(X[:80], nan=0.0).tolist()
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(base + "/predict",
                       {"model": "m", "rows": rows},
                       headers={"X-Priority": "low"})
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        body = json.loads(ei.value.read())
        assert body["code"] == "overload"
        assert body["retry_after_ms"] > 0
        assert sess.stats()["requests_overload"] >= 1

    def test_high_priority_still_admitted(self, overloaded_http):
        base, sess, bst, X = overloaded_http
        rows = np.nan_to_num(X[:8], nan=0.0)
        with self._post(base + "/predict",
                        {"model": "m", "rows": rows.tolist()},
                        headers={"X-Priority": "high"}) as resp:
            out = json.loads(resp.read())
        np.testing.assert_array_equal(
            np.asarray(out["predictions"]),
            bst.predict(rows, device="tpu", tpu_predict_device="true"))

    def test_queue_capacity_still_503(self):
        """The hard serving_queue_rows wall keeps its 503 (capacity)
        while admission sheds are 429 (overload)."""
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=50.0,
                         queue_rows=100, stats=stats)  # worker NOT started
        runner = lambda Xb: Xb[:, 0]  # noqa: E731
        b.submit("k", runner, np.zeros((100, 2)))
        with pytest.raises(ServingQueueFull):
            b.submit("k", runner, np.zeros((1, 2)))
        snap = stats.snapshot()
        assert snap["requests_shed"] == 1
        assert snap["requests_overload"] == 0


# ---------------------------------------------------------------------------
# Drain lifecycle
# ---------------------------------------------------------------------------
class TestDrain:
    def test_drain_flushes_zero_lost_zero_duplicated(self):
        """Every request admitted before drain() resolves exactly once;
        requests after drain are refused."""
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=32, max_wait_ms=50.0, stats=stats)
        served_rows = []

        def runner(Xb):
            time.sleep(0.005)  # make the flush non-trivial
            served_rows.append(int(Xb.shape[0]))
            return Xb[:, 0]

        reqs = [b.submit("k", runner, np.full((4, 2), float(i)))
                for i in range(12)]
        b.start()
        assert b.drain(timeout_s=30.0)
        with pytest.raises(RuntimeError, match="closed"):
            b.submit("k", runner, np.zeros((1, 2)))
        results = [b.wait(r, 5.0) for r in reqs]
        for i, out in enumerate(results):
            np.testing.assert_array_equal(out, np.full(4, float(i)))
        assert sum(served_rows) == 48  # every admitted row served once
        with b._cv:
            assert b._pending_rows == 0 and not b._queues
        b.close()

    def test_session_drain_and_post_drain_rejection(self):
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False})
        sess.load("m", booster=bst)
        try:
            sess.predict("m", X[:8])
            out = sess.drain()
            assert out["drained"] is True and out["queued_rows"] == 0
            with pytest.raises(ServingDraining):
                sess.predict("m", X[:8])
            st = sess.stats()
            assert st["drains"] == 1
            assert st["requests_drain_rejected"] == 1
            assert st["draining"] is True
            # idempotent
            assert sess.drain()["drained"] is True
            assert sess.stats()["drains"] == 1
        finally:
            sess.close()

    def test_http_drain_route_and_healthz(self):
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False})
        sess.load("m", booster=bst)
        server = serve_http(sess, "127.0.0.1", 0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            with urllib.request.urlopen(base + "/healthz") as resp:
                assert json.loads(resp.read())["ok"] is True
            req = urllib.request.Request(base + "/drain", data=b"{}")
            with urllib.request.urlopen(req) as resp:
                assert json.loads(resp.read())["drained"] is True
            # draining replicas drop out of LB rotation
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["draining"] is True
            # and predicts get a structured 503 + Retry-After
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"model": "m",
                                 "rows": [[0.0] * X.shape[1]]}).encode())
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["code"] == "draining"
            assert "Retry-After" in ei.value.headers
        finally:
            server.shutdown()
            sess.close()

    def test_drain_under_concurrent_load_no_lost_request(self):
        """Drain races 16 submitting threads: every accepted predict
        returns a correct result or a structured shed — never a hang,
        never a wrong answer."""
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False,
                                      "serving_max_wait_ms": 1.0})
        sess.load("m", booster=bst)
        oracle = bst.predict(X[:8], device="tpu", tpu_predict_device="true")
        n_threads, results, failures = 16, [], []
        barrier = threading.Barrier(n_threads + 1)

        def worker():
            barrier.wait()
            for _ in range(6):
                try:
                    got = sess.predict("m", X[:8], timeout_ms=10000)
                    if not np.array_equal(got, oracle):
                        failures.append("wrong answer")
                    results.append(1)
                except (ServingDraining, RuntimeError):
                    results.append(0)
                except Exception as exc:  # noqa: BLE001
                    failures.append(repr(exc))

        ts = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ts:
            t.start()
        barrier.wait()
        time.sleep(0.01)
        out = sess.drain()
        for t in ts:
            t.join()
        assert out["drained"] is True
        assert not failures, failures[:5]
        assert len(results) == n_threads * 6
        sess.close()


# ---------------------------------------------------------------------------
# Device failover: breaker x shed x deadline interplay
# ---------------------------------------------------------------------------
class TestFailover:
    def test_dispatch_raise_fails_over_riders_get_answers(self):
        """faultline serve_dispatch raise: every rider in the batch is
        answered via the walker, the failover is counted, and the shed
        counters stay untouched."""
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False,
                                      "serving_breaker_failures": 3})
        sess.load("m", booster=bst)
        oracle = bst.predict(X[:10], device="cpu")
        try:
            faultline.arm("serve_dispatch", action="raise", times=1)
            got = sess.predict("m", X[:10])
            np.testing.assert_allclose(got, oracle, rtol=0, atol=1e-12)
            st = sess.stats()
            # the entry's own predict catches the injected raise and
            # serves the batch via its internal walker fallback
            assert st["device_fallbacks"] >= 1
            assert st["requests_shed"] == 0
            assert st["requests_overload"] == 0
            assert st["requests_timeout"] == 0
        finally:
            sess.close()

    def test_dispatch_hang_watchdog_fails_over(self):
        """faultline serve_dispatch hang: the dispatch watchdog abandons
        the wedged thread, the batch re-runs on the walker, and the
        breaker records the failure — accepted requests never see it."""
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False,
                                      "serving_dispatch_timeout_ms": 300.0,
                                      "serving_breaker_failures": 1,
                                      "serving_breaker_cooldown_ms": 1e6})
        sess.load("m", booster=bst)
        oracle = bst.predict(X[:6], device="cpu")
        try:
            faultline.arm("serve_dispatch", action="hang", times=1)
            t0 = time.monotonic()
            got = sess.predict("m", X[:6], timeout_ms=30000)
            wall = time.monotonic() - t0
            np.testing.assert_allclose(got, oracle, rtol=0, atol=1e-12)
            assert wall < 10.0, "hang was not cut by the watchdog"
            st = sess.stats()
            assert st["dispatch_timeouts"] == 1
            assert st["dispatch_failovers"] == 1
            entry = sess.registry.resolve("m")
            assert entry.breaker.state == "open"
            assert entry.healthy is False
            assert any(m["key"] == "m@1" and m["healthy"] is False
                       for m in sess.models())
            # breaker open: the next request short-circuits to the
            # walker with zero device attempts (and zero new timeouts)
            got2 = sess.predict("m", X[:6])
            np.testing.assert_allclose(got2, oracle, rtol=0, atol=1e-12)
            assert sess.stats()["dispatch_timeouts"] == 1
        finally:
            sess.close()

    def test_stale_success_cannot_close_breaker(self):
        """A dispatch the watchdog abandoned (and recorded as failed)
        that completes LATER must not wipe the failure streak or close
        an open breaker — only an allowed half-open probe may."""
        from lightgbm_tpu.serving.stats import CircuitBreaker

        br = CircuitBreaker(threshold=3, cooldown_s=1e6)
        gen = br.generation          # slow attempt begins
        br.record_failure()          # watchdog abandons it
        br.record_success(gen)       # straggler completes minutes later
        assert br._failures == 1, "stale success wiped the streak"
        br.record_failure()
        br.record_failure()
        assert br.state == "open"
        br.record_success()          # unattributed late success
        assert br.state == "open", "open breaker closed without a probe"
        # an allowed half-open probe still closes it
        br.cooldown_s = 0.0
        assert br.allow()            # open -> half_open probe
        br.record_success(br.generation)
        assert br.state == "closed"

    def test_abandoned_dispatch_never_overlaps_new_device_work(self):
        """A slow (not wedged) dispatch abandoned by the watchdog keeps
        running on the serial helper; new batches fail over to the
        fallback instead of running the device runner CONCURRENTLY."""
        stats = ServingStats()
        b = MicroBatcher(max_batch_rows=64, max_wait_ms=0.0, stats=stats,
                         dispatch_timeout_ms=100.0)
        inflight, peak = [0], [0]
        lock = threading.Lock()

        def slow_runner(Xb):
            with lock:
                inflight[0] += 1
                peak[0] = max(peak[0], inflight[0])
            time.sleep(0.4)
            with lock:
                inflight[0] -= 1
            return Xb[:, 0]

        fallback = lambda Xb: Xb[:, 0] + 100.0  # noqa: E731
        b.start()
        try:
            r1 = b.submit("k", slow_runner, np.zeros((3, 2)),
                          fallback=fallback, on_error=lambda e: True)
            out1 = b.wait(r1, 5.0)   # watchdog @100ms -> fallback
            np.testing.assert_array_equal(out1, np.full(3, 100.0))
            # the abandoned runner is still sleeping: new device work
            # must be refused and served by the fallback
            r2 = b.submit("k", slow_runner, np.zeros((2, 2)),
                          fallback=fallback, on_error=lambda e: True)
            np.testing.assert_array_equal(b.wait(r2, 5.0),
                                          np.full(2, 100.0))
            assert peak[0] == 1, "device dispatches overlapped"
            assert stats.snapshot()["dispatch_timeouts"] == 1, \
                "busy-refusal miscounted as a watchdog timeout"
            assert stats.snapshot()["dispatch_failovers"] == 2
            time.sleep(0.5)          # the abandoned dispatch finishes
            r3 = b.submit("k", slow_runner, np.zeros((2, 2)),
                          fallback=fallback, on_error=lambda e: True)
            b.wait(r3, 5.0)
            assert peak[0] == 1
        finally:
            b.close()

    def test_caller_errors_do_not_fail_over(self):
        """A malformed request raises identically on both paths: no
        failover, no breaker damage, the caller gets the error."""
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False})
        sess.load("m", booster=bst)
        from lightgbm_tpu.utils.log import LightGBMError

        try:
            with pytest.raises(LightGBMError, match="features"):
                sess.predict("m", X[:4, :3])
            st = sess.stats()
            assert st["dispatch_failovers"] == 0
            assert sess.registry.resolve("m").breaker.state == "closed"
        finally:
            sess.close()

    def test_breaker_opens_under_concurrent_load_without_shed_inflation(self):
        """Concurrent load with repeated serve_dispatch injection: the
        breaker opens, every request is still answered correctly, and
        the failure path never inflates requests_shed /
        requests_overload / requests_expired."""
        bst, X = _train()
        sess = ServingSession(params={"serving_warmup": False,
                                      "serving_breaker_failures": 2,
                                      "serving_breaker_cooldown_ms": 1e6,
                                      "serving_max_wait_ms": 1.0})
        sess.load("m", booster=bst)
        oracle_dev = bst.predict(X[:8], device="tpu",
                                 tpu_predict_device="true")
        oracle_cpu = bst.predict(X[:8], device="cpu")
        faultline.arm("serve_dispatch", action="raise", times=4)
        n_threads, failures = 12, []
        barrier = threading.Barrier(n_threads)

        def worker():
            barrier.wait()
            for _ in range(4):
                try:
                    got = sess.predict("m", X[:8], timeout_ms=30000)
                    if not (np.array_equal(got, oracle_dev)
                            or np.allclose(got, oracle_cpu,
                                           rtol=0, atol=1e-12)):
                        failures.append("wrong answer")
                except Exception as exc:  # noqa: BLE001
                    failures.append(repr(exc))

        ts = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        try:
            assert not failures, failures[:5]
            st = sess.stats()
            assert st["breaker_open"] >= 1
            assert sess.registry.resolve("m").breaker.state == "open"
            assert st["requests_shed"] == 0
            assert st["requests_overload"] == 0
            assert st["requests_expired"] == 0
        finally:
            sess.close()


# ---------------------------------------------------------------------------
# Overload ramp (acceptance): p99 within SLO, sheds absorb, failover clean
# ---------------------------------------------------------------------------
class TestOverloadRamp:
    def _slow_session(self, bst, row_s=5e-4, slo_ms=250.0):
        """A session whose device path costs `row_s` PER ROW, so
        coalescing cannot absorb the offered load and the overload is
        real: capacity = 1/row_s rows/s, independent of batching."""
        sess = ServingSession(params={
            "serving_warmup": False, "serving_slo_ms": slo_ms,
            "serving_aimd_interval_ms": 5.0,
            "serving_aimd_step_rows": 64,
            "serving_max_batch_rows": 256,
            "serving_queue_rows": 8192,
            "serving_max_wait_ms": 1.0})
        sess.load("m", booster=bst)
        entry = sess.registry.resolve("m")
        real = entry.predict

        def slow_predict(Xb, **kw):
            if not kw.get("warmup"):
                time.sleep(row_s * Xb.shape[0])
            return real(Xb, **kw)

        entry.predict = slow_predict
        return sess

    def test_ramp_sheds_absorb_and_p99_holds(self):
        bst, X = _train()
        slo_ms = 250.0
        # capacity 2000 rows/s; 24 closed-loop workers x 16 rows with
        # ~8ms accepted service time offer far beyond 5x that
        sess = self._slow_session(bst, row_s=5e-4, slo_ms=slo_ms)
        stop = time.monotonic() + 4.0
        ok_lat, sheds, errors = [], [0], []

        def worker():
            while time.monotonic() < stop:
                t0 = time.monotonic()
                try:
                    sess.predict("m", X[:16], priority="low",
                                 deadline_ms=slo_ms)
                    ok_lat.append(time.monotonic() - t0)
                except (ServingOverloaded, ServingQueueFull,
                        ServingTimeout):
                    sheds[0] += 1
                    time.sleep(0.002)
                except Exception as exc:  # noqa: BLE001
                    errors.append(repr(exc))

        # capacity 2000 rows/s; 40 closed-loop 16-row workers keep
        # >=640 rows (320ms of device time) in flight — decisively past
        # the 250ms deadline so shedding MUST engage (24 workers sat
        # right at the boundary and flickered)
        ts = [threading.Thread(target=worker) for _ in range(40)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        try:
            assert not errors, errors[:5]
            assert len(ok_lat) > 20, "goodput collapsed under overload"
            assert sheds[0] > 0, "nothing shed at 5x saturation"
            p99 = sorted(ok_lat)[int(0.99 * (len(ok_lat) - 1))]
            # accepted requests hold the SLO (deadline-capped: an
            # accepted request can never report beyond its budget)
            assert p99 <= slo_ms / 1e3 * 1.5, \
                f"accepted p99 {p99 * 1e3:.0f}ms vs slo {slo_ms}ms"
            st = sess.stats()
            assert st["requests_overload"] + st["requests_shed"] \
                + st["requests_expired"] + st["requests_timeout"] > 0
        finally:
            sess.close()

    def test_mid_ramp_device_failure_zero_errors_to_accepted(self):
        """A device failure injected mid-load: accepted requests keep
        getting correct answers (failover/breaker), zero errors."""
        bst, X = _train()
        sess = ServingSession(params={
            "serving_warmup": False, "serving_breaker_failures": 2,
            "serving_breaker_cooldown_ms": 200.0,
            "serving_max_wait_ms": 1.0})
        sess.load("m", booster=bst)
        # a request may legally be served by EITHER path mid-failure:
        # the device kernel (bitwise vs the tpu oracle) or the walker
        # fallback (f64 host math)
        oracle_dev = bst.predict(X[:8], device="tpu",
                                 tpu_predict_device="true")
        oracle_cpu = bst.predict(X[:8], device="cpu")
        stop = time.monotonic() + 2.0
        errors, served = [], [0]

        def worker():
            while time.monotonic() < stop:
                try:
                    got = sess.predict("m", X[:8], timeout_ms=30000)
                    if not (np.array_equal(got, oracle_dev)
                            or np.allclose(got, oracle_cpu,
                                           rtol=0, atol=1e-12)):
                        errors.append("wrong answer")
                    served[0] += 1
                except Exception as exc:  # noqa: BLE001
                    errors.append(repr(exc))

        ts = [threading.Thread(target=worker) for _ in range(8)]
        for t in ts:
            t.start()
        time.sleep(0.3)
        faultline.arm("serve_dispatch", action="raise", times=6)
        for t in ts:
            t.join()
        try:
            assert not errors, errors[:5]
            assert served[0] > 0
            assert sess.stats()["device_fallbacks"] >= 1
        finally:
            sess.close()
