"""Quantized-gradient histogram pipeline (tpu_hist_precision=int16|int8).

Covers the ISSUE-4 acceptance matrix: float modes are bitwise no-ops
under the new quant params, integer histograms match an np.int64 oracle
EXACTLY on every backend (xla + both pallas variants), stochastic
rounding is unbiased in expectation and deterministic given the seed,
full trainings stay within 2e-3 of f32 quality on binary / multiclass /
regression, data-parallel int8 split decisions are bit-identical across
1/2/4 shard meshes (int32 psum is associative), and the optional leaf
refit changes values but never structure.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.models.learner import TPUTreeLearner
from lightgbm_tpu.ops import grower as G
from lightgbm_tpu.ops.histogram import (build_histogram,
                                        build_histogram_batched_t,
                                        pack_stats, quant_limit,
                                        quantize_values)


def _auc(y, score):
    """Rank-based AUC (no sklearn dependency in the test tier)."""
    n = len(y)
    order = np.argsort(score, kind="stable")
    rank = np.empty(n)
    rank[order] = np.arange(1, n + 1)
    pos = y > 0
    np_, nn = pos.sum(), n - pos.sum()
    return (rank[pos].sum() - np_ * (np_ + 1) / 2) / (np_ * nn)


def _binary_problem(n=3000, f=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2]
         + 0.3 * rng.normal(size=n) > 0).astype(np.float64)
    return X, y


def _train(X, y, prec, rounds=20, keep=False, **extra):
    p = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
         "min_data_in_leaf": 5, "verbosity": -1,
         "tpu_hist_precision": prec, **extra}
    ds = lgb.Dataset(X, label=y, params={"max_bin": p["max_bin"]})
    return lgb.train(p, ds, num_boost_round=rounds,
                     keep_training_booster=keep)


def _model_text(bst):
    return bst.model_to_string().split("\nparameters:")[0]


class TestQuantLimit:
    def test_type_max_when_rows_small(self):
        assert quant_limit("int8", 1000) == 127
        assert quant_limit("int16", 1000) == 32767

    def test_grid_narrows_for_large_row_counts(self):
        # int16 at 1M rows must cap so n * qmax fits int32
        q = quant_limit("int16", 1_000_000)
        assert q < 32767
        assert q * 1_000_000 <= 2 ** 31 - 1
        assert quant_limit("int8", 10_000_000) == 127

    def test_raises_past_int32_capacity(self):
        with pytest.raises(ValueError):
            quant_limit("int8", 2 ** 32)


class TestHistogramInt64Oracle:
    """int8/int16 histograms must equal exact int64 accumulation."""

    def _case(self, precision, n=2048, F=6, B=16, seed=1):
        rng = np.random.default_rng(seed)
        bins = rng.integers(0, B, size=(n, F)).astype(np.int32)
        q = quant_limit(precision, n)
        mask = (rng.random(n) < 0.8).astype(np.float32)
        g = (rng.integers(-q, q + 1, size=n) * (mask > 0)).astype(np.int32)
        h = (rng.integers(0, q + 1, size=n) * (mask > 0)).astype(np.int32)
        oracle = np.zeros((F, B, 3), np.int64)
        for f in range(F):
            np.add.at(oracle[f, :, 0], bins[:, f], g.astype(np.int64))
            np.add.at(oracle[f, :, 1], bins[:, f], h.astype(np.int64))
            np.add.at(oracle[f, :, 2], bins[:, f],
                      (mask > 0).astype(np.int64))
        return bins, g, h, mask, oracle

    @pytest.mark.parametrize("precision", ["int8", "int16"])
    def test_build_histogram_exact(self, precision):
        bins, g, h, mask, oracle = self._case(precision)
        stats = pack_stats(jnp.asarray(g), jnp.asarray(h),
                           jnp.asarray(mask), precision)
        assert stats.dtype == {"int8": jnp.int8,
                               "int16": jnp.int16}[precision]
        hist = np.asarray(build_histogram(
            jnp.asarray(bins), stats, 16, block_rows=512,
            precision=precision))
        assert hist.dtype == np.int32
        np.testing.assert_array_equal(hist.astype(np.int64), oracle)

    @pytest.mark.parametrize("impl", ["xla", "pallas", "pallas2"])
    def test_batched_slots_exact(self, impl):
        n, F, B, K = 1024, 5, 16, 4
        bins, g, h, mask, _ = self._case("int8", n=n, F=F, B=B)
        rng = np.random.default_rng(2)
        leaf = rng.integers(0, K, size=n).astype(np.int32)
        oracle = np.zeros((K, F, B, 3), np.int64)
        for k in range(K):
            m = leaf == k
            for f in range(F):
                np.add.at(oracle[k, f, :, 0], bins[m, f],
                          g[m].astype(np.int64))
                np.add.at(oracle[k, f, :, 1], bins[m, f],
                          h[m].astype(np.int64))
                np.add.at(oracle[k, f, :, 2], bins[m, f],
                          (mask > 0)[m].astype(np.int64))
        block = 256
        nb = n // block
        bins_tb = jnp.asarray(np.ascontiguousarray(bins.T)
                              .reshape(F, nb, block).transpose(1, 0, 2))
        stats = pack_stats(jnp.asarray(g), jnp.asarray(h),
                           jnp.asarray(mask), "int8").reshape(3, nb, block)
        hist = np.asarray(build_histogram_batched_t(
            bins_tb, stats, jnp.asarray(leaf.reshape(nb, block)),
            jnp.arange(K, dtype=jnp.int32), B, "int8", impl=impl))
        np.testing.assert_array_equal(hist.astype(np.int64), oracle)


class TestStochasticRounding:
    def test_unbiased_in_expectation(self):
        x = jnp.full(200000, 0.3)
        r = np.asarray(quantize_values(x, 1.0, 127, "stochastic",
                                       12, 34, 0, 7))
        assert set(np.unique(r)) <= {0, 1}
        # sigma = sqrt(0.21 / n) ~ 0.001; 5-sigma band
        assert abs(r.mean() - 0.3) < 5e-3

    def test_deterministic_given_seed_and_offset(self):
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=4096).astype(np.float32))
        a = np.asarray(quantize_values(x, 0.01, 127, "stochastic",
                                       12, 34, 0, 7))
        b = np.asarray(quantize_values(x, 0.01, 127, "stochastic",
                                       12, 34, 0, 7))
        c = np.asarray(quantize_values(x, 0.01, 127, "stochastic",
                                       99, 34, 0, 7))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)

    def test_shard_offset_slices_the_global_stream(self):
        # rows [1024:2048] quantized as a "shard" (row_offset=1024) must
        # equal the same slice of the whole-array draw: the invariance
        # that makes data-parallel quantization shard-count independent
        x = jnp.asarray(np.random.default_rng(1)
                        .normal(size=2048).astype(np.float32))
        whole = np.asarray(quantize_values(x, 0.01, 127, "stochastic",
                                           5, 6, 0, 7))
        shard = np.asarray(quantize_values(x[1024:], 0.01, 127,
                                           "stochastic", 5, 6, 1024, 7))
        np.testing.assert_array_equal(whole[1024:], shard)

    def test_nearest_is_rint(self):
        x = jnp.asarray([0.4, 0.6, -0.4, -0.6, 1.5, 2.5])
        r = np.asarray(quantize_values(x, 1.0, 127, "nearest"))
        np.testing.assert_array_equal(r, np.rint(np.asarray(x)))

    def test_values_stay_on_grid(self):
        x = jnp.asarray(np.random.default_rng(2)
                        .normal(size=1000).astype(np.float32) * 100)
        r = np.asarray(quantize_values(x, jnp.max(jnp.abs(x)) / 127,
                                       127, "stochastic", 1, 2, 0, 3))
        assert r.min() >= -127 and r.max() <= 127


class TestFloatPathsUnchanged:
    def test_quant_params_are_noops_for_float_precisions(self):
        X, y = _binary_problem(n=1200)
        base = _model_text(_train(X, y, "hilo", rounds=6))
        flipped = _model_text(_train(X, y, "hilo", rounds=6,
                                     tpu_quant_round="nearest",
                                     tpu_quant_refit_leaves=False))
        assert flipped == base

    def test_quantized_training_deterministic_given_seed(self):
        X, y = _binary_problem(n=1200)
        a = _model_text(_train(X, y, "int8", rounds=8, seed=11))
        b = _model_text(_train(X, y, "int8", rounds=8, seed=11))
        assert a == b

    def test_invalid_quant_config_rejected(self):
        X, y = _binary_problem(n=400)
        with pytest.raises(ValueError):
            _train(X, y, "int4", rounds=1)
        with pytest.raises(ValueError):
            _train(X, y, "int8", rounds=1, tpu_quant_round="banker")
        with pytest.raises(ValueError):
            _train(X, y, "int8", rounds=1, tpu_sparse_threshold=0.5,
                   enable_bundle=False)


class TestTrainQualityParity:
    """Full-train quality within 2e-3 of f32 (ISSUE-4 acceptance)."""

    def test_binary_auc(self):
        X, y = _binary_problem()
        aucs = {}
        for prec in ("f32", "int16", "int8"):
            pred = _train(X, y, prec).predict(X, raw_score=True)
            aucs[prec] = _auc(y, pred)
        assert abs(aucs["int16"] - aucs["f32"]) < 2e-3, aucs
        assert abs(aucs["int8"] - aucs["f32"]) < 2e-3, aucs

    def test_regression_l2(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(2000, 8))
        y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.normal(size=2000)
        mses = {}
        for prec in ("f32", "int16", "int8"):
            p = {"objective": "regression", "num_leaves": 31,
                 "max_bin": 63, "min_data_in_leaf": 5, "verbosity": -1,
                 "tpu_hist_precision": prec}
            ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
            bst = lgb.train(p, ds, num_boost_round=20)
            mses[prec] = float(np.mean((bst.predict(X) - y) ** 2))
        assert mses["int16"] <= mses["f32"] * 1.05, mses
        assert mses["int8"] <= mses["f32"] * 1.05, mses

    def test_multiclass_logloss(self):
        rng = np.random.default_rng(4)
        n = 1500
        X = rng.normal(size=(n, 8))
        y = (np.argmax(X[:, :3] + 0.3 * rng.normal(size=(n, 3)), axis=1)
             .astype(np.float64))
        lls = {}
        for prec in ("f32", "int8"):
            p = {"objective": "multiclass", "num_class": 3,
                 "num_leaves": 15, "max_bin": 63, "min_data_in_leaf": 5,
                 "verbosity": -1, "tpu_hist_precision": prec}
            ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
            bst = lgb.train(p, ds, num_boost_round=15)
            prob = np.clip(bst.predict(X), 1e-9, 1.0)
            lls[prec] = float(-np.mean(np.log(
                prob[np.arange(n), y.astype(int)])))
        assert lls["int8"] <= lls["f32"] + 2e-2, lls


class TestDataParallelBitwise:
    """int8 split decisions bit-identical across 1/2/4 shard meshes: the
    quantized rows are sharding-invariant (hashed global-row rounding),
    max-abs scales pmax exactly, and int32 histogram psum is associative
    — so EVERY record field (features, thresholds, gains, outputs)
    matches bitwise, not just approximately (contrast the float modes'
    0.85-agreement bound in test_parallel.py)."""

    def _grow_records(self, X, y, **cfg):
        params = {"objective": "binary", "max_bin": 63, "num_leaves": 15,
                  "min_data_in_leaf": 5, "tpu_block_rows": 512,
                  "tpu_hist_precision": "int8", "verbosity": -1}
        params.update(cfg)
        config = Config(params)
        td = TrainingData.from_matrix(X, y, config)
        learner = TPUTreeLearner(config, td)
        r = np.random.default_rng(3)
        grad = r.normal(size=learner.n).astype(np.float32)
        hess = np.abs(r.normal(size=learner.n)).astype(np.float32) + 0.1
        tree, leaf_ids, out = learner.train(jnp.asarray(grad),
                                            jnp.asarray(hess))
        return (np.asarray(jax.device_get(out["records"])),
                np.asarray(jax.device_get(leaf_ids)))

    def test_records_bitwise_across_shard_counts(self):
        rng = np.random.default_rng(7)
        X = rng.normal(size=(4096, 10))
        y = (X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0).astype(np.float64)
        rec1, l1 = self._grow_records(X, y)
        rec2, l2 = self._grow_records(X, y, tree_learner="data",
                                      num_machines=2)
        rec4, l4 = self._grow_records(X, y, tree_learner="data",
                                      num_machines=4)
        assert (rec1[:, G.REC_DID_SPLIT] > 0.5).sum() > 5  # real splits
        np.testing.assert_array_equal(rec1, rec2)
        np.testing.assert_array_equal(rec1, rec4)
        np.testing.assert_array_equal(l1, l2)
        np.testing.assert_array_equal(l1, l4)


class TestDataParallelModelBitwise:
    """End-to-end: serial and 4-shard data-parallel int8 trainings emit
    BITWISE-identical model files (refit off: the refit leaf values are
    the one f32 psum whose shard-order ulps could reach the model)."""

    def test_model_string_bitwise(self):
        X, y = _binary_problem(n=4096)
        texts = []
        for cfg in ({}, {"tree_learner": "data", "num_machines": 4}):
            texts.append(_model_text(_train(
                X, y, "int8", rounds=6, tpu_quant_refit_leaves=False,
                tpu_block_rows=512, **cfg)))
        assert texts[0] == texts[1]


class TestLeafRefit:
    def test_refit_changes_values_not_structure(self):
        # ONE round: from round 2 on the refit legitimately changes the
        # trajectory (refitted leaf values feed the next iteration's
        # gradients), so only the first tree's structure must match
        X, y = _binary_problem(n=2000)
        on = _train(X, y, "int8", rounds=1, tpu_quant_refit_leaves=True)
        off = _train(X, y, "int8", rounds=1,
                     tpu_quant_refit_leaves=False)
        ta = on._driver.models[0]
        tb = off._driver.models[0]
        assert ta.num_leaves == tb.num_leaves > 2
        ni = ta.num_leaves - 1
        np.testing.assert_array_equal(ta.split_feature[:ni],
                                      tb.split_feature[:ni])
        np.testing.assert_array_equal(ta.threshold_in_bin[:ni],
                                      tb.threshold_in_bin[:ni])
        assert not np.array_equal(ta.leaf_value[:ta.num_leaves],
                                  tb.leaf_value[:tb.num_leaves])

    def test_refit_auc_close_to_f32(self):
        X, y = _binary_problem(n=2000)
        auc_f = _auc(y, _train(X, y, "f32", rounds=15)
                     .predict(X, raw_score=True))
        auc_q = _auc(y, _train(X, y, "int8", rounds=15,
                               tpu_quant_refit_leaves=True)
                     .predict(X, raw_score=True))
        assert abs(auc_q - auc_f) < 2e-3, (auc_f, auc_q)

    def test_refit_scores_match_materialized_trees(self):
        # the fused step's device score state must agree with the host
        # trees it lazily materializes (the refit overrides BOTH sides
        # from the same device vector)
        X, y = _binary_problem(n=1500)
        bst = _train(X, y, "int8", rounds=6, keep=True,
                     tpu_quant_refit_leaves=True)
        dev_scores = np.asarray(bst._driver.train_scores.numpy())[0]
        replay = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(dev_scores, replay, rtol=1e-4,
                                   atol=1e-5)


class TestDeterministicModeKeepsInt:
    def test_deterministic_flag_does_not_force_f64(self):
        cfg = Config({"deterministic": True,
                      "tpu_hist_precision": "int8"})
        assert TPUTreeLearner._resolve_precision(cfg) == "int8"
        assert not jax.config.jax_enable_x64


class TestCompileCacheParam:
    def test_cache_dir_param_repoints_jax_cache(self, tmp_path):
        # tpu_compile_cache_dir must reach jax_compilation_cache_dir at
        # learner init (first device use) and actually persist entries
        # (the cache singleton latches its dir at first use; the wiring
        # resets it — see utils/backend.py enable_compilation_cache)
        import os

        cache = str(tmp_path / "xlacache")
        X, y = _binary_problem(n=500)
        prev = jax.config.jax_compilation_cache_dir
        try:
            bst = _train(X, y, "hilo", rounds=2, num_leaves=7,
                         tpu_compile_cache_dir=cache)
            assert (jax.config.jax_compilation_cache_dir or "") \
                .startswith(cache)
            entries = sum(len(f) for _, _, f in os.walk(cache))
            assert entries > 0
        finally:
            # restore the session's cache dir (already fingerprinted by
            # the import-time enable) and re-latch the singleton to it
            jax.config.update("jax_compilation_cache_dir", prev)
            try:
                import jax._src.compilation_cache as _cc

                _cc.reset_cache()
            except Exception:
                pass
