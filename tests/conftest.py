"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is
validated on `--xla_force_host_platform_device_count=8` CPU devices instead
(the driver separately dry-run-compiles the multi-chip path via
`__graft_entry__.dryrun_multichip`).  Must run before the first jax import.
"""

import os

# NOTE: the axon TPU plugin ignores JAX_PLATFORMS; JAX_PLATFORM_NAME works
os.environ.setdefault("JAX_PLATFORM_NAME", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest

REFERENCE_DIR = "/root/reference"
ORACLE_BIN = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          ".refbuild", "lightgbm")
ORACLE_LIB = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          ".refbuild", "lib_lightgbm.so")


def has_oracle() -> bool:
    return os.path.exists(ORACLE_BIN) and os.path.exists(ORACLE_LIB)


@pytest.fixture(scope="session")
def binary_example():
    """Load the reference binary_classification example data."""
    path = os.path.join(REFERENCE_DIR, "examples", "binary_classification")
    train = np.loadtxt(os.path.join(path, "binary.train"))
    test = np.loadtxt(os.path.join(path, "binary.test"))
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0],
        "X_test": test[:, 1:], "y_test": test[:, 0],
        "train_file": os.path.join(path, "binary.train"),
        "test_file": os.path.join(path, "binary.test"),
    }


@pytest.fixture(scope="session")
def rank_example():
    path = os.path.join(REFERENCE_DIR, "examples", "lambdarank")
    train = np.loadtxt(os.path.join(path, "rank.train"))
    test = np.loadtxt(os.path.join(path, "rank.test"))
    qtrain = np.loadtxt(os.path.join(path, "rank.train.query")).astype(np.int64)
    qtest = np.loadtxt(os.path.join(path, "rank.test.query")).astype(np.int64)
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0], "q_train": qtrain,
        "X_test": test[:, 1:], "y_test": test[:, 0], "q_test": qtest,
        "train_file": os.path.join(path, "rank.train"),
    }


@pytest.fixture(scope="session")
def regression_example():
    path = os.path.join(REFERENCE_DIR, "examples", "regression")
    train = np.loadtxt(os.path.join(path, "regression.train"))
    test = np.loadtxt(os.path.join(path, "regression.test"))
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0],
        "X_test": test[:, 1:], "y_test": test[:, 0],
        "train_file": os.path.join(path, "regression.train"),
    }


@pytest.fixture(scope="session")
def multiclass_example():
    path = os.path.join(REFERENCE_DIR, "examples", "multiclass_classification")
    train = np.loadtxt(os.path.join(path, "multiclass.train"))
    test = np.loadtxt(os.path.join(path, "multiclass.test"))
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0],
        "X_test": test[:, 1:], "y_test": test[:, 0],
        "train_file": os.path.join(path, "multiclass.train"),
    }
