"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is unavailable in CI; sharding correctness is
validated on `--xla_force_host_platform_device_count=8` CPU devices instead
(the driver separately dry-run-compiles the multi-chip path via
`__graft_entry__.dryrun_multichip`).  Must run before the first jax import.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Tests never touch the TPU: pin jax to the cpu backend (8 virtual devices
# for sharding tests) and drop the tunneled `axon` backend factory before
# the first backends() call, so a dead/slow tunnel cannot hang CPU-only
# test runs.  backend.py is loaded BY PATH, not via the package: importing
# `lightgbm_tpu.utils.backend` would first execute the whole package
# __init__ (basic/engine/models) before the pin runs — exactly the
# import-order hazard this block exists to close.
import importlib.util as _ilu

_spec = _ilu.spec_from_file_location(
    "_lgbm_backend_boot",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "lightgbm_tpu", "utils", "backend.py"))
_mod = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_mod)
_mod.pin_cpu_backend(force_device_count=8)

import numpy as np
import pytest


@pytest.fixture(scope="session", autouse=True)
def _blackbox_dumps_stay_out_of_the_repo(tmp_path_factory):
    """Crash-path tests (OOM exhaustion, collective chaos) dump a
    blackbox to the configured dir > $LIGHTGBM_TPU_BLACKBOX_DIR > cwd;
    cwd is the repo root under pytest, which is exactly how the stale
    `blackbox-host0.json` kept regrowing at the root (ISSUEs 16/18).
    Default the env fallback to a session temp dir so no test can
    strand a dump in the checkout; tests that assert on dump placement
    still override via monkeypatch.setenv / fr.configure(dump_dir=...)."""
    os.environ.setdefault(
        "LIGHTGBM_TPU_BLACKBOX_DIR",
        str(tmp_path_factory.mktemp("blackbox")))
    yield


REFERENCE_DIR = "/root/reference"
ORACLE_BIN = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          ".refbuild", "lightgbm")
ORACLE_LIB = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                          ".refbuild", "lib_lightgbm.so")


def has_oracle() -> bool:
    return os.path.exists(ORACLE_BIN) and os.path.exists(ORACLE_LIB)


@pytest.fixture(scope="session")
def binary_example():
    """Load the reference binary_classification example data."""
    path = os.path.join(REFERENCE_DIR, "examples", "binary_classification")
    train = np.loadtxt(os.path.join(path, "binary.train"))
    test = np.loadtxt(os.path.join(path, "binary.test"))
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0],
        "X_test": test[:, 1:], "y_test": test[:, 0],
        "train_file": os.path.join(path, "binary.train"),
        "test_file": os.path.join(path, "binary.test"),
    }


@pytest.fixture(scope="session")
def rank_example():
    # rank.train/.test are LibSVM-format: parse via the framework loader
    from lightgbm_tpu.io.parser import load_text_file
    path = os.path.join(REFERENCE_DIR, "examples", "lambdarank")
    Xtr, ytr, _, _, _, _ = load_text_file(os.path.join(path, "rank.train"))
    Xte, yte, _, _, _, _ = load_text_file(
        os.path.join(path, "rank.test"), num_features_hint=Xtr.shape[1])
    qtrain = np.loadtxt(os.path.join(path, "rank.train.query")).astype(np.int64)
    qtest = np.loadtxt(os.path.join(path, "rank.test.query")).astype(np.int64)
    return {
        "X_train": Xtr, "y_train": ytr, "q_train": qtrain,
        "X_test": Xte[:, :Xtr.shape[1]], "y_test": yte, "q_test": qtest,
        "train_file": os.path.join(path, "rank.train"),
    }


@pytest.fixture(scope="session")
def regression_example():
    path = os.path.join(REFERENCE_DIR, "examples", "regression")
    train = np.loadtxt(os.path.join(path, "regression.train"))
    test = np.loadtxt(os.path.join(path, "regression.test"))
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0],
        "X_test": test[:, 1:], "y_test": test[:, 0],
        "train_file": os.path.join(path, "regression.train"),
    }


@pytest.fixture(scope="session")
def multiclass_example():
    path = os.path.join(REFERENCE_DIR, "examples", "multiclass_classification")
    train = np.loadtxt(os.path.join(path, "multiclass.train"))
    test = np.loadtxt(os.path.join(path, "multiclass.test"))
    return {
        "X_train": train[:, 1:], "y_train": train[:, 0],
        "X_test": test[:, 1:], "y_test": test[:, 0],
        "train_file": os.path.join(path, "multiclass.train"),
    }
