"""CLI application tests (reference tests/cpp_test: run the CLI on the
shipped example configs)."""

import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute e2e trainings

from .conftest import REFERENCE_DIR

BINARY_DIR = os.path.join(REFERENCE_DIR, "examples", "binary_classification")


def run_cli_module(args, cwd):
    env = dict(os.environ)
    env["JAX_PLATFORM_NAME"] = "cpu"
    env.pop("JAX_PLATFORMS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # CPU run: skip the TPU tunnel
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run([sys.executable, "-m", "lightgbm_tpu"] + args,
                         cwd=cwd, env=env, capture_output=True, text=True,
                         timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"CLI failed:\n{out.stdout}\n{out.stderr}")
    return out.stdout


class TestCLI:
    def test_train_predict_cycle(self, tmp_path):
        model = str(tmp_path / "model.txt")
        stdout = run_cli_module([
            "task=train", f"data={BINARY_DIR}/binary.train",
            "objective=binary", "num_trees=10", "num_leaves=15",
            "metric=binary_logloss,auc", "is_training_metric=true",
            f"output_model={model}", "verbosity=1"], str(tmp_path))
        assert os.path.exists(model)
        assert "finished training" in stdout

        result = str(tmp_path / "preds.txt")
        run_cli_module([
            "task=predict", f"data={BINARY_DIR}/binary.test",
            f"input_model={model}", f"output_result={result}"],
            str(tmp_path))
        preds = np.loadtxt(result)
        labels = np.loadtxt(f"{BINARY_DIR}/binary.test")[:, 0]
        assert preds.shape == labels.shape
        assert 0.0 <= preds.min() and preds.max() <= 1.0
        auc_acc = ((preds > 0.5) == labels).mean()
        assert auc_acc > 0.7

    def test_train_conf_file(self, tmp_path):
        conf = tmp_path / "train.conf"
        model = tmp_path / "model.txt"
        conf.write_text(
            f"task = train\n"
            f"objective = binary\n"
            f"data = {BINARY_DIR}/binary.train\n"
            f"num_trees = 5\n"
            f"num_leaves = 7\n"
            f"output_model = {model}\n")
        stdout = run_cli_module([f"config={conf}"], str(tmp_path))
        assert os.path.exists(str(model))

    def test_cli_overrides_conf(self, tmp_path):
        conf = tmp_path / "train.conf"
        model = tmp_path / "model.txt"
        conf.write_text(
            f"task = train\n"
            f"objective = binary\n"
            f"data = {BINARY_DIR}/binary.train\n"
            f"num_trees = 50\n"
            f"output_model = {model}\n")
        run_cli_module([f"config={conf}", "num_trees=3", "num_leaves=7"],
                       str(tmp_path))
        text = open(str(model)).read()
        assert text.count("Tree=") == 3
