"""Golden-config consistency: our CLI vs the reference CLI on the SHIPPED
example train.conf files (the analog of reference tests/python_package_test/
test_consistency.py, which uses examples/*/train.conf as fixtures).

Each test runs both CLIs on the identical conf from the example directory
and compares the final training metric within a small tolerance — the
strongest end-to-end statement that config parsing, loading, binning,
growth, and metrics line up.
"""

import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # full example trainings

from .conftest import ORACLE_BIN, REFERENCE_DIR, has_oracle

EXAMPLES = os.path.join(REFERENCE_DIR, "examples")


def _run_ref_cli(example: str, tmp, overrides=()):
    conf = os.path.join(EXAMPLES, example, "train.conf")
    out = subprocess.run(
        [ORACLE_BIN, f"config={conf}", f"output_model={tmp}/ref_model.txt",
         *overrides],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.join(EXAMPLES, example))
    assert out.returncode == 0, out.stderr[-500:]
    return out.stdout


def _run_our_cli(example: str, tmp, overrides=()):
    conf = os.path.join(EXAMPLES, example, "train.conf")
    env = dict(os.environ)
    env["JAX_PLATFORM_NAME"] = "cpu"
    env.pop("JAX_PLATFORMS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu", f"config={conf}",
         f"output_model={tmp}/our_model.txt", "tpu_split_batch=1",
         *overrides],
        capture_output=True, text=True, timeout=900,
        cwd=os.path.join(EXAMPLES, example), env=env)
    assert out.returncode == 0, out.stderr[-800:]
    return out.stdout


def _final_metric(stdout: str, metric: str):
    """Last reported value of `metric`, robust to both CLI line formats
    (reference: 'Iteration:N, valid_1 auc : v' one metric per line; ours:
    one tab-joined line per iteration with every metric)."""
    pat = re.compile(re.escape(metric)
                     + r"\s*:\s*([-+]?\d*\.?\d+(?:[eE][-+]?\d+)?)")
    vals = [float(m.group(1)) for line in stdout.splitlines()
            for m in pat.finditer(line)]
    assert vals, f"no {metric} values in output"
    return vals[-1]


@pytest.mark.skipif(not has_oracle(), reason="reference oracle not built")
class TestGoldenConfigs:
    def test_binary_conf(self, tmp_path):
        # 60 trees: mid-curve f32 tie-break noise peaks near iter 40
        # (0.0102 logloss gap) and re-converges by 60 — budget without
        # loosening the 0.01 band
        cap = ("num_trees=60",)
        ref = _run_ref_cli("binary_classification", tmp_path, overrides=cap)
        ours = _run_our_cli("binary_classification", tmp_path, overrides=cap)
        for metric in ("binary_logloss", "auc"):
            r = _final_metric(ref, metric)
            o = _final_metric(ours, metric)
            assert abs(r - o) < 0.01, f"{metric}: ref {r} vs ours {o}"

    def test_binary_conf_sparse_storage(self, tmp_path):
        """The COO train-time storage must preserve the math contract
        against the REFERENCE oracle, not just against our own dense
        path.  The example is Higgs-dense, so threshold 0.5 routes its 3
        sparsest features (35-49% nonzero) through the COO pipeline; f32
        histogram precision isolates the path's structure from hilo
        cancellation in the zero-bin subtraction, which grows with the
        subtracted mass and is why the threshold targets TRULY sparse
        features in production."""
        ref = _run_ref_cli("binary_classification", tmp_path,
                           overrides=("num_trees=60",))
        ours = _run_our_cli("binary_classification", tmp_path,
                            overrides=("num_trees=60",
                                       "tpu_sparse_threshold=0.5",
                                       "tpu_hist_precision=f32",
                                       "enable_bundle=false"))
        assert "sparse storage:" in ours, "COO path never engaged"
        for metric in ("binary_logloss", "auc"):
            r = _final_metric(ref, metric)
            o = _final_metric(ours, metric)
            assert abs(r - o) < 0.01, f"{metric}: ref {r} vs ours {o}"

    def test_regression_conf(self, tmp_path):
        cap = ("num_trees=40",)
        ref = _run_ref_cli("regression", tmp_path, overrides=cap)
        ours = _run_our_cli("regression", tmp_path, overrides=cap)
        r = _final_metric(ref, "l2")
        o = _final_metric(ours, "l2")
        assert abs(r - o) < 0.02 * max(r, 1e-9), f"l2: ref {r} vs ours {o}"

    def test_multiclass_conf(self, tmp_path):
        # budget: 30 trees instead of the conf's 100 (identical on both
        # sides) keeps this under ~3 min so CI can run the whole tier
        cap = ("num_trees=30",)
        ref = _run_ref_cli("multiclass_classification", tmp_path,
                           overrides=cap)
        ours = _run_our_cli("multiclass_classification", tmp_path,
                            overrides=cap)
        r = _final_metric(ref, "multi_logloss")
        o = _final_metric(ours, "multi_logloss")
        assert abs(r - o) < 0.03, f"multi_logloss: ref {r} vs ours {o}"

    def test_lambdarank_conf(self, tmp_path):
        # the stock conf bags 90% of rows each iteration; the two
        # implementations' RNG streams differ, so band-parity is only
        # meaningful with bagging off (measured divergence on the stock
        # conf is ~0.04 ndcg@5 in OUR favor, 0.693 vs 0.653 — the
        # reference overfits this 201-query valid set after ~iter 10)
        det = ("bagging_freq=0", "bagging_fraction=1.0", "num_trees=30")
        ref = _run_ref_cli("lambdarank", tmp_path, overrides=det)
        ours = _run_our_cli("lambdarank", tmp_path, overrides=det)
        # ndcg@5 on the validation set
        r = _final_metric(ref, "ndcg@5")
        o = _final_metric(ours, "ndcg@5")
        assert abs(r - o) < 0.03, f"ndcg@5: ref {r} vs ours {o}"

    def test_lambdarank_stock_no_worse(self, tmp_path):
        """On the stock (bagged) conf, ours must be at least competitive."""
        cap = ("num_trees=30",)
        ref = _run_ref_cli("lambdarank", tmp_path, overrides=cap)
        ours = _run_our_cli("lambdarank", tmp_path, overrides=cap)
        r = _final_metric(ref, "ndcg@5")
        o = _final_metric(ours, "ndcg@5")
        assert o > r - 0.02, f"ndcg@5: ref {r} vs ours {o}"
