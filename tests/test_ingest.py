"""Device-parallel ingest parity suite.

Everything here is a BITWISE contract: the vectorized bin finder must
reproduce the scalar `greedy_find_bin_scalar` boundaries exactly, and
the ops/binning.py device kernel must reproduce scalar
`value_to_bin`/`values_to_bins` exactly — across NaN / zero-as-missing,
every MissingType, categorical unseen values, forced bins, max_bin edge
sizes, the uint8 -> uint16 storage crossover, and sampled-vs-full bin
finding.  A short training run closes the loop: a device-ingested
dataset must grow byte-identical trees.
"""

import json
import warnings

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.bin_mapper import (BinMapper, BinType, MissingType,
                                        greedy_find_bin,
                                        greedy_find_bin_scalar)
from lightgbm_tpu.io.dataset import TrainingData
from lightgbm_tpu.ops.binning import DeviceBinner, sort_keys


def _mixed_matrix(seed=0, n=4000, f=10):
    """Dense matrix exercising every routing corner: NaN, zeros near the
    kZeroThreshold band, a categorical column with unseen-at-predict
    values, constant (trivial) and integer-code columns."""
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[:, 1][rng.random(n) < 0.4] = 0.0
    X[:, 2][rng.random(n) < 0.25] = np.nan
    X[:, 3] = rng.choice([0, 1, 2, 5, 9, 300], size=n)     # categorical
    X[:, 4] = np.round(X[:, 4], 1)                         # heavy ties
    X[:, 5] = 1e-36 * rng.normal(size=n)                   # inside zero band
    X[:, 6] = 7.5                                          # trivial
    X[:, 7] = rng.integers(-3, 40, size=n)                 # negative ints
    return X


class TestVectorizedGreedy:
    def test_fuzz_bit_identical(self):
        rng = np.random.default_rng(42)
        for _ in range(150):
            nd = int(rng.integers(1, 400))
            dv = np.unique(np.sort(rng.normal(size=nd)))
            cnt = rng.integers(0, 25, size=len(dv)).astype(np.int64)
            cnt[int(rng.integers(0, len(dv)))] = int(rng.integers(0, 3000))
            total = int(cnt.sum()) + int(rng.integers(0, 50))
            mb = int(rng.choice([1, 2, 3, 15, 63, 255, 300]))
            mdib = int(rng.choice([0, 1, 3, 10]))
            assert greedy_find_bin(dv, cnt, mb, total, mdib) == \
                greedy_find_bin_scalar(dv.tolist(), cnt.tolist(), mb,
                                       total, mdib)

    def test_single_distinct_value(self):
        assert greedy_find_bin([1.5], [10], 16, 10, 3) == \
            greedy_find_bin_scalar([1.5], [10], 16, 10, 3)

    def test_zero_count_entries(self):
        # interior zero spliced at count 0 (find_bin does this)
        dv, cnt = [-2.0, 0.0, 3.0, 4.0], [5, 0, 5, 5]
        for mb in (2, 3, 16):
            assert greedy_find_bin(dv, cnt, mb, 15, 3) == \
                greedy_find_bin_scalar(dv, cnt, mb, 15, 3)


class TestSortKeys:
    def test_total_order_matches_f64(self):
        rng = np.random.default_rng(1)
        v = np.concatenate([
            rng.normal(size=500) * (10.0 ** rng.integers(-300, 300, 500)
                                    .astype(float)),
            [0.0, -0.0, np.inf, -np.inf, 1e-35, -1e-35, 5e-324, -5e-324,
             1.0, np.nextafter(1.0, 2.0)]])
        k = sort_keys(v)
        order = np.argsort(v, kind="stable")
        assert np.all(np.diff(k[order]) >= 0)
        # equal floats <-> equal keys (incl. -0.0 == +0.0)
        for i in range(len(v)):
            eq_f = v == v[i]
            eq_k = k == k[i]
            assert np.array_equal(eq_f, eq_k)

    def test_nan_sentinel(self):
        k = sort_keys(np.array([np.nan, np.inf, 1.0]))
        assert k[0] == np.iinfo(np.int64).max
        assert k[1] < k[0] and k[2] < k[1]


def _build_mappers(X, cfg=None, categorical=(3,)):
    td = TrainingData()
    td.feature_names = [f"Column_{i}" for i in range(X.shape[1])]
    td._find_mappers(X, cfg or Config({"max_bin": 63}), list(categorical),
                     {})
    return td


class TestDeviceKernelParity:
    @pytest.mark.parametrize("max_bin", [2, 3, 16, 255, 300])
    def test_mixed_corners(self, max_bin):
        X = _mixed_matrix(seed=max_bin)
        cfg = Config({"max_bin": max_bin})
        td = _build_mappers(X, cfg)
        used = td.used_feature_idx
        dtype = np.uint8 if td.max_num_bin <= 256 else np.uint16
        b = DeviceBinner.build(td.mappers, used, dtype, chunk_rows=512)
        assert b is not None
        dev = np.asarray(b.bin_matrix(X))
        host = np.stack([td.mappers[c].values_to_bins(X[:, c]).astype(dtype)
                         for c in used], axis=1)
        assert np.array_equal(dev, host)
        # scalar value_to_bin spot check on the corner rows
        for r in range(0, X.shape[0], 997):
            for j, c in enumerate(used):
                assert int(dev[r, j]) == td.mappers[c].value_to_bin(X[r, c])

    def test_missing_type_variants(self):
        rng = np.random.default_rng(5)
        n = 2000
        for zam, with_nan in [(False, False), (False, True), (True, False),
                              (True, True)]:
            vals = rng.normal(size=n)
            vals[rng.random(n) < 0.3] = 0.0
            if with_nan:
                vals[rng.random(n) < 0.2] = np.nan
            m = BinMapper()
            nz = vals[~((np.abs(vals) <= 1e-35) & ~np.isnan(vals))]
            m.find_bin(nz, n, max_bin=32, zero_as_missing=zam)
            b = DeviceBinner.build([m], [0], np.uint8, chunk_rows=256)
            dev = np.asarray(b.bin_matrix(vals[:, None]))[:, 0]
            assert np.array_equal(dev, m.values_to_bins(vals))

    def test_categorical_unseen_and_nan(self):
        rng = np.random.default_rng(6)
        vals = rng.choice([0, 1, 2, 5, 9], size=1000,
                          p=[0.4, 0.3, 0.2, 0.07, 0.03]).astype(float)
        m = BinMapper()
        m.find_bin(vals, 1000, max_bin=16, bin_type=BinType.CATEGORICAL)
        probe = np.array([0.0, 1.0, 9.0, 777.0, -1.0, -0.5, 3.5, np.nan,
                          np.inf, 1e18])
        b = DeviceBinner.build([m], [0], np.uint8, chunk_rows=256)
        dev = np.asarray(b.bin_matrix(probe[:, None]))[:, 0]
        assert np.array_equal(dev, m.values_to_bins(probe))
        assert int(dev[3]) == m.num_bin - 1  # unseen -> last bin

    def test_forced_bins_parity(self, tmp_path):
        X = _mixed_matrix(seed=9)
        forced = {0: [-1.0, 0.5], 4: [0.0, 1.0]}
        cfg = Config({"max_bin": 63})
        td = TrainingData()
        td.feature_names = [f"Column_{i}" for i in range(X.shape[1])]
        td._find_mappers(X, cfg, [3], {k: list(v)
                                       for k, v in forced.items()})
        used = td.used_feature_idx
        b = DeviceBinner.build(td.mappers, used, np.uint8, chunk_rows=1024)
        dev = np.asarray(b.bin_matrix(X))
        host = np.stack([td.mappers[c].values_to_bins(X[:, c])
                         .astype(np.uint8) for c in used], axis=1)
        assert np.array_equal(dev, host)

    def test_uint16_crossover(self):
        rng = np.random.default_rng(7)
        vals = rng.normal(size=5000)
        m = BinMapper()
        m.find_bin(vals, 5000, max_bin=400, min_data_in_bin=1)
        assert m.num_bin > 256  # crossover actually exercised
        b = DeviceBinner.build([m], [0], np.uint16, chunk_rows=2048)
        dev = np.asarray(b.bin_matrix(vals[:, None]))[:, 0]
        assert dev.dtype == np.uint16
        assert np.array_equal(dev, m.values_to_bins(vals).astype(np.uint16))

    def test_huge_category_ids_fall_back(self):
        m = BinMapper()
        m.find_bin(np.array([1e7, 1.0, 2.0] * 100), 300, max_bin=16,
                   bin_type=BinType.CATEGORICAL, min_data_in_bin=1)
        assert DeviceBinner.build([m], [0], np.uint8, 256) is None


class TestIngestEndToEnd:
    def test_dataset_bins_bit_identical(self):
        X = _mixed_matrix(seed=11)
        y = (np.nan_to_num(X[:, 0]) > 0).astype(float)
        kw = dict(label=y, categorical_features=[3])
        host = TrainingData.from_matrix(
            X, config=Config({"max_bin": 63, "tpu_ingest_device": "false"}),
            **kw)
        dev = TrainingData.from_matrix(
            X, config=Config({"max_bin": 63, "tpu_ingest_device": "true"}),
            **kw)
        assert dev.has_bins and dev._bins is None  # still device-resident
        assert np.array_equal(np.asarray(dev.bins), host.bins)
        assert dev._bins is not None  # property access materialized it

    def test_lazy_reductions_skip_host(self):
        X = _mixed_matrix(seed=12)
        td = TrainingData.from_matrix(
            X, config=Config({"tpu_ingest_device": "true"}))
        zf = td.column_zero_fraction()
        nz = td.column_nonzero_counts(
            np.array([m.default_bin for m in
                      (td.mappers[c] for c in td.used_feature_idx)]))
        samp = td.strided_row_sample(100)
        assert td._bins is None, "reductions must not materialize host bins"
        ref = TrainingData.from_matrix(
            X, config=Config({"tpu_ingest_device": "false"}))
        assert np.array_equal(zf, (ref.bins == 0).mean(axis=0))
        zb = np.array([ref.mappers[c].default_bin
                       for c in ref.used_feature_idx])
        assert np.array_equal(nz, (ref.bins != zb[None, :]).sum(axis=0))
        from lightgbm_tpu.io.bundling import _stride_sample

        assert np.array_equal(samp, _stride_sample(ref.bins, 100))

    def test_sampled_vs_full_equivalence(self):
        # bin_construct_sample_cnt >= n must bin-find on ALL rows: any
        # two over-sized settings give identical mappers
        X = _mixed_matrix(seed=13, n=1500)
        a = TrainingData.from_matrix(
            X, config=Config({"bin_construct_sample_cnt": 1500}))
        b = TrainingData.from_matrix(
            X, config=Config({"bin_construct_sample_cnt": 10 ** 7}))
        for ma, mb in zip(a.mappers, b.mappers):
            da, db = json.dumps(ma.to_dict()), json.dumps(mb.to_dict())
            assert da == db

    def test_trained_model_bit_identical(self):
        X = _mixed_matrix(seed=14)
        y = (np.nan_to_num(X[:, 0]) + (X[:, 3] == 2) > 0.3).astype(float)
        trees = {}
        for mode in ("false", "true"):
            ds = lgb.Dataset(X, label=y, categorical_feature=[3],
                             params={"max_bin": 63,
                                     "tpu_ingest_device": mode})
            bst = lgb.train({"objective": "binary", "num_leaves": 15,
                             "verbosity": -1, "tpu_ingest_device": mode},
                            ds, num_boost_round=6)
            s = bst.model_to_string()
            # strip the parameters trailer: tpu_ingest_device itself
            # legitimately differs there
            trees[mode] = s[:s.index("parameters:")]
        assert trees["false"] == trees["true"]

    def test_learner_bins_t_identical_device_layout(self):
        # enable_bundle=false + serial strategy = the device-side
        # transpose/pad path; the placed [G, n_pad] matrix must equal
        # the host-laid-out one byte for byte
        X = _mixed_matrix(seed=21, n=1200)
        y = (np.nan_to_num(X[:, 0]) > 0).astype(float)
        bt = {}
        for mode in ("false", "true"):
            ds = lgb.Dataset(X, label=y, categorical_feature=[3],
                             params={"enable_bundle": False,
                                     "tpu_ingest_device": mode})
            bst = lgb.train({"objective": "binary", "num_leaves": 7,
                             "verbosity": -1, "enable_bundle": False,
                             "tpu_ingest_device": mode},
                            ds, num_boost_round=2,
                            keep_training_booster=True)
            learner = bst._driver.learner
            bt[mode] = np.asarray(learner.bins_t)
            if mode == "true":
                # the device layout transposed in HBM; the host matrix
                # was never materialized by training
                assert ds._inner._bins is None
        assert np.array_equal(bt["false"], bt["true"])

    def test_device_ingest_chunking_boundaries(self):
        # multi-chunk with a ragged tail must equal single-chunk
        X = _mixed_matrix(seed=15, n=1111)
        cfgs = [Config({"tpu_ingest_device": "true",
                        "tpu_ingest_chunk_rows": c}) for c in (256, 4096)]
        a = TrainingData.from_matrix(X, config=cfgs[0])
        b = TrainingData.from_matrix(X, config=cfgs[1])
        assert np.array_equal(np.asarray(a.bins), np.asarray(b.bins))


class TestNumIterationsWarningDedupe:
    def test_warns_once_per_alias(self):
        import lightgbm_tpu.engine as engine

        X = np.random.default_rng(0).normal(size=(200, 4))
        y = (X[:, 0] > 0).astype(float)
        engine._warned_num_iter_aliases.discard("num_iterations")
        params = {"objective": "binary", "num_leaves": 4, "verbosity": -1,
                  "num_iterations": 2}
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for _ in range(3):
                lgb.train(dict(params), lgb.Dataset(X, label=y),
                          num_boost_round=5)
        hits = [x for x in w if "num_iterations" in str(x.message)]
        assert len(hits) == 1
