"""Native OpenMP forest predictor vs the Python tree traversal oracle.

The native walker (src/capi/forest_predictor.cpp) must reproduce
Tree.predict exactly — including zero/NaN missing routing and the
categorical NaN fold-to-category-0 rule (models/tree.py:216-233).
"""

import numpy as np
import pytest


def _native_available():
    from lightgbm_tpu.native import native_lib
    return native_lib() is not None


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="native lib not built")


def _train(X, y, params, rounds=6, keep=False):
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
    return lgb.train(params, ds, num_boost_round=rounds, verbose_eval=False,
                     keep_training_booster=keep)


def _python_raw(bst, X):
    out = np.zeros(len(X))
    for t in bst._driver.models:
        out += t.predict(X)
    return out


class TestForestPredictor:
    def test_numerical_missing_parity(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(900, 5))
        X[rng.random(X.shape) < 0.2] = np.nan
        y = np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) ** 2
        bst = _train(X, y, {"objective": "regression", "num_leaves": 15,
                            "min_data_in_leaf": 5})
        got = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(got, _python_raw(bst, X),
                                   rtol=1e-12, atol=1e-12)

    def test_categorical_nan_fold_parity(self):
        """NaN categorical at predict time folds to category 0 for
        non-NaN missing types; the native walker must agree."""
        rng = np.random.default_rng(3)
        n = 1200
        Xc = rng.integers(0, 6, size=n).astype(np.float64)
        X = np.column_stack([Xc, rng.normal(size=n)])
        y = (Xc < 2) * 2.0 + X[:, 1]
        bst = _train(X, y, {"objective": "regression", "num_leaves": 15,
                            "min_data_in_leaf": 5,
                            "categorical_feature": [0]})
        # NaN and fractional negatives in (-1, 0): both fold to category
        # 0 (truncation-before-negative-test, like the reference)
        vals = np.concatenate([np.full(30, np.nan), np.full(30, -0.5)])
        Xq = np.column_stack([vals, rng.normal(size=60)])
        got = bst.predict(Xq, raw_score=True)
        np.testing.assert_allclose(got, _python_raw(bst, Xq),
                                   rtol=1e-12, atol=1e-12)

    def test_leaf_index_parity(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(500, 4))
        y = X[:, 0] * X[:, 1]
        bst = _train(X, y, {"objective": "regression", "num_leaves": 7,
                            "min_data_in_leaf": 5})
        leaves = bst.predict(X, pred_leaf=True)
        expect = np.column_stack([t.predict_leaf(X)
                                  for t in bst._driver.models])
        np.testing.assert_array_equal(leaves, expect)


class TestBinnedForestWalker:
    def test_subset_matches_predict_binned(self):
        """The native binned-subset walker must reproduce the numpy
        bin-space traversal over mixed numerical/categorical trees with
        per-tree scales."""
        import lightgbm_tpu as lgb
        from lightgbm_tpu.models.gbdt import _predict_binned

        rng = np.random.default_rng(11)
        n = 1500
        Xc = rng.integers(0, 7, size=n).astype(np.float64)
        Xn = rng.normal(size=n)
        Xn[rng.random(n) < 0.15] = np.nan
        X = np.column_stack([Xc, Xn, rng.normal(size=n)])
        y = (Xc % 2) * 1.5 + np.nan_to_num(Xn)
        ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
        bst = _train(X, y, {"objective": "regression", "num_leaves": 15,
                            "min_data_in_leaf": 5,
                            "categorical_feature": [0]}, rounds=8,
                     keep=True)
        drv = bst._driver
        drv._materialize()
        bins = drv.train_data.bins
        meta = drv.learner.meta_np
        ids = [1, 3, 6]
        scales = [1.0, -2.0, 0.5]
        got = drv._score_trees_binned(
            bins, [drv.models[i] for i in ids], scales)
        want = np.zeros(bins.shape[0])
        for ti, sc in zip(ids, scales):
            want += sc * _predict_binned(drv.models[ti], bins, meta)
        np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)

    def test_dart_scores_consistent(self):
        """DART's batched native drop/restore keeps maintained scores
        equal to recomputed model predictions."""
        import lightgbm_tpu as lgb
        rng = np.random.default_rng(12)
        X = rng.normal(size=(1200, 4))
        y = X[:, 0] * 2 + np.sin(X[:, 1])
        ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
        bst = lgb.train({"objective": "regression", "boosting": "dart",
                         "num_leaves": 15, "drop_rate": 0.5,
                         "min_data_in_leaf": 5},
                        ds, num_boost_round=12, verbose_eval=False,
                        keep_training_booster=True)
        maintained = bst._driver.train_scores.numpy()[0]
        recomputed = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(maintained, recomputed,
                                   rtol=2e-5, atol=2e-5)


def test_num_threads_plumbing():
    """num_threads (and aliases) caps the native walker's OpenMP pool
    (reference honors it via omp_set_num_threads); smoke: the export
    exists and threaded predictions are unchanged."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.native import native_lib, set_num_threads

    lib = native_lib()
    assert hasattr(lib, "LGBMTPU_SetNumThreads")
    rng = np.random.default_rng(7)
    X = rng.normal(size=(1000, 4))
    y = rng.normal(size=1000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=5)
    base = bst.predict(X)
    set_num_threads(1)
    try:
        np.testing.assert_allclose(bst.predict(X), base)
        loaded = lgb.Booster(params={"nthread": 2},
                             model_str=bst.model_to_string())
        np.testing.assert_allclose(loaded.predict(X), base)
    finally:
        set_num_threads(0)  # restore the OpenMP default
