"""Native OpenMP forest predictor vs the Python tree traversal oracle.

The native walker (src/capi/forest_predictor.cpp) must reproduce
Tree.predict exactly — including zero/NaN missing routing and the
categorical NaN fold-to-category-0 rule (models/tree.py:216-233).
"""

import numpy as np
import pytest


def _native_available():
    from lightgbm_tpu.native import native_lib
    return native_lib() is not None


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="native lib not built")


def _train(X, y, params, rounds=6):
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=y, params={"max_bin": 32})
    return lgb.train(params, ds, num_boost_round=rounds, verbose_eval=False)


def _python_raw(bst, X):
    out = np.zeros(len(X))
    for t in bst._driver.models:
        out += t.predict(X)
    return out


class TestForestPredictor:
    def test_numerical_missing_parity(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(900, 5))
        X[rng.random(X.shape) < 0.2] = np.nan
        y = np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) ** 2
        bst = _train(X, y, {"objective": "regression", "num_leaves": 15,
                            "min_data_in_leaf": 5})
        got = bst.predict(X, raw_score=True)
        np.testing.assert_allclose(got, _python_raw(bst, X),
                                   rtol=1e-12, atol=1e-12)

    def test_categorical_nan_fold_parity(self):
        """NaN categorical at predict time folds to category 0 for
        non-NaN missing types; the native walker must agree."""
        rng = np.random.default_rng(3)
        n = 1200
        Xc = rng.integers(0, 6, size=n).astype(np.float64)
        X = np.column_stack([Xc, rng.normal(size=n)])
        y = (Xc < 2) * 2.0 + X[:, 1]
        bst = _train(X, y, {"objective": "regression", "num_leaves": 15,
                            "min_data_in_leaf": 5,
                            "categorical_feature": [0]})
        # NaN and fractional negatives in (-1, 0): both fold to category
        # 0 (truncation-before-negative-test, like the reference)
        vals = np.concatenate([np.full(30, np.nan), np.full(30, -0.5)])
        Xq = np.column_stack([vals, rng.normal(size=60)])
        got = bst.predict(Xq, raw_score=True)
        np.testing.assert_allclose(got, _python_raw(bst, Xq),
                                   rtol=1e-12, atol=1e-12)

    def test_leaf_index_parity(self):
        rng = np.random.default_rng(1)
        X = rng.normal(size=(500, 4))
        y = X[:, 0] * X[:, 1]
        bst = _train(X, y, {"objective": "regression", "num_leaves": 7,
                            "min_data_in_leaf": 5})
        leaves = bst.predict(X, pred_leaf=True)
        expect = np.column_stack([t.predict_leaf(X)
                                  for t in bst._driver.models])
        np.testing.assert_array_equal(leaves, expect)
