"""docs/Parameters.md is generated from the config registry
(tools/gen_params_doc.py, the analog of the reference's
helpers/parameter_generator.py pipeline); it must stay in sync."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_parameters_doc_in_sync(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "gen_params_doc", os.path.join(REPO, "tools", "gen_params_doc.py"))
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)

    out = tmp_path / "Parameters.md"
    gen.main(out_path=str(out))
    committed = open(os.path.join(REPO, "docs", "Parameters.md")).read()
    assert committed == out.read_text(), (
        "docs/Parameters.md is stale; run python tools/gen_params_doc.py")
